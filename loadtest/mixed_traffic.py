"""Mixed-traffic loadtest lane (ISSUE 9, three-class since ISSUE 10):
interactive notebook churn AND a steady serving request stream AND a batch
TPUJob stream through ONE cluster, gated by the existing SLO engine —
pass/fail is burn rate and firing alerts, never ad-hoc thresholds.

Three workload classes contend for the same chips:

- **interactive churn**: N TPU notebooks cycling stop→checkpoint→suspend→
  warm-pool-resume (the ISSUE 7 machinery) for the whole run, feeding the
  `resume-latency` SLO;
- **serving stream**: an InferenceEndpoint held Serving on its own slice
  while a real continuous-batching engine (serving/engine.py, tiny model on
  the driver CPU) takes a steady request stream joined to the endpoint's
  trace, feeding the `token-latency` and `serving-availability` SLOs;
- **batch stream**: back-to-back TPUJobs (gang admission through the same
  scheduler/slicepool, checkpoint cadence, step-acked completion) feeding
  the `job-completion` SLO and the queue-wait/goodput series.

The verdict is read back from the judgement layer itself: after the run the
SLO engine's statuses must show every gated SLO at-or-above objective over
the longest (scaled) window and the alert manager must hold zero firing
alerts. A saturated queue, a wedged resume, a stuck job, or a degraded
decode path fails here exactly the way it would page on-call.

  python loadtest/mixed_traffic.py --notebooks 3 --duration 20 --qps 20
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

GATED_SLOS = ("token-latency", "serving-availability", "resume-latency",
              "job-completion")


def run(args) -> None:
    import jax
    import jax.numpy as jnp

    from odh_kubeflow_tpu.api.core import Container
    from odh_kubeflow_tpu.api.inference import (
        InferenceEndpoint,
        ServingSpec,
    )
    from odh_kubeflow_tpu.api.notebook import Notebook, TPUSpec
    from odh_kubeflow_tpu.cluster import SimCluster
    from odh_kubeflow_tpu.controllers import Config, constants as C
    from odh_kubeflow_tpu.main import build_manager
    from odh_kubeflow_tpu.models import TransformerConfig, init_params
    from odh_kubeflow_tpu.probe import sim_agent_behavior
    from odh_kubeflow_tpu.serving.engine import QueueFull, ServingEngine

    from odh_kubeflow_tpu.api.job import TPUJob

    ns = args.namespace
    cluster = SimCluster().start()
    # one slice per notebook + one for the endpoint + one per batch
    # stream: churn contends, the endpoint's slice stays pinned, jobs cycle
    cluster.add_tpu_pool("mixed", "v5e", "2x2",
                         slices=args.notebooks + 1 + max(1, args.jobs))
    agents = {}
    cluster.add_pod_behavior(sim_agent_behavior(agents, duty=0.9))

    # the batch workload's step counter lives at the transport: every
    # learner-gang /tpu/checkpoint ack advances it (the job controller's
    # cadence window is the only caller)
    job_steps = {}

    def http_get(url, timeout=10.0):
        if "/tpu/checkpoint" in url and "-learner-" in url:
            name = url.split("//", 1)[1].split("-learner-", 1)[0]
            job_steps[name] = job_steps.get(name, 0) + 30
            return 200, json.dumps(
                {"saved": True, "step": job_steps[name]}
            ).encode()
        return cluster.http_get(url, timeout=timeout)
    config = Config(
        enable_culling=False,
        suspend_enabled=True,
        readiness_probe_period_s=0.15,
        suspend_checkpoint_window_s=1.0,
        resume_timeout_s=20.0,
        resume_max_attempts=4,
        reclaim_pending_grace_s=0.3,
        serving_loading_window_s=10.0,
        serving_drain_timeout_s=0.5,
        slo_enabled=True,
        # shrink the canonical burn windows so the run exercises the real
        # rule shapes inside --duration seconds. Scaled so the FAST (5m)
        # window spans half the run: scaling 6h into the run instead would
        # collapse 5m to ~duration/72 — at 10s runs that is a 140ms window
        # where a single 50ms scheduler hiccup reads as a 36% outage and
        # pages on noise no real deployment would see
        slo_window_scale=max(1e-4, args.duration / 600.0),
        canary_period_s=0.0,
        job_checkpoint_window_s=2.0,
        job_requeue_backoff_s=0.2,
    )
    mgr = build_manager(cluster.store, config, http_get=http_get)
    mgr.start()

    result = {"notebooks": args.notebooks, "duration_s": args.duration,
              "qps": args.qps}
    try:
        def wait_for(fn, timeout, msg):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if fn():
                    return
                time.sleep(0.05)
            raise SystemExit(f"loadtest setup timeout: {msg}")

        # -- the serving endpoint, pinned Serving on its own slice --
        ep = InferenceEndpoint()
        ep.metadata.name = "serve"
        ep.metadata.namespace = ns
        ep.spec.template.spec.containers = [Container(name="serve", image="s:1")]
        ep.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2")
        ep.spec.serving = ServingSpec(max_batch_slots=8, max_queue_depth=64,
                                      max_seq=256, max_new_tokens=64)
        cluster.client.create(ep)

        def ep_serving():
            got = cluster.client.get(InferenceEndpoint, ns, "serve")
            return got.metadata.annotations.get(
                C.INFERENCE_STATE_ANNOTATION) == "serving"

        wait_for(ep_serving, 40, "endpoint Serving")
        traceparent = cluster.client.get(
            InferenceEndpoint, ns, "serve"
        ).metadata.annotations.get(C.TRACEPARENT_ANNOTATION)

        # -- the interactive fleet --
        for i in range(args.notebooks):
            nb = Notebook()
            nb.metadata.name = f"churn-{i}"
            nb.metadata.namespace = ns
            nb.spec.template.spec.containers = [
                Container(name=f"churn-{i}", image="jax:1")
            ]
            nb.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2")
            cluster.client.create(nb)
        for i in range(args.notebooks):
            wait_for(
                lambda i=i: (
                    lambda got: got.status.tpu is not None
                    and got.status.tpu.mesh_ready
                )(cluster.client.get(Notebook, ns, f"churn-{i}")),
                60, f"churn-{i} mesh-ready",
            )
            agents[f"churn-{i}-0"].checkpoint_hook = lambda: {"step": 1}

        # -- serving stream (driver-side engine, tiny model) --
        cfg = TransformerConfig(
            vocab=512, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
            d_ff=128, max_seq=256, dtype=jnp.float32, use_flash=False,
            remat=False,
        )
        engine = ServingEngine(
            init_params(jax.random.PRNGKey(0), cfg), cfg,
            max_slots=8, max_seq=256, max_queue_depth=64, decode_burst=8,
        ).start()
        stream = {"submitted": 0, "rejected": 0, "handles": []}
        stop_stream = threading.Event()

        def drive_stream():
            rng = random.Random(0)
            period = 1.0 / max(0.1, args.qps)
            while not stop_stream.is_set():
                prompt = [rng.randrange(cfg.vocab) for _ in range(16)]
                try:
                    stream["handles"].append(engine.submit(
                        prompt, max_new=rng.choice((8, 16, 32, 64)),
                        traceparent=traceparent,
                    ))
                    stream["submitted"] += 1
                except QueueFull:
                    stream["rejected"] += 1
                stop_stream.wait(period)

        streamer = threading.Thread(target=drive_stream, daemon=True)
        streamer.start()

        # -- batch stream (ISSUE 10): back-to-back TPUJobs on the spare
        # slice, each admitted through the gang scheduler and completed by
        # step-acked cadence checkpoints --
        batch = {"submitted": 0, "succeeded": 0, "failed": 0}
        stop_jobs = threading.Event()

        def drive_jobs(stream: int):
            from odh_kubeflow_tpu.api.notebook import TPUSpec as _TPUSpec

            i = 0
            while not stop_jobs.is_set():
                name = f"batch-{stream}-{i}"
                job = TPUJob()
                job.metadata.name = name
                job.metadata.namespace = ns
                job.spec.template.spec.containers = [
                    Container(name=name, image="jax:1")
                ]
                job.spec.tpu = _TPUSpec(accelerator="v5e", topology="2x2")
                job.spec.steps = 90
                job.spec.checkpoint_period_s = 0.3
                cluster.client.create(job)
                batch["submitted"] += 1
                deadline = time.monotonic() + 30
                state = ""
                while time.monotonic() < deadline and not stop_jobs.is_set():
                    state = cluster.client.get(
                        TPUJob, ns, name
                    ).metadata.annotations.get(C.JOB_STATE_ANNOTATION, "")
                    if state in ("succeeded", "failed"):
                        break
                    time.sleep(0.05)
                if state == "succeeded":
                    batch["succeeded"] += 1
                elif state == "failed":
                    batch["failed"] += 1
                cluster.client.delete(TPUJob, ns, name)
                i += 1

        jobbers = [
            threading.Thread(target=drive_jobs, args=(s,), daemon=True)
            for s in range(max(0, args.jobs))
        ]
        for jobber in jobbers:
            jobber.start()

        # -- interactive churn until the deadline --
        churn_cycles = 0
        deadline = time.monotonic() + args.duration
        while time.monotonic() < deadline:
            name = f"churn-{churn_cycles % args.notebooks}"
            cluster.client.patch(Notebook, ns, name, {"metadata": {
                "annotations": {
                    C.STOP_ANNOTATION: "2026-01-01T00:00:00Z",
                    C.TPU_SUSPEND_STATE_ANNOTATION: "checkpointing",
                }}})
            wait_for(
                lambda: cluster.client.get(Notebook, ns, name)
                .metadata.annotations.get(C.TPU_SUSPEND_STATE_ANNOTATION)
                == "suspended",
                30, f"{name} suspended",
            )
            cluster.client.patch(Notebook, ns, name, {"metadata": {
                "annotations": {C.STOP_ANNOTATION: None}}})
            wait_for(
                lambda: not cluster.client.get(Notebook, ns, name)
                .metadata.annotations.get(C.TPU_SUSPEND_STATE_ANNOTATION),
                60, f"{name} resumed",
            )
            churn_cycles += 1

        stop_stream.set()
        stop_jobs.set()
        streamer.join(timeout=5)
        for jobber in jobbers:
            if jobber.is_alive():
                jobber.join(timeout=10)
        engine.stop(drain_timeout_s=10.0)

        # -- the verdict comes from the judgement layer --
        statuses = mgr.slo_engine.evaluate()
        alerts = mgr.alert_manager.status()
        all_firing = sorted(
            a.get("rule", a.get("name", "?")) for a in alerts.get("firing", [])
        )
        # the lane's verdict covers the SLOs the mixed traffic DRIVES; other
        # alerts are reported for the operator but don't fail a lane that
        # never exercised them
        firing = [
            name for name in all_firing
            if any(name.startswith(slo) for slo in GATED_SLOS)
        ]
        gates = {}
        ok = True
        for name in GATED_SLOS:
            st = statuses.get(name, {})
            compliance = st.get("compliance")
            objective = st.get("objective")
            passed = (
                compliance is not None and objective is not None
                and compliance >= objective
            )
            # an SLO with zero events judged compliant: an idle lane is not
            # a failure, but report it so the operator sees the coverage
            gates[name] = {
                "compliance": compliance,
                "objective": objective,
                "events": st.get("events"),
                "passed": passed,
            }
            ok = ok and passed
        ok = ok and not firing
        result.update({
            "churn_cycles": churn_cycles,
            "jobs_submitted": batch["submitted"],
            "jobs_succeeded": batch["succeeded"],
            "jobs_failed": batch["failed"],
            "requests_submitted": stream["submitted"],
            "requests_rejected": stream["rejected"],
            "requests_ok": sum(
                1 for h in stream["handles"] if h.result == "ok"
            ),
            "slo_gates": gates,
            "alerts_firing_gated": list(firing),
            "alerts_firing_all": list(all_firing),
            "passed": bool(ok),
        })
    finally:
        mgr.stop()
        cluster.stop()
    print(json.dumps(result, indent=2))
    if not result.get("passed"):
        raise SystemExit(1)


def main() -> None:
    # deployment-surface guard (ISSUE 14): the driver always runs armed
    # (DEPLOYGUARD=0 opts out) — a request escaping its declared flow/RBAC
    # surface fails the lane at the offending call, not as a fairness leak
    os.environ.setdefault("DEPLOYGUARD", "1")
    ap = argparse.ArgumentParser()
    ap.add_argument("--notebooks", type=int, default=3)
    ap.add_argument("--jobs", type=int, default=1,
                    help="concurrent batch TPUJob streams (0 disables)")
    ap.add_argument("--duration", type=float, default=20.0)
    ap.add_argument("--qps", type=float, default=20.0)
    ap.add_argument("--namespace", default="mixed")
    run(ap.parse_args())


if __name__ == "__main__":
    main()
