"""SLO-gated loadtest tiers (ISSUE 13): 200- and 500-object mixed-class
populations against the SHARDED, flow-controlled control plane.

One tier run drives, through a single store:

- a mixed population sized by --objects (CPU notebooks + TPU notebooks +
  InferenceEndpoints + back-to-back TPUJob streams, deterministic split),
- TWO shard managers (crc32 keyspace partition, per-shard leases) plus a
  warm standby for shard 0,
- a mid-run TPUJob admission storm slammed into the batch priority level
  while its seats are held — the storm must be shed THERE (429s at the
  batch level, zero sheds at exempt/workload-high),
- a kill of the active shard-0 leader mid-tier — the standby must take
  over within lease bounds with zero fenced-off duplicate writes, and the
  SLO verdict is read from the SURVIVING manager's own judgement layer.

Pass/fail is the SLO engine's statuses (readiness-latency-p99,
canary-readiness, job-completion, serving-availability) + firing alerts +
the control-plane gates above — never ad-hoc thresholds. The 200-object
tier is the CI lane (ci/loadtest.sh); the 500-object tier is the slow one:

  python loadtest/tiers.py --objects 200
  python loadtest/tiers.py --objects 500
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the SLOs this tier's traffic actually drives; their compliance + alerts
# are the verdict (ISSUE 13 acceptance list)
GATED_SLOS = ("readiness-latency-p99", "canary-readiness", "job-completion",
              "serving-availability")

STEP_PER_CKPT = 30
JOB_STREAMS = 6
STORM_THREADS = 12
STORM_PER_THREAD = 2


def composition(objects: int) -> dict:
    """Deterministic mixed-class split of the object budget."""
    endpoints = max(1, objects // 40)
    tpu_notebooks = max(2, objects // 20)
    jobs = max(4, objects // 4)
    return {
        "cpu_notebooks": objects - endpoints - tpu_notebooks - jobs,
        "tpu_notebooks": tpu_notebooks,
        "endpoints": endpoints,
        "jobs": jobs,
    }


def run(args) -> None:
    import jax
    import jax.numpy as jnp

    from odh_kubeflow_tpu.api.core import Container
    from odh_kubeflow_tpu.api.inference import InferenceEndpoint, ServingSpec
    from odh_kubeflow_tpu.api.job import TPUJob
    from odh_kubeflow_tpu.api.notebook import Notebook, TPUSpec
    from odh_kubeflow_tpu.apimachinery import (
        NotFoundError,
        TooManyRequestsError,
    )
    from odh_kubeflow_tpu.cluster import SimCluster
    from odh_kubeflow_tpu.cluster.flowcontrol import (
        FlowController,
        PriorityLevel,
        default_flow_schemas,
    )
    from odh_kubeflow_tpu.controllers import Config, constants as C
    from odh_kubeflow_tpu.main import build_manager
    from odh_kubeflow_tpu.models import TransformerConfig, init_params
    from odh_kubeflow_tpu.probe import sim_agent_behavior
    from odh_kubeflow_tpu.runtime import metrics as rm
    from odh_kubeflow_tpu.runtime.manager import ShardSpec
    from odh_kubeflow_tpu.serving.engine import QueueFull, ServingEngine

    ns = args.namespace
    mix = composition(args.objects)
    duration = args.duration or (20.0 + args.objects * 0.03)
    setup_budget = 120 + args.objects * 0.3
    # lease scaled with the population: the leader's renew thread is pure
    # python competing with every controller, probe, and engine thread for
    # the GIL, and at 500 objects it can be starved past a 2 s lease — which
    # the live standby elector correctly reads as leader death and steals.
    # The kill gate's bound scales with the same numbers, so the failover
    # guarantee stays proportional, not absolute.
    lease, renew = (2.0, 0.4) if args.objects <= 200 else (8.0, 1.0)

    cluster = SimCluster().start()
    # the batch budget is pinned narrow so the injected storm contends
    # deterministically; everything else is the default APF-analog layout
    fc = FlowController(
        schemas=default_flow_schemas(),
        levels=[
            PriorityLevel("exempt", exempt=True),
            PriorityLevel("system", seats=16, queue_length=64, queue_timeout_s=10.0),
            PriorityLevel("workload-high", seats=12, queue_length=64,
                          queue_timeout_s=10.0),
            PriorityLevel("batch", seats=4, queue_length=4, queue_timeout_s=0.3),
            PriorityLevel("default", seats=8, queue_length=32, queue_timeout_s=5.0),
        ],
    )
    cluster.store.flowcontrol = fc
    cluster.add_tpu_pool(
        "tiers", "v5e", "2x2",
        slices=mix["tpu_notebooks"] + mix["endpoints"] + JOB_STREAMS,
    )
    cluster.add_cpu_pool("cpu", nodes=max(3, args.objects // 40), cpu="64")
    agents = {}
    cluster.add_pod_behavior(sim_agent_behavior(agents, duty=0.9))

    job_steps = {}

    def http_get(url, timeout=10.0):
        if "/tpu/checkpoint" in url and "-learner-" in url:
            name = url.split("//", 1)[1].split("-learner-", 1)[0]
            job_steps[name] = job_steps.get(name, 0) + STEP_PER_CKPT
            return 200, json.dumps(
                {"saved": True, "step": job_steps[name]}
            ).encode()
        return cluster.http_get(url, timeout=timeout)

    config = Config(
        enable_culling=False,
        suspend_enabled=True,
        readiness_probe_period_s=0.15,
        serving_loading_window_s=10.0,
        serving_drain_timeout_s=0.5,
        slo_enabled=True,
        slo_window_scale=max(1e-4, duration / 600.0),
        # CPU canary: the black-box prober keeps driving the full create->
        # ready->delete path through the storm AND the failover window;
        # canary_timeout_s covers the lease-bound takeover gap so a probe
        # in flight during failover lands late, not failed
        canary_period_s=0.5,
        canary_timeout_s=30.0,
        job_checkpoint_window_s=2.0,
        job_requeue_backoff_s=0.2,
    )
    # only the shard-0 primary registers the (store-global) admission
    # webhook; shard 1 carries no judgement layer of its own — the SLO
    # engine reads the process-global registry, one evaluator is the truth
    mgr0 = build_manager(cluster.store, config, leader_election=True,
                         http_get=http_get, shard=ShardSpec(0, 2),
                         lease_duration=lease, renew_period=renew)
    mgr1 = build_manager(cluster.store,
                         dataclasses.replace(config, slo_enabled=False),
                         leader_election=True, http_get=http_get,
                         shard=ShardSpec(1, 2), lease_duration=lease,
                         renew_period=renew, register_webhook=False)
    # the warm standby for shard 0 carries its OWN judgement layer: after
    # the kill, the verdict must come from the surviving manager
    standby = build_manager(cluster.store, config, leader_election=True,
                            http_get=http_get, shard=ShardSpec(0, 2),
                            lease_duration=lease, renew_period=renew,
                            register_webhook=False)
    fenced0 = rm.fenced_writes_total.value()
    mgr0.start(wait_for_leadership_timeout=10)
    mgr1.start(wait_for_leadership_timeout=10)
    standby_up = threading.Event()

    def run_standby():
        # the wait must outlast the whole tier up to the kill: bring-up,
        # steady state, and the storm all happen before mgr0 dies. A timeout
        # here does NOT stop the elector, so an early give-up leaves a live
        # elector that steals the lease at the first starved renew — exactly
        # the spurious-failover the tier must not inject itself.
        standby.start(
            wait_for_leadership_timeout=int(setup_budget + duration + 600)
        )
        standby_up.set()

    standby_thread = threading.Thread(target=run_standby, daemon=True)
    standby_thread.start()

    driver = cluster.client
    result = {"objects": args.objects, "composition": mix,
              "duration_s": round(duration, 1)}
    failures = []

    def create_persistent(obj, attempts=200):
        for _ in range(attempts):
            try:
                return driver.create(obj)
            except TooManyRequestsError:
                time.sleep(0.05)
        raise SystemExit(f"create never admitted: {obj.metadata.name}")

    def wait_for(fn, timeout, msg):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                if fn():
                    return
            except TooManyRequestsError:
                pass
            time.sleep(0.05)
        raise SystemExit(f"tier setup timeout: {msg}")

    engine = None
    try:
        # ------------------------------------------------------------------
        # population bring-up (feeds readiness-latency-p99)
        # ------------------------------------------------------------------
        for i in range(mix["cpu_notebooks"]):
            nb = Notebook()
            nb.metadata.name = f"cpu-{i}"
            nb.metadata.namespace = ns
            nb.spec.template.spec.containers = [
                Container(name=f"cpu-{i}", image="jupyter:1")
            ]
            create_persistent(nb)
        for i in range(mix["tpu_notebooks"]):
            nb = Notebook()
            nb.metadata.name = f"tpu-{i}"
            nb.metadata.namespace = ns
            nb.spec.template.spec.containers = [
                Container(name=f"tpu-{i}", image="jax:1")
            ]
            nb.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2")
            create_persistent(nb)
        for i in range(mix["endpoints"]):
            ep = InferenceEndpoint()
            ep.metadata.name = f"serve-{i}"
            ep.metadata.namespace = ns
            ep.spec.template.spec.containers = [
                Container(name=f"serve-{i}", image="s:1")
            ]
            ep.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2")
            ep.spec.serving = ServingSpec(max_batch_slots=8, max_queue_depth=64,
                                          max_seq=256, max_new_tokens=64)
            create_persistent(ep)

        wait_for(
            lambda: all(
                driver.get(Notebook, ns, f"cpu-{i}").status.ready_replicas >= 1
                for i in range(mix["cpu_notebooks"])
            ),
            setup_budget, "CPU notebooks Ready",
        )
        wait_for(
            lambda: all(
                (lambda got: got.status.tpu is not None and got.status.tpu.mesh_ready)(
                    driver.get(Notebook, ns, f"tpu-{i}")
                )
                for i in range(mix["tpu_notebooks"])
            ),
            setup_budget, "TPU notebooks mesh-ready",
        )
        wait_for(
            lambda: all(
                driver.get(InferenceEndpoint, ns, f"serve-{i}")
                .metadata.annotations.get(C.INFERENCE_STATE_ANNOTATION) == "serving"
                for i in range(mix["endpoints"])
            ),
            setup_budget, "endpoints Serving",
        )
        traceparent = driver.get(
            InferenceEndpoint, ns, "serve-0"
        ).metadata.annotations.get(C.TRACEPARENT_ANNOTATION)

        # ------------------------------------------------------------------
        # batch streams (feeds job-completion) + serving stream
        # ------------------------------------------------------------------
        batch = {"submitted": 0, "succeeded": 0, "failed": 0}
        batch_lock = threading.Lock()
        stop_jobs = threading.Event()

        def drive_jobs(stream: int):
            i = 0
            while not stop_jobs.is_set():
                with batch_lock:
                    if batch["submitted"] >= mix["jobs"]:
                        return
                    batch["submitted"] += 1
                name = f"batch-{stream}-{i}"
                job = TPUJob()
                job.metadata.name = name
                job.metadata.namespace = ns
                job.spec.template.spec.containers = [
                    Container(name=name, image="jax:1")
                ]
                job.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2")
                job.spec.steps = 30
                job.spec.checkpoint_period_s = 0.1
                create_persistent(job)
                deadline = time.monotonic() + 60
                state = ""
                while time.monotonic() < deadline and not stop_jobs.is_set():
                    try:
                        state = driver.get(
                            TPUJob, ns, name
                        ).metadata.annotations.get(C.JOB_STATE_ANNOTATION, "")
                    except TooManyRequestsError:
                        pass  # the storm sheds driver polls too; keep going
                    if state in ("succeeded", "failed"):
                        break
                    time.sleep(0.05)
                with batch_lock:
                    if state == "succeeded":
                        batch["succeeded"] += 1
                    elif state == "failed":
                        batch["failed"] += 1
                try:
                    driver.delete(TPUJob, ns, name)
                except (NotFoundError, TooManyRequestsError):
                    pass
                i += 1

        jobbers = [
            threading.Thread(target=drive_jobs, args=(s,), daemon=True)
            for s in range(JOB_STREAMS)
        ]
        for jobber in jobbers:
            jobber.start()

        cfg = TransformerConfig(
            vocab=512, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
            d_ff=128, max_seq=256, dtype=jnp.float32, use_flash=False,
            remat=False,
        )
        engine = ServingEngine(
            init_params(jax.random.PRNGKey(0), cfg), cfg,
            max_slots=8, max_seq=256, max_queue_depth=64, decode_burst=8,
        ).start()
        stream = {"submitted": 0, "rejected": 0, "handles": []}
        stop_stream = threading.Event()

        def drive_stream():
            rng = random.Random(0)
            period = 1.0 / max(0.1, args.qps)
            while not stop_stream.is_set():
                prompt = [rng.randrange(cfg.vocab) for _ in range(16)]
                try:
                    stream["handles"].append(engine.submit(
                        prompt, max_new=rng.choice((8, 16, 32)),
                        traceparent=traceparent,
                    ))
                    stream["submitted"] += 1
                except QueueFull:
                    stream["rejected"] += 1
                stop_stream.wait(period)

        streamer = threading.Thread(target=drive_stream, daemon=True)
        streamer.start()

        t_run = time.monotonic()
        deadline = t_run + duration
        time.sleep(min(duration * 0.2, 5.0))

        # ------------------------------------------------------------------
        # the injected TPUJob admission storm: every batch seat is held while
        # anonymous creates slam the level — queue-full sheds are guaranteed,
        # and they must land at batch and ONLY at batch
        # ------------------------------------------------------------------
        storm = {"attempted": 0, "admitted": [], "shed_creates": 0}
        seats = fc.summary()["batch"]["seats"]
        hogs = [fc.admit("tpu-job") for _ in range(seats)]
        exempt_before = fc.summary()["exempt"]["dispatched"]

        def storm_driver(t: int):
            for i in range(STORM_PER_THREAD):
                name = f"storm-{t}-{i}"
                job = TPUJob()
                job.metadata.name = name
                job.metadata.namespace = ns
                job.spec.template.spec.containers = [
                    Container(name=name, image="jax:1")
                ]
                job.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2")
                job.spec.steps = 60
                job.spec.checkpoint_period_s = 0.2
                storm["attempted"] += 1
                try:
                    driver.create(job)
                    storm["admitted"].append(name)
                except TooManyRequestsError:
                    storm["shed_creates"] += 1

        stormers = [
            threading.Thread(target=storm_driver, args=(t,), daemon=True)
            for t in range(STORM_THREADS)
        ]
        for s in stormers:
            s.start()
        time.sleep(0.8)  # the storm beats on a saturated level
        for h in hogs:
            h.release()
        for s in stormers:
            s.join(20)
        # storm jobs that made it through admission are withdrawn: the storm
        # is load, not workload — it must not consume the job budget
        for name in storm["admitted"]:
            try:
                driver.delete(TPUJob, ns, name)
            except (NotFoundError, TooManyRequestsError):
                pass

        s = fc.summary()
        storm_shed = s["batch"]["rejected"] + s["batch"]["timed_out"]
        if storm_shed <= 0:
            failures.append("storm was never shed at the batch level")
        if s["workload-high"]["rejected"] or s["workload-high"]["timed_out"]:
            failures.append("protected workload-high level shed during the storm")
        if s["exempt"]["rejected"] or s["exempt"]["timed_out"]:
            failures.append("exempt (lease) traffic shed during the storm")
        if s["exempt"]["dispatched"] <= exempt_before:
            failures.append("no exempt traffic flowed through the storm")

        # ------------------------------------------------------------------
        # kill the active shard-0 leader mid-tier
        # ------------------------------------------------------------------
        time.sleep(0.5)
        t_kill = time.monotonic()
        mgr0.stop()
        # the graceful stop drains services for a while, and the elector
        # keeps renewing until it is stopped partway through — so the lease
        # only starts aging out at (at latest) stop-return. The lease-bound
        # gate measures from there to the standby's is_leader flip (the
        # failover event itself); controller/service bring-up on the new
        # leader is real work but not lease arithmetic, reported separately.
        stop_s = time.monotonic() - t_kill
        lease_bound = lease + 2 * renew + 2.0
        acquire_deadline = time.monotonic() + lease + 4 * renew + 10.0
        while (not standby.elector.is_leader.is_set()
               and time.monotonic() < acquire_deadline):
            time.sleep(0.01)
        if not standby.elector.is_leader.is_set():
            failures.append("standby never took over shard 0")
            takeover_s = None
        else:
            takeover_s = time.monotonic() - t_kill
            # past the bound means the storm starved failover: the old lease
            # ages out (>= the lease duration past the last renew), then one standby
            # acquire tick lands
            if takeover_s - stop_s > lease_bound:
                failures.append(
                    f"takeover took {takeover_s - stop_s:.2f}s past leader "
                    f"death (bound {lease_bound:.2f}s)"
                )
        if not standby_up.wait(90.0):
            failures.append("standby controllers never came up after takeover")
        standby_ready_s = time.monotonic() - t_kill

        # ------------------------------------------------------------------
        # ride out the rest of the tier on the surviving managers
        # ------------------------------------------------------------------
        # steady state until the deadline, then a completion tail so the job
        # quota actually runs (the tier's object count is the point); a hard
        # cap keeps a wedged stream from hanging the lane
        hard_cap = deadline + max(90.0, duration)
        while time.monotonic() < hard_cap:
            with batch_lock:
                done = batch["succeeded"] + batch["failed"]
                quota_done = batch["submitted"] >= mix["jobs"] and done >= mix["jobs"]
            if quota_done and time.monotonic() >= deadline:
                break
            time.sleep(0.1)
        stop_jobs.set()
        stop_stream.set()
        streamer.join(timeout=5)
        for jobber in jobbers:
            jobber.join(timeout=70)
        engine.stop(drain_timeout_s=10.0)

        fenced_delta = rm.fenced_writes_total.value() - fenced0
        if fenced_delta:
            failures.append(
                f"{fenced_delta} fenced-off write(s): the dying leader kept "
                "writing past its lease"
            )
        if not standby.healthz():
            failures.append("surviving shard-0 manager unhealthy after takeover")
        if not mgr1.healthz():
            failures.append("shard-1 manager unhealthy at tier end")

        # ------------------------------------------------------------------
        # the verdict comes from the SURVIVOR's judgement layer
        # ------------------------------------------------------------------
        statuses = standby.slo_engine.evaluate()
        alerts = standby.alert_manager.status()
        all_firing = sorted(
            a.get("rule", a.get("name", "?")) for a in alerts.get("firing", [])
        )
        firing = [
            name for name in all_firing
            if any(name.startswith(slo) for slo in GATED_SLOS)
        ]
        gates = {}
        ok = True
        for name in GATED_SLOS:
            st = statuses.get(name, {})
            compliance = st.get("compliance")
            objective = st.get("objective")
            passed = (
                compliance is not None and objective is not None
                and compliance >= objective
            )
            gates[name] = {
                "compliance": compliance,
                "objective": objective,
                "events": st.get("events"),
                "passed": passed,
            }
            ok = ok and passed
        ok = ok and not firing and not failures

        summary = fc.summary()
        result.update({
            "jobs_submitted": batch["submitted"],
            "jobs_succeeded": batch["succeeded"],
            "jobs_failed": batch["failed"],
            "requests_submitted": stream["submitted"],
            "requests_rejected": stream["rejected"],
            "requests_ok": sum(1 for h in stream["handles"] if h.result == "ok"),
            "storm": {
                "attempted": storm["attempted"],
                "admitted_then_withdrawn": len(storm["admitted"]),
                "driver_visible_sheds": storm["shed_creates"],
                "batch_level_sheds": storm_shed,
            },
            "takeover_s": round(takeover_s, 3) if takeover_s else None,
            "leader_stop_s": round(stop_s, 3),
            "takeover_past_leader_death_s": (
                round(takeover_s - stop_s, 3) if takeover_s else None
            ),
            "takeover_bound_s": round(lease_bound, 2),
            "standby_controllers_up_s": round(standby_ready_s, 3),
            "fenced_writes": fenced_delta,
            # the control-plane section: shed/queued/p99 wait per level
            "flowcontrol": {
                level: {
                    "dispatched": stats["dispatched"],
                    "shed": stats["rejected"] + stats["timed_out"],
                    "queued": stats["queued"],
                    "p99_wait_s": stats["p99_wait_s"],
                }
                for level, stats in summary.items()
            },
            "slo_gates": gates,
            "alerts_firing_gated": list(firing),
            "alerts_firing_all": list(all_firing),
            "control_plane_failures": list(failures),
            "passed": bool(ok),
        })
    finally:
        stop = getattr(standby, "stop", None)
        if stop:
            standby.stop()
        mgr1.stop()
        try:
            mgr0.stop()  # idempotent; killed mid-tier on the happy path
        except Exception:
            pass
        cluster.stop()
    print(json.dumps(result, indent=2))
    if not result.get("passed"):
        raise SystemExit(1)


def main() -> None:
    # deployment-surface guard (ISSUE 14): the tier always runs armed
    # (DEPLOYGUARD=0 opts out) — a shed-path or standby-takeover write that
    # escapes its declared flow/RBAC surface (a lease write misattributed
    # onto a workload flow after the shard failover, say) is a hard
    # RBACDriftError at the call, not a silent fairness leak
    os.environ.setdefault("DEPLOYGUARD", "1")
    ap = argparse.ArgumentParser()
    ap.add_argument("--objects", type=int, default=200, choices=(200, 500),
                    help="tier size: 200 (CI lane) or 500 (slow tier)")
    ap.add_argument("--duration", type=float, default=0.0,
                    help="steady-state seconds after bring-up "
                         "(0 = scale with --objects)")
    ap.add_argument("--qps", type=float, default=12.0)
    ap.add_argument("--namespace", default="tiers")
    run(ap.parse_args())


if __name__ == "__main__":
    main()
