"""SLO-gated loadtest tiers (ISSUE 13): 200- and 500-object mixed-class
populations against the SHARDED, flow-controlled control plane.

One tier run drives, through a single store:

- a mixed population sized by --objects (CPU notebooks + TPU notebooks +
  InferenceEndpoints + back-to-back TPUJob streams, deterministic split),
- TWO shard managers (crc32 keyspace partition, per-shard leases) plus a
  warm standby for shard 0,
- a mid-run TPUJob admission storm slammed into the batch priority level
  while its seats are held — the storm must be shed THERE (429s at the
  batch level, zero sheds at exempt/workload-high),
- a kill of the active shard-0 leader mid-tier — the standby must take
  over within lease bounds with zero fenced-off duplicate writes, and the
  SLO verdict is read from the SURVIVING manager's own judgement layer.

Pass/fail is the SLO engine's statuses (readiness-latency-p99,
canary-readiness, job-completion, serving-availability) + firing alerts +
the control-plane gates above — never ad-hoc thresholds. The 200-object
tier is the CI lane (ci/loadtest.sh); the 500-object tier is the slow one:

  python loadtest/tiers.py --objects 200
  python loadtest/tiers.py --objects 500

The multi-replica serving tier (ISSUE 16) drives an open-loop token stream
through the health-aware router against a replicated InferenceEndpoint
fleet, enacts the seeded router bad day (one whole replica gang preempted
mid-stream, one surviving replica slowed, probe flaps, the control-plane
schedule), forces one autoscale-up through the real ReplicaAutoscaler
decision path, and reads its verdict from the token-latency /
serving-availability SLO statuses + firing alerts — with zero dropped
in-flight requests and the batch/default flow levels never starved by
router traffic:

  python loadtest/tiers.py --tier fleet
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the SLOs this tier's traffic actually drives; their compliance + alerts
# are the verdict (ISSUE 13 acceptance list)
GATED_SLOS = ("readiness-latency-p99", "canary-readiness", "job-completion",
              "serving-availability")

# the serving-fleet tier's verdict SLOs (ISSUE 16): what the open-loop
# stream through the router actually exercises
FLEET_GATED_SLOS = ("token-latency", "serving-availability")

STEP_PER_CKPT = 30
JOB_STREAMS = 6
STORM_THREADS = 12
STORM_PER_THREAD = 2


def composition(objects: int) -> dict:
    """Deterministic mixed-class split of the object budget."""
    endpoints = max(1, objects // 40)
    tpu_notebooks = max(2, objects // 20)
    jobs = max(4, objects // 4)
    return {
        "cpu_notebooks": objects - endpoints - tpu_notebooks - jobs,
        "tpu_notebooks": tpu_notebooks,
        "endpoints": endpoints,
        "jobs": jobs,
    }


def run(args) -> None:
    import jax
    import jax.numpy as jnp

    from odh_kubeflow_tpu.api.core import Container
    from odh_kubeflow_tpu.api.inference import InferenceEndpoint, ServingSpec
    from odh_kubeflow_tpu.api.job import TPUJob
    from odh_kubeflow_tpu.api.notebook import Notebook, TPUSpec
    from odh_kubeflow_tpu.apimachinery import (
        NotFoundError,
        TooManyRequestsError,
    )
    from odh_kubeflow_tpu.cluster import SimCluster
    from odh_kubeflow_tpu.cluster.flowcontrol import (
        FlowController,
        PriorityLevel,
        default_flow_schemas,
    )
    from odh_kubeflow_tpu.controllers import Config, constants as C
    from odh_kubeflow_tpu.main import build_manager
    from odh_kubeflow_tpu.models import TransformerConfig, init_params
    from odh_kubeflow_tpu.probe import sim_agent_behavior
    from odh_kubeflow_tpu.runtime import metrics as rm
    from odh_kubeflow_tpu.runtime.manager import ShardSpec
    from odh_kubeflow_tpu.serving.engine import QueueFull, ServingEngine

    ns = args.namespace
    mix = composition(args.objects)
    duration = args.duration or (20.0 + args.objects * 0.03)
    setup_budget = 120 + args.objects * 0.3
    # lease scaled with the population: the leader's renew thread is pure
    # python competing with every controller, probe, and engine thread for
    # the GIL, and at 500 objects it can be starved past a 2 s lease — which
    # the live standby elector correctly reads as leader death and steals.
    # The kill gate's bound scales with the same numbers, so the failover
    # guarantee stays proportional, not absolute.
    lease, renew = (2.0, 0.4) if args.objects <= 200 else (8.0, 1.0)

    cluster = SimCluster().start()
    # the batch budget is pinned narrow so the injected storm contends
    # deterministically; everything else is the default APF-analog layout
    fc = FlowController(
        schemas=default_flow_schemas(),
        levels=[
            PriorityLevel("exempt", exempt=True),
            PriorityLevel("system", seats=16, queue_length=64, queue_timeout_s=10.0),
            PriorityLevel("workload-high", seats=12, queue_length=64,
                          queue_timeout_s=10.0),
            # the ISSUE-16 serving-requests schema names this level; the
            # pinned layout must carry it or FlowController refuses the
            # schema set at construction
            PriorityLevel("serving", seats=8, queue_length=32,
                          queue_timeout_s=5.0),
            PriorityLevel("batch", seats=4, queue_length=4, queue_timeout_s=0.3),
            PriorityLevel("default", seats=8, queue_length=32, queue_timeout_s=5.0),
        ],
    )
    cluster.store.flowcontrol = fc
    cluster.add_tpu_pool(
        "tiers", "v5e", "2x2",
        slices=mix["tpu_notebooks"] + mix["endpoints"] + JOB_STREAMS,
    )
    cluster.add_cpu_pool("cpu", nodes=max(3, args.objects // 40), cpu="64")
    agents = {}
    cluster.add_pod_behavior(sim_agent_behavior(agents, duty=0.9))

    job_steps = {}

    def http_get(url, timeout=10.0):
        if "/tpu/checkpoint" in url and "-learner-" in url:
            name = url.split("//", 1)[1].split("-learner-", 1)[0]
            job_steps[name] = job_steps.get(name, 0) + STEP_PER_CKPT
            return 200, json.dumps(
                {"saved": True, "step": job_steps[name]}
            ).encode()
        return cluster.http_get(url, timeout=timeout)

    config = Config(
        enable_culling=False,
        suspend_enabled=True,
        readiness_probe_period_s=0.15,
        serving_loading_window_s=10.0,
        serving_drain_timeout_s=0.5,
        slo_enabled=True,
        slo_window_scale=max(1e-4, duration / 600.0),
        # CPU canary: the black-box prober keeps driving the full create->
        # ready->delete path through the storm AND the failover window;
        # canary_timeout_s covers the lease-bound takeover gap so a probe
        # in flight during failover lands late, not failed
        canary_period_s=0.5,
        canary_timeout_s=30.0,
        job_checkpoint_window_s=2.0,
        job_requeue_backoff_s=0.2,
    )
    # only the shard-0 primary registers the (store-global) admission
    # webhook; shard 1 carries no judgement layer of its own — the SLO
    # engine reads the process-global registry, one evaluator is the truth
    mgr0 = build_manager(cluster.store, config, leader_election=True,
                         http_get=http_get, shard=ShardSpec(0, 2),
                         lease_duration=lease, renew_period=renew)
    mgr1 = build_manager(cluster.store,
                         dataclasses.replace(config, slo_enabled=False),
                         leader_election=True, http_get=http_get,
                         shard=ShardSpec(1, 2), lease_duration=lease,
                         renew_period=renew, register_webhook=False)
    # the warm standby for shard 0 carries its OWN judgement layer: after
    # the kill, the verdict must come from the surviving manager
    standby = build_manager(cluster.store, config, leader_election=True,
                            http_get=http_get, shard=ShardSpec(0, 2),
                            lease_duration=lease, renew_period=renew,
                            register_webhook=False)
    # back-to-back tiers share one process: the cumulative goodput ledgers
    # (runtime/accounting.py) must not inherit a previous tier's wall-clock
    # (ISSUE 17 bugfix — the old module-level accumulators never reset)
    from odh_kubeflow_tpu.runtime import cpprofile, jobmetrics
    from odh_kubeflow_tpu.tpu import telemetry as tpu_telemetry

    jobmetrics.reset_for_test()
    tpu_telemetry.goodput.reset_for_test()
    # CPPROFILE (ISSUE 20): back-to-back tiers must not inherit a previous
    # tier's cause/scan aggregates or takeover rows either
    cpprofile.reset()

    fenced0 = rm.fenced_writes_total.value()
    mgr0.start(wait_for_leadership_timeout=10)
    mgr1.start(wait_for_leadership_timeout=10)
    standby_up = threading.Event()

    def run_standby():
        # the wait must outlast the whole tier up to the kill: bring-up,
        # steady state, and the storm all happen before mgr0 dies. A timeout
        # here does NOT stop the elector, so an early give-up leaves a live
        # elector that steals the lease at the first starved renew — exactly
        # the spurious-failover the tier must not inject itself.
        standby.start(
            wait_for_leadership_timeout=int(setup_budget + duration + 600)
        )
        standby_up.set()

    standby_thread = threading.Thread(target=run_standby, daemon=True)
    standby_thread.start()

    driver = cluster.client
    result = {"objects": args.objects, "composition": mix,
              "duration_s": round(duration, 1)}
    failures = []

    def create_persistent(obj, attempts=200):
        for _ in range(attempts):
            try:
                return driver.create(obj)
            except TooManyRequestsError:
                time.sleep(0.05)
        raise SystemExit(f"create never admitted: {obj.metadata.name}")

    def wait_for(fn, timeout, msg):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                if fn():
                    return
            except TooManyRequestsError:
                pass
            time.sleep(0.05)
        raise SystemExit(f"tier setup timeout: {msg}")

    engine = None
    try:
        # ------------------------------------------------------------------
        # population bring-up (feeds readiness-latency-p99)
        # ------------------------------------------------------------------
        for i in range(mix["cpu_notebooks"]):
            nb = Notebook()
            nb.metadata.name = f"cpu-{i}"
            nb.metadata.namespace = ns
            nb.spec.template.spec.containers = [
                Container(name=f"cpu-{i}", image="jupyter:1")
            ]
            create_persistent(nb)
        for i in range(mix["tpu_notebooks"]):
            nb = Notebook()
            nb.metadata.name = f"tpu-{i}"
            nb.metadata.namespace = ns
            nb.spec.template.spec.containers = [
                Container(name=f"tpu-{i}", image="jax:1")
            ]
            nb.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2")
            create_persistent(nb)
        for i in range(mix["endpoints"]):
            ep = InferenceEndpoint()
            ep.metadata.name = f"serve-{i}"
            ep.metadata.namespace = ns
            ep.spec.template.spec.containers = [
                Container(name=f"serve-{i}", image="s:1")
            ]
            ep.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2")
            ep.spec.serving = ServingSpec(max_batch_slots=8, max_queue_depth=64,
                                          max_seq=256, max_new_tokens=64)
            create_persistent(ep)

        wait_for(
            lambda: all(
                driver.get(Notebook, ns, f"cpu-{i}").status.ready_replicas >= 1
                for i in range(mix["cpu_notebooks"])
            ),
            setup_budget, "CPU notebooks Ready",
        )
        wait_for(
            lambda: all(
                (lambda got: got.status.tpu is not None and got.status.tpu.mesh_ready)(
                    driver.get(Notebook, ns, f"tpu-{i}")
                )
                for i in range(mix["tpu_notebooks"])
            ),
            setup_budget, "TPU notebooks mesh-ready",
        )
        wait_for(
            lambda: all(
                driver.get(InferenceEndpoint, ns, f"serve-{i}")
                .metadata.annotations.get(C.INFERENCE_STATE_ANNOTATION) == "serving"
                for i in range(mix["endpoints"])
            ),
            setup_budget, "endpoints Serving",
        )
        traceparent = driver.get(
            InferenceEndpoint, ns, "serve-0"
        ).metadata.annotations.get(C.TRACEPARENT_ANNOTATION)

        # ------------------------------------------------------------------
        # batch streams (feeds job-completion) + serving stream
        # ------------------------------------------------------------------
        batch = {"submitted": 0, "succeeded": 0, "failed": 0}
        batch_lock = threading.Lock()
        stop_jobs = threading.Event()

        def drive_jobs(stream: int):
            i = 0
            while not stop_jobs.is_set():
                with batch_lock:
                    if batch["submitted"] >= mix["jobs"]:
                        return
                    batch["submitted"] += 1
                name = f"batch-{stream}-{i}"
                job = TPUJob()
                job.metadata.name = name
                job.metadata.namespace = ns
                job.spec.template.spec.containers = [
                    Container(name=name, image="jax:1")
                ]
                job.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2")
                job.spec.steps = 30
                job.spec.checkpoint_period_s = 0.1
                create_persistent(job)
                deadline = time.monotonic() + 60
                state = ""
                while time.monotonic() < deadline and not stop_jobs.is_set():
                    try:
                        state = driver.get(
                            TPUJob, ns, name
                        ).metadata.annotations.get(C.JOB_STATE_ANNOTATION, "")
                    except TooManyRequestsError:
                        pass  # the storm sheds driver polls too; keep going
                    if state in ("succeeded", "failed"):
                        break
                    time.sleep(0.05)
                with batch_lock:
                    if state == "succeeded":
                        batch["succeeded"] += 1
                    elif state == "failed":
                        batch["failed"] += 1
                try:
                    driver.delete(TPUJob, ns, name)
                except (NotFoundError, TooManyRequestsError):
                    pass
                i += 1

        jobbers = [
            threading.Thread(target=drive_jobs, args=(s,), daemon=True)
            for s in range(JOB_STREAMS)
        ]
        for jobber in jobbers:
            jobber.start()

        cfg = TransformerConfig(
            vocab=512, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
            d_ff=128, max_seq=256, dtype=jnp.float32, use_flash=False,
            remat=False,
        )
        engine = ServingEngine(
            init_params(jax.random.PRNGKey(0), cfg), cfg,
            max_slots=8, max_seq=256, max_queue_depth=64, decode_burst=8,
        ).start()
        stream = {"submitted": 0, "rejected": 0, "handles": []}
        stop_stream = threading.Event()

        def drive_stream():
            rng = random.Random(0)
            period = 1.0 / max(0.1, args.qps)
            while not stop_stream.is_set():
                prompt = [rng.randrange(cfg.vocab) for _ in range(16)]
                try:
                    stream["handles"].append(engine.submit(
                        prompt, max_new=rng.choice((8, 16, 32)),
                        traceparent=traceparent,
                    ))
                    stream["submitted"] += 1
                except QueueFull:
                    stream["rejected"] += 1
                stop_stream.wait(period)

        streamer = threading.Thread(target=drive_stream, daemon=True)
        streamer.start()

        t_run = time.monotonic()
        deadline = t_run + duration
        time.sleep(min(duration * 0.2, 5.0))

        # ------------------------------------------------------------------
        # the injected TPUJob admission storm: every batch seat is held while
        # anonymous creates slam the level — queue-full sheds are guaranteed,
        # and they must land at batch and ONLY at batch
        # ------------------------------------------------------------------
        storm = {"attempted": 0, "admitted": [], "shed_creates": 0}
        seats = fc.summary()["batch"]["seats"]
        hogs = [fc.admit("tpu-job") for _ in range(seats)]
        exempt_before = fc.summary()["exempt"]["dispatched"]

        def storm_driver(t: int):
            for i in range(STORM_PER_THREAD):
                name = f"storm-{t}-{i}"
                job = TPUJob()
                job.metadata.name = name
                job.metadata.namespace = ns
                job.spec.template.spec.containers = [
                    Container(name=name, image="jax:1")
                ]
                job.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2")
                job.spec.steps = 60
                job.spec.checkpoint_period_s = 0.2
                storm["attempted"] += 1
                try:
                    driver.create(job)
                    storm["admitted"].append(name)
                except TooManyRequestsError:
                    storm["shed_creates"] += 1

        stormers = [
            threading.Thread(target=storm_driver, args=(t,), daemon=True)
            for t in range(STORM_THREADS)
        ]
        for s in stormers:
            s.start()
        time.sleep(0.8)  # the storm beats on a saturated level
        for h in hogs:
            h.release()
        for s in stormers:
            s.join(20)
        # storm jobs that made it through admission are withdrawn: the storm
        # is load, not workload — it must not consume the job budget
        for name in storm["admitted"]:
            try:
                driver.delete(TPUJob, ns, name)
            except (NotFoundError, TooManyRequestsError):
                pass

        s = fc.summary()
        storm_shed = s["batch"]["rejected"] + s["batch"]["timed_out"]
        if storm_shed <= 0:
            failures.append("storm was never shed at the batch level")
        if s["workload-high"]["rejected"] or s["workload-high"]["timed_out"]:
            failures.append("protected workload-high level shed during the storm")
        if s["exempt"]["rejected"] or s["exempt"]["timed_out"]:
            failures.append("exempt (lease) traffic shed during the storm")
        if s["exempt"]["dispatched"] <= exempt_before:
            failures.append("no exempt traffic flowed through the storm")

        # ------------------------------------------------------------------
        # kill the active shard-0 leader mid-tier
        # ------------------------------------------------------------------
        time.sleep(0.5)
        t_kill = time.monotonic()
        mgr0.stop()
        # the graceful stop drains services for a while, and the elector
        # keeps renewing until it is stopped partway through — so the lease
        # only starts aging out at (at latest) stop-return. The lease-bound
        # gate measures from there to the standby's is_leader flip (the
        # failover event itself); controller/service bring-up on the new
        # leader is real work but not lease arithmetic, reported separately.
        stop_s = time.monotonic() - t_kill
        lease_bound = lease + 2 * renew + 2.0
        acquire_deadline = time.monotonic() + lease + 4 * renew + 10.0
        while (not standby.elector.is_leader.is_set()
               and time.monotonic() < acquire_deadline):
            time.sleep(0.01)
        if not standby.elector.is_leader.is_set():
            failures.append("standby never took over shard 0")
            takeover_s = None
        else:
            takeover_s = time.monotonic() - t_kill
            # past the bound means the storm starved failover: the old lease
            # ages out (>= the lease duration past the last renew), then one standby
            # acquire tick lands
            if takeover_s - stop_s > lease_bound:
                failures.append(
                    f"takeover took {takeover_s - stop_s:.2f}s past leader "
                    f"death (bound {lease_bound:.2f}s)"
                )
        if not standby_up.wait(90.0):
            failures.append("standby controllers never came up after takeover")
        standby_ready_s = time.monotonic() - t_kill

        # ------------------------------------------------------------------
        # ride out the rest of the tier on the surviving managers
        # ------------------------------------------------------------------
        # steady state until the deadline, then a completion tail so the job
        # quota actually runs (the tier's object count is the point); a hard
        # cap keeps a wedged stream from hanging the lane
        hard_cap = deadline + max(90.0, duration)
        while time.monotonic() < hard_cap:
            with batch_lock:
                done = batch["succeeded"] + batch["failed"]
                quota_done = batch["submitted"] >= mix["jobs"] and done >= mix["jobs"]
            if quota_done and time.monotonic() >= deadline:
                break
            time.sleep(0.1)
        stop_jobs.set()
        stop_stream.set()
        streamer.join(timeout=5)
        for jobber in jobbers:
            jobber.join(timeout=70)
        engine.stop(drain_timeout_s=10.0)

        fenced_delta = rm.fenced_writes_total.value() - fenced0
        if fenced_delta:
            failures.append(
                f"{fenced_delta} fenced-off write(s): the dying leader kept "
                "writing past its lease"
            )
        if not standby.healthz():
            failures.append("surviving shard-0 manager unhealthy after takeover")
        if not mgr1.healthz():
            failures.append("shard-1 manager unhealthy at tier end")

        # ------------------------------------------------------------------
        # chip-time conservation gate (ISSUE 17): the SURVIVOR's accountant
        # kept the ledger through the storm and the takeover — summed phase
        # chip-seconds must equal physical chips x its accounted wall-clock
        # within 1%, and a classification pass over the final state must
        # attribute every TPU node exactly once (zero unattributed)
        # ------------------------------------------------------------------
        accounting_section = None
        acct = getattr(standby, "accountant", None)
        if acct is None:
            failures.append("surviving manager carries no chip accountant")
        else:
            acct.tick()  # close the ledger at tier end
            cons = acct.conservation()
            snap = acct.snapshot(limit=10)
            if snap["ticks"] < 1:
                failures.append("chip accountant never ticked on the survivor")
            if cons["residual_ratio"] > 0.01:
                failures.append(
                    f"chip-time conservation broken: attributed "
                    f"{cons['attributed_chip_seconds']:.1f} chip-s vs "
                    f"physical {cons['physical_chip_seconds']:.1f} chip-s "
                    f"(residual {cons['residual_ratio']:.2%} > 1%)"
                )
            attrs = acct.classify()
            counts = {}
            for a in attrs:
                counts[a.node] = counts.get(a.node, 0) + 1
            from odh_kubeflow_tpu.api.core import Node as _Node
            from odh_kubeflow_tpu.tpu import TPU_RESOURCE as _TPU
            tpu_nodes = {
                n.metadata.name for n in cluster.client.list(_Node)
                if int(n.status.capacity.get(_TPU, "0") or 0) > 0
            }
            unattributed = sorted(tpu_nodes - set(counts))
            doubled = sorted(n for n, c in counts.items() if c > 1)
            if unattributed:
                failures.append(
                    f"{len(unattributed)} TPU node(s) unattributed at tier "
                    f"end: {unattributed[:5]}"
                )
            if doubled:
                failures.append(
                    f"TPU node(s) double-attributed at tier end: {doubled[:5]}"
                )
            accounting_section = {
                "conservation": {
                    k: round(v, 4) for k, v in cons.items()
                },
                "ticks": snap["ticks"],
                "fleet_utilization": snap["fleet_utilization"],
                "by_phase": snap["chip_seconds"]["by_phase"],
                "by_class": snap["chip_seconds"]["by_class"],
                "unattributed_nodes": len(unattributed),
                "double_attributed_nodes": len(doubled),
            }

        # ------------------------------------------------------------------
        # control-plane profile (ISSUE 20): when the tier runs CPPROFILE=1
        # (the ci/loadtest.sh default) the report carries the per-controller
        # cause/scan breakdown — why each controller's reconciles fired and
        # how many cached objects they walked — and the kill lane's takeover
        # is decomposed into its five phases from the SURVIVOR's tracker
        # ------------------------------------------------------------------
        cpprofile_section = None
        if cpprofile.enabled():
            cp = cpprofile.snapshot(limit=0)  # aggregates, not sample rows
            if not cp["controllers"]:
                failures.append(
                    "CPPROFILE armed but no reconcile causes recorded"
                )
            survivor_takeover = None
            for t in cp["takeovers"]:
                if (t.get("complete")
                        and t.get("manager") == standby.elector.identity):
                    survivor_takeover = t
            if takeover_s is not None and survivor_takeover is None:
                failures.append(
                    "CPPROFILE armed but the survivor's takeover was never "
                    "decomposed into phases"
                )
            cpprofile_section = {
                "controllers": {
                    name: {
                        "reconciles": s["reconciles"],
                        "causes": s["causes"],
                        "origins": s["origins"],
                        "scan_calls": s["scan_calls"],
                        "scanned": s["scanned"],
                        "used": s["used"],
                        "scans_per_reconcile": s["scans_per_reconcile"],
                    }
                    for name, s in cp["controllers"].items()
                },
                "sweeps": cp["sweeps"],
                "survivor_takeover": survivor_takeover,
            }

        # ------------------------------------------------------------------
        # the verdict comes from the SURVIVOR's judgement layer
        # ------------------------------------------------------------------
        statuses = standby.slo_engine.evaluate()
        alerts = standby.alert_manager.status()
        all_firing = sorted(
            a.get("rule", a.get("name", "?")) for a in alerts.get("firing", [])
        )
        firing = [
            name for name in all_firing
            if any(name.startswith(slo) for slo in GATED_SLOS)
        ]
        gates = {}
        ok = True
        for name in GATED_SLOS:
            st = statuses.get(name, {})
            compliance = st.get("compliance")
            objective = st.get("objective")
            passed = (
                compliance is not None and objective is not None
                and compliance >= objective
            )
            gates[name] = {
                "compliance": compliance,
                "objective": objective,
                "events": st.get("events"),
                "passed": passed,
            }
            ok = ok and passed
        ok = ok and not firing and not failures

        summary = fc.summary()
        result.update({
            "jobs_submitted": batch["submitted"],
            "jobs_succeeded": batch["succeeded"],
            "jobs_failed": batch["failed"],
            "requests_submitted": stream["submitted"],
            "requests_rejected": stream["rejected"],
            "requests_ok": sum(1 for h in stream["handles"] if h.result == "ok"),
            "storm": {
                "attempted": storm["attempted"],
                "admitted_then_withdrawn": len(storm["admitted"]),
                "driver_visible_sheds": storm["shed_creates"],
                "batch_level_sheds": storm_shed,
            },
            "takeover_s": round(takeover_s, 3) if takeover_s else None,
            "leader_stop_s": round(stop_s, 3),
            "takeover_past_leader_death_s": (
                round(takeover_s - stop_s, 3) if takeover_s else None
            ),
            "takeover_bound_s": round(lease_bound, 2),
            "standby_controllers_up_s": round(standby_ready_s, 3),
            "fenced_writes": fenced_delta,
            # the control-plane section: shed/queued/p99 wait per level
            "flowcontrol": {
                level: {
                    "dispatched": stats["dispatched"],
                    "shed": stats["rejected"] + stats["timed_out"],
                    "queued": stats["queued"],
                    "p99_wait_s": stats["p99_wait_s"],
                }
                for level, stats in summary.items()
            },
            "accounting": accounting_section,
            "cpprofile": cpprofile_section,
            "slo_gates": gates,
            "alerts_firing_gated": list(firing),
            "alerts_firing_all": list(all_firing),
            "control_plane_failures": list(failures),
            "passed": bool(ok),
        })
    finally:
        stop = getattr(standby, "stop", None)
        if stop:
            standby.stop()
        mgr1.stop()
        try:
            mgr0.stop()  # idempotent; killed mid-tier on the happy path
        except Exception:
            pass
        cluster.stop()
    print(json.dumps(result, indent=2))
    if not result.get("passed"):
        raise SystemExit(1)


def run_fleet(args) -> None:
    """The multi-replica serving tier (ISSUE 16). Exit status is the SLO
    verdict; "zero dropped in-flight requests" is a hard gate — every
    routed request must end `ok` or be a client-visible 429 shed, never
    vanish."""
    import jax
    import jax.numpy as jnp

    from odh_kubeflow_tpu.api.core import ConfigMap, Container, Pod
    from odh_kubeflow_tpu.api.inference import (
        AutoscalingSpec,
        InferenceEndpoint,
        ServingSpec,
    )
    from odh_kubeflow_tpu.api.job import TPUJob
    from odh_kubeflow_tpu.api.notebook import TPUSpec
    from odh_kubeflow_tpu.apimachinery import (
        NotFoundError,
        TooManyRequestsError,
    )
    from odh_kubeflow_tpu.cluster import SimCluster
    from odh_kubeflow_tpu.cluster.faults import seeded_router_bad_day
    from odh_kubeflow_tpu.cluster.flowcontrol import FlowController
    from odh_kubeflow_tpu.controllers import Config, constants as C
    from odh_kubeflow_tpu.controllers.inference import (
        endpoint_desired_replicas,
    )
    from odh_kubeflow_tpu.main import build_manager
    from odh_kubeflow_tpu.models import TransformerConfig, init_params
    from odh_kubeflow_tpu.probe import sim_agent_behavior
    from odh_kubeflow_tpu.runtime.autoscaler import ReplicaAutoscaler
    from odh_kubeflow_tpu.serving.engine import QueueFull, ServingEngine
    from odh_kubeflow_tpu.serving.router import TokenRouter

    ns = args.namespace
    name = "fleet"
    duration = args.duration or 25.0
    setup_budget = 120.0

    cluster = SimCluster().start()
    fc = FlowController()  # the default layout includes the serving level
    cluster.store.flowcontrol = fc
    cluster.add_tpu_pool("fleet", "v5e", "2x2", slices=6)
    agents = {}
    cluster.add_pod_behavior(sim_agent_behavior(agents, duty=0.9))

    config = Config(
        enable_culling=False,
        suspend_enabled=True,
        readiness_probe_period_s=0.15,
        serving_loading_window_s=10.0,
        serving_drain_timeout_s=0.5,
        slo_enabled=True,
        slo_window_scale=max(1e-4, duration / 600.0),
        # the router knobs ride the ENV_CONTRACT like every other knob; the
        # tier consumes them from the same Config the manager runs on
        router_eject_failures=3,
        router_hedge_after_s=0.5,
    )
    mgr = build_manager(cluster.store, config, http_get=cluster.http_get)
    mgr.start()

    driver = cluster.client
    result = {"tier": "fleet", "duration_s": round(duration, 1)}
    failures = []

    def wait_for(fn, timeout, msg):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                if fn():
                    return time.monotonic()
            except TooManyRequestsError:
                pass
            time.sleep(0.05)
        raise SystemExit(f"fleet tier timeout: {msg}")

    def get_ep():
        return driver.get(InferenceEndpoint, ns, name)

    def serving_replicas():
        try:
            return get_ep().status.serving_replicas
        except TooManyRequestsError:
            return -1

    def replica_nodes_map():
        out = {}
        for pod in driver.list(Pod, namespace=ns):
            labels = pod.metadata.labels
            if labels.get(C.INFERENCE_NAME_LABEL) != name:
                continue
            if not pod.spec.node_name:
                continue
            idx = int(labels.get(C.INFERENCE_REPLICA_LABEL, "0"))
            out.setdefault(idx, []).append(pod.spec.node_name)
        return out

    cfg = TransformerConfig(
        vocab=256, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=64, max_seq=128, dtype=jnp.float32, use_flash=False,
        remat=False,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)

    def mk_engine():
        return ServingEngine(
            params, cfg, max_slots=4, max_seq=128, max_queue_depth=32,
            decode_burst=8,
        ).start()

    class SlowEngine:
        """The bad-day plan's slow replica, applied at the engine boundary:
        every handoff pays the seeded latency factor, so the router's
        TTFT-tail scoring and hedging must route around it."""

        def __init__(self, engine, delay_s):
            self.engine = engine
            self.delay_s = delay_s

        def submit(self, prompt, max_new, traceparent=None):
            time.sleep(self.delay_s)
            return self.engine.submit(prompt, max_new, traceparent)

        def stats(self):
            return self.engine.stats()

        def cancel(self, handle):
            return self.engine.cancel(handle)

    engines = {}
    stream = {"ok": 0, "shed": 0, "dropped": 0, "hedged": 0, "retried": 0}
    stream_lock = threading.Lock()
    errors = []
    stop_stream = threading.Event()
    pace = threading.Semaphore(0)

    try:
        # ------------------------------------------------------------------
        # fleet bring-up: replicas=2, autoscaling 1..3
        # ------------------------------------------------------------------
        ep = InferenceEndpoint()
        ep.metadata.name = name
        ep.metadata.namespace = ns
        ep.spec.template.spec.containers = [
            Container(name=name, image="serve:1")
        ]
        ep.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2")
        ep.spec.serving = ServingSpec(
            max_batch_slots=4, max_queue_depth=32, max_seq=128,
            max_new_tokens=16, replicas=2,
            autoscaling=AutoscalingSpec(min_replicas=1, max_replicas=3),
        )
        driver.create(ep)
        wait_for(
            lambda: get_ep().metadata.annotations.get(
                C.INFERENCE_STATE_ANNOTATION) == "serving"
            and serving_replicas() >= 2,
            setup_budget, "fleet Serving at 2 replicas",
        )

        router = TokenRouter(
            endpoint=f"{ns}/{name}",
            flow_controller=fc,
            breaker_failure_threshold=config.router_eject_failures,
            hedge_after_s=config.router_hedge_after_s,
        )
        for idx in (0, 1):
            engines[idx] = mk_engine()
            router.add_replica(idx, engines[idx])

        # ------------------------------------------------------------------
        # open-loop stream through the router (feeds token-latency +
        # serving-availability) + background batch/default traffic that must
        # NEVER be starved by it
        # ------------------------------------------------------------------
        def request_worker(widx):
            rng = random.Random(1000 + widx)
            while True:
                pace.acquire()
                if stop_stream.is_set():
                    return
                prompt = [rng.randrange(cfg.vocab) for _ in range(8)]
                try:
                    res = router.generate(
                        prompt, max_new=rng.choice((8, 12, 16)),
                        wait_timeout_s=30.0,
                    )
                    with stream_lock:
                        if res.handle.result == "ok":
                            stream["ok"] += 1
                        else:
                            stream["dropped"] += 1
                        if res.hedged:
                            stream["hedged"] += 1
                        if res.retries:
                            stream["retried"] += 1
                except QueueFull:
                    with stream_lock:
                        stream["shed"] += 1
                except Exception as e:  # a vanished request is a DROP
                    with stream_lock:
                        stream["dropped"] += 1
                        errors.append(repr(e))

        workers = [
            threading.Thread(target=request_worker, args=(w,), daemon=True)
            for w in range(12)
        ]
        for w in workers:
            w.start()

        def pacer():
            period = 1.0 / max(0.1, args.qps)
            while not stop_stream.is_set():
                pace.release()
                stop_stream.wait(period)

        pacer_thread = threading.Thread(target=pacer, daemon=True)

        fair = {"batch": 0, "default": 0}
        stop_fair = threading.Event()

        def fairness_driver():
            # anonymous read probes classified by KIND: TPUJob -> the batch
            # level, ConfigMap -> default. A NotFound is a successful probe
            # (admission happened); a 429 surfaces in the level's shed
            # counters, which the starvation gate below reads.
            while not stop_fair.is_set():
                for kind, level in ((TPUJob, "batch"), (ConfigMap, "default")):
                    try:
                        driver.get(kind, ns, "fairness-probe")
                        fair[level] += 1
                    except NotFoundError:
                        fair[level] += 1
                    except TooManyRequestsError:
                        pass
                stop_fair.wait(0.05)

        fair_before = {
            level: fc.summary()[level]["rejected"]
            + fc.summary()[level]["timed_out"]
            for level in ("batch", "default")
        }
        fairness_thread = threading.Thread(target=fairness_driver,
                                           daemon=True)
        fairness_thread.start()
        pacer_thread.start()

        t_run = time.monotonic()
        deadline = t_run + duration
        time.sleep(duration * 0.25)

        # ------------------------------------------------------------------
        # the seeded router bad day: one whole replica gang preempted
        # mid-stream, one survivor slowed, probe flaps, the control-plane
        # schedule — then the fleet must return to strength through the
        # repair/warm-pool paths with zero dropped in-flight requests
        # ------------------------------------------------------------------
        plan = seeded_router_bad_day(
            cluster, seed=args.seed, replica_nodes=replica_nodes_map(),
            grace_s=0.5,
        )
        victim = plan["killed_replica"]
        slow = plan["slow_replica"]
        if slow is not None:
            router.add_replica(
                slow,
                SlowEngine(engines[slow],
                           delay_s=0.01 * plan["slow_factor"]),
            )
        # the victim replica leaves rotation FIRST (route-first, exactly the
        # drain ordering the controller uses), then its engine dies with a
        # bounded drain — in-flight work completes or comes back `canceled`,
        # and canceled is retried on a different replica by the router
        router.remove_replica(victim)
        victim_engine = engines.pop(victim)
        threading.Thread(
            target=lambda: victim_engine.stop(drain_timeout_s=8.0),
            daemon=True,
        ).start()

        t_killed = time.monotonic()
        replaced_at = wait_for(
            lambda: serving_replicas() >= 2,
            setup_budget, "killed replica re-placed",
        )
        result["replica_replace_s"] = round(replaced_at - t_killed, 2)
        engines[victim] = mk_engine()
        router.add_replica(victim, engines[victim])

        # ------------------------------------------------------------------
        # one forced autoscale-up through the REAL decision path: a hot
        # signal pushed through ReplicaAutoscaler.tick() writes the
        # desired-replicas annotation; the controller's scale-up is a warm
        # bind from the pool
        # ------------------------------------------------------------------
        scaler = ReplicaAutoscaler(
            mgr, period_s=9999.0,
            signals_fn=lambda _ep: {"burn_rate": 10.0, "queue_depth": 99.0,
                                    "slot_occupancy": 1.0},
        )
        before_up = endpoint_desired_replicas(get_ep())
        t_scale = time.monotonic()
        # the bad day's throttle rules can 429 any single annotation
        # patch; the real autoscaler just retries next period, so the
        # forced decision ticks until the write lands (bounded)
        after_up = before_up
        tick_deadline = time.monotonic() + 15.0
        while time.monotonic() < tick_deadline:
            scaler.tick()
            try:
                after_up = endpoint_desired_replicas(get_ep())
            except TooManyRequestsError:
                after_up = before_up
            if after_up > before_up:
                break
            time.sleep(0.1)
        if after_up != before_up + 1:
            failures.append(
                f"forced autoscale-up did not move desired replicas "
                f"({before_up} -> {after_up})"
            )
        scaled_at = wait_for(
            lambda: serving_replicas() >= after_up,
            setup_budget, "autoscale-up replica Serving",
        )
        result["scale_up_reaction_s"] = round(scaled_at - t_scale, 2)
        new_idx = max(
            set(range(after_up)) - set(router.replicas()),
            default=after_up - 1,
        )
        engines[new_idx] = mk_engine()
        router.add_replica(new_idx, engines[new_idx])

        # ------------------------------------------------------------------
        # ride out the rest of the tier, then drain the stream
        # ------------------------------------------------------------------
        while time.monotonic() < deadline:
            time.sleep(0.1)
        stop_stream.set()
        for _ in workers:
            pace.release()
        pacer_thread.join(timeout=5)
        for w in workers:
            w.join(timeout=45)
        stop_fair.set()
        fairness_thread.join(timeout=5)
        for engine in engines.values():
            engine.stop(drain_timeout_s=10.0)

        # ------------------------------------------------------------------
        # gates: zero drops, fairness, the SLO verdict
        # ------------------------------------------------------------------
        if stream["dropped"]:
            failures.append(
                f"{stream['dropped']} in-flight request(s) dropped: "
                f"{errors[:3]}"
            )
        if not stream["ok"]:
            failures.append("no request ever completed through the router")
        summary = fc.summary()
        for level in ("batch", "default"):
            shed = (summary[level]["rejected"] + summary[level]["timed_out"]
                    - fair_before[level])
            if shed:
                failures.append(
                    f"{level} level shed {shed} request(s) under router "
                    "traffic"
                )
        if not fair["batch"] or not fair["default"]:
            failures.append("background batch/default traffic never flowed")
        if summary["serving"]["dispatched"] <= 0:
            failures.append("router traffic never rode the serving level")

        statuses = mgr.slo_engine.evaluate()
        alerts = mgr.alert_manager.status()
        all_firing = sorted(
            a.get("rule", a.get("name", "?"))
            for a in alerts.get("firing", [])
        )
        firing = [
            n for n in all_firing
            if any(n.startswith(slo) for slo in FLEET_GATED_SLOS)
        ]
        gates = {}
        ok = True
        for slo_name in FLEET_GATED_SLOS:
            st = statuses.get(slo_name, {})
            compliance = st.get("compliance")
            objective = st.get("objective")
            passed = (
                compliance is not None and objective is not None
                and compliance >= objective
            )
            gates[slo_name] = {
                "compliance": compliance,
                "objective": objective,
                "events": st.get("events"),
                "passed": passed,
            }
            ok = ok and passed
        ok = ok and not firing and not failures

        result.update({
            "bad_day_plan": plan,
            "requests": dict(stream),
            "fairness_probes": dict(fair),
            "flowcontrol": {
                level: {
                    "dispatched": stats["dispatched"],
                    "shed": stats["rejected"] + stats["timed_out"],
                    "queued": stats["queued"],
                    "p99_wait_s": stats["p99_wait_s"],
                }
                for level, stats in summary.items()
            },
            "slo_gates": gates,
            "alerts_firing_gated": list(firing),
            "alerts_firing_all": list(all_firing),
            "failures": list(failures),
            "passed": bool(ok),
        })
    finally:
        stop_stream.set()
        for _ in range(64):
            pace.release()
        for engine in engines.values():
            try:
                engine.stop()
            except Exception:
                pass
        mgr.stop()
        cluster.stop()
    print(json.dumps(result, indent=2))
    if not result.get("passed"):
        raise SystemExit(1)


def main() -> None:
    # deployment-surface guard (ISSUE 14): the tier always runs armed
    # (DEPLOYGUARD=0 opts out) — a shed-path or standby-takeover write that
    # escapes its declared flow/RBAC surface (a lease write misattributed
    # onto a workload flow after the shard failover, say) is a hard
    # RBACDriftError at the call, not a silent fairness leak
    os.environ.setdefault("DEPLOYGUARD", "1")
    # control-plane profiler (ISSUE 20): the tier always runs armed
    # (CPPROFILE=0 opts out) — the report gains the per-controller
    # cause/scan breakdown and the kill lane's takeover decomposition
    os.environ.setdefault("CPPROFILE", "1")
    ap = argparse.ArgumentParser()
    ap.add_argument("--tier", default="mixed", choices=("mixed", "fleet"),
                    help="mixed: the 200/500-object control-plane tier; "
                         "fleet: the multi-replica serving tier (ISSUE 16)")
    ap.add_argument("--objects", type=int, default=200, choices=(200, 500),
                    help="mixed-tier size: 200 (CI lane) or 500 (slow tier)")
    ap.add_argument("--duration", type=float, default=0.0,
                    help="steady-state seconds after bring-up "
                         "(0 = scale with the tier)")
    ap.add_argument("--qps", type=float, default=12.0)
    ap.add_argument("--seed", type=int, default=16,
                    help="fleet tier: the seeded_router_bad_day seed")
    ap.add_argument("--namespace", default="tiers")
    args = ap.parse_args()
    if args.tier == "fleet":
        if args.qps == 12.0:
            args.qps = 8.0  # the fleet default: open-loop but sustainable
        run_fleet(args)
    else:
        run(args)


if __name__ == "__main__":
    main()
