"""Loadtest: template N Notebook(+PVC) CRs and measure controller behavior.

The reference's loadtest (notebook-controller/loadtest/start_notebooks.py:51-96)
templates N Notebook+PVC pairs and kubectl-applies them at a live cluster. This
harness does the same against the in-process cluster — so it actually measures
(create storm -> all slices mesh-ready, p50/p95/max) — or, with --emit, prints
the templated CRs as YAML for kubectl against a real cluster.

  python loadtest/start_notebooks.py --notebooks 50
  python loadtest/start_notebooks.py --notebooks 20 --accelerator v5p --topology 2x2x4
  python loadtest/start_notebooks.py --notebooks 3 --emit | kubectl apply -f -
"""
from __future__ import annotations

import argparse
import json
import math
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def template_notebook(name: str, namespace: str, accelerator: str, topology: str,
                      image: str, pvc: bool):
    docs = []
    if pvc:
        docs.append(
            {
                "apiVersion": "v1",
                "kind": "PersistentVolumeClaim",
                "metadata": {"name": f"{name}-volume", "namespace": namespace},
                "spec": {
                    "accessModes": ["ReadWriteOnce"],
                    "resources": {"requests": {"storage": "10Gi"}},
                },
            }
        )
    spec = {
        "template": {
            "spec": {
                "containers": [
                    {
                        "name": name,
                        "image": image,
                        "volumeMounts": (
                            [{"name": "workspace", "mountPath": "/home/jovyan"}]
                            if pvc
                            else []
                        ),
                    }
                ],
                "volumes": (
                    [
                        {
                            "name": "workspace",
                            "persistentVolumeClaim": {"claimName": f"{name}-volume"},
                        }
                    ]
                    if pvc
                    else []
                ),
            }
        }
    }
    if accelerator:
        spec["tpu"] = {"accelerator": accelerator, "topology": topology}
    docs.append(
        {
            "apiVersion": "kubeflow.org/v1beta1",
            "kind": "Notebook",
            "metadata": {"name": name, "namespace": namespace},
            "spec": spec,
        }
    )
    return docs


def emit(args) -> None:
    import yaml

    docs = []
    for i in range(args.notebooks):
        docs += template_notebook(
            f"{args.prefix}{i}", args.namespace, args.accelerator, args.topology,
            args.image, pvc=not args.no_pvc,
        )
    for d in docs:
        sys.stdout.write("---\n")
        yaml.safe_dump(d, sys.stdout, sort_keys=False)


def run_sim(args) -> None:
    from odh_kubeflow_tpu.api.notebook import Notebook
    from odh_kubeflow_tpu.apimachinery import default_scheme
    from odh_kubeflow_tpu.cluster import SimCluster
    from odh_kubeflow_tpu.controllers import Config
    from odh_kubeflow_tpu.main import build_manager
    from odh_kubeflow_tpu.probe import sim_agent_behavior
    from odh_kubeflow_tpu.tpu import plan_slice

    cluster = SimCluster().start()
    agents = {}
    cluster.add_pod_behavior(sim_agent_behavior(agents, duty=0.9))
    if args.accelerator:
        shape = plan_slice(args.accelerator, topology=args.topology)
        cluster.add_tpu_pool(
            "load", args.accelerator, args.topology, slices=args.notebooks
        )
        chips_per_nb = shape.chips
    else:
        cluster.add_cpu_pool("load", nodes=max(1, args.notebooks // 8))
        chips_per_nb = 0

    teardown = []
    watch_store = None
    if args.remote:
        try:
            store, client, watch_store = _remote_stack(
                cluster, Config(), teardown, qps=args.qps, burst=args.burst
            )
        except Exception:
            # partial stacks must still tear down (a started TLS server
            # would otherwise outlive the failure)
            for fn in reversed(teardown):
                fn()
            cluster.stop()
            raise
        mgr = build_manager(store, Config(), http_get=cluster.http_get)
    else:
        mgr = build_manager(cluster.store, Config(), http_get=cluster.http_get)
        client = cluster.client
    mgr.start()
    t0 = {}
    admission_s = {}
    phases = {}  # name -> {phase: t_since_create}

    def observe(name: str, status: dict) -> bool:
        """Update phase milestones (first-seen, relative to CR create) from a
        status dict; True once the notebook is ready. Milestones: status
        populated -> core reconciler processed the CR; pods Ready -> kubelet
        ran every host; devices -> probe agents report chips; mesh_ready ->
        the device-visibility readiness gate is green."""
        now = time.monotonic() - t0[name]
        ph = phases.setdefault(name, {})
        # wire-shape (Go json tag) field names: this consumes raw API JSON
        tpu = status.get("tpu") or {}
        ready_replicas = status.get("readyReplicas", 0)
        if (tpu or ready_replicas) and "reconciled" not in ph:
            ph["reconciled"] = now
        # only stamp pods_ready once the slice size is PUBLISHED (tpu.hosts)
        # — defaulting to 1 would record multi-host slices ~N-1 pods early
        hosts = tpu.get("hosts", 0) if args.accelerator else 1
        if hosts and ready_replicas >= hosts and "pods_ready" not in ph:
            ph["pods_ready"] = now
        if args.accelerator and tpu.get("chipsVisible") and \
                "devices_visible" not in ph:
            ph["devices_visible"] = now
        ready = tpu.get("meshReady", False) if args.accelerator \
            else ready_replicas >= 1
        if ready and "mesh_ready" not in ph:
            ph["mesh_ready"] = now
        return bool(ready)

    watcher = None
    try:
        if watch_store is not None:
            # watch-driven readiness: the old tight polling loop issued ~25
            # unthrottled GET sweeps per 100 ms against the same apiserver
            # the manager talks to — the load GENERATOR was the biggest
            # single consumer of server capacity. One watch stream is how
            # kubectl wait does it, and costs the server one event fan-out.
            watcher = watch_store.watch(
                "kubeflow.org/v1beta1", "Notebook", namespace=args.namespace
            )
        created = time.monotonic()
        for i in range(args.notebooks):
            name = f"{args.prefix}{i}"
            for doc in template_notebook(
                name, args.namespace, args.accelerator, args.topology, args.image,
                pvc=not args.no_pvc,
            ):
                t_call = time.monotonic()
                t0[name] = t_call
                client.create(default_scheme.decode(doc))
                if doc["kind"] == "Notebook":
                    # CREATE round-trip = apiserver + admission webhook chain
                    admission_s[name] = time.monotonic() - t_call
        storm_s = time.monotonic() - created

        latencies = {}
        deadline = time.monotonic() + args.timeout
        pending = {f"{args.prefix}{i}" for i in range(args.notebooks)}
        while pending and time.monotonic() < deadline:
            if watcher is not None:
                ev = watcher.get(timeout=0.25)
                if ev is None:
                    continue
                name = ev.object.get("metadata", {}).get("name", "")
                if name not in pending:
                    continue
                if observe(name, ev.object.get("status", {}) or {}):
                    latencies[name] = phases[name]["mesh_ready"]
                    pending.discard(name)
            else:
                for name in list(pending):
                    nb = client.get(Notebook, args.namespace, name)
                    if observe(name, nb.status.to_dict()):
                        latencies[name] = phases[name]["mesh_ready"]
                        pending.discard(name)
                time.sleep(0.005)
    finally:
        if watcher is not None:
            watcher.stop()
        mgr.stop()
        for fn in reversed(teardown):
            fn()
        cluster.stop()

    def p50(xs):
        xs = [x for x in xs if x is not None]
        return round(statistics.median(xs), 4) if xs else None

    phase_p50 = {
        "admission_s": p50(list(admission_s.values())),
        "reconciled_s": p50([ph.get("reconciled") for ph in phases.values()]),
        "pods_ready_s": p50([ph.get("pods_ready") for ph in phases.values()]),
        "devices_visible_s": p50(
            [ph.get("devices_visible") for ph in phases.values()]
        ),
        "mesh_ready_s": p50([ph.get("mesh_ready") for ph in phases.values()]),
    }

    vals = sorted(latencies.values())
    result = {
        "transport": "remote (wire protocol, TLS)" if args.remote else "in-process",
        "notebooks": args.notebooks,
        "ready": len(vals),
        "timed_out": args.notebooks - len(vals),
        "create_storm_s": round(storm_s, 4),
        "chips_bound": chips_per_nb * len(vals),
        "ready_p50_s": round(statistics.median(vals), 4) if vals else None,
        "ready_p95_s": (
            round(vals[min(len(vals) - 1, math.ceil(0.95 * (len(vals) - 1)))], 4)
            if vals
            else None
        ),
        "ready_max_s": round(vals[-1], 4) if vals else None,
        # per-phase p50s (first-seen relative to CR create): where the
        # latency actually goes — admission round-trip, core reconcile (STS
        # up), kubelet (pods Ready), probe agents (devices visible), and
        # the device-visibility readiness gate
        "phase_p50": phase_p50,
    }
    if args.remote and getattr(store, "throttle", None) is not None:
        # client-side QPS/burst limiter (cluster/remote.py _TokenBucket):
        # how often the storm actually hit the rate limit
        result["client_throttle"] = {
            "qps": store.throttle.qps,
            "burst": int(store.throttle.burst),
            "throttled_requests": store.throttle.waits,
            "throttle_wait_s": round(store.throttle.waited_s, 3),
        }
    print(json.dumps(result))
    if result["timed_out"]:
        raise SystemExit(1)


def _remote_stack(cluster, config, teardown, qps=100.0, burst=200):
    """The shared wire-protocol stack (cluster/remote_fixture.py): TLS
    apiserver + HTTPS admission webhook around the sim's store."""
    from odh_kubeflow_tpu.cluster import Client, RemoteStore
    from odh_kubeflow_tpu.cluster.remote_fixture import build_remote_stack

    api, store, _ = build_remote_stack(
        cluster.store, config, teardown, token="loadtest", qps=qps, burst=burst
    )
    # the load GENERATOR polls readiness in a tight loop; give it its own
    # unthrottled client so the driver's polling doesn't eat the manager's
    # QPS budget (two clients = two rate limiters, as in a real cluster)
    poller = RemoteStore(api.base_url, token="loadtest", ca_file=store.ca_file, qps=0)
    return store, Client(poller), poller


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--notebooks", type=int, default=3)  # reference default
    ap.add_argument("--namespace", default="loadtest")
    ap.add_argument("--prefix", default="loadtest-nb-")
    ap.add_argument("--image", default="jupyter-tpu:latest")
    ap.add_argument("--accelerator", default="v5e")
    ap.add_argument("--topology", default="2x2")
    ap.add_argument("--no-pvc", action="store_true")
    ap.add_argument("--timeout", type=float, default=120.0)
    ap.add_argument("--emit", action="store_true", help="print CR YAML and exit")
    ap.add_argument(
        "--remote",
        action="store_true",
        help="run the manager over the wire-protocol apiserver (TLS + webhook)",
    )
    # reference notebook-controller/main.go:65-85 --qps/--burst analog.
    # Defaults are a production-scale setting (client-go's 20/30 measurably
    # serializes the readiness-probe polling at storm scale — the stats block
    # in the output shows how often the limiter engaged either way)
    ap.add_argument("--qps", type=float, default=100.0,
                    help="manager client QPS limit (0 = unthrottled)")
    ap.add_argument("--burst", type=int, default=200,
                    help="manager client burst size")
    args = ap.parse_args()
    if args.accelerator in ("", "none", "cpu"):
        args.accelerator = ""
    try:
        if args.emit:
            emit(args)
        else:
            run_sim(args)
    except BrokenPipeError:  # `--emit | head` etc.
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())


if __name__ == "__main__":
    main()
