"""Loadtest: template N Notebook(+PVC) CRs and measure controller behavior.

The reference's loadtest (notebook-controller/loadtest/start_notebooks.py:51-96)
templates N Notebook+PVC pairs and kubectl-applies them at a live cluster. This
harness does the same against the in-process cluster — so it actually measures
(create storm -> all slices mesh-ready, p50/p95/max) — or, with --emit, prints
the templated CRs as YAML for kubectl against a real cluster.

  python loadtest/start_notebooks.py --notebooks 50
  python loadtest/start_notebooks.py --notebooks 20 --accelerator v5p --topology 2x2x4
  python loadtest/start_notebooks.py --notebooks 3 --emit | kubectl apply -f -
"""
from __future__ import annotations

import argparse
import json
import math
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def template_notebook(name: str, namespace: str, accelerator: str, topology: str,
                      image: str, pvc: bool):
    docs = []
    if pvc:
        docs.append(
            {
                "apiVersion": "v1",
                "kind": "PersistentVolumeClaim",
                "metadata": {"name": f"{name}-volume", "namespace": namespace},
                "spec": {
                    "accessModes": ["ReadWriteOnce"],
                    "resources": {"requests": {"storage": "10Gi"}},
                },
            }
        )
    spec = {
        "template": {
            "spec": {
                "containers": [
                    {
                        "name": name,
                        "image": image,
                        "volumeMounts": (
                            [{"name": "workspace", "mountPath": "/home/jovyan"}]
                            if pvc
                            else []
                        ),
                    }
                ],
                "volumes": (
                    [
                        {
                            "name": "workspace",
                            "persistentVolumeClaim": {"claimName": f"{name}-volume"},
                        }
                    ]
                    if pvc
                    else []
                ),
            }
        }
    }
    if accelerator:
        spec["tpu"] = {"accelerator": accelerator, "topology": topology}
    docs.append(
        {
            "apiVersion": "kubeflow.org/v1beta1",
            "kind": "Notebook",
            "metadata": {"name": name, "namespace": namespace},
            "spec": spec,
        }
    )
    return docs


def emit(args) -> None:
    import yaml

    docs = []
    for i in range(args.notebooks):
        docs += template_notebook(
            f"{args.prefix}{i}", args.namespace, args.accelerator, args.topology,
            args.image, pvc=not args.no_pvc,
        )
    for d in docs:
        sys.stdout.write("---\n")
        yaml.safe_dump(d, sys.stdout, sort_keys=False)


def run_sim(args) -> None:
    from odh_kubeflow_tpu.api.notebook import Notebook
    from odh_kubeflow_tpu.apimachinery import default_scheme
    from odh_kubeflow_tpu.cluster import SimCluster
    from odh_kubeflow_tpu.controllers import Config
    from odh_kubeflow_tpu.main import build_manager
    from odh_kubeflow_tpu.probe import sim_agent_behavior
    from odh_kubeflow_tpu.tpu import plan_slice

    cluster = SimCluster().start()
    agents = {}
    cluster.add_pod_behavior(sim_agent_behavior(agents, duty=0.9))
    if args.accelerator:
        shape = plan_slice(args.accelerator, topology=args.topology)
        cluster.add_tpu_pool(
            "load", args.accelerator, args.topology, slices=args.notebooks
        )
        chips_per_nb = shape.chips
    else:
        cluster.add_cpu_pool("load", nodes=max(1, args.notebooks // 8))
        chips_per_nb = 0

    teardown = []
    if args.remote:
        try:
            store, client = _remote_stack(
                cluster, Config(), teardown, qps=args.qps, burst=args.burst
            )
        except Exception:
            # partial stacks must still tear down (a started TLS server
            # would otherwise outlive the failure)
            for fn in reversed(teardown):
                fn()
            cluster.stop()
            raise
        mgr = build_manager(store, Config(), http_get=cluster.http_get)
    else:
        mgr = build_manager(cluster.store, Config(), http_get=cluster.http_get)
        client = cluster.client
    mgr.start()
    t0 = {}
    try:
        created = time.monotonic()
        for i in range(args.notebooks):
            name = f"{args.prefix}{i}"
            for doc in template_notebook(
                name, args.namespace, args.accelerator, args.topology, args.image,
                pvc=not args.no_pvc,
            ):
                t0[name] = time.monotonic()
                client.create(default_scheme.decode(doc))
        storm_s = time.monotonic() - created

        latencies = {}
        deadline = time.monotonic() + args.timeout
        pending = {f"{args.prefix}{i}" for i in range(args.notebooks)}
        while pending and time.monotonic() < deadline:
            for name in list(pending):
                nb = client.get(Notebook, args.namespace, name)
                ready = (
                    nb.status.tpu.mesh_ready
                    if (args.accelerator and nb.status.tpu)
                    else nb.status.ready_replicas >= 1
                )
                if ready:
                    latencies[name] = time.monotonic() - t0[name]
                    pending.discard(name)
            time.sleep(0.005)
    finally:
        mgr.stop()
        for fn in reversed(teardown):
            fn()
        cluster.stop()

    vals = sorted(latencies.values())
    result = {
        "transport": "remote (wire protocol, TLS)" if args.remote else "in-process",
        "notebooks": args.notebooks,
        "ready": len(vals),
        "timed_out": args.notebooks - len(vals),
        "create_storm_s": round(storm_s, 4),
        "chips_bound": chips_per_nb * len(vals),
        "ready_p50_s": round(statistics.median(vals), 4) if vals else None,
        "ready_p95_s": (
            round(vals[min(len(vals) - 1, math.ceil(0.95 * (len(vals) - 1)))], 4)
            if vals
            else None
        ),
        "ready_max_s": round(vals[-1], 4) if vals else None,
    }
    if args.remote and getattr(store, "throttle", None) is not None:
        # client-side QPS/burst limiter (cluster/remote.py _TokenBucket):
        # how often the storm actually hit the rate limit
        result["client_throttle"] = {
            "qps": store.throttle.qps,
            "burst": int(store.throttle.burst),
            "throttled_requests": store.throttle.waits,
            "throttle_wait_s": round(store.throttle.waited_s, 3),
        }
    print(json.dumps(result))
    if result["timed_out"]:
        raise SystemExit(1)


def _remote_stack(cluster, config, teardown, qps=100.0, burst=200):
    """The shared wire-protocol stack (cluster/remote_fixture.py): TLS
    apiserver + HTTPS admission webhook around the sim's store."""
    from odh_kubeflow_tpu.cluster import Client, RemoteStore
    from odh_kubeflow_tpu.cluster.remote_fixture import build_remote_stack

    api, store, _ = build_remote_stack(
        cluster.store, config, teardown, token="loadtest", qps=qps, burst=burst
    )
    # the load GENERATOR polls readiness in a tight loop; give it its own
    # unthrottled client so the driver's polling doesn't eat the manager's
    # QPS budget (two clients = two rate limiters, as in a real cluster)
    poller = RemoteStore(api.base_url, token="loadtest", ca_file=store.ca_file, qps=0)
    return store, Client(poller)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--notebooks", type=int, default=3)  # reference default
    ap.add_argument("--namespace", default="loadtest")
    ap.add_argument("--prefix", default="loadtest-nb-")
    ap.add_argument("--image", default="jupyter-tpu:latest")
    ap.add_argument("--accelerator", default="v5e")
    ap.add_argument("--topology", default="2x2")
    ap.add_argument("--no-pvc", action="store_true")
    ap.add_argument("--timeout", type=float, default=120.0)
    ap.add_argument("--emit", action="store_true", help="print CR YAML and exit")
    ap.add_argument(
        "--remote",
        action="store_true",
        help="run the manager over the wire-protocol apiserver (TLS + webhook)",
    )
    # reference notebook-controller/main.go:65-85 --qps/--burst analog.
    # Defaults are a production-scale setting (client-go's 20/30 measurably
    # serializes the readiness-probe polling at storm scale — the stats block
    # in the output shows how often the limiter engaged either way)
    ap.add_argument("--qps", type=float, default=100.0,
                    help="manager client QPS limit (0 = unthrottled)")
    ap.add_argument("--burst", type=int, default=200,
                    help="manager client burst size")
    args = ap.parse_args()
    if args.accelerator in ("", "none", "cpu"):
        args.accelerator = ""
    try:
        if args.emit:
            emit(args)
        else:
            run_sim(args)
    except BrokenPipeError:  # `--emit | head` etc.
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())


if __name__ == "__main__":
    main()
