#!/usr/bin/env python3
"""Pin a released controller image tag into the deploy tree.

The reference ships releasing/update-manifests-images, a ruamel-yaml patcher
that rewrites image tags inside kustomize manifests in place (reference
releasing/update-manifests-images:50-120). This build's manifests are
GENERATED from deploy/params.env by odh_kubeflow_tpu.deploy (the drift gate
ci/generate_manifests.sh keeps the tree honest), so the release updater has
one job: rewrite the params.env pin and regenerate — the generator, not a
YAML patcher, is the single source of truth.

Usage:
    releasing/update_image_tag.py v1.2.0
    releasing/update_image_tag.py --image ghcr.io/me/controller v1.2.0
    releasing/update_image_tag.py --check v1.2.0   # verify-only (CI)
"""
from __future__ import annotations

import argparse
import pathlib
import re
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
PARAMS = REPO / "deploy" / "params.env"
IMAGE_KEY = "odh-notebook-controller-image"
VERSION_FILE = pathlib.Path(__file__).resolve().parent / "version"


def current_pin() -> str:
    for line in PARAMS.read_text().splitlines():
        if line.startswith(f"{IMAGE_KEY}="):
            return line.split("=", 1)[1]
    raise SystemExit(f"{IMAGE_KEY} not found in {PARAMS}")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("tag", help="release tag, e.g. v1.2.0")
    ap.add_argument(
        "--image", default=None,
        help="image repository (default: keep the repository from params.env)",
    )
    ap.add_argument(
        "--check", action="store_true",
        help="verify params.env + the generated tree already carry the tag",
    )
    args = ap.parse_args()
    if not re.fullmatch(r"v\d+\.\d+\.\d+(-[A-Za-z0-9.]+)?", args.tag):
        raise SystemExit(f"tag {args.tag!r} is not vMAJOR.MINOR.PATCH[-suffix]")

    repo_part = args.image or current_pin().rsplit(":", 1)[0]
    pinned = f"{repo_part}:{args.tag}"

    if args.check:
        if current_pin() != pinned:
            print(f"params.env pins {current_pin()}, expected {pinned}")
            return 1
        print(f"image pin ok: {pinned}")
        return 0

    lines = PARAMS.read_text().splitlines()
    out = [
        f"{IMAGE_KEY}={pinned}" if line.startswith(f"{IMAGE_KEY}=") else line
        for line in lines
    ]
    PARAMS.write_text("\n".join(out) + "\n")
    VERSION_FILE.write_text(args.tag + "\n")
    # regenerate the committed manifest trees from the new pin (the same
    # command the drift gate runs)
    subprocess.run(
        [sys.executable, "-m", "odh_kubeflow_tpu.deploy", "generate",
         "--root", "deploy"],
        cwd=REPO, check=True,
    )
    print(f"pinned {pinned}; deploy/ regenerated (commit both)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
