"""Bench trajectory ledger: declared headline metrics, vs-prior deltas, and
the regression gate (ISSUE 15).

Five rounds of BENCH_rNN.json sat uncompared by any machinery — ROADMAP
item 3 demands "every claim lands in bench.py with a vs-prior-round delta",
and this module is that layer:

- **HEADLINES** is the single source of truth for what the bench is judged
  on: each entry declares the metric's name, the json path into the bench
  report where it lives, which direction is better, and the fractional
  regression tolerance the gate enforces. `check_headlines()` validates the
  registry slo-lint style (unique names, known directions, sane tolerances)
  and is wired into `ci/bench_gate.sh`.
- **load_trajectory()** parses the committed BENCH_rNN.json files. Rounds
  are driver wrappers ({n, cmd, rc, tail, parsed}); a wrapper whose
  `parsed` is null (r05's truncated tail) falls back to the raw
  BENCH_rNN_insession.json report when one is committed.
- **stamp()** is called by bench.main() on every report: it attaches a
  `ledger` block with a `vs_prior` delta for EVERY declared headline
  (computed against the last committed round that carried the metric) and a
  `where_time_went` per-phase breakdown mined from the PROFILE=1 profiler —
  the data-plane twin of the control plane's `readiness_phases`.
- **gate()** is the CI lane: registry lint, then the committed trajectory's
  latest round judged against its prior (a committed regression past
  tolerance fails the tree), then optionally a fresh report file judged the
  same way. `quick_proxy()` runs a tiny CPU serving episode under
  PROFILE=1 + JAXGUARD=1 and enforces the machine-independent invariants
  (one batched drain per burst, compile budget held, phase coverage >= 0.9)
  — the subset of the bench contract a CPU lane can honestly gate.

Tolerances are declared per headline because the headlines have different
noise floors: kernel/train numbers are slope-measured (tunnel jitter
cancels) and hold ~10%; the control-plane p50 is an in-process sim number
dominated by host scheduling noise (r04 -> r05 moved +52% with zero
control-plane changes), so its tolerance is wide and documented as such.
"""
from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

SCHEMA = "bench-ledger/v1"

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# the declared headline registry — ONE source of truth
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Headline:
    name: str
    path: Tuple[str, ...]  # json path into a bench report
    direction: str  # "higher" | "lower" is better
    tolerance: float  # fractional regression allowed before the gate fails
    note: str = ""


HEADLINES: Tuple[Headline, ...] = (
    Headline(
        name="train_step_tokens_per_s_v5e1",
        path=("detail", "train_step", "tokens_per_s"),
        direction="higher",
        tolerance=0.10,
        note="flagship train step, two-length slope (tunnel cancels)",
    ),
    Headline(
        name="train_step_mfu",
        path=("detail", "train_step", "mfu_est"),
        direction="higher",
        tolerance=0.10,
        note="estimated model-FLOPs utilization of the train step",
    ),
    Headline(
        name="kernel_mfu",
        path=("detail", "kernels", "kernel_mfu"),
        direction="higher",
        tolerance=0.10,
        note="VERDICT-r1 acceptance number (flash kernel at 4k)",
    ),
    Headline(
        name="decode_tokens_per_s",
        path=("detail", "decode", "decode_only_tokens_per_s"),
        direction="higher",
        tolerance=0.15,
        note="single-slot autoregressive decode throughput",
    ),
    Headline(
        name="serving_goodput_vs_static_batch",
        path=("detail", "serving", "goodput_vs_static_batch"),
        direction="higher",
        tolerance=0.15,
        note="continuous batching vs static at equal slots (>= 1.5x "
             "acceptance); no committed round carries it yet, so vs_prior "
             "is null until the first TPU run after ISSUE 9 lands one",
    ),
    Headline(
        name="router_added_latency_p50_ms",
        path=("detail", "serving", "fleet", "router_added_latency_p50_ms"),
        direction="lower",
        tolerance=0.75,
        note="in-process router tax (p50 routed - p50 direct, tiny model); "
             "sub-ms host scheduling noise dominates, so only "
             "order-of-magnitude breaks should gate; no committed round "
             "carries it yet (vs_prior null until the first post-ISSUE 16 "
             "bench round)",
    ),
    Headline(
        name="scale_up_reaction_s",
        path=("detail", "serving", "fleet", "scale_up_reaction_s"),
        direction="lower",
        tolerance=0.75,
        note="hot autoscaler tick -> new replica Serving in the in-process "
             "sim (annotation write + warm bind + gang readiness); "
             "dominated by probe cadence and host scheduling, wide "
             "tolerance catches order-of-magnitude breaks only; no "
             "committed round carries it yet",
    ),
    Headline(
        name="fleet_utilization",
        path=("detail", "accounting", "fleet_utilization"),
        direction="higher",
        tolerance=0.05,
        note="fraction of accounted chip-seconds in productive phases over "
             "the scripted ISSUE 17 episode; the script is deterministic "
             "on a sim clock, so any movement is a classifier change — "
             "tight tolerance on purpose",
    ),
    Headline(
        name="chip_seconds_per_ready_notebook",
        path=("detail", "accounting", "chip_seconds_per_ready_notebook"),
        direction="lower",
        tolerance=0.05,
        note="end-to-end chip-second cost per notebook that reached ready "
             "in the scripted ISSUE 17 episode (starting/idle/repair "
             "overhead included); deterministic sim clock, tight tolerance",
    ),
    Headline(
        name="cache_scans_per_reconcile",
        path=("detail", "control_plane", "cpprofile",
              "cache_scans_per_reconcile"),
        direction="lower",
        tolerance=0.75,
        note="CPPROFILE=1 fleet-wide flat-cache walk cost over the storm "
             "episode: cached objects scanned per reconcile across every "
             "controller. The denominator ROADMAP item 5's indexing/"
             "fan-out refactor is gated against; the cause MIX shifts with "
             "host-scheduling-dependent requeue counts, so the tolerance "
             "is wide and only order-of-magnitude breaks gate; no "
             "committed round carries it yet (vs_prior null until the "
             "first post-ISSUE 20 round)",
    ),
    Headline(
        name="takeover_relist_share",
        path=("detail", "control_plane", "cpprofile",
              "takeover_relist_share"),
        direction="lower",
        tolerance=0.75,
        note="CPPROFILE=1 share of completed manager-takeover wall-clock "
             "spent in the relist phase (aggregate over the episode's "
             "managers). The cold-cache cost a delta-relist would remove; "
             "phase boundaries ride host scheduling, so wide tolerance — "
             "order-of-magnitude breaks only; no committed round carries "
             "it yet",
    ),
    Headline(
        name="cr_to_mesh_ready_p50_s",
        path=("detail", "control_plane", "cr_to_mesh_ready_p50_s"),
        direction="lower",
        tolerance=0.75,
        note="in-process sim latency dominated by host scheduling noise "
             "(r04 -> r05 moved +52% with zero control-plane changes); "
             "wide tolerance catches order-of-magnitude breaks only",
    ),
)


def check_headlines(
    headlines: Sequence[Headline] = HEADLINES,
) -> List[str]:
    """Registry validation, slo-lint style: a list of human-readable
    problems, empty when the registry is well-formed."""
    problems: List[str] = []
    seen: set = set()
    for h in headlines:
        where = f"headline {h.name!r}"
        if not h.name or not re.fullmatch(r"[a-z][a-z0-9_]*", h.name):
            problems.append(f"{where}: name must be snake_case")
        if h.name in seen:
            problems.append(f"{where}: duplicate name")
        seen.add(h.name)
        if h.direction not in ("higher", "lower"):
            problems.append(
                f"{where}: direction must be 'higher' or 'lower', "
                f"got {h.direction!r}"
            )
        if not h.path or not all(
            isinstance(p, str) and p for p in h.path
        ):
            problems.append(f"{where}: path must be non-empty str segments")
        if not (0.0 < h.tolerance < 1.0):
            problems.append(
                f"{where}: tolerance must be a fraction in (0, 1), "
                f"got {h.tolerance}"
            )
        if h.tolerance > 0.25 and not h.note:
            problems.append(
                f"{where}: a tolerance this wide ({h.tolerance}) must carry "
                f"a note documenting why"
            )
    return problems


# ---------------------------------------------------------------------------
# trajectory loading
# ---------------------------------------------------------------------------


def _extract(report: Optional[Dict[str, Any]],
             path: Tuple[str, ...]) -> Optional[float]:
    node: Any = report
    for seg in path:
        if not isinstance(node, dict) or seg not in node:
            return None
        node = node[seg]
    return node if isinstance(node, (int, float)) else None


def load_trajectory(
    root: Optional[str] = None,
) -> List[Tuple[int, Dict[str, Any]]]:
    """The committed BENCH_rNN.json rounds as [(round, report)], ascending.
    Driver wrappers contribute their `parsed` report; a null `parsed` falls
    back to the round's raw _insession report when committed (r05). Rounds
    with no recoverable report are skipped. `root` (or $BENCH_LEDGER_DIR)
    overrides the repo root — the doctored-regression tests use this."""
    root = root or os.environ.get("BENCH_LEDGER_DIR") or _ROOT
    rounds: Dict[int, Dict[str, Any]] = {}
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return []
    for fname in names:
        m = re.fullmatch(r"BENCH_r(\d+)\.json", fname)
        if not m:
            continue
        n = int(m.group(1))
        try:
            with open(os.path.join(root, fname)) as f:
                obj = json.load(f)
        except (OSError, ValueError):
            continue
        report = obj.get("parsed") if "parsed" in obj else obj
        if report is None:
            fallback = os.path.join(root, f"BENCH_r{n:02d}_insession.json")
            try:
                with open(fallback) as f:
                    report = json.load(f)
            except (OSError, ValueError):
                report = None
        if isinstance(report, dict):
            rounds[n] = report
    return sorted(rounds.items())


# ---------------------------------------------------------------------------
# vs_prior + where_time_went
# ---------------------------------------------------------------------------


def _judge(h: Headline, value: Optional[float],
           prior: Optional[float]) -> Dict[str, Any]:
    entry: Dict[str, Any] = {
        "value": value,
        "prior": prior,
        "direction": h.direction,
        "tolerance": h.tolerance,
        "delta_frac": None,
        "regressed": False,
    }
    if value is None or prior is None or prior == 0:
        return entry
    delta = (value - prior) / abs(prior)
    entry["delta_frac"] = round(delta, 4)
    if h.direction == "higher":
        entry["regressed"] = delta < -h.tolerance
    else:
        entry["regressed"] = delta > h.tolerance
    return entry


def vs_prior(
    report: Dict[str, Any],
    trajectory: Optional[List[Tuple[int, Dict[str, Any]]]] = None,
    root: Optional[str] = None,
) -> Dict[str, Any]:
    """The `ledger` block for one bench report: every declared headline with
    its value, the last committed round that carried the metric, and the
    tolerance-judged delta. Headlines the report (or the whole trajectory)
    doesn't carry get null values — absence is visible, never silent."""
    if trajectory is None:
        trajectory = load_trajectory(root)
    headlines: Dict[str, Any] = {}
    for h in HEADLINES:
        value = _extract(report, h.path)
        prior = prior_round = None
        for n, past in reversed(trajectory):
            if past is report:
                continue
            v = _extract(past, h.path)
            if v is not None:
                prior, prior_round = v, n
                break
        entry = _judge(h, value, prior)
        entry["prior_round"] = prior_round
        headlines[h.name] = entry
    return {
        "schema": SCHEMA,
        "trajectory_rounds": [n for n, _ in trajectory],
        "headlines": headlines,
    }


def where_time_went(
    snapshot: Optional[Dict[str, Any]] = None,
    regions: Sequence[str] = ("serving.decode_burst", "bench.train_step"),
) -> Dict[str, Any]:
    """Per-phase breakdown for the data-plane hot regions, mined from the
    PROFILE=1 profiler — the data-plane twin of `readiness_phases`. Phase
    SELF times partition the region total (profiler accounting invariant),
    so `coverage` — their sum over the region total — lands >= 0.9 on a
    healthy run; a low coverage means untracked time inside the region."""
    if snapshot is None:
        from odh_kubeflow_tpu.utils import profiler

        snapshot = profiler.snapshot()
    out: Dict[str, Any] = {}
    for name in regions:
        s = (snapshot.get("regions") or {}).get(name)
        if not s or not s.get("phases"):
            continue
        total = s.get("total_s") or 0.0
        phases = {}
        covered = 0.0
        for pname, ps in s["phases"].items():
            covered += ps["self_s"]
            phases[pname] = {
                "self_s": round(ps["self_s"], 6),
                "frac": round(ps["self_s"] / total, 4) if total else None,
            }
        out[name] = {
            "count": s["count"],
            "total_s": round(total, 6),
            "coverage": round(covered / total, 4) if total else None,
            "phases": phases,
        }
    return out


def stamp(
    result: Dict[str, Any],
    root: Optional[str] = None,
    snapshot: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Attach the ledger block (+ where_time_went under detail) to a bench
    report in place. bench.main() calls this on every emitted report; never
    raises — a ledger failure must not cost the bench artifact."""
    try:
        result["ledger"] = vs_prior(result, root=root)
        wtw = where_time_went(snapshot)
        if wtw:
            result.setdefault("detail", {})["where_time_went"] = wtw
    except Exception as e:  # pragma: no cover - defensive
        result["ledger"] = {"schema": SCHEMA, "error": repr(e)[:300]}
    return result


# ---------------------------------------------------------------------------
# the gate
# ---------------------------------------------------------------------------


def gate_trajectory(
    trajectory: Optional[List[Tuple[int, Dict[str, Any]]]] = None,
    root: Optional[str] = None,
) -> List[str]:
    """Judge the trajectory's LATEST round against its prior rounds: a list
    of failures (empty = green). A committed round that regressed a declared
    headline past tolerance fails the tree — the gate the next perf PR is
    judged by."""
    if trajectory is None:
        trajectory = load_trajectory(root)
    if len(trajectory) < 2:
        return []  # nothing to compare yet — vacuously green
    latest_n, latest = trajectory[-1]
    block = vs_prior(latest, trajectory=trajectory[:-1])
    failures = []
    for name, entry in block["headlines"].items():
        if entry["regressed"]:
            failures.append(
                f"headline {name!r}: r{latest_n:02d} value {entry['value']} "
                f"regressed {entry['delta_frac']:+.1%} vs "
                f"r{entry['prior_round']:02d} ({entry['prior']}), tolerance "
                f"{entry['tolerance']:.0%} ({entry['direction']} is better)"
            )
    return failures


def gate_report(path: str, root: Optional[str] = None) -> List[str]:
    """Judge a fresh report file against the committed trajectory — the
    lane a perf PR runs on its own bench output before committing it."""
    with open(path) as f:
        report = json.load(f)
    block = vs_prior(report, root=root)
    return [
        f"headline {name!r}: value {e['value']} regressed "
        f"{e['delta_frac']:+.1%} vs r{e['prior_round']:02d} ({e['prior']}), "
        f"tolerance {e['tolerance']:.0%}"
        for name, e in block["headlines"].items()
        if e["regressed"]
    ]


def quick_proxy() -> Dict[str, Any]:
    """The CPU-proxy subset: a tiny serving episode under PROFILE=1 +
    JAXGUARD=1 enforcing the machine-independent bench invariants —
    exactly one batched post-burst drain, compile budget held, and
    where_time_went phase coverage >= 0.9 of the region total. Raises
    AssertionError on violation; returns the mined breakdown."""
    import jax.numpy as jnp

    from odh_kubeflow_tpu.models import TransformerConfig, init_params
    from odh_kubeflow_tpu.serving.engine import ServingEngine
    from odh_kubeflow_tpu.utils import profiler

    prev = {k: os.environ.get(k) for k in ("PROFILE", "JAXGUARD")}
    os.environ["PROFILE"] = "1"
    os.environ["JAXGUARD"] = "1"
    try:
        profiler.reset()
        import jax

        cfg = TransformerConfig(
            vocab=128, d_model=32, n_layers=2, n_heads=2, d_ff=64,
            max_seq=64, dtype=jnp.float32, use_flash=False,
        )
        params = init_params(jax.random.PRNGKey(0), cfg)
        engine = ServingEngine(params, cfg, max_slots=2, max_seq=64,
                               max_queue_depth=8, decode_burst=4)
        for i, n in enumerate((6, 10, 4)):
            engine.submit([1 + i, 2, 3, 4], max_new=n)
        while not engine.idle():
            engine.step()
        stats = engine.stats()
        assert stats["host_transfers_last_burst"] == 1, (
            f"{stats['host_transfers_last_burst']} host transfers in the "
            "last burst — steady state is ONE batched drain"
        )
        from odh_kubeflow_tpu.analysis import hotregions

        budget = hotregions.get("serving.decode_burst").compile_budget
        assert stats["decode_burst_recompiles"] <= budget, (
            f"decode burst traced {stats['decode_burst_recompiles']}x, "
            f"budget {budget}"
        )
        wtw = where_time_went(regions=("serving.decode_burst",))
        assert "serving.decode_burst" in wtw, (
            "profiler captured no serving.decode_burst region — the engine "
            "step scope or the PROFILE arming is broken"
        )
        cov = wtw["serving.decode_burst"]["coverage"]
        assert cov is not None and cov >= 0.9, (
            f"phase coverage {cov} < 0.9 — phases no longer partition the "
            "decode burst (untracked time inside the region)"
        )
        return wtw
    finally:
        profiler.reset()
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="bench.ledger",
        description="bench trajectory ledger: registry lint, regression "
                    "gate, CPU-proxy invariants",
    )
    ap.add_argument("--lint", action="store_true",
                    help="validate the headline registry")
    ap.add_argument("--gate", action="store_true",
                    help="judge the committed trajectory's latest round")
    ap.add_argument("--report", metavar="FILE",
                    help="judge a fresh report file against the trajectory")
    ap.add_argument("--quick", action="store_true",
                    help="run the CPU-proxy invariant subset")
    args = ap.parse_args(argv)
    rc = 0
    ran = False
    if args.lint:
        ran = True
        problems = check_headlines()
        for p in problems:
            print(f"ledger-lint: {p}")
        print(f"ledger-lint: {len(HEADLINES)} headline(s), "
              f"{len(problems)} problem(s)")
        rc |= 1 if problems else 0
    if args.gate:
        ran = True
        failures = gate_trajectory()
        for f_ in failures:
            print(f"bench-gate: {f_}")
        traj = load_trajectory()
        print(f"bench-gate: {len(traj)} round(s), "
              f"{len(failures)} regression(s)")
        rc |= 1 if failures else 0
    if args.report:
        ran = True
        failures = gate_report(args.report)
        for f_ in failures:
            print(f"bench-gate[report]: {f_}")
        print(f"bench-gate[report]: {len(failures)} regression(s)")
        rc |= 1 if failures else 0
    if args.quick:
        ran = True
        try:
            wtw = quick_proxy()
        except AssertionError as e:
            print(f"bench-gate[quick]: FAIL: {e}")
            rc |= 1
        else:
            cov = wtw["serving.decode_burst"]["coverage"]
            print(f"bench-gate[quick]: ok (decode-burst phase coverage "
                  f"{cov})")
    if not ran:
        ap.print_help()
        return 2
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
