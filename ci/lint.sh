#!/usr/bin/env bash
# Static-analysis gate: ruff (style+bugbear), mypy (types), pip-audit
# (vulnerable deps) — the analogs of the reference's golangci-lint /
# semgrep.yaml / govulncheck workflow (SURVEY §4).
#
# The hermetic dev image ships none of these and forbids pip install, so
# locally this degrades to a stdlib syntax gate (compileall) with a loud
# note; the CI `lint` job pip-installs the real tools first, so the gate is
# real where it matters.
set -euo pipefail
cd "$(dirname "$0")/.."

rc=0
# operator-lint: the in-tree AST invariant checks (ci/analysis.sh) — unlike
# ruff/mypy these have no dependencies, so they gate everywhere, including
# the hermetic dev image
echo "== operator-lint (ci/analysis.sh) =="
./ci/analysis.sh || rc=1

# deployment-surface conformance (ISSUE 14): the deploylint checkers also run
# in the default pass above; this lane adds the committed-manifest
# regeneration gate (build_manifests.sh --check) and the deploylint/
# DEPLOYGUARD contract tests
echo "== deploylint (ci/analysis.sh --deploy) =="
./ci/analysis.sh --deploy || rc=1

# bench trajectory regression gate (ISSUE 15): headline-registry lint, the
# committed BENCH_rNN.json trajectory judged against declared tolerances,
# and the quick CPU-proxy invariant subset (ci/bench_gate.sh)
echo "== bench gate (ci/bench_gate.sh) =="
./ci/bench_gate.sh || rc=1

if python -m ruff --version >/dev/null 2>&1; then
    echo "== ruff check =="
    python -m ruff check odh_kubeflow_tpu tests loadtest bench.py __graft_entry__.py || rc=1
else
    echo "== ruff unavailable: stdlib compileall syntax gate only =="
    python -m compileall -q odh_kubeflow_tpu tests loadtest bench.py __graft_entry__.py || rc=1
fi

if python -m mypy --version >/dev/null 2>&1; then
    echo "== mypy =="
    python -m mypy --config-file pyproject.toml || rc=1
else
    echo "== mypy unavailable (skipped locally; enforced in CI) =="
fi

if python -m pip_audit --version >/dev/null 2>&1; then
    echo "== pip-audit =="
    python -m pip_audit || rc=1
else
    echo "== pip-audit unavailable (skipped locally; enforced in CI) =="
fi
exit $rc
