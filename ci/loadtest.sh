#!/usr/bin/env bash
# SLO-gated loadtest lane (ISSUE 13): the 200-object mixed-class tier
# (loadtest/tiers.py) against the sharded, flow-controlled control plane.
#
# The tier brings up CPU+TPU notebooks, InferenceEndpoints and back-to-back
# TPUJob streams through one store under two shard managers + a warm
# standby, slams a TPUJob admission storm into the batch priority level
# mid-run, then kills the active shard-0 leader. Its exit status IS the SLO
# verdict: the surviving manager's own SLO engine must show every gated SLO
# (readiness-latency-p99, canary-readiness, job-completion,
# serving-availability) at-or-above objective with zero gated firing
# alerts, the storm must have been shed at batch and ONLY batch, takeover
# must land within lease bounds, and zero writes may hit the fence.
#
#   ./ci/loadtest.sh                 # the 200-object CI tier
#   LOADTEST_TIER=500 ./ci/loadtest.sh   # the slow 500-object tier (manual /
#                                        # nightly: not part of tier-1 time)
set -euo pipefail
cd "$(dirname "$0")/.."

TIER="${LOADTEST_TIER:-200}"
export PYTHONHASHSEED="${PYTHONHASHSEED:-0}"
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
# deployment-surface guard (ISSUE 14): the tier runs armed, so a lease write
# misattributed onto a workload flow after the shard-leader kill — or any
# request exceeding the declared RBAC — fails the tier at the offending call
# instead of leaking into the fairness accounting
export DEPLOYGUARD="${DEPLOYGUARD:-1}"
# control-plane profiler (ISSUE 20): the tier runs armed so the report
# carries per-controller reconcile-cause/cache-scan breakdowns and the
# kill lane's takeover decomposed into its five phases
export CPPROFILE="${CPPROFILE:-1}"

echo "=== loadtest lane: ${TIER}-object tier (DEPLOYGUARD=$DEPLOYGUARD CPPROFILE=$CPPROFILE) ==="
python loadtest/tiers.py --objects "$TIER" "$@"
echo "=== loadtest lane: ${TIER}-object tier passed its SLO verdict ==="
