#!/usr/bin/env bash
# Operator-lint lane (ISSUE 3): the AST invariant checks over the whole
# package — cache-mutation, lock-discipline, lock-order, swallowed-exception,
# metric/annotation conventions — followed by the checker contract tests
# (every checker must flag its fixture violation AND pass its clean twin).
#
# Exit is nonzero on ANY unsuppressed finding: intentional exceptions live as
# inline `# lint: disable=<check>` pragmas next to a justification comment,
# so this lane going red means a NEW invariant violation, never a known one.
#
#   ./ci/analysis.sh                 # full pass + contract tests
#   ./ci/analysis.sh --audit         # also show what the pragmas suppress
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== operator-lint static pass =="
python -m odh_kubeflow_tpu.analysis odh_kubeflow_tpu

if [[ "${1:-}" == "--audit" ]]; then
    echo "== suppressed findings (pragma audit) =="
    python -m odh_kubeflow_tpu.analysis --include-suppressed odh_kubeflow_tpu || true
fi

if python -m pytest --version >/dev/null 2>&1; then
    echo "== analysis contract tests =="
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest tests/ -q -m analysis \
        -p no:cacheprovider -p no:randomly
else
    # the static pass above is dependency-free and already gated; only the
    # pytest contract layer is skipped in a bare environment
    echo "== pytest unavailable: contract tests skipped (static pass gated) =="
fi
