#!/usr/bin/env bash
# Operator-lint lane (ISSUE 3, grown in ISSUE 8): the AST invariant checks
# over the whole package — cache-mutation, lock-discipline, lock-order,
# swallowed-exception, metric/annotation conventions, machine-conformance —
# the pragma budget gate, and the checker contract tests (every checker must
# flag its fixture violation AND pass its clean twin).
#
# Exit is nonzero on ANY unsuppressed finding: intentional exceptions live as
# inline `# lint: disable=<check>` pragmas next to a justification comment,
# AND every pragma is budgeted in ci/pragma_allowlist.txt — this lane going
# red means a NEW invariant violation or a NEW unreviewed suppression, never
# a known one.
#
#   ./ci/analysis.sh                 # full pass + pragma gate + contract tests
#   ./ci/analysis.sh --audit         # also show what the pragmas suppress
#   ./ci/analysis.sh --machines      # machine-conformance + the systematic
#                                    # interleaving explorer only (ISSUE 8)
#   ./ci/analysis.sh --jax           # the jaxlint family + JAXGUARD contract
#                                    # tests only (ISSUE 12)
#   ./ci/analysis.sh --deploy        # the deploylint family + DEPLOYGUARD
#                                    # contract tests only (ISSUE 14)
set -euo pipefail
cd "$(dirname "$0")/.."

if [[ "${1:-}" == "--jax" ]]; then
    # the data-plane discipline lane (ISSUE 12): the four jaxlint checkers
    # package-wide (zero unsuppressed findings is the acceptance bar), the
    # pragma budget gate, and the jaxlint/jaxguard contract tests
    echo "== jaxlint static pass (retrace/transfer/donation/psum-axis) =="
    python -m odh_kubeflow_tpu.analysis \
        --check retrace-hazard --check host-transfer \
        --check donation-discipline --check psum-axis odh_kubeflow_tpu
    echo "== pragma budget gate =="
    python -m odh_kubeflow_tpu.analysis --pragma-gate ci/pragma_allowlist.txt
    if python -m pytest --version >/dev/null 2>&1; then
        echo "== jaxlint/jaxguard contract tests =="
        JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest \
            tests/test_analysis.py tests/test_jaxguard.py -q \
            -m "analysis and not slow" \
            -p no:cacheprovider -p no:randomly
    fi
    exit 0
fi

if [[ "${1:-}" == "--deploy" ]]; then
    # the deployment-surface conformance lane (ISSUE 14): the four deploylint
    # checkers package-wide — RBAC coverage (verbs used vs granted, both
    # directions), CRD schema drift against the committed manifests, the env
    # contract (every os.environ read resolves to a declared ENV_CONTRACT
    # knob), flow-schema coverage (every flow classifies non-default, every
    # served webhook path is registered) — plus the committed-manifest
    # regeneration gate and the deploylint/DEPLOYGUARD contract tests.
    # When a DEPLOYGUARD surface artifact exists (a faults.sh DEPLOYGUARD=1
    # iteration dumps one via DEPLOYGUARD_SURFACE_OUT), the rbac-coverage
    # checker consumes it for runtime-confident stale-rule findings.
    SURFACE_ARGS=()
    if [[ -n "${DEPLOY_SURFACE:-}" && -f "${DEPLOY_SURFACE:-}" ]]; then
        echo "== deploylint: using runtime surface artifact ${DEPLOY_SURFACE} =="
        SURFACE_ARGS=(--deploy-surface "$DEPLOY_SURFACE")
    fi
    echo "== deploylint static pass (rbac/crd-drift/env-contract/flow-schema) =="
    python -m odh_kubeflow_tpu.analysis \
        --check rbac-coverage --check crd-schema-drift \
        --check env-contract --check flow-schema-coverage \
        "${SURFACE_ARGS[@]}" odh_kubeflow_tpu
    echo "== pragma budget gate =="
    python -m odh_kubeflow_tpu.analysis --pragma-gate ci/pragma_allowlist.txt
    echo "== committed-manifest regeneration gate =="
    ./ci/build_manifests.sh --check
    if python -m pytest --version >/dev/null 2>&1; then
        echo "== deploylint/deployguard contract tests =="
        JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest \
            tests/test_analysis.py tests/test_deployguard.py -q \
            -m "deploylint and not slow" \
            -p no:cacheprovider -p no:randomly
    fi
    exit 0
fi

if [[ "${1:-}" == "--machines" ]]; then
    echo "== machine-conformance static pass =="
    python -m odh_kubeflow_tpu.analysis --check machine-conformance odh_kubeflow_tpu
    echo "== systematic interleaving explorer (bounded exhaustive) =="
    python -m odh_kubeflow_tpu.analysis --explore
    if python -m pytest --version >/dev/null 2>&1; then
        # the full file, slow tier included: the P=1 interleaving space
        echo "== machine/explorer contract tests (incl. slow tier) =="
        JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest \
            tests/test_explore.py -q -m analysis \
            -p no:cacheprovider -p no:randomly
    fi
    exit 0
fi

echo "== operator-lint static pass =="
python -m odh_kubeflow_tpu.analysis odh_kubeflow_tpu

echo "== pragma budget gate =="
python -m odh_kubeflow_tpu.analysis --pragma-gate ci/pragma_allowlist.txt

if [[ "${1:-}" == "--audit" ]]; then
    echo "== suppressed findings (pragma audit) =="
    python -m odh_kubeflow_tpu.analysis --include-suppressed odh_kubeflow_tpu || true
fi

if python -m pytest --version >/dev/null 2>&1; then
    echo "== analysis contract tests =="
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest tests/ -q \
        -m "analysis and not slow" \
        -p no:cacheprovider -p no:randomly
else
    # the static pass above is dependency-free and already gated; only the
    # pytest contract layer is skipped in a bare environment
    echo "== pytest unavailable: contract tests skipped (static pass gated) =="
fi
