#!/usr/bin/env bash
# Bench trajectory regression gate (ISSUE 15): the bench/ledger.py lanes —
#
#   1. ledger lint: the declared headline registry (name, direction,
#      tolerance — bench's single source of perf truth) is well-formed,
#      slo-lint style.
#   2. trajectory gate: the committed BENCH_rNN.json trajectory's latest
#      round judged against its prior — a committed round that regressed a
#      declared headline past its tolerance fails the tree.
#   3. quick CPU proxy: a tiny serving episode under PROFILE=1 + JAXGUARD=1
#      enforcing the machine-independent invariants (one batched drain per
#      burst, compile budget held, where_time_went phase coverage >= 0.9).
#      CPU wall-clock can't honestly judge TPU headlines, so the proxy
#      gates structure, not speed.
#
# A fresh TPU bench report gates the same way before being committed:
#   BENCH_REPORT=/path/to/report.json ./ci/bench_gate.sh
set -euo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

rc=0
echo "== bench gate: headline registry lint =="
python -m bench.ledger --lint || rc=1

echo "== bench gate: committed trajectory =="
python -m bench.ledger --gate || rc=1

if [ -n "${BENCH_REPORT:-}" ]; then
    echo "== bench gate: fresh report ${BENCH_REPORT} =="
    python -m bench.ledger --report "${BENCH_REPORT}" || rc=1
fi

echo "== bench gate: quick CPU-proxy invariants =="
python -m bench.ledger --quick || rc=1

if [ "$rc" -eq 0 ]; then
    echo "== bench gate: green =="
else
    echo "== bench gate: FAILED =="
fi
exit $rc
