#!/usr/bin/env bash
# Build-validate every overlay (the reference's ci/kustomize.sh: kustomize
# build each config tree and fail on error).
#
#   ./ci/build_manifests.sh          # build-validate all overlays
#   ./ci/build_manifests.sh --check  # additionally regenerate the full tree
#                                    # into a temp dir and diff it against the
#                                    # committed deploy/ — non-mutating (unlike
#                                    # generate_manifests.sh, which rewrites
#                                    # the working tree and leans on git), so
#                                    # it is safe mid-edit and in a dirty tree
set -euo pipefail
cd "$(dirname "$0")/.."

if [[ "${1:-}" == "--check" ]]; then
    TMP="$(mktemp -d)"
    trap 'rm -rf "$TMP"' EXIT
    python -m odh_kubeflow_tpu.deploy generate --root "$TMP" \
        --params deploy/params.env >/dev/null
    rc=0
    while IFS= read -r -d '' gen; do
        rel="${gen#"$TMP"/}"
        if ! diff -u "deploy/${rel}" "$gen" >/dev/null 2>&1; then
            echo "ERROR: deploy/${rel} drifted from the generators:" >&2
            diff -u "deploy/${rel}" "$gen" >&2 || true
            rc=1
        fi
    done < <(find "$TMP" -type f -print0 | sort -z)
    if [[ $rc -ne 0 ]]; then
        echo "Run: python -m odh_kubeflow_tpu.deploy generate --root deploy" >&2
        exit 1
    fi
    echo "deploy/ manifests match the generators"
fi

for overlay in base standalone gke dev; do
  echo "--- building overlay: ${overlay}"
  python -m odh_kubeflow_tpu.deploy build "${overlay}" --params deploy/params.env >/dev/null
done
echo "all overlays build"
