#!/usr/bin/env bash
# Build-validate every overlay (the reference's ci/kustomize.sh: kustomize
# build each config tree and fail on error).
set -euo pipefail
cd "$(dirname "$0")/.."

for overlay in base standalone gke dev; do
  echo "--- building overlay: ${overlay}"
  python -m odh_kubeflow_tpu.deploy build "${overlay}" --params deploy/params.env >/dev/null
done
echo "all overlays build"
