#!/usr/bin/env bash
# Metrics exposition lint lane (ISSUE 2 satellite; rules ported to Python in
# ISSUE 3): delegate the registry naming/exposition rules to the analysis
# package — odh_kubeflow_tpu/analysis/metric_rules.py is the ONE source of
# truth, shared with the static metric-convention AST checker — then rerun
# the observability-marked pytest contract tests (exposition round-trip,
# +Inf buckets, label escaping).
#
# Since ISSUE 20 the registry lint also covers the CPPROFILE=1 control-plane
# profiler families (runtime/cpprofile.py, registered at import): the
# cp_reconcile_cause_total / cp_cache_scan_objects_total counters and the
# cp_queue_wait / cp_reconcile_work / cp_takeover_phase histograms, whose
# sub-ms bucket layouts are range-checked against HISTOGRAM_RANGES.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== registry lint (delegated to odh_kubeflow_tpu.analysis) =="
python -m odh_kubeflow_tpu.analysis --registry-lint

echo "== observability contract tests =="
python -m pytest tests/ -q -m observability -p no:cacheprovider
