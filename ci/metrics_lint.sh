#!/usr/bin/env bash
# Metrics exposition lint lane (ISSUE 2 satellite; rules ported to Python in
# ISSUE 3): delegate the registry naming/exposition rules to the analysis
# package — odh_kubeflow_tpu/analysis/metric_rules.py is the ONE source of
# truth, shared with the static metric-convention AST checker — then rerun
# the observability-marked pytest contract tests (exposition round-trip,
# +Inf buckets, label escaping).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== registry lint (delegated to odh_kubeflow_tpu.analysis) =="
python -m odh_kubeflow_tpu.analysis --registry-lint

echo "== observability contract tests =="
python -m pytest tests/ -q -m observability -p no:cacheprovider
