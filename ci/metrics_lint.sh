#!/usr/bin/env bash
# Metrics exposition lint lane (ISSUE 2 satellite): import the package,
# instantiate every metric-registration site, render the GLOBAL registry and
# fail on naming-convention violations (counters without `_total`, metrics
# with empty help strings, invalid metric names) plus any exposition text a
# standard scraper would reject. Then rerun the observability-marked pytest
# contract tests (exposition round-trip, +Inf buckets, label escaping).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== registry lint =="
python - <<'PY'
import re
import sys

# Import every module that registers series at import or construction time.
import odh_kubeflow_tpu.runtime.metrics as m  # resilience + controller-runtime series
import odh_kubeflow_tpu.runtime.workqueue  # noqa: F401
import odh_kubeflow_tpu.runtime.controller  # noqa: F401
import odh_kubeflow_tpu.tpu.telemetry  # noqa: F401  # TPU-side series
from odh_kubeflow_tpu.controllers.metrics import NotebookMetrics

NotebookMetrics(m.global_registry)  # controller series register in __init__

NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
violations = []
for metric in m.global_registry._metrics.values():
    if not NAME_RE.match(metric.name):
        violations.append(f"{metric.name}: invalid metric name")
    if isinstance(metric, m.Counter) and not metric.name.endswith("_total"):
        violations.append(f"{metric.name}: counter without _total suffix")
    if not metric.help.strip():
        violations.append(f"{metric.name}: empty help string")
    for label in metric.label_names:
        if not LABEL_RE.match(label) or label == "le":
            violations.append(f"{metric.name}: invalid label name {label!r}")

text = m.global_registry.render()
families = set()
for line in text.splitlines():
    if line.startswith("# HELP "):
        families.add(line.split(" ", 3)[2])
for metric in m.global_registry._metrics.values():
    if metric.name not in families:
        violations.append(f"{metric.name}: missing from rendered exposition")

if violations:
    print("metrics lint FAILED:")
    for v in violations:
        print(f"  - {v}")
    sys.exit(1)
print(f"metrics lint OK: {len(m.global_registry._metrics)} families, "
      f"{len(text.splitlines())} exposition lines")
PY

echo "== observability contract tests =="
python -m pytest tests/ -q -m observability -p no:cacheprovider
