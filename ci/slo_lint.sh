#!/usr/bin/env bash
# SLO definition lint lane (ISSUE 5 satellite): every SLO indicator and
# alert rule must reference metric families that actually exist in the live
# registry — validated by analysis/metric_rules.py check_slo_definitions,
# the same one-source-of-truth pattern as the registry lint — then the
# slo-marked pytest contract tests rerun (burn-rate math, alert lifecycle,
# inhibition, flight-recorder bundles, the bad-day acceptance soak).
#
# Since ISSUE 7 the lint also covers the suspend/resume layer: the
# `resume-latency` SLO's notebook_resume_seconds histogram and the
# slice_pool_{size,hit_ratio} gauges (cluster/slicepool.py) register into
# the same live registry the lint checks, so a renamed pool series or an
# off-bucket resume threshold fails here, not in a dashboard.
#
# Since ISSUE 9 it covers the serving layer too: the `token-latency` SLO's
# inference_token_latency_seconds histogram (threshold must sit on a real
# bucket) and the `serving-availability` ratio over
# inference_requests_total{result} (serving/metrics.py — jax-free precisely
# so this lint sees the families on the manager image).
#
# Since ISSUE 10 it covers the batch layer: the `job-completion` SLO's
# good-vs-total ratio over tpu_jobs_total{result} plus the queue-wait/
# completion histograms and the goodput gauge (runtime/jobmetrics.py —
# jax-free for the same reason), so a renamed job family or a dead label
# fails here, not in a dashboard.
#
# Since ISSUE 17 it covers the fleet accounting layer: the
# `fleet-utilization` SLO's tpu_fleet_utilization_ratio gauge plus the
# tpu_chip_seconds_total{workload_class,phase} ledger family
# (runtime/accounting.py — jax-free again), so the conservation ledger's
# exported surface is lint-checked with everything else.
#
# Since ISSUE 20 the live registry the lint loads also carries the
# CPPROFILE=1 control-plane profiler families (runtime/cpprofile.py —
# jax-free, registered at import): cp_reconcile_cause_total,
# cp_cache_scan_objects_total, and the cp_* queue-wait/work/takeover-phase
# histograms, so an SLO or alert referencing them resolves here too.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== slo definition lint (delegated to odh_kubeflow_tpu.analysis) =="
python -m odh_kubeflow_tpu.analysis --slo-lint

echo "== slo contract tests =="
python -m pytest tests/ -q -m "slo and not slow" -p no:cacheprovider
