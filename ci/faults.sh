#!/usr/bin/env bash
# Fault-injection CI lane: the deterministic fault tests (tests/test_faults.py,
# marker `faults`), rerun in a stress loop to flush out flaky recovery paths.
#
# Recovery code is exactly the code whose bugs hide behind timing: a watch
# re-establishment that loses an event only fails when the drop lands in a
# 10ms window. One green run proves little; N consecutive green runs with a
# pinned hash seed (dict iteration order stable across runs) is the lane's
# actual signal. The injection schedules themselves are seeded/counted —
# no wall-clock randomness — so a failure here reproduces locally with the
# same command.
#
#   ./ci/faults.sh            # default: 3 iterations
#   FAULTS_REPEAT=10 ./ci/faults.sh
#   FAULTS_REPEAT=1 ./ci/faults.sh -k watch   # forward extra pytest args
set -euo pipefail
cd "$(dirname "$0")/.."

REPEAT="${FAULTS_REPEAT:-3}"
export PYTHONHASHSEED="${PYTHONHASHSEED:-0}"
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

for i in $(seq 1 "$REPEAT"); do
    echo "=== faults lane: iteration $i/$REPEAT (PYTHONHASHSEED=$PYTHONHASHSEED) ==="
    python -m pytest tests/test_faults.py -q -m "faults and not slow" \
        -p no:cacheprovider -p no:randomly "$@"
done

# one more pass with the runtime race detector armed (utils/racecheck.py):
# instrumented locks raise deterministically on any acquisition-order
# inversion the chaos run exercises, and the informer cache's write barrier
# raises on in-place mutation of cache-owned objects — every chaos soak
# doubles as a race run
echo "=== faults lane: RACECHECK=1 iteration ==="
RACECHECK=1 python -m pytest tests/test_faults.py -q -m "faults and not slow" \
    -p no:cacheprovider -p no:randomly "$@"

# ...and one with the runtime INVARIANT monitor armed (utils/invcheck.py,
# ISSUE 8): every store write re-judges machine-transition legality, the
# pool-claim CAS contract, and the chip budget — a lost-update race that
# lands an undeclared state transition fails AT THE WRITE, not as a
# mysteriously wedged notebook three minutes later
echo "=== faults lane: INVCHECK=1 iteration ==="
INVCHECK=1 python -m pytest tests/test_faults.py -q -m "faults and not slow" \
    -p no:cacheprovider -p no:randomly "$@"

# slice chaos lane (ISSUE 4): preemption / chip / ICI faults through the
# repair path — the seeded slice "bad day" asserts the acceptance invariant
# (every faulted notebook returns to Ready with a slice.repair trace, or
# ends in an explicit RepairFailed event; zero silently stuck), rerun under
# the same stress loop + one RACECHECK=1 iteration. Since ISSUE 5 the soak
# also asserts the flight recorder captured >= 1 slice-degraded incident
# bundle — every iteration below doubles as that observability gate.
for i in $(seq 1 "$REPEAT"); do
    echo "=== slice chaos lane: iteration $i/$REPEAT ==="
    python -m pytest tests/test_slice_repair.py -q -m "slice_repair and not slow" \
        -p no:cacheprovider -p no:randomly "$@"
done
echo "=== slice chaos lane: RACECHECK=1 iteration ==="
RACECHECK=1 python -m pytest tests/test_slice_repair.py -q -m "slice_repair and not slow" \
    -p no:cacheprovider -p no:randomly "$@"
echo "=== slice chaos lane: INVCHECK=1 iteration ==="
INVCHECK=1 python -m pytest tests/test_slice_repair.py -q -m "slice_repair and not slow" \
    -p no:cacheprovider -p no:randomly "$@"

# pool-churn soak lane (ISSUE 7): the suspend/resume/reclaim cycle under the
# seeded pool bad day (warm-host poisoning + reclaim-race conflict storms +
# the control-plane schedule) — asserts no notebook is ever silently stuck
# in Resuming, canary CRs are never reclaim victims, and oversubscription
# degrades by suspending, never by RepairFailed/ResumeFailed
for i in $(seq 1 "$REPEAT"); do
    echo "=== pool churn lane: iteration $i/$REPEAT ==="
    python -m pytest tests/test_suspend.py -q -m "suspend and not slow" \
        -p no:cacheprovider -p no:randomly "$@"
done
echo "=== pool churn lane: RACECHECK=1 iteration ==="
RACECHECK=1 python -m pytest tests/test_suspend.py -q -m "suspend and not slow" \
    -p no:cacheprovider -p no:randomly "$@"
echo "=== pool churn lane: INVCHECK=1 iteration ==="
INVCHECK=1 python -m pytest tests/test_suspend.py -q -m "suspend and not slow" \
    -p no:cacheprovider -p no:randomly "$@"

# serving lane (ISSUE 9): the InferenceEndpoint machine under faults — the
# serving slice preempted mid-stream (requests drain or fail fast, the
# endpoint machine owns recovery and the repair controller never fights it),
# promotion warm-binds, drain/terminate, restore-verification mismatch as an
# explicit LoadFailed — rerun under the stress loop + one RACECHECK=1 and
# one INVCHECK=1 iteration (the inference machine is INVCHECK-covered via
# analysis/machines.py, kind-aware)
for i in $(seq 1 "$REPEAT"); do
    echo "=== serving lane: iteration $i/$REPEAT ==="
    python -m pytest tests/test_serving.py -q -m "serving and not slow" \
        -p no:cacheprovider -p no:randomly "$@"
done
echo "=== serving lane: RACECHECK=1 iteration ==="
RACECHECK=1 python -m pytest tests/test_serving.py -q -m "serving and not slow" \
    -p no:cacheprovider -p no:randomly "$@"
echo "=== serving lane: INVCHECK=1 iteration ==="
INVCHECK=1 python -m pytest tests/test_serving.py -q -m "serving and not slow" \
    -p no:cacheprovider -p no:randomly "$@"

# ...and one with the compile/transfer/donation guard armed (utils/
# jaxguard.py, ISSUE 12): the decode burst must hold its declared compile
# budget with ZERO in-region host transfers, prefill stays within its one
# budgeted fetch, and every donated KV-cache buffer must actually alias —
# the serving soak doubles as a compilation-discipline run
echo "=== serving lane: JAXGUARD=1 iteration ==="
JAXGUARD=1 python -m pytest tests/test_serving.py -q -m "serving and not slow" \
    -p no:cacheprovider -p no:randomly "$@"

# ...and one with the deployment-surface guard armed (utils/deployguard.py,
# ISSUE 14): every typed-client call attributes (flow, verb, kind) and a
# manager-flow request exceeding the declared RBAC — or lease traffic
# misattributed onto a workload flow — raises RBACDriftError AT the call
echo "=== serving lane: DEPLOYGUARD=1 iteration ==="
DEPLOYGUARD=1 python -m pytest tests/test_serving.py -q -m "serving and not slow" \
    -p no:cacheprovider -p no:randomly "$@"

# ...and one with the continuous profiler armed (utils/profiler.py,
# ISSUE 15): every decode burst decomposes into its admit/prefill/scan/
# batched_drain/emit phases under fault churn — the soak proves the frame
# accounting survives exception paths (a failed burst must not leak a
# frame and skew every later where_time_went breakdown)
echo "=== serving lane: PROFILE=1 iteration ==="
PROFILE=1 python -m pytest tests/test_serving.py -q -m "serving and not slow" \
    -p no:cacheprovider -p no:randomly "$@"

# job lane (ISSUE 10): the gang-scheduled TPUJob machine under faults —
# host preemption mid-Running (checkpoint-preempt-requeue, resume from the
# acked step), the reclaimer taking a batch slice for an interactive
# arrival, sebulba dual-gang admission atomicity, and the seeded mixed
# bad-day soak asserting no job is ever silently stuck in Admitted/
# Preempted — rerun under the stress loop + one RACECHECK=1 and one
# INVCHECK=1 iteration (the job machine is INVCHECK-covered kind-aware via
# analysis/machines.py)
for i in $(seq 1 "$REPEAT"); do
    echo "=== job lane: iteration $i/$REPEAT ==="
    python -m pytest tests/test_job.py -q -m "job and not slow" \
        -p no:cacheprovider -p no:randomly "$@"
done
echo "=== job lane: RACECHECK=1 iteration ==="
RACECHECK=1 python -m pytest tests/test_job.py -q -m "job and not slow" \
    -p no:cacheprovider -p no:randomly "$@"
echo "=== job lane: INVCHECK=1 iteration ==="
INVCHECK=1 python -m pytest tests/test_job.py -q -m "job and not slow" \
    -p no:cacheprovider -p no:randomly "$@"

# the job lane's generate()/train-step paths run under the same guard: any
# jitted entry point that retraces per call or silently drops a donation
# fails here (ISSUE 12)
echo "=== job lane: JAXGUARD=1 iteration ==="
JAXGUARD=1 python -m pytest tests/test_job.py -q -m "job and not slow" \
    -p no:cacheprovider -p no:randomly "$@"

echo "=== job lane: DEPLOYGUARD=1 iteration ==="
DEPLOYGUARD=1 python -m pytest tests/test_job.py -q -m "job and not slow" \
    -p no:cacheprovider -p no:randomly "$@"

# overload lane (ISSUE 13): the apiserver_overload schedule (429 bursts +
# latency injection + store throttles) under a TPUJob admission storm
# against the flow-controlled, sharded control plane — asserts the storm is
# shed at the batch priority level, exempt (lease) traffic is NEVER starved,
# zero silently-stuck objects, and the sharding/fencing contract holds
# (stand-down before the next write on lease loss, dead-elector healthz,
# fenced retries rejected not duplicated) — rerun under the stress loop +
# one RACECHECK=1 and one INVCHECK=1 iteration
for i in $(seq 1 "$REPEAT"); do
    echo "=== overload lane: iteration $i/$REPEAT ==="
    python -m pytest tests/test_overload.py tests/test_sharding.py tests/test_flowcontrol.py \
        -q -m "(overload or flowcontrol) and not slow" \
        -p no:cacheprovider -p no:randomly "$@"
done
echo "=== overload lane: RACECHECK=1 iteration ==="
RACECHECK=1 python -m pytest tests/test_overload.py tests/test_sharding.py tests/test_flowcontrol.py \
    -q -m "(overload or flowcontrol) and not slow" \
    -p no:cacheprovider -p no:randomly "$@"
echo "=== overload lane: INVCHECK=1 iteration ==="
INVCHECK=1 python -m pytest tests/test_overload.py tests/test_sharding.py tests/test_flowcontrol.py \
    -q -m "(overload or flowcontrol) and not slow" \
    -p no:cacheprovider -p no:randomly "$@"
# CPPROFILE=1 (ISSUE 20): the control-plane profiler rides the widest
# informer->workqueue->reconcile churn in the suite — cause stamping, scan
# accounting and takeover tracking must never deadlock or change overload/
# fencing semantics while armed
echo "=== overload lane: CPPROFILE=1 iteration ==="
CPPROFILE=1 python -m pytest tests/test_overload.py tests/test_sharding.py tests/test_flowcontrol.py \
    -q -m "(overload or flowcontrol) and not slow" \
    -p no:cacheprovider -p no:randomly "$@"

# the overload lane's DEPLOYGUARD=1 iteration doubles as the surface
# recorder: the shard-failover storm exercises the widest (flow, verb, kind)
# surface in the suite, and the dumped artifact feeds
# `ci/analysis.sh --deploy` (DEPLOY_SURFACE=...) for runtime-confident
# stale-RBAC findings. Misattributed lease writes after failover — a lease
# renewal issued from a workload flow instead of the elector's exempt
# client — are a hard RBACDriftError here, not a silent fairness leak.
echo "=== overload lane: DEPLOYGUARD=1 iteration (surface artifact) ==="
DEPLOYGUARD=1 DEPLOYGUARD_SURFACE_OUT="${DEPLOYGUARD_SURFACE_OUT:-}" \
    python -m pytest tests/test_overload.py tests/test_sharding.py tests/test_flowcontrol.py \
    -q -m "(overload or flowcontrol) and not slow" \
    -p no:cacheprovider -p no:randomly "$@"

# router lane (ISSUE 16): the serving-fleet resilience surface — breaker
# ejection/re-admission, cross-replica retries, hedging cancels the loser,
# route-first drain with zero dropped in-flight requests, cold-wake, the
# SLO-burn autoscaler's stabilization damping + min-replicas floor, and the
# seeded router bad day's determinism — rerun under the stress loop + one
# RACECHECK=1, one INVCHECK=1, and one DEPLOYGUARD=1 iteration (the router's
# cold-wake patch and the autoscaler sweep are manager flows, so their
# traffic is RBAC-enforced at the call)
for i in $(seq 1 "$REPEAT"); do
    echo "=== router lane: iteration $i/$REPEAT ==="
    python -m pytest tests/test_router.py tests/test_autoscaler.py \
        -q -m "(router or autoscaler) and not slow" \
        -p no:cacheprovider -p no:randomly "$@"
done
echo "=== router lane: RACECHECK=1 iteration ==="
RACECHECK=1 python -m pytest tests/test_router.py tests/test_autoscaler.py \
    -q -m "(router or autoscaler) and not slow" \
    -p no:cacheprovider -p no:randomly "$@"
echo "=== router lane: INVCHECK=1 iteration ==="
INVCHECK=1 python -m pytest tests/test_router.py tests/test_autoscaler.py \
    -q -m "(router or autoscaler) and not slow" \
    -p no:cacheprovider -p no:randomly "$@"
echo "=== router lane: DEPLOYGUARD=1 iteration ==="
DEPLOYGUARD=1 python -m pytest tests/test_router.py tests/test_autoscaler.py \
    -q -m "(router or autoscaler) and not slow" \
    -p no:cacheprovider -p no:randomly "$@"

echo "=== faults lane: $REPEAT/$REPEAT iterations green (+1 racecheck +1 invcheck, +1 jaxguard +1 deployguard on serving/job/overload, incl. slice chaos + pool churn + serving + job + overload + router) ==="
