#!/usr/bin/env bash
# Manifest-drift gate (the reference's ci/generate_code.sh: regenerate with
# controller-gen and fail on git diff; here the generator is
# odh_kubeflow_tpu.deploy and the tree is deploy/).
set -euo pipefail
cd "$(dirname "$0")/.."

python -m odh_kubeflow_tpu.deploy generate --root deploy

if ! git diff --quiet -- deploy/; then
  echo "ERROR: deploy/ manifests drifted from the generators." >&2
  echo "Run: python -m odh_kubeflow_tpu.deploy generate" >&2
  git --no-pager diff --stat -- deploy/ >&2
  exit 1
fi
echo "deploy/ manifests up to date"
