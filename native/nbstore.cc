// nbstore: the native storage core of the in-process control plane.
//
// The reference's control plane is a compiled Go binary on top of etcd
// (kube-apiserver); this library is the equivalent storage engine for the
// TPU build's in-process cluster: canonical-JSON object buckets with a
// monotonically increasing resourceVersion counter and snapshot-isolated
// reads (every get returns an independent buffer, so Python-side mutation
// can never corrupt stored state). Admission, finalizer semantics, GC and
// watch fan-out stay in Python (cluster/store.py); this owns the bytes.
//
// C ABI only — consumed via ctypes (no pybind11 in the image).
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <unordered_map>

namespace {

struct Entry {
  std::string json;
  std::string ns;      // metadata.namespace, extracted by the Python binding
  std::string labels;  // "k\x1Fv\x1Fk2\x1Fv2" pairs, unit-separated
};

struct Bucket {
  // std::map keeps keys ordered, so list() is deterministic without a sort.
  std::map<std::string, Entry> objs;
};

struct Handle {
  std::mutex mu;
  uint64_t rv = 0;
  std::unordered_map<std::string, Bucket> buckets;
};

// Record separator between JSON docs in list/keys output (never appears in
// JSON text, so no escaping is needed).
constexpr char kSep = '\x1e';

char* dup_buf(const std::string& s, int64_t* out_len) {
  char* p = static_cast<char*>(std::malloc(s.size() ? s.size() : 1));
  if (p != nullptr && !s.empty()) std::memcpy(p, s.data(), s.size());
  *out_len = static_cast<int64_t>(s.size());
  return p;
}

// selector and labels are "k\x1Fv\x1Fk2\x1Fv2"; every selector pair must
// appear in labels (subset match, the match_labels semantics).
constexpr char kUnit = '\x1f';

bool labels_match(const std::string& labels, const std::string& selector) {
  size_t pos = 0;
  while (pos < selector.size()) {
    size_t key_end = selector.find(kUnit, pos);
    if (key_end == std::string::npos) return false;  // malformed: odd fields
    size_t val_end = selector.find(kUnit, key_end + 1);
    if (val_end == std::string::npos) val_end = selector.size();
    const std::string pair = selector.substr(pos, val_end - pos);
    // find `pair` in labels aligned to pair boundaries
    bool found = false;
    size_t lpos = 0;
    while (lpos < labels.size()) {
      size_t lkey_end = labels.find(kUnit, lpos);
      if (lkey_end == std::string::npos) break;
      size_t lval_end = labels.find(kUnit, lkey_end + 1);
      if (lval_end == std::string::npos) lval_end = labels.size();
      if (labels.compare(lpos, lval_end - lpos, pair) == 0) {
        found = true;
        break;
      }
      lpos = lval_end + 1;
    }
    if (!found) return false;
    pos = val_end + 1;
  }
  return true;
}

}  // namespace

extern "C" {

enum NbsStatus {
  NBS_OK = 0,
  NBS_NOT_FOUND = 1,
  NBS_EXISTS = 2,
  NBS_NO_MEM = 3,
};

void* nbs_new() { return new (std::nothrow) Handle(); }

void nbs_destroy(void* h) { delete static_cast<Handle*>(h); }

uint64_t nbs_next_rv(void* h) {
  auto* s = static_cast<Handle*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  return ++s->rv;
}

// Unconditional upsert (create-vs-update preconditions are enforced by the
// Python store, which owns admission + optimistic-concurrency semantics).
// ns/labels are pre-extracted metadata used for native-side list filtering;
// labels is "k\x1Fv\x1Fk2\x1Fv2" (unit-separated pairs).
int nbs_put(void* h, const char* bucket, const char* key, const char* json,
            int64_t len, const char* ns, const char* labels) {
  auto* s = static_cast<Handle*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  try {
    // Build the entry fully before touching the map: a bad_alloc mid-assign
    // must not leave a phantom empty entry behind (a later get would feed
    // b"" to json.loads and a create-retry would see AlreadyExists).
    Entry e;
    e.json.assign(json, static_cast<size_t>(len));
    e.ns = ns ? ns : "";
    e.labels = labels ? labels : "";
    s->buckets[bucket].objs[key] = std::move(e);
  } catch (const std::bad_alloc&) {
    // bad_alloc must not cross the C ABI (std::terminate); report it so the
    // Python side can raise MemoryError instead of aborting the process.
    return NBS_NO_MEM;
  }
  return NBS_OK;
}

int nbs_get(void* h, const char* bucket, const char* key, char** out,
            int64_t* out_len) {
  auto* s = static_cast<Handle*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  auto b = s->buckets.find(bucket);
  if (b == s->buckets.end()) return NBS_NOT_FOUND;
  auto it = b->second.objs.find(key);
  if (it == b->second.objs.end()) return NBS_NOT_FOUND;
  *out = dup_buf(it->second.json, out_len);
  return *out ? NBS_OK : NBS_NO_MEM;
}

int nbs_pop(void* h, const char* bucket, const char* key, char** out,
            int64_t* out_len) {
  auto* s = static_cast<Handle*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  auto b = s->buckets.find(bucket);
  if (b == s->buckets.end()) return NBS_NOT_FOUND;
  auto it = b->second.objs.find(key);
  if (it == b->second.objs.end()) return NBS_NOT_FOUND;
  *out = dup_buf(it->second.json, out_len);
  if (*out == nullptr) return NBS_NO_MEM;
  b->second.objs.erase(it);
  return NBS_OK;
}

int nbs_contains(void* h, const char* bucket, const char* key) {
  auto* s = static_cast<Handle*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  auto b = s->buckets.find(bucket);
  return b != s->buckets.end() && b->second.objs.count(key) ? 1 : 0;
}

int64_t nbs_count(void* h, const char* bucket) {
  auto* s = static_cast<Handle*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  auto b = s->buckets.find(bucket);
  return b == s->buckets.end() ? 0 : static_cast<int64_t>(b->second.objs.size());
}

// All values in key order, '\x1e'-separated, as one snapshot buffer.
// has_ns != 0 filters to Entry.ns == ns; selector (same unit-separated pair
// encoding as put) requires every pair to be present in Entry.labels — the
// match happens here so Python never deserializes non-matching objects.
int nbs_list(void* h, const char* bucket, int has_ns, const char* ns,
             const char* selector, char** out, int64_t* out_len) {
  auto* s = static_cast<Handle*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  std::string joined;
  try {
    auto b = s->buckets.find(bucket);
    if (b != s->buckets.end()) {
      const std::string want_ns = ns ? ns : "";
      const std::string sel = selector ? selector : "";
      for (const auto& kv : b->second.objs) {
        const Entry& e = kv.second;
        if (has_ns && e.ns != want_ns) continue;
        if (!sel.empty() && !labels_match(e.labels, sel)) continue;
        if (!joined.empty()) joined.push_back(kSep);
        joined += e.json;
      }
    }
  } catch (const std::bad_alloc&) {
    // the concatenation buffer is the library's largest allocation — OOM here
    // must surface as NBS_NO_MEM, not std::terminate across the C ABI
    return NBS_NO_MEM;
  }
  *out = dup_buf(joined, out_len);
  return *out ? NBS_OK : NBS_NO_MEM;
}

// All bucket names that currently hold at least one object.
int nbs_bucket_names(void* h, char** out, int64_t* out_len) {
  auto* s = static_cast<Handle*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  std::string joined;
  try {
    for (const auto& kv : s->buckets) {
      if (kv.second.objs.empty()) continue;
      if (!joined.empty()) joined.push_back(kSep);
      joined += kv.first;
    }
  } catch (const std::bad_alloc&) {
    return NBS_NO_MEM;
  }
  *out = dup_buf(joined, out_len);
  return *out ? NBS_OK : NBS_NO_MEM;
}

void nbs_buf_free(char* p) { std::free(p); }

}  // extern "C"
