"""Slice-repair controller: survive the accelerator layer.

PR 1 hardened the control plane; this controller hardens the part that
actually fails in production TPU fleets — the slice itself. Host preemptions
and maintenance events, dead chips, and degraded ICI links take down a whole
multi-host slice at once, and without repair a preempted host leaves the
StatefulSet half-dead and the Notebook permanently Ready=False.

State machine (durable in annotations — SURVEY §5: the API server is the
database — mirrored into the `Degraded` condition for humans):

    Ready ──fault──> Degraded ──evict──> Repairing ──mesh ready──> Ready
                        │                    │
                        │ (checkpoint-       │ (bounded, jittered retry
                        │  before-evict      │  while capacity recovers)
                        │  window)           └──attempts exhausted──> RepairFailed

Fault detection, two layers:
- **node-level**: a pod's node carries the preemption taint / maintenance
  notice or has gone Ready=False (cluster/faults.py PREEMPTION_TAINT_KEY) —
  trusted immediately, a taint is not a transient,
- **device-level**: the `TPUHealthy` condition the probe gate aggregates from
  per-host /tpu/readiness reports (controllers/probe_status.py). ChipFailure/
  ICIDegraded are affirmative measurements from reachable agents and trigger
  immediately (when every pod is Ready — the devices are sick, not the pods);
  HostUnreachable must persist for a dwell before it counts, so a transient
  probe partition never evicts a healthy gang.

Repair path: coordinate a checkpoint-before-evict window (annotation-signaled;
every host's /tpu/checkpoint hook is driven — probe/agent.py wired to
models/checkpoint.py), evict the whole gang, and let the scheduler re-place it
all-or-nothing — landing in a different node pool of the same topology when
the original pool is short. While capacity recovers the controller retries
with bounded, jittered backoff; exhaustion is an explicit terminal
`RepairFailed` event, never a silently stuck notebook. The restarted workload
re-runs jax.distributed.initialize() and restores from the checkpoint
(parallel/distributed.py reinitialize_after_repair + models/checkpoint.py).

Telemetry closes the loop: interruption counters, the MTTR histogram, the
goodput integrator (tpu/telemetry.py) and `slice.repair` trace spans joined
to the notebook's readiness trace, so one preemption→ready-again episode is
one connected trace.
"""
from __future__ import annotations

import json
import logging
import random
import time
from typing import Dict, List, Optional, Tuple

from ..api.core import Node, Pod, emit_deduped_event
from ..api.notebook import Notebook
from ..apimachinery import (
    NotFoundError,
    now_rfc3339,
    parse_time,
    rfc3339_precise,
)
from ..cluster.client import retry_on_conflict
from ..cluster.faults import MAINTENANCE_WINDOW_ANNOTATION, PREEMPTION_TAINT_KEY
from ..runtime.controller import Request, Result
from ..runtime.flightrecorder import recorder
from ..runtime.manager import Manager
from ..tpu import plan_slice, telemetry
from ..utils.tracing import record_span
from . import constants as C
from .conditions import condition_is, get_condition, write_condition
from .config import Config
from .culling import HTTPGet, _default_http_get
from .notebook import per_ordinal_probe_urls

log = logging.getLogger(__name__)

# annotation values of the repair-state machine
STATE_DEGRADED = "degraded"
STATE_REPAIRING = "repairing"
STATE_FAILED = "failed"

# HostUnreachable (probe-measured absence) must persist this long before it
# becomes a repair trigger — affirmative faults (taints, chip/ICI reports)
# need no dwell. Overridable per-instance for tests.
DEFAULT_UNREACHABLE_DWELL_S = 15.0


class SliceRepairController:
    def __init__(
        self,
        manager: Manager,
        config: Optional[Config] = None,
        http_get: Optional[HTTPGet] = None,
    ):
        self.manager = manager
        self.client = manager.client
        # repair decisions and state transitions read fresh (the informer
        # cache after our own annotation writes is stale exactly in the
        # write-to-dispatch window)
        self.api_reader = manager.api_reader
        self.config = config or Config()
        self.http_get = http_get or _default_http_get
        self.unreachable_dwell_s = DEFAULT_UNREACHABLE_DWELL_S
        # in-memory only (best-effort across restarts; the durable machine
        # lives in annotations): goodput integrator anchors, next-attempt
        # deadlines, evict timestamps for the reschedule trace span, and
        # per-episode checkpoint acks (ordinal -> acked step) so a host that
        # saved once is not re-driven every poll of the window
        self._last_seen: Dict[str, float] = {}
        self._next_attempt: Dict[str, float] = {}
        self._evicted_at: Dict[str, float] = {}
        self._ckpt_acked: Dict[str, Dict[int, Optional[int]]] = {}
        # notebooks currently inside a repair episode, mirrored into the
        # tpu_slice_repairs_in_progress gauge (the alert manager's
        # slice-repair inhibitor reads it)
        self._in_repair: set = set()

    def setup(self) -> None:
        def pod_is_labeled(ev: str, obj: dict, old: Optional[dict]) -> bool:
            return C.NOTEBOOK_NAME_LABEL in obj.get("metadata", {}).get("labels", {})

        def map_pod(obj: dict) -> List[tuple]:
            meta = obj.get("metadata", {})
            name = meta.get("labels", {}).get(C.NOTEBOOK_NAME_LABEL)
            return [(meta.get("namespace", ""), name)] if name else []

        def map_node(obj: dict) -> List[tuple]:
            """Node events (taint landing, drain, restore) -> the notebooks
            whose pods sit on that node."""
            node_name = obj.get("metadata", {}).get("name", "")
            out = set()
            for p in self.client.list(Pod):
                if p.spec.node_name != node_name:
                    continue
                nb = p.metadata.labels.get(C.NOTEBOOK_NAME_LABEL)
                if nb:
                    out.add((p.metadata.namespace, nb))
            return sorted(out)

        (
            self.manager.builder("slice-repair")
            .for_(Notebook)
            .watches(Node, map_node)
            .watches(Pod, map_pod, predicate=pod_is_labeled)
            .with_workers(self.config.max_concurrent_reconciles)
            .complete(self.reconcile)
        )

    # ---------- reconcile ----------

    def reconcile(self, req: Request) -> Optional[Result]:
        try:
            # FRESH read: the state machine transitions on its own annotation
            # writes, and the cached view is stale exactly in the write-to-
            # informer-dispatch window — a cached read could re-enter a state
            # and double-count the interruption
            nb = self.api_reader.get(Notebook, req.namespace, req.name)
        except NotFoundError:
            self._forget(req.key)
            return None
        if nb.metadata.deletion_timestamp:
            self._forget(req.key)
            return None
        if nb.spec.tpu is None or not nb.spec.tpu.accelerator:
            return None  # CPU notebook: no slice to repair

        ann = nb.metadata.annotations
        state = ann.get(C.TPU_REPAIR_STATE_ANNOTATION, "")
        # gauge for the alert manager's inhibitor: an ACTIVE episode
        # (degraded/repairing) inhibits readiness-category alerts; terminal
        # RepairFailed does not — a permanently broken slice must page
        self._track_repair(req.key, state in (STATE_DEGRADED, STATE_REPAIRING))

        if C.STOP_ANNOTATION in ann:
            # stopped (user or culler): a scaled-away slice has nothing to
            # repair — abort any in-flight episode explicitly
            if state:
                self._patch_annotations(nb, self._clear_updates())
                write_condition(
                    self.client, self.api_reader, nb,
                    C.TPU_DEGRADED_CONDITION, "False", "Stopped",
                    "repair aborted: notebook stopped",
                )
            self._forget(req.key)
            return None

        if ann.get(C.TPU_SUSPEND_STATE_ANNOTATION):
            # suspend machine owns the slice (resuming: stop already cleared
            # but the warm-pool bind is in flight). A half-started resume
            # looks exactly like HostUnreachable — "repairing" (evicting) it
            # would race the suspend controller for the same warm slice.
            # Contract (ARCHITECTURE.md): repair waits; the suspend machine's
            # own bounded attempts + the reclaimer handle a wedged resume.
            if state:
                self._patch_annotations(nb, self._clear_updates())
                write_condition(
                    self.client, self.api_reader, nb,
                    C.TPU_DEGRADED_CONDITION, "False", "Suspended",
                    "repair aborted: suspend/resume machine owns the slice",
                )
            self._forget(req.key)
            return None

        now = time.time()
        # goodput integrator: every reconcile extends tracked lifetime; time
        # spent in any repair state is downtime
        last = self._last_seen.get(req.key)
        self._last_seen[req.key] = now
        if last is not None and now > last:
            telemetry.goodput.observe(
                now - last, downtime_s=(now - last) if state else 0.0
            )

        shape = plan_slice(
            nb.spec.tpu.accelerator, nb.spec.tpu.topology, nb.spec.tpu.chips
        )
        pods = [
            p
            for p in self.client.list(
                Pod,
                namespace=nb.metadata.namespace,
                labels={C.NOTEBOOK_NAME_LABEL: nb.metadata.name},
            )
            if not p.metadata.deletion_timestamp
        ]
        threat = self._detect(nb, pods, shape, now)

        # The pod-condition mirror (notebook.py) preserves repair-owned
        # conditions from ITS cached snapshot, so a stale snapshot can
        # resurrect an older Degraded value over a fresh write. Ownership is
        # therefore level-triggered: every pass re-asserts the condition the
        # current state implies (a no-op write when it already matches).
        if not state:
            if threat is None:
                cur = get_condition(nb, C.TPU_DEGRADED_CONDITION)
                if cur is not None and cur.status == "True":
                    self._assert_degraded(
                        nb, "False", "Repaired",
                        "slice healthy; stale Degraded condition healed",
                    )
                # steady-state heartbeat (probe-gate idiom): keeps detection
                # alive when events are missed AND gives the goodput
                # integrator fair samples of healthy time — purely
                # event-driven sampling clusters during repair and would
                # overstate downtime
                return Result(
                    requeue_after=max(1.0, self.config.readiness_probe_period_s * 6)
                )
            return self._enter_degraded(nb, threat, now)
        if state == STATE_DEGRADED:
            self._assert_degraded(
                nb, "True", nb.metadata.annotations.get(
                    C.TPU_REPAIR_CAUSE_ANNOTATION, "SliceDegraded"
                ),
                "slice degraded; checkpoint-before-evict window open",
            )
            return self._run_checkpoint_window(nb, shape, pods, now, req)
        if state == STATE_REPAIRING:
            self._assert_degraded(
                nb, "True", "Repairing",
                "gang evicted; waiting for all-or-nothing re-placement",
            )
            return self._await_repair(nb, shape, pods, threat, now, req)
        if state == STATE_FAILED:
            # terminal — but not a dead end: if the slice comes back anyway
            # (capacity restored, operator intervention), close the episode
            if self._slice_healthy(nb, pods, shape, threat):
                return self._complete(nb, now, req, after_failure=True)
            self._assert_degraded(
                nb, "True", "RepairFailed",
                "repair abandoned; operator attention required",
            )
            return None
        log.warning("unknown repair state %r on %s; clearing", state, req.key)
        self._patch_annotations(nb, {C.TPU_REPAIR_STATE_ANNOTATION: None})
        return Result(requeue_after=0.05)

    # ---------- detection ----------

    def _detect(
        self, nb: Notebook, pods: List[Pod], shape, now: float
    ) -> Optional[Tuple[str, str, Optional[float]]]:
        """(cause, message, evict_by_ts) or None. Node-level signals always
        count; device-level signals (TPUHealthy) per the dwell rules above."""
        for p in pods:
            if not p.spec.node_name:
                continue
            try:
                node = self.client.get(Node, "", p.spec.node_name)
            except NotFoundError:
                return (
                    "HostPreempted",
                    f"node {p.spec.node_name} is gone",
                    None,
                )
            tainted = any(
                t.get("key") == PREEMPTION_TAINT_KEY
                for t in node.spec.get("taints", [])
            )
            not_ready = any(
                c.type == "Ready" and c.status == "False"
                for c in node.status.conditions
            )
            if tainted or not_ready:
                evict_by = None
                notice = node.metadata.annotations.get(
                    MAINTENANCE_WINDOW_ANNOTATION, ""
                )
                if notice:
                    try:
                        evict_by = parse_time(notice).timestamp()
                    except ValueError:
                        evict_by = None
                return (
                    "HostPreempted",
                    f"host {node.metadata.name} "
                    + ("has a maintenance/preemption taint" if tainted else "is NotReady"),
                    evict_by,
                )

        cond = get_condition(nb, C.TPU_HEALTHY_CONDITION)
        if cond is None or cond.status != "False":
            return None
        ready_pods = sum(1 for p in pods if p.is_ready())
        reason = cond.reason or "TPUUnhealthy"
        if reason in ("ChipFailure", "ICIDegraded") and ready_pods >= shape.hosts:
            # affirmative device fault measured by reachable agents on a
            # fully-Ready gang: trust it immediately
            return reason, cond.message or reason, None
        persisted = 0.0
        if cond.last_transition_time:
            try:
                persisted = now - parse_time(cond.last_transition_time).timestamp()
            except ValueError:
                persisted = 0.0
        if persisted >= self.unreachable_dwell_s:
            # probe-measured absence (crashed agent, wedged host, half-dead
            # gang) that outlived the dwell: no longer a transient
            return (
                "HostUnreachable",
                cond.message or "hosts unreachable beyond the dwell window",
                None,
            )
        return None

    def _slice_healthy(
        self, nb: Notebook, pods: List[Pod], shape, threat
    ) -> bool:
        return (
            threat is None
            and nb.status.tpu is not None
            and nb.status.tpu.mesh_ready
            and condition_is(nb, C.TPU_HEALTHY_CONDITION, "True")
            and sum(1 for p in pods if p.is_ready()) >= shape.hosts
        )

    # ---------- state transitions ----------

    def _enter_degraded(
        self, nb: Notebook, threat: Tuple[str, str, Optional[float]], now: float
    ) -> Result:
        cause, message, evict_by = threat
        # fresh episode: no checkpoint acks carried over from a prior one
        self._ckpt_acked.pop(
            f"{nb.metadata.namespace}/{nb.metadata.name}", None
        )
        deadline = now + self.config.checkpoint_window_s
        if evict_by is not None:
            # the host is going away at evict_by regardless: the checkpoint
            # window must finish before the platform drains under us
            deadline = min(deadline, evict_by)
        self._patch_annotations(
            nb,
            {
                C.TPU_REPAIR_STATE_ANNOTATION: STATE_DEGRADED,
                C.TPU_REPAIR_STARTED_ANNOTATION: rfc3339_precise(now),
                C.TPU_REPAIR_CAUSE_ANNOTATION: cause,
                C.TPU_REPAIR_ATTEMPTS_ANNOTATION: "0",
                C.TPU_CHECKPOINT_REQUEST_ANNOTATION: rfc3339_precise(deadline),
            },
        )
        write_condition(
            self.client, self.api_reader, nb,
            C.TPU_DEGRADED_CONDITION, "True", cause, message,
        )
        self._emit_event(nb, "SliceDegraded", f"slice degraded ({cause}): {message}")
        telemetry.slice_interruptions_total.inc(cause=cause)
        key = f"{nb.metadata.namespace}/{nb.metadata.name}"
        self._track_repair(key, True)
        # flight recorder: the Degraded entry IS an incident — snapshot the
        # ring + CR/pod state now, while the evidence is still in the buffer
        recorder.record(
            "transition", machine="slice-repair", notebook=key,
            state=STATE_DEGRADED, cause=cause,
        )
        recorder.snapshot(
            "slice-degraded", subject=key, client=self.client,
            notebooks=[(nb.metadata.namespace, nb.metadata.name)],
            extra={"cause": cause, "message": message},
        )
        log.warning(
            "slice degraded: %s/%s (%s) — checkpoint window until %s",
            nb.metadata.namespace, nb.metadata.name, cause, rfc3339_precise(deadline),
        )
        return Result(requeue_after=0.01)

    def _run_checkpoint_window(
        self, nb: Notebook, shape, pods: List[Pod], now: float, req: Request
    ) -> Result:
        ann = nb.metadata.annotations
        deadline = now
        try:
            deadline = parse_time(
                ann.get(C.TPU_CHECKPOINT_REQUEST_ANNOTATION, "")
            ).timestamp()
        except ValueError:
            pass
        ready_pods = [p for p in pods if p.is_ready()]
        # which ORDINALS are ready right now (pod {sts}-{i}): only those can
        # ack, and every one of them must before an early proceed — counting
        # acks against a shifting ready-count could skip a live host's save
        ready_ordinals = set()
        for p in ready_pods:
            try:
                ready_ordinals.add(int(p.metadata.name.rsplit("-", 1)[1]))
            except (IndexError, ValueError):
                continue
        # drive only ready, not-yet-acked ordinals: a saved host must not
        # re-save on every poll, and a dead host's connect timeout must not
        # be paid every poll either
        acked = self._ckpt_acked.setdefault(req.key, {})
        pending = sorted(ready_ordinals - set(acked))
        if pending:
            for ordinal, ack in self._checkpoint_sweep(nb, shape.hosts, pending):
                if ack and ack.get("saved"):
                    acked[ordinal] = ack.get("step")
        # proceed when every currently-ready host acked, when nothing is
        # left to checkpoint, or when the window lapses — never block the
        # evict past the deadline (the platform's drain won't wait either)
        all_acked = bool(ready_ordinals) and ready_ordinals <= set(acked)
        if not (all_acked or not ready_pods or now >= deadline):
            # un-acked hosts left: re-poll at the probe cadence, not a tight
            # loop — each sweep can block on a dead host's connect timeout
            return Result(requeue_after=max(
                0.02,
                min(self.config.readiness_probe_period_s, deadline - now),
            ))

        updates = {
            C.TPU_REPAIR_STATE_ANNOTATION: STATE_REPAIRING,
            C.TPU_REPAIR_ATTEMPTS_ANNOTATION: "1",
            C.TPU_CHECKPOINT_REQUEST_ANNOTATION: None,
        }
        self._ckpt_acked.pop(req.key, None)
        if acked:
            telemetry.slice_checkpoint_saves_total.inc(len(acked))
            steps = [s for s in acked.values() if s is not None]
            if steps:
                # the contract: the LAST ACKED STEP, for the resumed
                # workload to restore — never a timestamp masquerading as one
                updates[C.TPU_CHECKPOINT_SAVED_ANNOTATION] = str(max(steps))
        started = self._started_ts(nb, now)
        record_span(
            "slice.checkpoint",
            traceparent=nb.metadata.annotations.get(C.TRACEPARENT_ANNOTATION),
            start_time=started,
            end_time=now,
            notebook=nb.metadata.name,
            hosts_acked=len(acked),
            hosts_ready=len(ready_pods),
        )
        self._patch_annotations(nb, updates)
        write_condition(
            self.client, self.api_reader, nb,
            C.TPU_DEGRADED_CONDITION, "True", "Repairing",
            f"gang evicted after checkpoint window ({len(acked)} hosts saved); "
            "waiting for all-or-nothing re-placement",
        )
        self._emit_event(
            nb, "SliceRepairing",
            f"evicting gang for repair ({len(acked)}/{shape.hosts} hosts "
            "checkpointed); rescheduling all-or-nothing",
        )
        self._evict(nb, pods)
        self._evicted_at[req.key] = now
        self._next_attempt[req.key] = now + self._backoff(1)
        recorder.record(
            "transition", machine="slice-repair", notebook=req.key,
            state=STATE_REPAIRING, hosts_acked=len(acked),
        )
        log.info(
            "slice repair: evicted gang of %s/%s (%d/%d hosts checkpointed)",
            nb.metadata.namespace, nb.metadata.name, len(acked), shape.hosts,
        )
        return Result(requeue_after=0.05)

    def _await_repair(
        self, nb: Notebook, shape, pods: List[Pod], threat, now: float, req: Request
    ) -> Optional[Result]:
        if self._slice_healthy(nb, pods, shape, threat):
            return self._complete(nb, now, req)

        # a rescheduled pod that landed on an unhealthy node (raced the taint)
        # poisons the gang: re-evict immediately, uncounted — this is a
        # placement race, not a capacity wait
        placed = [p for p in pods if p.spec.node_name]
        if any(not self._node_ok(p.spec.node_name) for p in placed):
            self._evict(nb, pods)
            return Result(requeue_after=0.05)

        deadline = self._next_attempt.get(req.key)
        ann = nb.metadata.annotations
        attempts = int(ann.get(C.TPU_REPAIR_ATTEMPTS_ANNOTATION, "1") or 1)
        if deadline is None:
            # controller restarted mid-repair: re-derive from the durable
            # attempt counter
            deadline = now + self._backoff(attempts)
            self._next_attempt[req.key] = deadline
        if now < deadline:
            return Result(requeue_after=max(0.02, deadline - now))

        # one full backoff window without recovery: count an attempt
        attempts += 1
        if attempts > self.config.repair_max_attempts:
            return self._fail(nb, now, req)
        self._patch_annotations(
            nb, {C.TPU_REPAIR_ATTEMPTS_ANNOTATION: str(attempts)}
        )
        self._next_attempt[req.key] = now + self._backoff(attempts)
        # a gang that sat out a whole window either half-placed (sibling
        # pinning holds it in a pool that cannot complete) or fully placed
        # under an AFFIRMATIVE threat (taint still there / devices still
        # sick: an evict raced or the replacement is equally bad) is wedged:
        # evict and let the scheduler try fresh, all-or-nothing, possibly
        # elsewhere. HostUnreachable deliberately does not count — it is
        # what a merely-slow bring-up looks like, and evicting on it would
        # loop a recovering gang back to zero.
        affirmative = threat is not None and threat[0] in (
            "HostPreempted", "ChipFailure", "ICIDegraded",
        )
        if placed and (len(placed) < shape.hosts or affirmative):
            self._evict(nb, pods)
        log.info(
            "slice repair: %s/%s still down (attempt %d/%d)",
            nb.metadata.namespace, nb.metadata.name,
            attempts, self.config.repair_max_attempts,
        )
        return Result(requeue_after=max(0.02, self._next_attempt[req.key] - now))

    def _complete(
        self, nb: Notebook, now: float, req: Request, after_failure: bool = False
    ) -> Optional[Result]:
        ann = nb.metadata.annotations
        started = self._started_ts(nb, now)
        mttr = max(0.0, now - started)
        cause = ann.get(C.TPU_REPAIR_CAUSE_ANNOTATION, "")
        attempts = ann.get(C.TPU_REPAIR_ATTEMPTS_ANNOTATION, "")
        telemetry.slice_repair_duration_seconds.observe(mttr)
        telemetry.slice_repairs_total.inc(result="repaired")
        span = record_span(
            "slice.repair",
            traceparent=ann.get(C.TRACEPARENT_ANNOTATION),
            start_time=started,
            end_time=now,
            notebook=nb.metadata.name,
            namespace=nb.metadata.namespace,
            cause=cause,
            attempts=attempts,
            mttr_s=round(mttr, 3),
            result="repaired" if not after_failure else "repaired-after-failure",
        )
        evicted = self._evicted_at.pop(req.key, None)
        if evicted is not None and span is not None:
            record_span(
                "slice.reschedule",
                traceparent=span.traceparent,
                start_time=evicted,
                end_time=now,
                notebook=nb.metadata.name,
            )
        updates = self._clear_updates()
        # culling-clock contract: the repair window must not count as
        # idleness — restart the idle clock at repair completion
        updates[C.LAST_ACTIVITY_ANNOTATION] = now_rfc3339()
        updates[C.LAST_ACTIVITY_CHECK_TIMESTAMP_ANNOTATION] = now_rfc3339()
        self._patch_annotations(nb, updates)
        write_condition(
            self.client, self.api_reader, nb,
            C.TPU_DEGRADED_CONDITION, "False", "Repaired",
            f"slice repaired in {mttr:.1f}s ({cause})",
        )
        self._emit_event(
            nb, "SliceRepaired",
            f"slice repaired in {mttr:.1f}s (cause: {cause or 'unknown'}, "
            f"attempts: {attempts or '1'})",
            etype="Normal",
        )
        self._next_attempt.pop(req.key, None)
        self._track_repair(req.key, False)
        recorder.record(
            "transition", machine="slice-repair", notebook=req.key,
            state="ready", mttr_s=round(mttr, 3), cause=cause,
        )
        log.info(
            "slice repaired: %s/%s in %.2fs (%s)",
            nb.metadata.namespace, nb.metadata.name, mttr, cause,
        )
        return None

    def _fail(self, nb: Notebook, now: float, req: Request) -> Optional[Result]:
        ann = nb.metadata.annotations
        started = self._started_ts(nb, now)
        cause = ann.get(C.TPU_REPAIR_CAUSE_ANNOTATION, "")
        telemetry.slice_repairs_total.inc(result="failed")
        record_span(
            "slice.repair",
            traceparent=ann.get(C.TRACEPARENT_ANNOTATION),
            start_time=started,
            end_time=now,
            notebook=nb.metadata.name,
            namespace=nb.metadata.namespace,
            cause=cause,
            result="failed",
        )
        self._patch_annotations(
            nb, {C.TPU_REPAIR_STATE_ANNOTATION: STATE_FAILED}
        )
        msg = (
            f"repair abandoned after {self.config.repair_max_attempts} "
            f"attempts (cause: {cause or 'unknown'}); slice capacity never "
            "recovered — operator attention required"
        )
        write_condition(
            self.client, self.api_reader, nb,
            C.TPU_DEGRADED_CONDITION, "True", "RepairFailed", msg,
        )
        self._emit_event(nb, "RepairFailed", msg)
        self._next_attempt.pop(req.key, None)
        self._evicted_at.pop(req.key, None)
        self._track_repair(req.key, False)
        recorder.record(
            "transition", machine="slice-repair", notebook=req.key,
            state=STATE_FAILED, cause=cause,
        )
        recorder.snapshot(
            "repair-failed", subject=req.key, client=self.client,
            notebooks=[(nb.metadata.namespace, nb.metadata.name)],
            extra={"cause": cause, "attempts": self.config.repair_max_attempts},
        )
        log.error("slice repair FAILED: %s/%s (%s)",
                  nb.metadata.namespace, nb.metadata.name, cause)
        return None

    # ---------- checkpoint sweep ----------

    CHECKPOINT_TIMEOUT_S = 2.0

    def _checkpoint_sweep(
        self, nb: Notebook, hosts: int, ordinals: List[int]
    ) -> List[Tuple[int, Optional[dict]]]:
        """Drive the given ordinals' /tpu/checkpoint hooks concurrently
        (same transport/addressing as the readiness gate); (ordinal, None)
        for unreachable hosts."""
        from concurrent.futures import ThreadPoolExecutor

        def probe(url: str) -> Optional[dict]:
            try:
                try:
                    status, body = self.http_get(url, timeout=self.CHECKPOINT_TIMEOUT_S)
                except TypeError:  # custom http_get without timeout kwarg
                    status, body = self.http_get(url)
                if status != 200:
                    raise ConnectionError(f"GET {url} -> {status}")
                return json.loads(body.decode() or "null")
            except Exception as e:
                log.debug("checkpoint probe %s unreachable: %s", url, e)
                return None

        urls = per_ordinal_probe_urls(
            self.client, self.config, nb, hosts, "/tpu/checkpoint"
        )
        targets = [(i, urls[i]) for i in ordinals if i < len(urls)]
        if not targets:
            return []
        with ThreadPoolExecutor(max_workers=min(16, len(targets))) as pool:
            acks = list(pool.map(probe, [u for _, u in targets]))
        return [(i, a) for (i, _), a in zip(targets, acks)]

    # ---------- helpers ----------

    def _assert_degraded(
        self, nb: Notebook, status: str, reason: str, message: str
    ) -> None:
        """Re-assert the owned Degraded condition when status/reason drifted
        (stale mirror snapshot); keeps the richer original message when the
        condition is already right, so steady state costs zero writes."""
        cur = get_condition(nb, C.TPU_DEGRADED_CONDITION)
        if cur is not None and cur.status == status and cur.reason == reason:
            return
        write_condition(
            self.client, self.api_reader, nb,
            C.TPU_DEGRADED_CONDITION, status, reason, message,
        )

    def _node_ok(self, node_name: str) -> bool:
        try:
            node = self.client.get(Node, "", node_name)
        except NotFoundError:
            return False
        if any(
            t.get("key") == PREEMPTION_TAINT_KEY
            for t in node.spec.get("taints", [])
        ):
            return False
        return not any(
            c.type == "Ready" and c.status == "False"
            for c in node.status.conditions
        )

    def _evict(self, nb: Notebook, pods: List[Pod]) -> None:
        """Delete the whole gang: the StatefulSet recreates every ordinal and
        the scheduler re-places them all-or-nothing (a fresh gang — no
        sibling pinning — so a healthy pool of the same topology can win)."""
        for p in pods:
            try:
                self.client.delete(Pod, p.metadata.namespace, p.metadata.name)
            except NotFoundError:
                pass  # racing drain/scale-down deleted it first

    def _backoff(self, attempts: int) -> float:
        base = min(
            self.config.repair_backoff_max_s,
            self.config.repair_backoff_s * (2 ** max(0, attempts - 1)),
        )
        # jitter so a pool-wide preemption's repairs don't re-place in
        # lockstep against the recovering capacity
        return base * (0.75 + 0.5 * random.random())

    def _started_ts(self, nb: Notebook, fallback: float) -> float:
        try:
            return parse_time(
                nb.metadata.annotations.get(C.TPU_REPAIR_STARTED_ANNOTATION, "")
            ).timestamp()
        except ValueError:
            return fallback

    @staticmethod
    def _clear_updates() -> dict:
        return {
            C.TPU_REPAIR_STATE_ANNOTATION: None,
            C.TPU_REPAIR_STARTED_ANNOTATION: None,
            C.TPU_REPAIR_CAUSE_ANNOTATION: None,
            C.TPU_REPAIR_ATTEMPTS_ANNOTATION: None,
            C.TPU_CHECKPOINT_REQUEST_ANNOTATION: None,
        }

    def _track_repair(self, key: str, active: bool) -> None:
        if active:
            self._in_repair.add(key)
        else:
            self._in_repair.discard(key)
        # written unconditionally (not only on change): the gauge is
        # process-global, and a controller stopped mid-episode leaves a
        # stale non-zero value a fresh instance's empty set would otherwise
        # never overwrite — permanently inhibiting readiness alerts
        telemetry.slice_repairs_in_progress.set(float(len(self._in_repair)))

    def _forget(self, key: str) -> None:
        self._last_seen.pop(key, None)
        self._next_attempt.pop(key, None)
        self._evicted_at.pop(key, None)
        self._ckpt_acked.pop(key, None)
        self._track_repair(key, False)

    def _patch_annotations(self, nb: Notebook, updates: dict) -> None:
        def attempt():
            return self.client.patch(
                Notebook,
                nb.metadata.namespace,
                nb.metadata.name,
                {"metadata": {"annotations": updates}},
            )

        try:
            retry_on_conflict(attempt)
        except NotFoundError:
            pass  # deleted mid-transition; the delete path forgets state

    def _emit_event(
        self, nb: Notebook, reason: str, message: str, etype: str = "Warning"
    ) -> None:
        """One Event per notebook+reason, deduplicated Kubernetes-style via
        the shared emitter (api/core.py emit_deduped_event — same mechanics
        as the scheduler's Unschedulable events)."""
        emit_deduped_event(
            self.client, nb, f"{nb.metadata.name}.{reason.lower()}",
            reason=reason, message=message, etype=etype,
            api_version=nb.api_version or "kubeflow.org/v1beta1",
            kind="Notebook",
        )
