from . import constants
from .config import Config
from .metrics import NotebookMetrics
from .notebook import EventMirrorController, NotebookReconciler, hosts_service_name
from .culling import CullingReconciler
from .inference import InferenceEndpointReconciler
from .job import TPUJobReconciler
from .probe_status import ProbeStatusController
from .slice_repair import SliceRepairController
from .suspend import SuspendResumeController
from .webhook import NotebookWebhook
from .extension import TPUWorkbenchReconciler
