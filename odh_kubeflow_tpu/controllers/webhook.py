"""Mutating admission webhook for Notebooks.

TPU-native re-design of the reference's NotebookWebhook (reference
odh-notebook-controller/controllers/notebook_webhook.go): runs in the store's
admission chain (failurePolicy=Fail) on CREATE/UPDATE of every served Notebook
version. Responsibilities, in handler order (mirroring Handle :352-499):

- CREATE: inject the reconciliation lock (`kubeflow-resource-stopped` =
  "odh-notebook-controller-lock") so the StatefulSet starts at replicas 0
  until the extension controller finishes satellite setup (:105-114),
- validate `spec.tpu` (fail-closed: a bad topology never reaches etcd —
  the TPU-native replacement for image-stream validation),
- resolve the image from the workbench image catalog ConfigMap when the
  `last-image-selection` annotation is present (ImageStream analog :787-894),
- mount the CA bundle ConfigMap when present (:618-781),
- mount/unmount the Feast client config by label (:432-444),
- inject the auth proxy sidecar when `inject-auth` is set, with
  annotation-tunable, validated resources (:177-326, :126-173),
- inject cluster egress-proxy env when enabled (:566-615),
- update-blocking: if only webhook-caused podspec drift would restart a
  running notebook, revert the podspec and set `update-pending` (:505-564).
"""
from __future__ import annotations

import copy
import logging
from typing import Any, Dict, List, Optional

from ..api.core import (
    ConfigMap,
    Container,
    ContainerPort,
    EnvVar,
    ResourceRequirements,
    Volume,
    VolumeMount,
)
from ..api.notebook import Notebook
from ..apimachinery import AdmissionDeniedError, InvalidError, NotFoundError, default_scheme
from ..cluster.client import Client
from ..cluster.store import AdmissionRequest, Store
from ..tpu import plan_slice
from ..utils import parse_quantity
from ..utils.diff import first_difference
from ..utils import tracing
from ..utils.tracing import webhook_tracer
from . import constants as C
from .config import Config

log = logging.getLogger(__name__)

CA_BUNDLE_CONFIGMAP = "workbench-trusted-ca-bundle"
CA_BUNDLE_MOUNT_PATH = "/etc/pki/tls/custom-certs"
CA_BUNDLE_VOLUME = "trusted-ca"
IMAGE_CATALOG_CONFIGMAP = "notebook-images"
PROXY_CONFIGMAP = "cluster-proxy-config"
AUTH_PROXY_CONTAINER = "kube-rbac-proxy"
AUTH_PROXY_PORT = 8443
# Distinctive prefixed name (reference notebook_feast_config.go:27) so
# unmount can never collide with a user-defined volume.
FEAST_VOLUME = "odh-feast-config"
FEAST_MOUNT_PATH = "/opt/app-root/src/feast-config"
# pipeline-runtimes catalog mount (reference notebook_runtime.go:216-285)
RUNTIME_IMAGES_VOLUME = "pipeline-runtime-images"
RUNTIME_IMAGES_MOUNT_PATH = "/opt/app-root/pipeline-runtimes/"
# Elyra runtime config mount (reference notebook_dspa_secret.go:375-449)
ELYRA_VOLUME = "elyra-dsp-details"
ELYRA_MOUNT_PATH = "/opt/app-root/runtimes"


class NotebookWebhook:
    def __init__(self, client: Client, config: Optional[Config] = None):
        self.client = client
        self.config = config or Config()

    def register(self, store: Store) -> None:
        store.register_webhook(
            "notebook-mutator",
            "kubeflow.org/v1beta1",
            "Notebook",
            ["CREATE", "UPDATE"],
            self.handle,
        )

    # ---------- entrypoint ----------

    def handle(self, req: AdmissionRequest) -> Dict[str, Any]:
        nb = default_scheme.decode({**req.object, "kind": "Notebook"})
        assert isinstance(nb, Notebook)
        # readiness trace root: CREATE opens `notebook.ready` (closed by the
        # probe-status gate at first mesh-ready) and stamps its traceparent
        # on the CR — every later actor joins this trace via the annotation
        root = None
        if (
            req.operation == "CREATE"
            and C.TRACEPARENT_ANNOTATION not in nb.metadata.annotations
        ):
            root = tracing.begin_root(
                "notebook.ready",
                key=nb.key(),  # re-admission of a retried CREATE replaces
                # the stale root the failed attempt stranded
                notebook=nb.metadata.name,
                namespace=nb.metadata.namespace,
            )
            if root is not None:
                nb.metadata.annotations[C.TRACEPARENT_ANNOTATION] = root.traceparent
        traceparent = nb.metadata.annotations.get(C.TRACEPARENT_ANNOTATION)
        try:
            return self._handle_traced(req, nb, traceparent)
        except Exception:
            # denied CREATE: the notebook never existed — drop its open root
            if root is not None:
                tracing.discard_root(root.trace_id)
            raise

    def _handle_traced(
        self, req: AdmissionRequest, nb: Notebook, traceparent: Optional[str]
    ) -> Dict[str, Any]:
        with webhook_tracer.start_span(
            "webhook.mutate",
            traceparent=traceparent,
            notebook=nb.metadata.name,
            namespace=nb.metadata.namespace,
            operation=req.operation,
        ) as span:
            user_podspec = copy.deepcopy(nb.spec.template.spec.to_dict())

            if req.operation == "CREATE":
                self.validate_name(nb)
                self.inject_reconciliation_lock(nb)

            self.validate_tpu(nb, span)
            self.set_container_image_from_catalog(nb, span)
            self.check_and_mount_ca_bundle(nb)
            self.sync_and_mount_runtime_images(nb)
            if self.config.set_pipeline_secret:
                self.sync_and_mount_elyra_config(nb)
            if nb.metadata.labels.get(C.FEAST_LABEL) == "true":
                self.mount_feast_config(nb)
            else:
                self.unmount_feast_config(nb)
            if nb.metadata.annotations.get(C.INJECT_AUTH_ANNOTATION) == "true":
                self.inject_auth_proxy(nb)
            else:
                self.remove_auth_proxy(nb)
            if self.config.inject_cluster_proxy_env:
                self.inject_proxy_env(nb)

            if req.operation == "UPDATE" and req.old_object is not None:
                self.maybe_block_restart(nb, user_podspec, req.old_object, span)

            return nb.to_dict()

    # ---------- mutations ----------

    @staticmethod
    def _remove_volume_and_mounts(podspec, name: str) -> None:
        podspec.volumes = [v for v in podspec.volumes if v.name != name]
        for container in podspec.containers:
            container.volume_mounts = [
                m for m in container.volume_mounts if m.name != name
            ]

    def _strip_legacy_feast_volume(self, nb: Notebook) -> Optional[dict]:
        """Migrate specs admitted under the pre-rename volume name
        'feast-config' — but only when the volume is identifiably ours
        (backed by the `{name}-feast-config` ConfigMap), so a user volume
        that happens to share the generic name is never touched. Returns the
        legacy volume's configMap source so the re-mount can preserve its
        optionality for workloads that relied on it."""
        podspec = nb.spec.template.spec
        legacy = podspec.volume("feast-config")
        if legacy is None or (legacy.config_map or {}).get("name") != (
            f"{nb.metadata.name}-feast-config"
        ):
            return None
        self._remove_volume_and_mounts(podspec, "feast-config")
        return legacy.config_map

    def mount_feast_config(self, nb: Notebook) -> None:
        """Label `opendatahub.io/feast-integration=true` mounts the
        `{name}-feast-config` ConfigMap at the Feast client path in the
        primary container (reference notebook_feast_config.go:53-117)."""
        legacy_source = self._strip_legacy_feast_volume(nb)
        container = self._primary_container(nb)
        if container is None:
            return
        podspec = nb.spec.template.spec
        if podspec.volume(FEAST_VOLUME) is None:
            # required, like the reference: a missing ConfigMap should hold
            # the pod at ContainerCreating, not start without it. Migrated
            # legacy volumes keep their source verbatim (incl. optional:true)
            # so previously-working pods are never retroactively wedged.
            source = legacy_source or {"name": f"{nb.metadata.name}-feast-config"}
            podspec.volumes.append(Volume(name=FEAST_VOLUME, config_map=source))
        if not any(m.name == FEAST_VOLUME for m in container.volume_mounts):
            container.volume_mounts.append(
                VolumeMount(
                    name=FEAST_VOLUME, mount_path=FEAST_MOUNT_PATH, read_only=True
                )
            )

    def unmount_feast_config(self, nb: Notebook) -> None:
        """Label removed ⇒ volume + mounts go away (reference :120-146)."""
        self._strip_legacy_feast_volume(nb)
        self._remove_volume_and_mounts(nb.spec.template.spec, FEAST_VOLUME)

    def _mount_into_all_containers(
        self, nb: Notebook, volume: Volume, mount_path: str
    ) -> None:
        """Idempotently add a volume + a mount in EVERY container (both
        pipeline mounts apply to all containers in the reference:
        notebook_runtime.go:216-285, notebook_dspa_secret.go:375-449)."""
        podspec = nb.spec.template.spec
        if podspec.volume(volume.name) is None:
            podspec.volumes.append(volume)
        for container in podspec.containers:
            if not any(m.name == volume.name for m in container.volume_mounts):
                container.volume_mounts.append(
                    VolumeMount(name=volume.name, mount_path=mount_path, read_only=True)
                )

    def sync_and_mount_runtime_images(self, nb: Notebook) -> None:
        """Sync the per-namespace `pipeline-runtime-images` catalog, then
        mount it at the pipeline-runtimes path in all containers (reference
        notebook_webhook.go:400-410 + notebook_runtime.go:216-285). Syncing
        at admission means the FIRST pod already sees its runtimes — no
        blocked-update cycle later."""
        from .extension import RUNTIME_IMAGES_CONFIGMAP, sync_runtime_images

        try:
            have_catalog = sync_runtime_images(
                self.client, self.config, nb.metadata.namespace
            )
        except Exception as e:  # sync problems must not reject the write
            log.warning("runtime-images sync failed for %s: %r", nb.key(), e)
            have_catalog = nb.spec.template.spec.volume(RUNTIME_IMAGES_VOLUME) is not None
        if not have_catalog:
            self._remove_volume_and_mounts(
                nb.spec.template.spec, RUNTIME_IMAGES_VOLUME
            )
            return
        self._mount_into_all_containers(
            nb,
            Volume(
                name=RUNTIME_IMAGES_VOLUME,
                config_map={"name": RUNTIME_IMAGES_CONFIGMAP},
            ),
            RUNTIME_IMAGES_MOUNT_PATH,
        )

    def sync_and_mount_elyra_config(self, nb: Notebook) -> None:
        """Sync the `ds-pipeline-config` Secret (DSPA-derived Elyra runtime
        config), then mount it at /opt/app-root/runtimes in all containers
        (reference notebook_webhook.go:413-429 + notebook_dspa_secret.go
        :375-449)."""
        from .extension import ELYRA_SECRET_NAME, sync_elyra_secret

        try:
            have_secret = sync_elyra_secret(
                self.client, self.config, nb.metadata.namespace
            )
        except Exception as e:
            log.warning("elyra-config sync failed for %s: %r", nb.key(), e)
            have_secret = nb.spec.template.spec.volume(ELYRA_VOLUME) is not None
        if not have_secret:
            self._remove_volume_and_mounts(nb.spec.template.spec, ELYRA_VOLUME)
            return
        self._mount_into_all_containers(
            nb,
            Volume(name=ELYRA_VOLUME, secret={"secretName": ELYRA_SECRET_NAME}),
            ELYRA_MOUNT_PATH,
        )

    def inject_reconciliation_lock(self, nb: Notebook) -> None:
        """The webhook<->extension-controller handshake: replicas stay 0 until
        the extension controller removes this annotation (SURVEY §1 coupling)."""
        nb.metadata.annotations.setdefault(
            C.STOP_ANNOTATION, C.RECONCILIATION_LOCK_VALUE
        )

    def validate_name(self, nb: Notebook) -> None:
        """Names longer than a DNS label cannot materialize: the ClusterIP
        Service shares the notebook's name (reference generateService
        :525-552 — same constraint there) and pod DNS addressing rides it.
        Fail at admission with a clear message instead of letting the
        reconciler crash-loop on Service creation."""
        if len(nb.metadata.name) > 63:
            raise AdmissionDeniedError(
                f"metadata.name {nb.metadata.name!r} is {len(nb.metadata.name)} "
                "chars; notebook names must be <= 63 (DNS label: the Service "
                "and per-pod DNS share the name)"
            )

    def validate_tpu(self, nb: Notebook, span) -> None:
        if nb.spec.tpu is None or not nb.spec.tpu.accelerator:
            return
        try:
            shape = plan_slice(
                nb.spec.tpu.accelerator, nb.spec.tpu.topology, nb.spec.tpu.chips
            )
        except InvalidError as e:
            span.add_event("tpu-spec-rejected", error=str(e))
            raise AdmissionDeniedError(f"spec.tpu invalid: {e}") from e
        runtime = nb.spec.tpu.runtime
        if runtime and runtime not in ("jax", "pytorch-xla"):
            raise AdmissionDeniedError(
                f"spec.tpu.runtime {runtime!r} not supported (jax | pytorch-xla)"
            )
        span.set_attribute("tpu.accelerator_type", shape.accelerator_type)
        span.set_attribute("tpu.hosts", shape.hosts)

    def _primary_container(self, nb: Notebook) -> Optional[Container]:
        podspec = nb.spec.template.spec
        for c in podspec.containers:
            if c.name == nb.metadata.name:
                return c
        return podspec.containers[0] if podspec.containers else None

    def set_container_image_from_catalog(self, nb: Notebook, span) -> None:
        """Workbench image catalog: `last-image-selection: name:tag` resolves
        through the `notebook-images` ConfigMap (data: "name:tag" -> image
        ref) in the image namespace (annotation) or controller namespace —
        the ImageStream-lookup analog (reference :787-894)."""
        selection = nb.metadata.annotations.get(C.IMAGE_SELECTION_ANNOTATION, "")
        if not selection or ":" not in selection:
            return
        ns = (
            nb.metadata.annotations.get(C.IMAGE_NAMESPACE_ANNOTATION)
            or self.config.controller_namespace
        )
        try:
            catalog = self.client.get(ConfigMap, ns, IMAGE_CATALOG_CONFIGMAP)
        except NotFoundError:
            span.add_event("imagecatalog-miss", namespace=ns)
            return
        image = catalog.data.get(selection)
        if not image:
            span.add_event("imagecatalog-selection-missing", selection=selection)
            return
        container = self._primary_container(nb)
        if container is not None and container.image != image:
            container.image = image

    def check_and_mount_ca_bundle(self, nb: Notebook) -> None:
        """Mount `workbench-trusted-ca-bundle` (assembled by the extension
        controller) into every container, with the usual TLS env contract."""
        try:
            cm = self.client.get(
                ConfigMap, nb.metadata.namespace, CA_BUNDLE_CONFIGMAP
            )
        except NotFoundError:
            return
        if "ca-bundle.crt" not in cm.data:
            return
        podspec = nb.spec.template.spec
        if podspec.volume(CA_BUNDLE_VOLUME) is None:
            podspec.volumes.append(
                Volume(
                    name=CA_BUNDLE_VOLUME,
                    config_map={
                        "name": CA_BUNDLE_CONFIGMAP,
                        "optional": True,
                        "items": [
                            {"key": "ca-bundle.crt", "path": "ca-bundle.crt"}
                        ],
                    },
                )
            )
        bundle_path = f"{CA_BUNDLE_MOUNT_PATH}/ca-bundle.crt"
        for container in podspec.containers:
            if container.name == AUTH_PROXY_CONTAINER:
                continue
            if not any(m.name == CA_BUNDLE_VOLUME for m in container.volume_mounts):
                container.volume_mounts.append(
                    VolumeMount(name=CA_BUNDLE_VOLUME, mount_path=CA_BUNDLE_MOUNT_PATH)
                )
            for env_name in ("PIP_CERT", "REQUESTS_CA_BUNDLE", "SSL_CERT_FILE",
                             "PIPELINES_SSL_SA_CERTS", "GIT_SSL_CAINFO"):
                if not container.get_env(env_name):
                    container.set_env(env_name, bundle_path)

    def parse_auth_sidecar_resources(self, nb: Notebook) -> ResourceRequirements:
        """Annotation-tunable sidecar resources with validation (reference
        parseAndValidateAuthSidecarResources :126-173); invalid -> deny."""
        defaults = {
            C.AUTH_SIDECAR_CPU_REQUEST_ANNOTATION: "100m",
            C.AUTH_SIDECAR_MEMORY_REQUEST_ANNOTATION: "64Mi",
            C.AUTH_SIDECAR_CPU_LIMIT_ANNOTATION: "100m",
            C.AUTH_SIDECAR_MEMORY_LIMIT_ANNOTATION: "64Mi",
        }
        values: Dict[str, str] = {}
        for ann, default in defaults.items():
            raw = nb.metadata.annotations.get(ann, default)
            try:
                parse_quantity(raw)
            except InvalidError:
                raise AdmissionDeniedError(
                    f"invalid resource quantity {raw!r} in annotation {ann}"
                )
            values[ann] = raw
        return ResourceRequirements(
            requests={
                "cpu": values[C.AUTH_SIDECAR_CPU_REQUEST_ANNOTATION],
                "memory": values[C.AUTH_SIDECAR_MEMORY_REQUEST_ANNOTATION],
            },
            limits={
                "cpu": values[C.AUTH_SIDECAR_CPU_LIMIT_ANNOTATION],
                "memory": values[C.AUTH_SIDECAR_MEMORY_LIMIT_ANNOTATION],
            },
        )

    def inject_auth_proxy(self, nb: Notebook) -> None:
        """kube-rbac-proxy-style sidecar: fronts the notebook on :8443, doing
        a SubjectAccessReview against `get notebooks/{name}` (reference
        InjectKubeRbacProxy :177-326; config objects come from the extension
        controller)."""
        resources = self.parse_auth_sidecar_resources(nb)
        podspec = nb.spec.template.spec
        sidecar = podspec.container(AUTH_PROXY_CONTAINER)
        desired = Container(
            name=AUTH_PROXY_CONTAINER,
            image=self.config.auth_proxy_image,
            args=[
                f"--secure-listen-address=0.0.0.0:{AUTH_PROXY_PORT}",
                f"--upstream=http://127.0.0.1:{C.NOTEBOOK_PORT}/",
                "--config-file=/etc/kube-rbac-proxy/config-file.yaml",
                "--tls-cert-file=/etc/tls/private/tls.crt",
                "--tls-private-key-file=/etc/tls/private/tls.key",
                "--v=2",
            ],
            ports=[ContainerPort(name="https", container_port=AUTH_PROXY_PORT, protocol="TCP")],
            resources=resources,
            volume_mounts=[
                VolumeMount(name="kube-rbac-proxy-config", mount_path="/etc/kube-rbac-proxy"),
                VolumeMount(name="kube-rbac-proxy-tls", mount_path="/etc/tls/private"),
            ],
        )
        if sidecar is None:
            podspec.containers.append(desired)
        else:
            sidecar.image = desired.image
            sidecar.args = desired.args
            sidecar.resources = desired.resources
            sidecar.ports = desired.ports
            sidecar.volume_mounts = desired.volume_mounts
        for vol_name, source in (
            (
                "kube-rbac-proxy-config",
                {"config_map": {"name": f"{nb.metadata.name}-kube-rbac-proxy-config"}},
            ),
            (
                "kube-rbac-proxy-tls",
                {"secret": {"secretName": f"{nb.metadata.name}-tls"}},
            ),
        ):
            if podspec.volume(vol_name) is None:
                podspec.volumes.append(Volume(name=vol_name, **source))

    def remove_auth_proxy(self, nb: Notebook) -> None:
        podspec = nb.spec.template.spec
        podspec.containers = [
            c for c in podspec.containers if c.name != AUTH_PROXY_CONTAINER
        ]
        podspec.volumes = [
            v
            for v in podspec.volumes
            if v.name not in ("kube-rbac-proxy-config", "kube-rbac-proxy-tls")
        ]

    def inject_proxy_env(self, nb: Notebook) -> None:
        """Cluster egress proxy env from the `cluster-proxy-config` ConfigMap
        (the cluster Proxy CR analog, reference :566-615)."""
        try:
            cm = self.client.get(
                ConfigMap, self.config.controller_namespace, PROXY_CONFIGMAP
            )
        except NotFoundError:
            return
        mapping = {
            "HTTP_PROXY": cm.data.get("httpProxy", ""),
            "HTTPS_PROXY": cm.data.get("httpsProxy", ""),
            "NO_PROXY": cm.data.get("noProxy", ""),
        }
        for container in nb.spec.template.spec.containers:
            if container.name == AUTH_PROXY_CONTAINER:
                continue
            for name, value in mapping.items():
                # user wins if EITHER case is set: set_env matches the
                # existing var, so writing one case would clobber the other
                if value and not container.get_env(name) and not container.get_env(
                    name.lower()
                ):
                    container.set_env(name, value)
                    container.set_env(name.lower(), value)

    # ---------- update blocking ----------

    def maybe_block_restart(
        self,
        nb: Notebook,
        user_podspec: Dict[str, Any],
        old_object: Dict[str, Any],
        span,
    ) -> None:
        """Don't restart a RUNNING notebook for webhook-only drift: an 8-host
        training slice must not bounce because a sidecar image was rebumped
        (reference maybeRestartRunningNotebook :505-564; SURVEY §7 hard
        part (b))."""
        with webhook_tracer.start_span(
            "notebook-webhook.maybe-restart", notebook=nb.metadata.name
        ) as inner:
            old_nb = default_scheme.decode({**old_object, "kind": "Notebook"})
            old_annotations = old_nb.metadata.annotations
            # stopped or being restarted: updates apply freely
            if C.STOP_ANNOTATION in old_annotations:
                self._clear_update_pending(nb)
                return
            if old_annotations.get(C.NOTEBOOK_RESTART_ANNOTATION) == "true":
                self._clear_update_pending(nb)
                return

            old_podspec = old_nb.spec.template.spec.to_dict()
            mutated_podspec = nb.spec.template.spec.to_dict()

            if first_difference(old_podspec, user_podspec) is not None:
                # the USER changed the podspec: they asked for the restart
                self._clear_update_pending(nb)
                return
            reason = first_difference(old_podspec, mutated_podspec)
            if reason is None:
                self._clear_update_pending(nb)
                return
            # webhook-only drift: revert and mark pending
            from ..api.core import PodSpec

            nb.spec.template.spec = PodSpec.from_dict(old_podspec)
            nb.metadata.annotations[C.UPDATE_PENDING_ANNOTATION] = reason
            inner.set_attribute("update.pending", reason)

    def _clear_update_pending(self, nb: Notebook) -> None:
        nb.metadata.annotations.pop(C.UPDATE_PENDING_ANNOTATION, None)
