"""Controller configuration.

The reference stacks CLI flags + env vars + kustomize params (SURVEY §5
config/flag system); this build centralizes them in one dataclass whose
from_env() reads the same env names the reference uses, so deployment
manifests translate directly."""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional


def _env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


@dataclass(frozen=True)
class EnvKnob:
    """One declared environment knob — the env half of the deployment-surface
    contract (analysis/deploysurface.py). The env-contract checker
    (analysis/checkers/deploylint.py) proves every os.environ read
    package-wide resolves to an entry here, that every entry has a live
    reader, and that manifest=True knobs ride the generated Deployment env
    stanza / culler ConfigMap (deploy/manifests.py)."""

    name: str
    default: str
    consumer: str  # module that reads it
    doc: str
    # True: the generated manifests must carry this knob (and vice versa)
    manifest: bool = False


@dataclass
class Config:
    # core reconciler (reference notebook_controller.go:238,514,576-599)
    cluster_domain: str = "cluster.local"
    add_fsgroup: bool = True

    # culling (reference culling_controller.go:525-558; minutes, same defaults)
    enable_culling: bool = False
    cull_idle_time_min: float = 1440.0
    idleness_check_period_min: float = 1.0
    dev_mode: bool = False

    # TPU-native culling signal: require BOTH Jupyter-idle and TPU-idle
    tpu_idle_threshold: float = 0.05  # duty cycle below which the slice is idle
    probe_port: int = 8889
    # probe circuit breaker (runtime/breaker.py): after `threshold`
    # consecutive jupyter-probe failures for one notebook, skip probing it
    # for a growing cooldown instead of paying connect timeouts every cycle
    probe_breaker_threshold: int = 3
    probe_breaker_cooldown_s: float = 30.0
    # device-visibility readiness gate (controllers/probe_status.py): poll
    # cadence for /tpu/readiness until the mesh gate is green
    readiness_probe_period_s: float = 10.0
    # slice repair (controllers/slice_repair.py): the checkpoint-before-evict
    # window (how long a Degraded slice gets to save state before the gang is
    # evicted), and the bounded jittered retry while capacity recovers —
    # attempt N waits ~ base * 2^N (+/- jitter), RepairFailed after max
    checkpoint_window_s: float = 30.0
    repair_max_attempts: int = 6
    repair_backoff_s: float = 1.0
    repair_backoff_max_s: float = 30.0
    # suspend/resume + warm slice pools (controllers/suspend.py,
    # cluster/slicepool.py): culling a TPU notebook checkpoints kernel state
    # and releases the slice mesh-formed into a warm pool instead of tearing
    # it down; resume binds from the pool (hit) or falls back to cold
    # placement (miss). Opt-in like culling itself.
    suspend_enabled: bool = False
    # checkpoint-before-suspend window (the cull path's analog of the repair
    # path's checkpoint_window_s)
    suspend_checkpoint_window_s: float = 15.0
    # per-ordinal checkpoint-hook retries inside the window: bounded, jittered
    # (the cluster/client.py 429 pattern) so one transient probe-agent blip
    # never aborts the whole suspend
    suspend_checkpoint_retries: int = 3
    suspend_checkpoint_backoff_s: float = 0.2
    # resume: one attempt = one warm-claim-or-cold-placement try; a resume
    # that hasn't reached mesh-ready within resume_timeout_s re-claims (a
    # poisoned warm slice must not wedge the notebook), ResumeFailed after max
    resume_timeout_s: float = 60.0
    resume_max_attempts: int = 6
    # oversubscription policy: total admitted chip demand (active + suspended
    # notebooks) may exceed physical chips up to this budget; a cold create /
    # resume that finds no capacity reclaims the lowest-priority pool-idle or
    # suspend-eligible slice. 0 = no budget cap (reclaim still gated on a
    # suitable victim existing). Demand beyond the budget queues, untouched.
    chip_budget: int = 0
    # how long a TPU pod must sit unschedulable before the reclaimer acts —
    # the scheduler's capacity-freed fast path gets first shot
    reclaim_pending_grace_s: float = 1.0
    # slice-pool pre-warming (ISSUE 9 satellite): keep this many warm slices
    # of the configured shape AHEAD of demand (spin up, mesh-form, park)
    # instead of only recycling suspended ones. 0 = off.
    pool_prewarm: int = 0
    pool_prewarm_accelerator: str = "v5e"
    pool_prewarm_topology: str = "2x2"
    # inference serving (controllers/inference.py): how long Loading gets to
    # reach mesh-ready + verified restore before LoadFailed, and the default
    # drain window a stopped endpoint's in-flight requests get (overridable
    # per-endpoint via spec.serving.drainTimeoutS)
    serving_loading_window_s: float = 30.0
    serving_drain_timeout_s: float = 5.0
    # batch/RL jobs (controllers/job.py): the bounded window a cadence or
    # preempt checkpoint gets before the job moves on, the requeue
    # backoff a preempted job waits before re-admitting (an instant
    # re-admission would race the very requester its slice was reclaimed
    # for), and the bind timeout after which an Admitted job whose gangs
    # never all came ready parks and requeues instead of wedging (a
    # claimed slice can die under the gang mid-bind)
    job_checkpoint_window_s: float = 10.0
    job_requeue_backoff_s: float = 2.0
    job_admission_timeout_s: float = 120.0
    # SLO engine + alerting (runtime/slo.py, runtime/alerts.py): window_scale
    # shrinks the canonical 5m/30m/1h/6h burn windows (soaks/tests run the
    # real rule shapes in seconds); eval period 0 derives from the scale
    slo_enabled: bool = True
    slo_window_scale: float = 1.0
    slo_eval_period_s: float = 0.0
    # black-box canary prober (runtime/prober.py): period 0 disables; an
    # accelerator/topology makes the canary exercise the device-visibility
    # gate instead of a plain CPU notebook
    canary_period_s: float = 0.0
    canary_timeout_s: float = 120.0
    canary_namespace: str = "slo-canary"
    canary_accelerator: str = ""
    canary_topology: str = ""
    # SLO-burn replica autoscaler (runtime/autoscaler.py): period 0 disables
    # and gates the main.py wiring; stabilization/idle are the DEFAULTS an
    # endpoint's autoscaling spec can override per endpoint
    autoscale_period_s: float = 0.0
    autoscale_stabilization_s: float = 30.0
    autoscale_idle_s: float = 120.0
    # fleet chip-time accountant (runtime/accounting.py): period 0 disables
    # the ledger service and gates the main.py wiring; idle window is the
    # threshold past which a bound+ready notebook counts idle-bound
    accounting_period_s: float = 1.0
    accounting_idle_after_s: float = 300.0
    # token router (serving/router.py): consecutive failures before a
    # replica is ejected, and the tail-hedge trigger (0 disables hedging)
    router_eject_failures: int = 3
    router_hedge_after_s: float = 0.0
    # MaxConcurrentReconciles analog: worker threads per controller. The
    # workqueue's per-key single-flight makes >1 safe; under create storms
    # (and over the higher-latency remote transport) it is the difference
    # between serial and pipelined reconciles
    max_concurrent_reconciles: int = 4
    # status-write coalescing window (runtime/coalesce.py): adjacent status
    # mirror patches for one object within this window batch into a single
    # PATCH (leading-edge write-through, so steady state is unchanged).
    # 0 disables coalescing entirely
    status_coalesce_window_s: float = 0.05

    # extension controller / webhook (reference odh main.go + webhook consts)
    auth_proxy_image: str = "kube-rbac-proxy:latest"
    gateway_name: str = "data-science-gateway"
    gateway_namespace: str = "openshift-ingress"
    controller_namespace: str = "tpu-notebooks-system"
    set_pipeline_rbac: bool = False
    set_pipeline_secret: bool = False
    inject_cluster_proxy_env: bool = False

    @classmethod
    def from_env(cls) -> "Config":
        c = cls()
        c.cluster_domain = os.environ.get("CLUSTER_DOMAIN", c.cluster_domain)
        c.add_fsgroup = _env_bool("ADD_FSGROUP", c.add_fsgroup)
        c.enable_culling = _env_bool("ENABLE_CULLING", c.enable_culling)
        if os.environ.get("CULL_IDLE_TIME"):
            c.cull_idle_time_min = float(os.environ["CULL_IDLE_TIME"])
        if os.environ.get("IDLENESS_CHECK_PERIOD"):
            c.idleness_check_period_min = float(os.environ["IDLENESS_CHECK_PERIOD"])
        if os.environ.get("TPU_IDLE_THRESHOLD"):
            # the culler ConfigMap has always shipped this key
            # (deploy/manifests.py culler_config) but nothing consumed it —
            # found by the env-contract checker's manifest direction
            c.tpu_idle_threshold = max(0.0, float(os.environ["TPU_IDLE_THRESHOLD"]))
        c.dev_mode = _env_bool("DEV", c.dev_mode)
        c.auth_proxy_image = os.environ.get("AUTH_PROXY_IMAGE", c.auth_proxy_image)
        c.gateway_name = os.environ.get("NOTEBOOK_GATEWAY_NAME", c.gateway_name)
        c.gateway_namespace = os.environ.get(
            "NOTEBOOK_GATEWAY_NAMESPACE", c.gateway_namespace
        )
        c.controller_namespace = os.environ.get("K8S_NAMESPACE", c.controller_namespace)
        c.set_pipeline_rbac = _env_bool("SET_PIPELINE_RBAC", c.set_pipeline_rbac)
        c.set_pipeline_secret = _env_bool("SET_PIPELINE_SECRET", c.set_pipeline_secret)
        c.inject_cluster_proxy_env = _env_bool(
            "INJECT_CLUSTER_PROXY_ENV", c.inject_cluster_proxy_env
        )
        if os.environ.get("PROBE_BREAKER_THRESHOLD"):
            c.probe_breaker_threshold = max(
                1, int(os.environ["PROBE_BREAKER_THRESHOLD"])
            )
        if os.environ.get("PROBE_BREAKER_COOLDOWN_S"):
            c.probe_breaker_cooldown_s = float(
                os.environ["PROBE_BREAKER_COOLDOWN_S"]
            )
        if os.environ.get("READINESS_PROBE_PERIOD_S"):
            c.readiness_probe_period_s = float(os.environ["READINESS_PROBE_PERIOD_S"])
        if os.environ.get("CHECKPOINT_WINDOW_S"):
            c.checkpoint_window_s = float(os.environ["CHECKPOINT_WINDOW_S"])
        if os.environ.get("REPAIR_MAX_ATTEMPTS"):
            # clamp: at least one attempt, or every degradation would be
            # declared RepairFailed before the first re-placement
            c.repair_max_attempts = max(1, int(os.environ["REPAIR_MAX_ATTEMPTS"]))
        if os.environ.get("REPAIR_BACKOFF_S"):
            c.repair_backoff_s = float(os.environ["REPAIR_BACKOFF_S"])
        if os.environ.get("REPAIR_BACKOFF_MAX_S"):
            c.repair_backoff_max_s = float(os.environ["REPAIR_BACKOFF_MAX_S"])
        c.suspend_enabled = _env_bool("ENABLE_SUSPEND", c.suspend_enabled)
        if os.environ.get("SUSPEND_CHECKPOINT_WINDOW_S"):
            c.suspend_checkpoint_window_s = float(
                os.environ["SUSPEND_CHECKPOINT_WINDOW_S"]
            )
        if os.environ.get("RESUME_TIMEOUT_S"):
            # clamp: a zero/negative timeout would burn every resume attempt
            # in one reconcile pass and land straight in ResumeFailed
            c.resume_timeout_s = max(0.1, float(os.environ["RESUME_TIMEOUT_S"]))
        if os.environ.get("RESUME_MAX_ATTEMPTS"):
            c.resume_max_attempts = max(1, int(os.environ["RESUME_MAX_ATTEMPTS"]))
        if os.environ.get("CHIP_BUDGET"):
            c.chip_budget = max(0, int(os.environ["CHIP_BUDGET"]))
        if os.environ.get("RECLAIM_PENDING_GRACE_S"):
            c.reclaim_pending_grace_s = max(
                0.0, float(os.environ["RECLAIM_PENDING_GRACE_S"])
            )
        if os.environ.get("POOL_PREWARM"):
            c.pool_prewarm = max(0, int(os.environ["POOL_PREWARM"]))
        c.pool_prewarm_accelerator = os.environ.get(
            "POOL_PREWARM_ACCELERATOR", c.pool_prewarm_accelerator
        )
        c.pool_prewarm_topology = os.environ.get(
            "POOL_PREWARM_TOPOLOGY", c.pool_prewarm_topology
        )
        if os.environ.get("SERVING_LOADING_WINDOW_S"):
            # clamp: a zero window would declare LoadFailed before the first
            # readiness probe ever ran
            c.serving_loading_window_s = max(
                0.1, float(os.environ["SERVING_LOADING_WINDOW_S"])
            )
        if os.environ.get("SERVING_DRAIN_TIMEOUT_S"):
            c.serving_drain_timeout_s = max(
                0.0, float(os.environ["SERVING_DRAIN_TIMEOUT_S"])
            )
        if os.environ.get("JOB_CHECKPOINT_WINDOW_S"):
            # clamp: a zero window would abandon every save before the first
            # checkpoint probe ever ran
            c.job_checkpoint_window_s = max(
                0.1, float(os.environ["JOB_CHECKPOINT_WINDOW_S"])
            )
        if os.environ.get("JOB_REQUEUE_BACKOFF_S"):
            c.job_requeue_backoff_s = max(
                0.0, float(os.environ["JOB_REQUEUE_BACKOFF_S"])
            )
        if os.environ.get("JOB_ADMISSION_TIMEOUT_S"):
            # 0 disables the bind timeout entirely
            c.job_admission_timeout_s = max(
                0.0, float(os.environ["JOB_ADMISSION_TIMEOUT_S"])
            )
        c.slo_enabled = _env_bool("SLO_ENABLED", c.slo_enabled)
        if os.environ.get("SLO_WINDOW_SCALE"):
            # clamp: non-positive would collapse every burn window to zero
            c.slo_window_scale = max(1e-6, float(os.environ["SLO_WINDOW_SCALE"]))
        if os.environ.get("SLO_EVAL_PERIOD_S"):
            c.slo_eval_period_s = max(0.0, float(os.environ["SLO_EVAL_PERIOD_S"]))
        if os.environ.get("STATUS_COALESCE_WINDOW_S"):
            c.status_coalesce_window_s = max(
                0.0, float(os.environ["STATUS_COALESCE_WINDOW_S"])
            )
        if os.environ.get("CANARY_PERIOD_S"):
            c.canary_period_s = max(0.0, float(os.environ["CANARY_PERIOD_S"]))
        if os.environ.get("CANARY_TIMEOUT_S"):
            c.canary_timeout_s = max(1.0, float(os.environ["CANARY_TIMEOUT_S"]))
        c.canary_namespace = os.environ.get("CANARY_NAMESPACE", c.canary_namespace)
        c.canary_accelerator = os.environ.get(
            "CANARY_ACCELERATOR", c.canary_accelerator
        )
        c.canary_topology = os.environ.get("CANARY_TOPOLOGY", c.canary_topology)
        if os.environ.get("AUTOSCALE_PERIOD_S"):
            c.autoscale_period_s = max(
                0.0, float(os.environ["AUTOSCALE_PERIOD_S"])
            )
        if os.environ.get("AUTOSCALE_STABILIZATION_S"):
            c.autoscale_stabilization_s = max(
                0.0, float(os.environ["AUTOSCALE_STABILIZATION_S"])
            )
        if os.environ.get("AUTOSCALE_IDLE_S"):
            c.autoscale_idle_s = max(0.0, float(os.environ["AUTOSCALE_IDLE_S"]))
        if os.environ.get("ACCOUNTING_PERIOD_S"):
            c.accounting_period_s = max(
                0.0, float(os.environ["ACCOUNTING_PERIOD_S"])
            )
        if os.environ.get("ACCOUNTING_IDLE_AFTER_S"):
            c.accounting_idle_after_s = max(
                0.0, float(os.environ["ACCOUNTING_IDLE_AFTER_S"])
            )
        if os.environ.get("ROUTER_EJECT_FAILURES"):
            # clamp: 0 would eject a replica on its first hiccup forever
            c.router_eject_failures = max(
                1, int(os.environ["ROUTER_EJECT_FAILURES"])
            )
        if os.environ.get("ROUTER_HEDGE_AFTER_S"):
            c.router_hedge_after_s = max(
                0.0, float(os.environ["ROUTER_HEDGE_AFTER_S"])
            )
        if os.environ.get("MAX_CONCURRENT_RECONCILES"):
            # clamp: 0/negative would spawn no workers and silently disable
            # every controller
            c.max_concurrent_reconciles = max(
                1, int(os.environ["MAX_CONCURRENT_RECONCILES"])
            )
        return c


# ---------------------------------------------------------------------------
# ENV_CONTRACT: every environment knob the package reads, declared once.
# The env-contract checker fails on undeclared reads and dead entries;
# keep consumer/doc accurate — they are the operator-facing registry.
# ---------------------------------------------------------------------------

ENV_CONTRACT: tuple = (
    # -- manager config (this module, Config.from_env) --
    EnvKnob("CLUSTER_DOMAIN", "cluster.local", "controllers/config.py",
            "cluster DNS suffix for service URLs"),
    EnvKnob("ADD_FSGROUP", "true", "controllers/config.py",
            "inject pod fsGroup for notebook volumes"),
    EnvKnob("ENABLE_CULLING", "false", "controllers/config.py",
            "enable the idle-culling controller", manifest=True),
    EnvKnob("CULL_IDLE_TIME", "1440", "controllers/config.py",
            "idle minutes before a notebook is culled", manifest=True),
    EnvKnob("IDLENESS_CHECK_PERIOD", "1", "controllers/config.py",
            "minutes between idleness probes", manifest=True),
    EnvKnob("TPU_IDLE_THRESHOLD", "0.05", "controllers/config.py",
            "TPU duty cycle below which a slice counts idle", manifest=True),
    EnvKnob("DEV", "false", "controllers/config.py",
            "dev mode: relax webhook/cert requirements"),
    EnvKnob("NOTEBOOK_GATEWAY_NAME", "data-science-gateway",
            "controllers/config.py", "Gateway routes attach to"),
    EnvKnob("NOTEBOOK_GATEWAY_NAMESPACE", "openshift-ingress",
            "controllers/config.py", "namespace of the Gateway"),
    EnvKnob("K8S_NAMESPACE", "tpu-notebooks-system", "controllers/config.py",
            "the manager's own namespace", manifest=True),
    EnvKnob("AUTH_PROXY_IMAGE", "kube-rbac-proxy:latest",
            "controllers/config.py",
            "kube-rbac-proxy sidecar image for oauth workbenches",
            manifest=True),
    EnvKnob("SET_PIPELINE_RBAC", "false", "controllers/config.py",
            "grant pipeline RBAC per workbench namespace"),
    EnvKnob("SET_PIPELINE_SECRET", "false", "controllers/config.py",
            "mirror the elyra pipeline secret per workbench"),
    EnvKnob("INJECT_CLUSTER_PROXY_ENV", "false", "controllers/config.py",
            "inject cluster-wide proxy env into notebooks"),
    EnvKnob("PROBE_BREAKER_THRESHOLD", "3", "controllers/config.py",
            "consecutive probe failures before the circuit opens"),
    EnvKnob("PROBE_BREAKER_COOLDOWN_S", "30", "controllers/config.py",
            "probe circuit-breaker cooldown seconds"),
    EnvKnob("READINESS_PROBE_PERIOD_S", "10", "controllers/config.py",
            "device-visibility readiness poll period"),
    EnvKnob("CHECKPOINT_WINDOW_S", "30", "controllers/config.py",
            "checkpoint-before-evict window for degraded slices"),
    EnvKnob("REPAIR_MAX_ATTEMPTS", "6", "controllers/config.py",
            "re-placement attempts before RepairFailed"),
    EnvKnob("REPAIR_BACKOFF_S", "1", "controllers/config.py",
            "base repair retry backoff"),
    EnvKnob("REPAIR_BACKOFF_MAX_S", "30", "controllers/config.py",
            "repair retry backoff cap"),
    EnvKnob("ENABLE_SUSPEND", "false", "controllers/config.py",
            "cull TPU notebooks into the warm slice pool"),
    EnvKnob("SUSPEND_CHECKPOINT_WINDOW_S", "15", "controllers/config.py",
            "checkpoint-before-suspend window"),
    EnvKnob("RESUME_TIMEOUT_S", "60", "controllers/config.py",
            "per-attempt resume-to-mesh-ready timeout"),
    EnvKnob("RESUME_MAX_ATTEMPTS", "6", "controllers/config.py",
            "resume attempts before ResumeFailed"),
    EnvKnob("CHIP_BUDGET", "0", "controllers/config.py",
            "oversubscription budget in chips (also read by utils/invcheck)"),
    EnvKnob("RECLAIM_PENDING_GRACE_S", "1", "controllers/config.py",
            "unschedulable grace before reclaim acts"),
    EnvKnob("POOL_PREWARM", "0", "controllers/config.py",
            "warm slices to keep ahead of demand"),
    EnvKnob("POOL_PREWARM_ACCELERATOR", "v5e", "controllers/config.py",
            "accelerator type of pre-warmed slices"),
    EnvKnob("POOL_PREWARM_TOPOLOGY", "2x2", "controllers/config.py",
            "topology of pre-warmed slices"),
    EnvKnob("SERVING_LOADING_WINDOW_S", "30", "controllers/config.py",
            "InferenceEndpoint Loading window before LoadFailed"),
    EnvKnob("SERVING_DRAIN_TIMEOUT_S", "5", "controllers/config.py",
            "default endpoint drain window (also serving/__main__)"),
    EnvKnob("JOB_CHECKPOINT_WINDOW_S", "10", "controllers/config.py",
            "TPUJob checkpoint window"),
    EnvKnob("JOB_REQUEUE_BACKOFF_S", "2", "controllers/config.py",
            "preempted-job requeue backoff"),
    EnvKnob("JOB_ADMISSION_TIMEOUT_S", "120", "controllers/config.py",
            "gang-bind timeout before a job parks and requeues"),
    EnvKnob("SLO_ENABLED", "true", "controllers/config.py",
            "run the SLO engine"),
    EnvKnob("SLO_WINDOW_SCALE", "1", "controllers/config.py",
            "shrink factor for burn-rate windows in soaks"),
    EnvKnob("SLO_EVAL_PERIOD_S", "0", "controllers/config.py",
            "SLO evaluation period (0 = derive from scale)"),
    EnvKnob("STATUS_COALESCE_WINDOW_S", "0.05", "controllers/config.py",
            "status-write coalescing window (0 disables)"),
    EnvKnob("CANARY_PERIOD_S", "0", "controllers/config.py",
            "canary probe period (0 disables; also gates main.py wiring)"),
    EnvKnob("CANARY_TIMEOUT_S", "120", "controllers/config.py",
            "canary round-trip timeout"),
    EnvKnob("CANARY_NAMESPACE", "slo-canary", "controllers/config.py",
            "namespace canary notebooks land in"),
    EnvKnob("CANARY_ACCELERATOR", "", "controllers/config.py",
            "canary TPU accelerator ('' = CPU canary)"),
    EnvKnob("CANARY_TOPOLOGY", "", "controllers/config.py",
            "canary TPU topology"),
    EnvKnob("AUTOSCALE_PERIOD_S", "0", "controllers/config.py",
            "replica-autoscaler sweep period (0 disables; also gates "
            "main.py wiring)"),
    EnvKnob("AUTOSCALE_STABILIZATION_S", "30", "controllers/config.py",
            "default scale-down stabilization window (flap damping)"),
    EnvKnob("AUTOSCALE_IDLE_S", "120", "controllers/config.py",
            "default idle window before scale-to-zero parks an endpoint"),
    EnvKnob("ACCOUNTING_PERIOD_S", "1", "controllers/config.py",
            "chip-time accountant tick period (0 disables; also gates "
            "main.py wiring)"),
    EnvKnob("ACCOUNTING_IDLE_AFTER_S", "300", "controllers/config.py",
            "activity staleness before bound chips count idle-bound"),
    EnvKnob("ROUTER_EJECT_FAILURES", "3", "controllers/config.py",
            "consecutive failures before the router ejects a replica"),
    EnvKnob("ROUTER_HEDGE_AFTER_S", "0", "controllers/config.py",
            "router tail-hedge trigger (0 disables hedging)"),
    EnvKnob("MAX_CONCURRENT_RECONCILES", "4", "controllers/config.py",
            "worker threads per controller"),
    # -- manager process wiring (main.py) --
    EnvKnob("LOG_FORMAT", "text", "main.py", "text or json log output"),
    EnvKnob("KUBERNETES_SERVICE_HOST", "", "main.py",
            "in-cluster apiserver host (also cluster/remote.py)"),
    EnvKnob("KUBERNETES_SERVICE_PORT", "443", "cluster/remote.py",
            "in-cluster apiserver port"),
    EnvKnob("KUBECONFIG", "", "main.py",
            "out-of-cluster kubeconfig path (also cluster/remote.py)"),
    EnvKnob("KUBE_API_QPS", "20", "main.py",
            "client-side rate limit for the remote transport"),
    EnvKnob("KUBE_API_BURST", "30", "main.py",
            "client-side burst for the remote transport"),
    EnvKnob("WEBHOOK_CERT_DIR", "/tmp/k8s-webhook-server/serving-certs",
            "main.py", "webhook TLS cert directory"),
    EnvKnob("WEBHOOK_PORT", "9443", "main.py", "webhook listen port"),
    EnvKnob("METRICS_PORT", "8080", "main.py", "metrics listen port"),
    EnvKnob("HEALTH_PORT", "8081", "main.py", "health listen port"),
    # -- probe agent (runs in the notebook pod, not the manager) --
    EnvKnob("NB_PROBE_PORT", "8889", "probe/__main__.py",
            "probe agent listen port"),
    EnvKnob("NB_TPU_CHIPS_EXPECTED", "0", "probe/agent.py",
            "chips the agent expects to see locally"),
    EnvKnob("NB_TPU_HOSTS", "1", "probe/agent.py",
            "hosts in the slice gang"),
    EnvKnob("JAX_PROCESS_ID", "0", "probe/agent.py",
            "process index (also parallel/distributed.py)"),
    EnvKnob("TPU_RUNTIME_METRICS_PORTS", "", "probe/agent.py",
            "libtpu runtime metrics ports to scrape"),
    EnvKnob("HOSTNAME", "", "probe/agent.py",
            "pod hostname for ordinal derivation"),
    # -- serving engine (decode pod) --
    EnvKnob("SERVING_PORT", "8000", "serving/__main__.py",
            "inference server listen port"),
    EnvKnob("SERVING_MAX_SLOTS", "8", "serving/server.py",
            "continuous-batching slot count"),
    EnvKnob("SERVING_MAX_SEQ", "2048", "serving/server.py",
            "max sequence length"),
    EnvKnob("SERVING_MAX_QUEUE", "64", "serving/server.py",
            "admission queue bound"),
    EnvKnob("SERVING_DECODE_BURST", "8", "serving/server.py",
            "decode steps per scheduler turn"),
    EnvKnob("SERVING_CHECKPOINT", "", "serving/server.py",
            "checkpoint path to restore"),
    EnvKnob("SERVING_MODEL_CONFIG", "", "serving/server.py",
            "model config JSON path"),
    # -- multi-host runtime (parallel/distributed.py) --
    EnvKnob("JAX_NUM_PROCESSES", "1", "parallel/distributed.py",
            "process count for jax.distributed"),
    EnvKnob("TPU_WORKER_ID", "0", "parallel/distributed.py",
            "worker ordinal fallback for process id"),
    EnvKnob("JAX_COORDINATOR_ADDRESS", "", "parallel/distributed.py",
            "coordinator address for multi-host init"),
    EnvKnob("TPU_WORKER_HOSTNAMES", "", "parallel/distributed.py",
            "comma-separated gang hostnames"),
    # -- debug / guard rails (utils/, cluster/remote_fixture.py) --
    EnvKnob("ODH_WIRE_DEBUG_DIR", "", "cluster/remote_fixture.py",
            "dump wire-protocol transcripts here"),
    EnvKnob("RACECHECK", "0", "utils/racecheck.py",
            "arm the lock-discipline runtime guard"),
    EnvKnob("INVCHECK", "0", "utils/invcheck.py",
            "arm the invariant-monitor runtime guard"),
    EnvKnob("JAXGUARD", "0", "utils/jaxguard.py",
            "arm the data-plane discipline runtime guard"),
    EnvKnob("DEPLOYGUARD", "0", "utils/deployguard.py",
            "arm the deployment-surface runtime guard"),
    EnvKnob("DEPLOYGUARD_SURFACE_OUT", "", "utils/deployguard.py",
            "dump the recorded (flow, verb, kind) surface to this path"),
    EnvKnob("PROFILE", "0", "utils/profiler.py",
            "arm the continuous data-plane profiler"),
    EnvKnob("CPPROFILE", "0", "runtime/cpprofile.py",
            "arm the control-plane profiler (reconcile causes, cache-scan "
            "accounting, takeover decomposition)"),
)
