"""Controller configuration.

The reference stacks CLI flags + env vars + kustomize params (SURVEY §5
config/flag system); this build centralizes them in one dataclass whose
from_env() reads the same env names the reference uses, so deployment
manifests translate directly."""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional


def _env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


@dataclass
class Config:
    # core reconciler (reference notebook_controller.go:238,514,576-599)
    cluster_domain: str = "cluster.local"
    add_fsgroup: bool = True

    # culling (reference culling_controller.go:525-558; minutes, same defaults)
    enable_culling: bool = False
    cull_idle_time_min: float = 1440.0
    idleness_check_period_min: float = 1.0
    dev_mode: bool = False

    # TPU-native culling signal: require BOTH Jupyter-idle and TPU-idle
    tpu_idle_threshold: float = 0.05  # duty cycle below which the slice is idle
    probe_port: int = 8889
    # probe circuit breaker (runtime/breaker.py): after `threshold`
    # consecutive jupyter-probe failures for one notebook, skip probing it
    # for a growing cooldown instead of paying connect timeouts every cycle
    probe_breaker_threshold: int = 3
    probe_breaker_cooldown_s: float = 30.0
    # device-visibility readiness gate (controllers/probe_status.py): poll
    # cadence for /tpu/readiness until the mesh gate is green
    readiness_probe_period_s: float = 10.0
    # slice repair (controllers/slice_repair.py): the checkpoint-before-evict
    # window (how long a Degraded slice gets to save state before the gang is
    # evicted), and the bounded jittered retry while capacity recovers —
    # attempt N waits ~ base * 2^N (+/- jitter), RepairFailed after max
    checkpoint_window_s: float = 30.0
    repair_max_attempts: int = 6
    repair_backoff_s: float = 1.0
    repair_backoff_max_s: float = 30.0
    # suspend/resume + warm slice pools (controllers/suspend.py,
    # cluster/slicepool.py): culling a TPU notebook checkpoints kernel state
    # and releases the slice mesh-formed into a warm pool instead of tearing
    # it down; resume binds from the pool (hit) or falls back to cold
    # placement (miss). Opt-in like culling itself.
    suspend_enabled: bool = False
    # checkpoint-before-suspend window (the cull path's analog of the repair
    # path's checkpoint_window_s)
    suspend_checkpoint_window_s: float = 15.0
    # per-ordinal checkpoint-hook retries inside the window: bounded, jittered
    # (the cluster/client.py 429 pattern) so one transient probe-agent blip
    # never aborts the whole suspend
    suspend_checkpoint_retries: int = 3
    suspend_checkpoint_backoff_s: float = 0.2
    # resume: one attempt = one warm-claim-or-cold-placement try; a resume
    # that hasn't reached mesh-ready within resume_timeout_s re-claims (a
    # poisoned warm slice must not wedge the notebook), ResumeFailed after max
    resume_timeout_s: float = 60.0
    resume_max_attempts: int = 6
    # oversubscription policy: total admitted chip demand (active + suspended
    # notebooks) may exceed physical chips up to this budget; a cold create /
    # resume that finds no capacity reclaims the lowest-priority pool-idle or
    # suspend-eligible slice. 0 = no budget cap (reclaim still gated on a
    # suitable victim existing). Demand beyond the budget queues, untouched.
    chip_budget: int = 0
    # how long a TPU pod must sit unschedulable before the reclaimer acts —
    # the scheduler's capacity-freed fast path gets first shot
    reclaim_pending_grace_s: float = 1.0
    # slice-pool pre-warming (ISSUE 9 satellite): keep this many warm slices
    # of the configured shape AHEAD of demand (spin up, mesh-form, park)
    # instead of only recycling suspended ones. 0 = off.
    pool_prewarm: int = 0
    pool_prewarm_accelerator: str = "v5e"
    pool_prewarm_topology: str = "2x2"
    # inference serving (controllers/inference.py): how long Loading gets to
    # reach mesh-ready + verified restore before LoadFailed, and the default
    # drain window a stopped endpoint's in-flight requests get (overridable
    # per-endpoint via spec.serving.drainTimeoutS)
    serving_loading_window_s: float = 30.0
    serving_drain_timeout_s: float = 5.0
    # batch/RL jobs (controllers/job.py): the bounded window a cadence or
    # preempt checkpoint gets before the job moves on, the requeue
    # backoff a preempted job waits before re-admitting (an instant
    # re-admission would race the very requester its slice was reclaimed
    # for), and the bind timeout after which an Admitted job whose gangs
    # never all came ready parks and requeues instead of wedging (a
    # claimed slice can die under the gang mid-bind)
    job_checkpoint_window_s: float = 10.0
    job_requeue_backoff_s: float = 2.0
    job_admission_timeout_s: float = 120.0
    # SLO engine + alerting (runtime/slo.py, runtime/alerts.py): window_scale
    # shrinks the canonical 5m/30m/1h/6h burn windows (soaks/tests run the
    # real rule shapes in seconds); eval period 0 derives from the scale
    slo_enabled: bool = True
    slo_window_scale: float = 1.0
    slo_eval_period_s: float = 0.0
    # black-box canary prober (runtime/prober.py): period 0 disables; an
    # accelerator/topology makes the canary exercise the device-visibility
    # gate instead of a plain CPU notebook
    canary_period_s: float = 0.0
    canary_timeout_s: float = 120.0
    canary_namespace: str = "slo-canary"
    canary_accelerator: str = ""
    canary_topology: str = ""
    # MaxConcurrentReconciles analog: worker threads per controller. The
    # workqueue's per-key single-flight makes >1 safe; under create storms
    # (and over the higher-latency remote transport) it is the difference
    # between serial and pipelined reconciles
    max_concurrent_reconciles: int = 4
    # status-write coalescing window (runtime/coalesce.py): adjacent status
    # mirror patches for one object within this window batch into a single
    # PATCH (leading-edge write-through, so steady state is unchanged).
    # 0 disables coalescing entirely
    status_coalesce_window_s: float = 0.05

    # extension controller / webhook (reference odh main.go + webhook consts)
    auth_proxy_image: str = "kube-rbac-proxy:latest"
    gateway_name: str = "data-science-gateway"
    gateway_namespace: str = "openshift-ingress"
    controller_namespace: str = "tpu-notebooks-system"
    set_pipeline_rbac: bool = False
    set_pipeline_secret: bool = False
    inject_cluster_proxy_env: bool = False

    @classmethod
    def from_env(cls) -> "Config":
        c = cls()
        c.cluster_domain = os.environ.get("CLUSTER_DOMAIN", c.cluster_domain)
        c.add_fsgroup = _env_bool("ADD_FSGROUP", c.add_fsgroup)
        c.enable_culling = _env_bool("ENABLE_CULLING", c.enable_culling)
        if os.environ.get("CULL_IDLE_TIME"):
            c.cull_idle_time_min = float(os.environ["CULL_IDLE_TIME"])
        if os.environ.get("IDLENESS_CHECK_PERIOD"):
            c.idleness_check_period_min = float(os.environ["IDLENESS_CHECK_PERIOD"])
        c.dev_mode = _env_bool("DEV", c.dev_mode)
        c.gateway_name = os.environ.get("NOTEBOOK_GATEWAY_NAME", c.gateway_name)
        c.gateway_namespace = os.environ.get(
            "NOTEBOOK_GATEWAY_NAMESPACE", c.gateway_namespace
        )
        c.controller_namespace = os.environ.get("K8S_NAMESPACE", c.controller_namespace)
        c.set_pipeline_rbac = _env_bool("SET_PIPELINE_RBAC", c.set_pipeline_rbac)
        c.set_pipeline_secret = _env_bool("SET_PIPELINE_SECRET", c.set_pipeline_secret)
        c.inject_cluster_proxy_env = _env_bool(
            "INJECT_CLUSTER_PROXY_ENV", c.inject_cluster_proxy_env
        )
        if os.environ.get("PROBE_BREAKER_THRESHOLD"):
            c.probe_breaker_threshold = max(
                1, int(os.environ["PROBE_BREAKER_THRESHOLD"])
            )
        if os.environ.get("PROBE_BREAKER_COOLDOWN_S"):
            c.probe_breaker_cooldown_s = float(
                os.environ["PROBE_BREAKER_COOLDOWN_S"]
            )
        if os.environ.get("READINESS_PROBE_PERIOD_S"):
            c.readiness_probe_period_s = float(os.environ["READINESS_PROBE_PERIOD_S"])
        if os.environ.get("CHECKPOINT_WINDOW_S"):
            c.checkpoint_window_s = float(os.environ["CHECKPOINT_WINDOW_S"])
        if os.environ.get("REPAIR_MAX_ATTEMPTS"):
            # clamp: at least one attempt, or every degradation would be
            # declared RepairFailed before the first re-placement
            c.repair_max_attempts = max(1, int(os.environ["REPAIR_MAX_ATTEMPTS"]))
        if os.environ.get("REPAIR_BACKOFF_S"):
            c.repair_backoff_s = float(os.environ["REPAIR_BACKOFF_S"])
        if os.environ.get("REPAIR_BACKOFF_MAX_S"):
            c.repair_backoff_max_s = float(os.environ["REPAIR_BACKOFF_MAX_S"])
        c.suspend_enabled = _env_bool("ENABLE_SUSPEND", c.suspend_enabled)
        if os.environ.get("SUSPEND_CHECKPOINT_WINDOW_S"):
            c.suspend_checkpoint_window_s = float(
                os.environ["SUSPEND_CHECKPOINT_WINDOW_S"]
            )
        if os.environ.get("RESUME_TIMEOUT_S"):
            # clamp: a zero/negative timeout would burn every resume attempt
            # in one reconcile pass and land straight in ResumeFailed
            c.resume_timeout_s = max(0.1, float(os.environ["RESUME_TIMEOUT_S"]))
        if os.environ.get("RESUME_MAX_ATTEMPTS"):
            c.resume_max_attempts = max(1, int(os.environ["RESUME_MAX_ATTEMPTS"]))
        if os.environ.get("CHIP_BUDGET"):
            c.chip_budget = max(0, int(os.environ["CHIP_BUDGET"]))
        if os.environ.get("RECLAIM_PENDING_GRACE_S"):
            c.reclaim_pending_grace_s = max(
                0.0, float(os.environ["RECLAIM_PENDING_GRACE_S"])
            )
        if os.environ.get("POOL_PREWARM"):
            c.pool_prewarm = max(0, int(os.environ["POOL_PREWARM"]))
        c.pool_prewarm_accelerator = os.environ.get(
            "POOL_PREWARM_ACCELERATOR", c.pool_prewarm_accelerator
        )
        c.pool_prewarm_topology = os.environ.get(
            "POOL_PREWARM_TOPOLOGY", c.pool_prewarm_topology
        )
        if os.environ.get("SERVING_LOADING_WINDOW_S"):
            # clamp: a zero window would declare LoadFailed before the first
            # readiness probe ever ran
            c.serving_loading_window_s = max(
                0.1, float(os.environ["SERVING_LOADING_WINDOW_S"])
            )
        if os.environ.get("SERVING_DRAIN_TIMEOUT_S"):
            c.serving_drain_timeout_s = max(
                0.0, float(os.environ["SERVING_DRAIN_TIMEOUT_S"])
            )
        if os.environ.get("JOB_CHECKPOINT_WINDOW_S"):
            # clamp: a zero window would abandon every save before the first
            # checkpoint probe ever ran
            c.job_checkpoint_window_s = max(
                0.1, float(os.environ["JOB_CHECKPOINT_WINDOW_S"])
            )
        if os.environ.get("JOB_REQUEUE_BACKOFF_S"):
            c.job_requeue_backoff_s = max(
                0.0, float(os.environ["JOB_REQUEUE_BACKOFF_S"])
            )
        if os.environ.get("JOB_ADMISSION_TIMEOUT_S"):
            # 0 disables the bind timeout entirely
            c.job_admission_timeout_s = max(
                0.0, float(os.environ["JOB_ADMISSION_TIMEOUT_S"])
            )
        c.slo_enabled = _env_bool("SLO_ENABLED", c.slo_enabled)
        if os.environ.get("SLO_WINDOW_SCALE"):
            # clamp: non-positive would collapse every burn window to zero
            c.slo_window_scale = max(1e-6, float(os.environ["SLO_WINDOW_SCALE"]))
        if os.environ.get("SLO_EVAL_PERIOD_S"):
            c.slo_eval_period_s = max(0.0, float(os.environ["SLO_EVAL_PERIOD_S"]))
        if os.environ.get("STATUS_COALESCE_WINDOW_S"):
            c.status_coalesce_window_s = max(
                0.0, float(os.environ["STATUS_COALESCE_WINDOW_S"])
            )
        if os.environ.get("CANARY_PERIOD_S"):
            c.canary_period_s = max(0.0, float(os.environ["CANARY_PERIOD_S"]))
        if os.environ.get("CANARY_TIMEOUT_S"):
            c.canary_timeout_s = max(1.0, float(os.environ["CANARY_TIMEOUT_S"]))
        c.canary_namespace = os.environ.get("CANARY_NAMESPACE", c.canary_namespace)
        c.canary_accelerator = os.environ.get(
            "CANARY_ACCELERATOR", c.canary_accelerator
        )
        c.canary_topology = os.environ.get("CANARY_TOPOLOGY", c.canary_topology)
        if os.environ.get("MAX_CONCURRENT_RECONCILES"):
            # clamp: 0/negative would spawn no workers and silently disable
            # every controller
            c.max_concurrent_reconciles = max(
                1, int(os.environ["MAX_CONCURRENT_RECONCILES"])
            )
        return c
