"""Notebook condition helpers shared by the device-health gate and the
slice-repair controller.

NotebookStatus.conditions has TWO writers: the core reconciler mirrors pod 0's
conditions (notebook.py _update_status), and the repair stack owns the
device/repair conditions (`TPUHealthy`, `Degraded` — constants.py). The mirror
preserves the repair-owned types; this module gives the repair stack a safe
read-modify-write (`write_condition`: fresh read under conflict retry,
everything else in the conditions list untouched) so neither writer can lose
the other's entries. The upsert mechanics delegate to the apimachinery
helper, so transition-time rules live in exactly one place.
"""
from __future__ import annotations

from typing import List, Optional

from ..api.notebook import Notebook
from ..apimachinery import Condition, NotFoundError
from ..apimachinery import get_condition as _get_in_list
from ..apimachinery import set_condition as _upsert_in_list
from ..cluster.client import retry_on_conflict
from ..runtime.flightrecorder import recorder
from . import constants as C

# condition types owned by the repair/SLO stack, NOT the pod-condition
# mirror (the mirror preserves these when rebuilding from pod 0)
REPAIR_OWNED_CONDITIONS = (
    C.TPU_HEALTHY_CONDITION,
    C.TPU_DEGRADED_CONDITION,
    C.SLO_DEGRADED_CONDITION,
)


def get_condition(nb: Notebook, ctype: str) -> Optional[Condition]:
    return _get_in_list(nb.status.conditions, ctype)


def condition_is(nb: Notebook, ctype: str, status: str) -> bool:
    c = get_condition(nb, ctype)
    return c is not None and c.status == status


def upsert_condition(
    conditions: List[Condition],
    ctype: str,
    status: str,
    reason: str = "",
    message: str = "",
) -> bool:
    """In-place upsert (apimachinery transition-time semantics: the
    timestamp only moves on a status flip); returns whether anything
    changed."""
    cur = _get_in_list(conditions, ctype)
    if cur is not None and cur.status == status and cur.reason == reason \
            and cur.message == message:
        return False
    conditions[:] = _upsert_in_list(
        conditions,
        Condition(type=ctype, status=status, reason=reason, message=message),
    )
    return True


def write_condition(
    client,
    api_reader,
    nb: Notebook,
    ctype: str,
    status: str,
    reason: str = "",
    message: str = "",
) -> None:
    """Write one condition via fresh-read RMW under conflict retry. No-ops
    (same status/reason/message) cost one read and zero writes. Writes that
    actually land are sampled into the flight-recorder ring — condition
    transitions are the incident bundle's state-machine timeline."""
    # cheap pre-check against the object in hand; a stale cache self-heals
    # level-triggered (the event that updates it re-enqueues the notebook)
    cur = get_condition(nb, ctype)
    if cur is not None and cur.status == status and cur.reason == reason \
            and cur.message == message:
        return

    def attempt() -> bool:
        fresh = api_reader.get(Notebook, nb.metadata.namespace, nb.metadata.name)
        if upsert_condition(fresh.status.conditions, ctype, status, reason, message):
            client.update_status(fresh)
            return True
        return False

    try:
        changed = retry_on_conflict(attempt)
    except NotFoundError:
        return  # deleted mid-reconcile
    if changed:
        recorder.record(
            "condition",
            notebook=f"{nb.metadata.namespace}/{nb.metadata.name}",
            type=ctype,
            status=status,
            reason=reason,
        )
