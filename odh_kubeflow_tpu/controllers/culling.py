"""Culling controller: idle notebooks release their TPU slice.

Faithful to the reference's state machine (reference
culling_controller.go: Reconcile :86-203, notebookIsIdle :220-241,
getNotebookResourceResponse :243-273, updateTimestampFromKernelsActivity
:371-402, setStopAnnotation :475-492, env parsing :525-558) with one
TPU-native extension: a notebook is only idle when the Jupyter signal AND the
TPU duty-cycle signal agree. Kernels can sit "idle" while an async JAX job
hammers the slice, and a busy-looking kernel can hold zero chips — on TPU
hardware the slice is the money, so both must be quiet before the stop
annotation fires and replicas -> 0 frees the whole slice.

Annotations (same keys as the reference):
- notebooks.kubeflow.org/last-activity
- notebooks.kubeflow.org/last_activity_check_timestamp
- kubeflow-resource-stopped  (set with the cull timestamp when idle)
"""
from __future__ import annotations

import json
import logging
import time
from typing import Callable, List, Optional, Tuple

from ..api.core import Pod
from ..api.notebook import Notebook
from ..apimachinery import NotFoundError, now_rfc3339, parse_time, rfc3339
from ..cluster.client import retry_on_conflict
from ..runtime.breaker import CircuitBreaker
from ..runtime.controller import Request, Result
from ..runtime.flightrecorder import recorder
from ..runtime.manager import Manager
from ..tpu import plan_slice
from . import constants as C
from .conditions import condition_is
from .config import Config
from .metrics import NotebookMetrics
from .notebook import per_ordinal_probe_urls, statefulset_name

log = logging.getLogger(__name__)

HTTPGet = Callable[[str], Tuple[int, bytes]]


def _default_http_get(url: str, timeout: float = 10.0) -> Tuple[int, bytes]:
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as resp:  # noqa: S310
        return resp.status, resp.read()


class CullingReconciler:
    def __init__(
        self,
        manager: Manager,
        config: Optional[Config] = None,
        http_get: Optional[HTTPGet] = None,
        metrics: Optional[NotebookMetrics] = None,
    ):
        self.manager = manager
        self.client = manager.client
        # culling is DESTRUCTIVE (replicas -> 0 frees the slice): every read
        # feeding the idle decision must be fresh, not informer-cache stale —
        # a lagging cache after un-stop briefly looks idle and would re-cull
        self.api_reader = manager.api_reader
        self.config = config or Config()
        self.http_get = http_get or _default_http_get
        self.metrics = metrics or NotebookMetrics(manager.metrics)
        # per-notebook probe circuit breaker: repeated probe failures open
        # it, and the reconcile then skips + requeues with backoff instead
        # of paying a connect timeout against a dead agent every cycle
        self.breaker = CircuitBreaker(
            failure_threshold=self.config.probe_breaker_threshold,
            cooldown_s=self.config.probe_breaker_cooldown_s,
        )

    def setup(self) -> None:
        """Gated on ENABLE_CULLING exactly like the reference's main()
        (notebook-controller/main.go:111-119): disabled -> no controller."""
        if not self.config.enable_culling:
            log.info("culling disabled (ENABLE_CULLING not set)")
            return
        self.manager.builder("culling").for_(Notebook).with_workers(
            self.config.max_concurrent_reconciles
        ).complete(self.reconcile)

    # ---------- URLs ----------

    def jupyter_url(self, nb: Notebook, resource: str) -> str:
        """Reference URL shape (culling_controller.go:252-259); DEV mode goes
        through a local proxy the way the reference uses kubectl proxy."""
        if self.config.dev_mode:
            return (
                f"http://localhost:8001/api/v1/namespaces/{nb.metadata.namespace}"
                f"/services/{nb.metadata.name}:http-notebook/proxy"
                f"/notebook/{nb.metadata.namespace}/{nb.metadata.name}/api/{resource}"
            )
        return (
            f"http://{nb.metadata.name}.{nb.metadata.namespace}.svc."
            f"{self.config.cluster_domain}"
            f"/notebook/{nb.metadata.namespace}/{nb.metadata.name}/api/{resource}"
        )

    def probe_urls(self, nb: Notebook) -> List[str]:
        """Per-host TPU utilization endpoints (multi-host slices: every host)."""
        if nb.spec.tpu is None or not nb.spec.tpu.accelerator:
            return []
        shape = plan_slice(
            nb.spec.tpu.accelerator, nb.spec.tpu.topology, nb.spec.tpu.chips
        )
        return per_ordinal_probe_urls(
            self.api_reader, self.config, nb, shape.hosts, "/tpu/utilization"
        )

    # ---------- probes ----------

    def _get_json(self, url: str):
        status, body = self.http_get(url)
        if status != 200:
            raise ConnectionError(f"GET {url} -> {status}")
        return json.loads(body.decode() or "null")

    def probe_jupyter(self, nb: Notebook) -> Tuple[bool, float]:
        """(busy, last_activity_ts). Raises on probe failure."""
        kernels = self._get_json(self.jupyter_url(nb, "kernels")) or []
        try:
            terminals = self._get_json(self.jupyter_url(nb, "terminals")) or []
        except Exception:
            terminals = []  # terminals API can be disabled (reference tolerates)
        busy = any(k.get("execution_state") == "busy" for k in kernels)
        last = 0.0
        for item in list(kernels) + list(terminals):
            ts = item.get("last_activity", "")
            if ts:
                try:
                    last = max(last, parse_time(ts).timestamp())
                except ValueError:
                    pass
        return busy, last

    def probe_tpu(self, nb: Notebook) -> Optional[Tuple[bool, float]]:
        """(busy, last_busy_ts) aggregated over hosts; None when there is no
        TPU or no host could be probed (fall back to the Jupyter signal so a
        probe-less image can still be culled)."""
        urls = self.probe_urls(nb)
        if not urls:
            return None
        busy = False
        last = 0.0
        reached = 0
        for url in urls:
            try:
                data = self._get_json(url)
            except Exception as e:
                # per-host degradation is expected (multi-host slices probe
                # every ordinal; a rebooting host must not veto the verdict)
                # but it must be visible when someone goes looking
                log.debug("culling: tpu probe %s unreachable: %s", url, e)
                continue
            reached += 1
            if float(data.get("duty_cycle", 0.0)) > self.config.tpu_idle_threshold:
                busy = True
            if data.get("warming"):
                # the monitor does not yet have a full observation window:
                # no idleness verdict — treat as busy rather than cull a
                # notebook during probe bring-up
                busy = True
            ts = data.get("last_busy", "")
            if ts:
                try:
                    last = max(last, parse_time(ts).timestamp())
                except ValueError:
                    pass
        if reached == 0:
            return None
        return busy, last

    # ---------- reconcile ----------

    def reconcile(self, req: Request) -> Optional[Result]:
        period_s = self.config.idleness_check_period_min * 60.0
        try:
            nb = self.api_reader.get(Notebook, req.namespace, req.name)
        except NotFoundError:
            self.breaker.forget(req.key)  # no monotonic growth across churn
            return None
        if nb.metadata.deletion_timestamp:
            return None

        annotations = nb.metadata.annotations

        # stopped (incl. reconciliation lock): drop activity annotations and
        # wait for the unstop watch event (reference :104-117)
        if C.STOP_ANNOTATION in annotations:
            self._remove_activity_annotations(nb)
            return None

        # mid-repair (Degraded or the repair-state machine active): the
        # notebook is DOWN, not idle — its pods are evicted/rescheduling and
        # every probe would fail. Suspend the idleness clock entirely: no
        # probe, no cull, no annotation advance. The slice-repair controller
        # resets last-activity at repair completion, so recovery time never
        # counts as idleness (a preempted notebook must not be culled for
        # "idling" during its own repair).
        if (
            C.TPU_REPAIR_STATE_ANNOTATION in annotations
            or condition_is(nb, C.TPU_DEGRADED_CONDITION, "True")
        ):
            return Result(requeue_after=period_s)

        # mid-resume (suspend controller driving Resuming/ResumeFailed, stop
        # annotation already gone): same contract as repair — the notebook is
        # coming back, not idling. No probe, no cull, no annotation advance;
        # the suspend controller re-arms last-activity at resume completion,
        # so a just-resumed notebook starts a FRESH idle clock instead of
        # being re-culled off its preserved pre-suspend last-activity.
        if annotations.get(C.TPU_SUSPEND_STATE_ANNOTATION):
            return Result(requeue_after=period_s)

        # pod 0 gone, going, or not yet Ready: nothing to probe (reference
        # :120-135, strengthened). Idleness is only measurable on a READY
        # pod: a terminating pod's server answers probes for seconds after
        # deletion, and a Pending replacement can be probed THROUGH stale
        # Service endpoints still pointing at the previous incarnation —
        # either way the culler would judge a notebook idle while its real
        # pod hasn't started, re-cull it, and the stop annotation then
        # blocks the recreate forever (a level-triggering deadlock observed
        # under CPU starvation with sub-second cull thresholds; unreachable
        # at the reference's minute-scale thresholds, but the state machine
        # should not depend on that).
        try:
            pod0 = self.api_reader.get(
                Pod, nb.metadata.namespace, f"{statefulset_name(nb.metadata.name)}-0"
            )
            if pod0.metadata.deletion_timestamp:
                raise NotFoundError("pod terminating")
        except NotFoundError:
            self._remove_activity_annotations(nb)
            return Result(requeue_after=period_s)
        if not pod0.is_ready():
            # exists but not Ready (starting, or a readiness flap): skip the
            # probe — KEEPING the annotations, so a flapping-but-idle
            # notebook's idle clock is not reset — and come back. Probing
            # here can hit stale Service endpoints still pointing at the
            # previous incarnation's server and judge a pod idle before it
            # has started.
            return Result(requeue_after=period_s)

        # first sight: initialize the annotation state machine (reference :141-153)
        if C.LAST_ACTIVITY_ANNOTATION not in annotations:
            self._patch_annotations(
                nb,
                {
                    C.LAST_ACTIVITY_ANNOTATION: now_rfc3339(),
                    C.LAST_ACTIVITY_CHECK_TIMESTAMP_ANNOTATION: now_rfc3339(),
                },
            )
            return Result(requeue_after=period_s)

        # respect the check cadence (reference :156-159, 205-217)
        check_ts = annotations.get(C.LAST_ACTIVITY_CHECK_TIMESTAMP_ANNOTATION, "")
        if check_ts:
            try:
                elapsed = time.time() - parse_time(check_ts).timestamp()
                if elapsed < period_s:
                    return Result(requeue_after=period_s - elapsed)
            except ValueError:
                pass

        # probe circuit breaker: a notebook whose agent keeps failing is
        # skipped (requeue with the breaker's cooldown) instead of hammered —
        # one dead agent must not absorb this controller's worker time
        if not self.breaker.allow(req.key):
            return Result(
                requeue_after=max(0.05, min(self.breaker.retry_after(req.key), period_s))
            )

        # probe (reference :165-167; TPU extension)
        try:
            jupyter_busy, jupyter_last = self.probe_jupyter(nb)
        except Exception as e:
            log.warning("culling: jupyter probe failed for %s: %s", req.key, e)
            if self.breaker.record_failure(req.key):
                log.warning(
                    "culling: probe breaker OPEN for %s (%d consecutive failures)",
                    req.key,
                    self.config.probe_breaker_threshold,
                )
            self._patch_annotations(
                nb, {C.LAST_ACTIVITY_CHECK_TIMESTAMP_ANNOTATION: now_rfc3339()}
            )
            return Result(requeue_after=period_s)
        self.breaker.record_success(req.key)
        tpu = self.probe_tpu(nb)

        busy = jupyter_busy or (tpu is not None and tpu[0])
        prev_last = 0.0
        try:
            prev_last = parse_time(annotations[C.LAST_ACTIVITY_ANNOTATION]).timestamp()
        except (KeyError, ValueError):
            pass
        if busy:
            last_activity = time.time()
        else:
            candidates = [prev_last, jupyter_last] + ([tpu[1]] if tpu else [])
            last_activity = max(candidates)  # monotonic guard (reference :371-402)

        updates = {
            C.LAST_ACTIVITY_ANNOTATION: rfc3339(last_activity),
            C.LAST_ACTIVITY_CHECK_TIMESTAMP_ANNOTATION: now_rfc3339(),
        }

        idle_s = time.time() - last_activity
        if idle_s > self.config.cull_idle_time_min * 60.0:
            # cull: stop annotation scales the slice away (reference :475-492)
            updates[C.STOP_ANNOTATION] = now_rfc3339()
            if self.config.suspend_enabled and nb.spec.tpu is not None \
                    and nb.spec.tpu.accelerator:
                # suspend, don't tear down: the checkpointing stamp rides the
                # SAME patch as the stop annotation, so the core reconciler
                # can never scale the slice away before the suspend
                # controller's checkpoint window ran (controllers/suspend.py)
                updates[C.TPU_SUSPEND_STATE_ANNOTATION] = "checkpointing"
            self._patch_annotations(nb, updates)
            self.metrics.notebook_culling_total.inc()
            self.metrics.last_culling_timestamp.set(time.time())
            # flight recorder: a cull is a state-machine transition a later
            # incident bundle must explain ("who scaled this slice away?")
            recorder.record(
                "transition", machine="culling", notebook=req.key,
                state="culled", idle_s=round(idle_s, 1),
            )
            log.info("culled %s after %.0fs idle", req.key, idle_s)
            return None
        self._patch_annotations(nb, updates)
        return Result(requeue_after=period_s)

    # ---------- annotation writes (always with conflict retry) ----------

    def _patch_annotations(self, nb: Notebook, updates: dict) -> None:
        def attempt():
            return self.client.patch(
                Notebook,
                nb.metadata.namespace,
                nb.metadata.name,
                {"metadata": {"annotations": updates}},
            )

        retry_on_conflict(attempt)

    def _remove_activity_annotations(self, nb: Notebook) -> None:
        if (
            C.LAST_ACTIVITY_ANNOTATION not in nb.metadata.annotations
            and C.LAST_ACTIVITY_CHECK_TIMESTAMP_ANNOTATION not in nb.metadata.annotations
        ):
            return
        self._patch_annotations(
            nb,
            {
                C.LAST_ACTIVITY_ANNOTATION: None,
                C.LAST_ACTIVITY_CHECK_TIMESTAMP_ANNOTATION: None,
            },
        )

