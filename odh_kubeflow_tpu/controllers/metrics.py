"""Notebook controller metrics.

Same five series as the reference collector (reference
pkg/metrics/metrics.go:22-99) plus the TPU-native ones the north star demands:
chips bound and the Notebook-CR->slice-ready latency histogram (the self-
measured headline metric)."""
from __future__ import annotations

from typing import Optional

from ..api.apps import StatefulSet
from ..api.core import Node
from ..api.notebook import Notebook
from ..cluster.client import Client
from ..runtime.metrics import Registry
from ..tpu import TPU_RESOURCE
from . import constants as C

_GKE_ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"


class NotebookMetrics:
    def __init__(self, registry: Registry, client: Optional[Client] = None):
        self.registry = registry
        self.client = client
        self.notebook_create_total = registry.counter(
            "notebook_create_total", "Total times of creating notebook"
        )
        self.notebook_create_failed_total = registry.counter(
            "notebook_create_failed_total", "Total failure times of creating notebook"
        )
        self.notebook_culling_total = registry.counter(
            "notebook_culling_total", "Total times of culling notebook"
        )
        self.last_culling_timestamp = registry.gauge(
            "last_notebook_culling_timestamp_seconds",
            "Timestamp of the last notebook culling in seconds",
        )
        self.notebook_running = registry.gauge(
            "notebook_running_total", "Current running notebooks in the cluster"
        )
        # TPU-native series
        self.tpu_chips_bound = registry.gauge(
            "notebook_tpu_chips_bound", "TPU chips currently bound to notebooks"
        )
        self.probe_unreachable_total = registry.counter(
            "notebook_probe_unreachable_total",
            "Per-host readiness probes that found the agent unreachable "
            "(partitions, crashed probe processes, bring-up races)",
        )
        self.slice_ready_seconds = registry.histogram(
            "notebook_slice_ready_seconds",
            "Notebook CR to slice-ready latency (the north-star metric)",
            buckets=(0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60, 120, 300),
        )
        self.probe_sweep_seconds = registry.histogram(
            "notebook_probe_sweep_seconds",
            "Wall-clock of one all-ordinals readiness probe sweep",
            buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 2.5, 5, 10),
        )
        # fleet capacity, per accelerator type (from Node allocatable — the
        # TPU analog of cluster GPU-capacity dashboards)
        self.tpu_chips_allocatable = registry.gauge(
            "tpu_chips_allocatable",
            "TPU chips allocatable in the cluster, by accelerator",
            labels=("accelerator",),
        )
        # availability SLI (runtime/slo.py notebook-availability objective):
        # of the non-stopped TPU notebooks that have EVER been mesh-ready,
        # the fraction mesh-ready right now. Previously-ready only, so fleet
        # bring-up doesn't read as an availability incident — bring-up is
        # the readiness-latency SLO's jurisdiction
        self.notebook_available_ratio = registry.gauge(
            "notebook_available_ratio",
            "Fraction of previously-ready, non-stopped TPU notebooks "
            "currently mesh-ready (1.0 when none qualify)",
        )
        self._seen_accelerators: set = set()
        if client is not None:
            registry.add_collector(self._scrape)

    def _scrape(self) -> None:
        """Pull-style collector: list StatefulSets at scrape time (reference
        Metrics.scrape :82-99) and aggregate running notebooks + bound chips,
        plus fleet chip capacity from Node allocatable."""
        assert self.client is not None
        # deferred import (notebook.py imports this module at load time),
        # once per scrape
        from .notebook import statefulset_name

        running = 0
        chips = 0
        for sts in self.client.list(StatefulSet):
            if C.NOTEBOOK_NAME_LABEL not in sts.spec.template.metadata.labels:
                continue
            owner_nb = sts.metadata.labels.get(C.NOTEBOOK_NAME_LABEL, "")
            # STS names are the CLAMPED form of the notebook name
            if statefulset_name(owner_nb) != sts.metadata.name:
                continue
            ready = sts.status.ready_replicas
            if ready > 0:
                running += 1
            for c in sts.spec.template.spec.containers:
                if c.resources and c.resources.requests.get(TPU_RESOURCE):
                    chips += ready * int(float(c.resources.requests[TPU_RESOURCE]))
        self.notebook_running.set(running)
        self.tpu_chips_bound.set(chips)

        qualifying = available = 0
        try:
            for nb in self.client.list(Notebook):
                if (
                    nb.spec.tpu is None
                    or not nb.spec.tpu.accelerator
                    or nb.metadata.deletion_timestamp
                    or C.STOP_ANNOTATION in nb.metadata.annotations
                    # mid-suspend/resume (controllers/suspend.py) is a
                    # PLANNED transition, not downtime: a fleet-wide morning
                    # rush of resumes must not burn the availability budget
                    # (resume slowness is the resume-latency SLO's
                    # jurisdiction, exactly as bring-up belongs to
                    # readiness-latency). Terminal resume-failed is NOT
                    # planned — a user locked out of a dead resume is
                    # exactly what availability must page on, so it stays
                    # counted (and, never mesh-ready, counts unavailable).
                    or nb.metadata.annotations.get(
                        C.TPU_SUSPEND_STATE_ANNOTATION
                    ) in ("checkpointing", "suspended", "resuming")
                    or nb.status.tpu is None
                    or not nb.status.tpu.first_ready_time
                ):
                    continue
                qualifying += 1
                if nb.status.tpu.mesh_ready:
                    available += 1
        except Exception as e:
            import logging

            logging.getLogger(__name__).warning(
                "availability scrape: Notebook list failed: %r", e
            )
        else:
            self.notebook_available_ratio.set(
                available / qualifying if qualifying else 1.0
            )

        capacity: dict = {}
        try:
            nodes = self.client.list(Node)
        except Exception as e:
            import logging

            logging.getLogger(__name__).warning("capacity scrape: Node list failed: %r", e)
            return  # keep last values rather than zeroing on a transient error
        for node in nodes:
            alloc = (node.status.allocatable or {}).get(TPU_RESOURCE)
            if not alloc:
                continue
            accel = node.metadata.labels.get(_GKE_ACCELERATOR_LABEL, "unknown")
            capacity[accel] = capacity.get(accel, 0) + int(float(alloc))
        for accel, total in sorted(capacity.items()):
            self.tpu_chips_allocatable.set(total, accelerator=accel)
        # zero series for accelerator types that left the cluster — stale
        # phantom capacity must not outlive its nodes
        for accel in self._seen_accelerators - set(capacity):
            self.tpu_chips_allocatable.set(0, accelerator=accel)
        self._seen_accelerators |= set(capacity)
