"""InferenceEndpoint reconciler: notebook→serving promotion (ISSUE 9).

Opens the second workload class the ROADMAP's north star demands: a
notebook's model+checkpoint promoted into a long-lived serving deployment
that contends for the same chips as the interactive fleet. The reconciler
deliberately reuses the notebook stack end to end — StatefulSet + headless
per-host Service for gang DNS, the TPU scheduler's gang placement and
claimed-pool reservations, the warm slice pool, the probe agent's /tpu/*
surface, the gateway HTTPRoute shape, the SLO engine — rather than growing a
parallel serving stack.

State machine (annotation-durable like suspend/repair; declared as data in
analysis/machines.py so PR 8's conformance checker and INVCHECK cover it
from day one):

    Pending ("") ──gang ready──> Loading ──verified──> Serving ⇄ Suspended
         │                          │  window expired /        │ stop
         │ stop                     │  checksum mismatch       v
         └────> Draining <──────────┴──> LoadFailed       Draining
                   │ drained/deadline     (terminal, self-healing,
                   v                       incident bundle)
               Terminated (replicas 0; slice released warm)

Serving is FLEET management (ISSUE 16): `spec.serving.replicas` /
`spec.serving.autoscaling` (or the autoscaler's desired-replicas
annotation) sets how many independent replica GANGS to run — each its own
StatefulSet + gang-DNS headless Service + slicepool claim. Scale-up is a
warm bind from the pool; scale-down is a route-first bounded per-replica
drain back to the pool (the router stops picking `status.drainingReplicas`
before the slice releases); desired 0 with `scaleToZero` parks the whole
endpoint Suspended-with-a-route that cold-wakes when anything bumps
desired replicas back up. The endpoint stays Serving while >= 1 gang is
healthy, carrying a DegradedServing condition below full strength; only a
FULL outage re-enters Loading.

- **Promotion is a warm bind.** With ``spec.notebookRef`` naming a
  just-suspended notebook, Pending claims the source's released slice from
  the warm pool under the endpoint's own key (the scheduler's claimed-pool
  check admits only the claimant's pods) and inherits the slice shape and
  checkpoint lineage (saved step + checksum annotations) — promotion skips
  the cold admission→schedule→mesh path entirely.
- **Loading verifies the restore.** Every host must report /tpu/readiness
  green AND ordinal 0's /tpu/restore checksum must match the checksum the
  suspend-side checkpoint acked (ISSUE 9 satellite: "the restored kernel
  equals the saved one" is asserted, not assumed). A mismatch or an expired
  window is an explicit LoadFailed with an incident bundle, never a silent
  wedge.
- **Draining fails fast, never hangs.** A stop (user, or the
  oversubscription reclaimer victimizing a lower-priority endpoint) tears
  the route down FIRST, gives in-flight requests a bounded window, then
  scales the gang away and releases the slice warm (general capacity when
  reclaim-forced). A Draining endpoint is never a reclaim victim.
- **No repair-machine fight by construction:** slice-repair watches
  Notebooks only; a preempted serving host surfaces as lost readiness here
  (Serving→Loading re-verify) while the drain/terminate path stays
  exclusively this machine's.
"""
from __future__ import annotations

import json
import logging
import time
from typing import Dict, List, Optional, Tuple

from ..api.apps import StatefulSet
from ..api.core import (
    Container,
    ContainerPort,
    Pod,
    ResourceRequirements,
    Service,
    ServicePort,
    Toleration,
    emit_deduped_event,
)
from ..api.gateway import (
    HTTPBackendRef,
    HTTPPathMatch,
    HTTPRoute,
    HTTPRouteMatch,
    HTTPRouteRule,
    ParentReference,
)
from ..api.inference import InferenceEndpoint
from ..api.notebook import Notebook, TPUSpec, TPUStatus
from ..apimachinery import (
    AlreadyExistsError,
    Condition,
    NotFoundError,
    parse_time,
    rfc3339_precise,
    sanitize_name,
)
from ..cluster.client import retry_on_conflict
from ..cluster.slicepool import SlicePool
from ..runtime.controller import Request, Result
from ..runtime.flightrecorder import recorder
from ..runtime.manager import Manager
from ..serving import metrics as serving_metrics
from ..tpu import SliceShape, TPU_RESOURCE, plan_slice, tpu_env
from ..utils import tracing
from ..utils.tracing import record_span
from . import constants as C
from .config import Config
from .culling import HTTPGet, _default_http_get

log = logging.getLogger(__name__)

# annotation values of the inference endpoint machine ("" = Pending)
STATE_LOADING = "loading"
STATE_SERVING = "serving"
STATE_DRAINING = "draining"
STATE_TERMINATED = "terminated"
STATE_LOAD_FAILED = "load-failed"
STATE_SUSPENDED = "suspended"  # scale-to-zero park (ISSUE 16)

INFERENCE_PORT = 8000  # in-pod serving engine HTTP port


def endpoint_priority(ep: InferenceEndpoint) -> int:
    """Reclaim ordering for endpoints: spec.tpu.priority, with the unset
    default ABOVE interactive notebooks (ISSUE 9 bugfix) — live traffic
    outranks an idle notebook unless the operator says otherwise."""
    if ep.spec.tpu is not None:
        try:
            explicit = int(ep.spec.tpu.priority)
        except (TypeError, ValueError):
            explicit = 0
        if explicit:
            return explicit
    return C.ENDPOINT_DEFAULT_PRIORITY


def probe_restore_ack(http_get, url: str, timeout: float = 2.0) -> Optional[dict]:
    """GET an agent's /tpu/restore and parse the ack; None = unreachable.
    The ONE copy of the probe protocol both restore-verification consumers
    (the resume path in suspend.py and the endpoint Loading gate here)
    share — ack parsing and timeout handling must never drift apart."""
    try:
        try:
            status, body = http_get(url, timeout=timeout)
        except TypeError:  # custom http_get without timeout kwarg
            status, body = http_get(url)
        if status != 200:
            raise ConnectionError(f"GET {url} -> {status}")
        return json.loads(body.decode() or "null") or {}
    except Exception as e:
        log.debug("restore probe %s failed: %s", url, e)
        return None


def classify_restore(ack: Optional[dict], expected: str) -> Tuple[str, str]:
    """Shared verdict over a /tpu/restore ack vs the saved digest:
    (ok | mismatch | unverified, detail)."""
    if not expected:
        return "unverified", "no saved-checkpoint checksum to verify against"
    if ack is None:
        return "unverified", "restore probe unreachable"
    if not ack.get("restored"):
        return "unverified", ack.get("reason") or "restore not performed"
    got = str(ack.get("checksum") or "")
    if not got:
        return "unverified", "restore ack carried no checksum"
    if got == expected:
        return "ok", f"checksum {got} matches (step {ack.get('step')})"
    return "mismatch", f"saved {expected} != restored {got}"


def source_notebook(client, ep: InferenceEndpoint) -> Optional[Notebook]:
    """The promotion source named by spec.notebookRef (None when absent or
    deleted)."""
    ref = ep.spec.notebook_ref
    if ref is None or not ref.name:
        return None
    ns = ref.namespace or ep.metadata.namespace
    try:
        return client.get(Notebook, ns, ref.name)
    except NotFoundError:
        return None


def resolve_endpoint_tpu(client, ep: InferenceEndpoint) -> Optional[TPUSpec]:
    """The endpoint's slice shape: its own spec.tpu, else inherited from the
    promotion source (shared with the oversubscription reclaimer, which must
    shape-match endpoint victims exactly like notebook victims)."""
    if ep.spec.tpu is not None and ep.spec.tpu.accelerator:
        return ep.spec.tpu
    src = source_notebook(client, ep)
    if src is not None and src.spec.tpu is not None and \
            src.spec.tpu.accelerator:
        return src.spec.tpu
    return None


def endpoint_statefulset_name(name: str, replica: int = 0) -> str:
    """`-serve` suffix keeps a promoted endpoint's workload disjoint from a
    same-named notebook's STS/pods in the same namespace. Replica 0 keeps
    the pre-fleet name (upgrades roll nothing); replica i >= 1 appends
    `-r{i}` — each replica gang is its OWN StatefulSet."""
    suffix = "-serve" if replica <= 0 else f"-serve-r{replica}"
    return sanitize_name(f"{name}{suffix}", max_len=52)


def endpoint_service_name(name: str) -> str:
    return sanitize_name(f"{name}-serve", max_len=63)


def endpoint_hosts_service_name(name: str, replica: int = 0) -> str:
    suffix = "-serve-hosts" if replica <= 0 else f"-serve-r{replica}-hosts"
    return sanitize_name(f"{name}{suffix}", max_len=63)


def endpoint_desired_replicas(ep: InferenceEndpoint) -> int:
    """The fleet size the controller converges toward: the autoscaler's
    desired-replicas annotation when present (the HPA analog — the
    autoscaler owns that annotation, this controller owns the state
    machine), else `spec.serving.replicas`, clamped into
    `spec.serving.autoscaling.{min,max}`. 0 is only reachable with
    `autoscaling.scaleToZero` — anything else floors at minReplicas."""
    serving = ep.spec.serving
    try:
        static = max(1, int(serving.replicas or 1))
    except (TypeError, ValueError):
        static = 1
    desired = static
    raw = ep.metadata.annotations.get(C.INFERENCE_DESIRED_REPLICAS_ANNOTATION)
    if raw is not None:
        try:
            desired = int(raw)
        except (TypeError, ValueError):
            desired = static
    auto = serving.autoscaling
    if auto is None:
        return max(1, desired)
    hi = max(1, int(auto.max_replicas))
    lo = max(1, min(int(auto.min_replicas), hi))
    if desired <= 0:
        return 0 if auto.scale_to_zero else lo
    return min(hi, max(lo, desired))


def endpoint_route_name(ep: InferenceEndpoint) -> str:
    return sanitize_name(
        f"{ep.metadata.namespace}-{ep.metadata.name}-serve", max_len=63
    )


class InferenceEndpointReconciler:
    def __init__(
        self,
        manager: Manager,
        config: Optional[Config] = None,
        http_get: Optional[HTTPGet] = None,
    ):
        self.manager = manager
        self.client = manager.client
        self.api_reader = manager.api_reader
        self.config = config or Config()
        self.http_get = http_get or _default_http_get
        self.pool = SlicePool(manager.client)

    def setup(self) -> None:
        def pod_is_endpoint(ev: str, obj: dict, old: Optional[dict]) -> bool:
            return C.INFERENCE_NAME_LABEL in obj.get("metadata", {}).get(
                "labels", {}
            )

        def map_pod(obj: dict) -> List[tuple]:
            meta = obj.get("metadata", {})
            name = meta.get("labels", {}).get(C.INFERENCE_NAME_LABEL)
            return [(meta.get("namespace", ""), name)] if name else []

        (
            self.manager.builder("inference-endpoint")
            .for_(InferenceEndpoint)
            .owns(StatefulSet)
            .owns(Service)
            .watches(Pod, map_pod, predicate=pod_is_endpoint)
            .with_workers(self.config.max_concurrent_reconciles)
            .complete(self.reconcile)
        )

    # ---------- spec resolution ----------

    def _source_notebook(self, ep: InferenceEndpoint) -> Optional[Notebook]:
        return source_notebook(self.client, ep)

    def _resolve_tpu(self, ep: InferenceEndpoint) -> Optional[TPUSpec]:
        return resolve_endpoint_tpu(self.client, ep)

    # ---------- reconcile ----------

    def reconcile(self, req: Request) -> Optional[Result]:
        try:
            ep = self.api_reader.get(InferenceEndpoint, req.namespace, req.name)
        except NotFoundError:
            self._release_claims(req.key, back_to_warm=True)
            tracing.discard_root_for(f"endpoint:{req.key}")
            return None
        if ep.metadata.deletion_timestamp:
            self._release_claims(req.key, back_to_warm=True)
            tracing.discard_root_for(f"endpoint:{req.key}")
            return None

        tpu = self._resolve_tpu(ep)
        if tpu is None:
            self._emit_event(
                ep, "EndpointInvalid",
                "no TPU spec: set spec.tpu or point spec.notebookRef at a "
                "TPU notebook to inherit its slice shape",
            )
            return None
        shape = plan_slice(tpu.accelerator, tpu.topology, tpu.chips)

        self._ensure_trace_root(ep)
        ann = ep.metadata.annotations
        state = ann.get(C.INFERENCE_STATE_ANNOTATION, "")
        stopped = C.STOP_ANNOTATION in ann
        now = time.time()

        if stopped:
            if state in (
                "", STATE_LOADING, STATE_SERVING, STATE_LOAD_FAILED,
                STATE_SUSPENDED,
            ):
                # route down FIRST: no new traffic lands while the drain
                # window runs; the in-pod engine fails leftovers fast
                self._delete_route(ep)
                drain_s = ep.spec.serving.drain_timeout_s or \
                    self.config.serving_drain_timeout_s
                self._patch_annotations(
                    ep,
                    {
                        C.INFERENCE_STATE_ANNOTATION: STATE_DRAINING,
                        C.INFERENCE_DRAIN_DEADLINE_ANNOTATION: (
                            rfc3339_precise(now + drain_s)
                        ),
                        C.INFERENCE_LOADING_DEADLINE_ANNOTATION: None,
                        C.INFERENCE_REPLICA_DRAIN_ANNOTATION: None,
                        C.INFERENCE_SUSPENDED_AT_ANNOTATION: None,
                    },
                )
                self._emit_event(
                    ep, "EndpointDraining",
                    f"stop requested: route removed, in-flight requests get "
                    f"{drain_s:.0f}s to drain before the slice scales away",
                    etype="Normal",
                )
                recorder.record(
                    "transition", machine="inference", endpoint=req.key,
                    state=STATE_DRAINING,
                    reclaim=bool(ann.get(C.TPU_RECLAIM_ANNOTATION)),
                )
                return Result(requeue_after=0.02)
            if state == STATE_DRAINING:
                return self._run_drain(ep, shape, now, req)
            if state == STATE_TERMINATED:
                # parked: keep replicas at 0, nothing else to converge
                self._reconcile_workload(ep, shape, replicas=0)
                self._mirror_status(ep, shape, phase="Terminated")
                return None
            log.warning("unknown inference state %r on %s; clearing",
                        state, req.key)
            self._patch_annotations(
                ep, {C.INFERENCE_STATE_ANNOTATION: None}
            )
            return Result(requeue_after=0.05)

        # -- not stopped --
        if state in (STATE_TERMINATED, STATE_LOAD_FAILED, STATE_DRAINING):
            # unstop (Terminated), self-heal (LoadFailed: pods came back or
            # the spec changed), or a stop withdrawn mid-drain: a fresh
            # Pending episode re-converges everything level-triggered.
            # (draining->"" rides the defensive-clear edge: the stop was
            # withdrawn before the drain finished, nothing was torn down)
            self._patch_annotations(
                ep,
                {
                    C.INFERENCE_STATE_ANNOTATION: None,
                    C.INFERENCE_DRAIN_DEADLINE_ANNOTATION: None,
                    C.INFERENCE_LOADING_DEADLINE_ANNOTATION: None,
                },
            )
            recorder.record(
                "transition", machine="inference", endpoint=req.key,
                state="pending", from_state=state,
            )
            return Result(requeue_after=0.02)
        if state == STATE_SUSPENDED:
            if endpoint_desired_replicas(ep) > 0:
                # cold-wake: the router's first request (or the autoscaler,
                # or an operator) bumped desired replicas — a fresh Pending
                # episode warm-binds from the pool, route already up
                self._patch_annotations(
                    ep,
                    {
                        C.INFERENCE_STATE_ANNOTATION: None,
                        C.INFERENCE_SUSPENDED_AT_ANNOTATION: None,
                    },
                )
                self._emit_event(
                    ep, "EndpointWaking",
                    "cold-wake from scale-to-zero: desired replicas > 0, "
                    "re-placing the fleet (warm bind when the pool has the "
                    "shape)",
                    etype="Normal",
                )
                recorder.record(
                    "transition", machine="inference", endpoint=req.key,
                    state="pending", from_state=STATE_SUSPENDED,
                    reason="cold-wake",
                )
                return Result(requeue_after=0.02)
            return self._hold_suspended(ep, shape)
        if state == "":
            return self._run_pending(ep, shape, now, req)
        if state == STATE_LOADING:
            return self._run_loading(ep, shape, now, req)
        if state == STATE_SERVING:
            return self._run_serving(ep, shape, now, req)
        log.warning("unknown inference state %r on %s; clearing", state, req.key)
        self._patch_annotations(ep, {C.INFERENCE_STATE_ANNOTATION: None})
        return Result(requeue_after=0.05)

    # ---------- Pending ----------

    def _run_pending(
        self, ep: InferenceEndpoint, shape: SliceShape, now: float, req: Request
    ) -> Result:
        fleet = max(1, endpoint_desired_replicas(ep))
        self._ensure_promotion(ep, shape, req)
        self._reconcile_workload(ep, shape, replicas=shape.hosts, fleet=fleet)
        self._mirror_status(ep, shape, phase="Pending", desired=fleet)
        if self._hosts_ready(ep, shape):
            window = self.config.serving_loading_window_s
            self._patch_annotations(
                ep,
                {
                    C.INFERENCE_STATE_ANNOTATION: STATE_LOADING,
                    C.INFERENCE_LOADING_DEADLINE_ANNOTATION: (
                        rfc3339_precise(now + window)
                    ),
                },
            )
            recorder.record(
                "transition", machine="inference", endpoint=req.key,
                state=STATE_LOADING,
            )
            return Result(requeue_after=0.02)
        # pressure valve for cold promotions: a gang sitting unschedulable
        # past the grace takes the lowest-priority matching IDLE warm slice
        # (active-victim reclaim stays the suspend controller's monopoly —
        # one writer per policy)
        self._maybe_reclaim_idle_for(ep, shape, now)
        return Result(
            requeue_after=max(0.05, self.config.readiness_probe_period_s / 2)
        )

    def _ensure_promotion(
        self, ep: InferenceEndpoint, shape: SliceShape, req: Request
    ) -> None:
        """One-shot promotion bind: inherit the source notebook's checkpoint
        lineage and claim its warm slice when it just suspended. Idempotent
        — an existing claim under our key (or the stamped promoted-from
        annotation) means the bind already happened."""
        ann = ep.metadata.annotations
        if C.INFERENCE_PROMOTED_FROM_ANNOTATION in ann:
            return
        src = self._source_notebook(ep)
        if src is None:
            return
        src_ann = src.metadata.annotations
        src_state = src_ann.get(C.TPU_SUSPEND_STATE_ANNOTATION, "")
        src_stopped = (
            C.STOP_ANNOTATION in src_ann
            and src_ann[C.STOP_ANNOTATION] != C.RECONCILIATION_LOCK_VALUE
        )
        if src_state == "checkpointing" or (src_stopped and not src_state):
            # the source's suspend is IN FLIGHT: its warm release and
            # checkpoint lineage are one window away. Stamping now would
            # make the one-shot bind permanent-cold and inherit nothing —
            # defer, the next reconcile retries (the advertised flow is
            # "stop the notebook, create the endpoint" back to back)
            return
        src_key = f"{src.metadata.namespace}/{src.metadata.name}"
        updates: Dict[str, Optional[str]] = {
            C.INFERENCE_PROMOTED_FROM_ANNOTATION: src_key,
        }
        for key in (
            C.TPU_CHECKPOINT_SAVED_ANNOTATION,
            C.TPU_CHECKPOINT_CHECKSUM_ANNOTATION,
        ):
            value = src.metadata.annotations.get(key)
            if value and key not in ann:
                updates[key] = value
        warm = False
        if any(p.spec.node_name for p in self._pods(ep)):
            pass  # pods already placed: a claim now would strand a reservation
        elif not any(
            e.claimed_by == req.key
            for e in self.pool.entries(include_unhealthy=True)
        ):
            if src_state == "suspended":
                entry = self.pool.claim(
                    shape.gke_accelerator, shape.topology, req.key
                )
                warm = entry is not None
        serving_metrics.inference_endpoint_promotions_total.inc(
            bind="warm" if warm else "cold"
        )
        self._patch_annotations(ep, updates)
        self._emit_event(
            ep, "EndpointPromoted",
            f"promoted from notebook {src_key}: "
            + ("claimed its warm slice from the pool (warm bind)" if warm
               else "no warm slice to claim; cold placement"),
            etype="Normal",
        )
        record_span(
            "endpoint.promotion",
            traceparent=ep.metadata.annotations.get(C.TRACEPARENT_ANNOTATION),
            endpoint=ep.metadata.name,
            namespace=ep.metadata.namespace,
            source=src_key,
            warm_bind=warm,
        )
        log.info("promotion %s <- %s (%s bind)", req.key, src_key,
                 "warm" if warm else "cold")

    def _maybe_reclaim_idle_for(
        self, ep: InferenceEndpoint, shape: SliceShape, now: float
    ) -> None:
        pending = [
            p for p in self._pods(ep)
            if not p.spec.node_name and not p.metadata.deletion_timestamp
        ]
        if not pending:
            return
        oldest = now
        for p in pending:
            try:
                oldest = min(
                    oldest, parse_time(p.metadata.creation_timestamp).timestamp()
                )
            except (ValueError, TypeError):
                pass
        if now - oldest < self.config.reclaim_pending_grace_s:
            return
        victim = self.pool.reclaim_idle(shape.gke_accelerator, shape.topology)
        if victim is not None:
            self._emit_event(
                ep, "SliceReclaimed",
                f"reclaimed idle warm slice {victim.pool} (priority "
                f"{victim.priority}) to place this endpoint", etype="Normal",
            )

    # ---------- Loading ----------

    def _run_loading(
        self, ep: InferenceEndpoint, shape: SliceShape, now: float, req: Request
    ) -> Optional[Result]:
        fleet = max(1, endpoint_desired_replicas(ep))
        self._reconcile_workload(ep, shape, replicas=shape.hosts, fleet=fleet)
        self._mirror_status(ep, shape, phase="Loading", desired=fleet)
        deadline_s = ep.metadata.annotations.get(
            C.INFERENCE_LOADING_DEADLINE_ANNOTATION, ""
        )
        try:
            deadline = parse_time(deadline_s).timestamp()
        except ValueError:
            deadline = now + self.config.serving_loading_window_s

        gang = self._first_ready_gang(ep, shape)
        if gang is not None and self._mesh_ready(ep, shape, replica=gang):
            verdict, detail = self._verify_restore(ep, shape, replica=gang)
            if verdict == "mismatch":
                return self._fail_loading(
                    ep, now, req,
                    f"restore verification FAILED: {detail} — the restored "
                    "kernel does not equal the saved one",
                )
            return self._complete_loading(ep, shape, now, req, verdict)
        if now >= deadline:
            return self._fail_loading(
                ep, now, req,
                f"loading window expired before any replica gang reached "
                f"mesh-ready ({self._ready_count(ep)}/{shape.hosts} hosts "
                f"ready)",
            )
        return Result(requeue_after=max(
            0.02, min(self.config.readiness_probe_period_s / 2, deadline - now)
        ))

    def _verify_restore(
        self, ep: InferenceEndpoint, shape: SliceShape, replica: int = 0
    ) -> Tuple[str, str]:
        """Ordinal 0's /tpu/restore checksum vs the saved-checkpoint digest
        inherited at promotion (the digest is ordinal 0's own — per-shard
        saves make cross-ordinal comparison meaningless). Returns
        (ok|mismatch|unverified, detail) via the shared protocol."""
        expected = ep.metadata.annotations.get(
            C.TPU_CHECKPOINT_CHECKSUM_ANNOTATION, ""
        )
        urls = self._probe_urls(ep, shape, "/tpu/restore", replica=replica)
        ack = probe_restore_ack(self.http_get, urls[0]) if (
            expected and urls
        ) else None
        verdict, detail = classify_restore(ack, expected)
        serving_metrics.inference_restore_verifications_total.inc(
            result=verdict
        )
        return verdict, detail

    def _complete_loading(
        self, ep: InferenceEndpoint, shape: SliceShape, now: float,
        req: Request, verify_verdict: str,
    ) -> Optional[Result]:
        # bind window over: the slice is plainly owned by its pods — pool
        # marks off so a later drain re-releases it cleanly (suspend idiom)
        self._release_claims(req.key, back_to_warm=False)
        self._patch_annotations(
            ep,
            {
                C.INFERENCE_STATE_ANNOTATION: STATE_SERVING,
                C.INFERENCE_LOADING_DEADLINE_ANNOTATION: None,
            },
        )
        self._ensure_route(ep)
        self._mirror_status(
            ep, shape, phase="Serving",
            desired=max(1, endpoint_desired_replicas(ep)),
        )
        self._emit_event(
            ep, "EndpointServing",
            "serving: every host mesh-ready, restore "
            + ("verified" if verify_verdict == "ok" else verify_verdict)
            + ", route live",
            etype="Normal",
        )
        recorder.record(
            "transition", machine="inference", endpoint=req.key,
            state=STATE_SERVING, restore=verify_verdict,
        )
        self._close_ready_root(ep, now)
        log.info("endpoint %s serving (restore %s)", req.key, verify_verdict)
        return Result(requeue_after=max(
            1.0, self.config.readiness_probe_period_s * 6
        ))

    def _fail_loading(
        self, ep: InferenceEndpoint, now: float, req: Request, message: str
    ) -> None:
        self._patch_annotations(
            ep,
            {
                C.INFERENCE_STATE_ANNOTATION: STATE_LOAD_FAILED,
                C.INFERENCE_LOADING_DEADLINE_ANNOTATION: None,
            },
        )
        self._emit_event(ep, "LoadFailed", message)
        recorder.record(
            "transition", machine="inference", endpoint=req.key,
            state=STATE_LOAD_FAILED,
        )
        recorder.snapshot(
            "endpoint-load-failed", subject=req.key, client=self.client,
            extra={"message": message},
        )
        log.error("endpoint %s LoadFailed: %s", req.key, message)
        return None

    # ---------- Serving ----------

    def _run_serving(
        self, ep: InferenceEndpoint, shape: SliceShape, now: float, req: Request
    ) -> Optional[Result]:
        """Serving is fleet management (ISSUE 16): converge the replica-gang
        count toward `endpoint_desired_replicas`, where scale-up is a warm
        bind from the pool, scale-down is a route-first bounded per-replica
        drain back to the pool, and desired 0 (scaleToZero) parks the whole
        endpoint Suspended-with-a-route. The endpoint stays Serving while
        >= 1 gang is healthy (DegradedServing condition below full
        strength); only a FULL outage re-enters Loading to re-form."""
        desired = endpoint_desired_replicas(ep)
        auto = ep.spec.serving.autoscaling
        if desired == 0 and auto is not None and auto.scale_to_zero:
            return self._park_suspended(ep, shape, now, req)
        desired = max(1, desired)

        drain = self._replica_drain(ep)
        observed = self._observed_fleet(ep)
        if drain is not None:
            victim, deadline = drain
            if desired > victim:
                # scale-down withdrawn (burn came back): keep the victim
                self._patch_annotations(
                    ep, {C.INFERENCE_REPLICA_DRAIN_ANNOTATION: None}
                )
                return Result(requeue_after=0.02)
            # victim stays up (its STS untouched) but OUT of rotation: the
            # router reads status.draining_replicas and stops picking it
            self._reconcile_workload(
                ep, shape, replicas=shape.hosts, fleet=victim + 1
            )
            self._ensure_route(ep)
            self._mirror_status(
                ep, shape, phase="Serving", desired=desired, draining=victim
            )
            if now >= deadline:
                self._retire_replica(ep, shape, victim, now, req, desired)
                return Result(requeue_after=0.02)
            return Result(requeue_after=max(0.02, min(deadline - now, 1.0)))

        if observed > desired:
            # route-first: pick the highest gang as victim, open its bounded
            # drain window; the slice releases warm at retire
            victim = observed - 1
            drain_s = ep.spec.serving.drain_timeout_s or \
                self.config.serving_drain_timeout_s
            self._patch_annotations(
                ep,
                {
                    C.INFERENCE_REPLICA_DRAIN_ANNOTATION: json.dumps(
                        {"replica": victim,
                         "deadline": rfc3339_precise(now + drain_s)}
                    ),
                },
            )
            self._emit_event(
                ep, "ReplicaDraining",
                f"scale-down {observed}->{desired}: replica {victim} out of "
                f"rotation, in-flight requests get {drain_s:.0f}s before its "
                "slice releases warm",
                etype="Normal",
            )
            recorder.record(
                "scale", machine="inference", endpoint=req.key,
                direction="down", replica=victim, fleet=observed,
                desired=desired,
            )
            return Result(requeue_after=0.02)

        if observed < desired:
            # scale-up: one warm-bind attempt per missing gang before the
            # STSs materialize — a pool hit skips the cold placement path
            warm = 0
            for _ in range(observed, desired):
                entry = self.pool.claim(
                    shape.gke_accelerator, shape.topology, req.key
                )
                if entry is not None:
                    warm += 1
            self._reconcile_workload(
                ep, shape, replicas=shape.hosts, fleet=desired
            )
            self._ensure_route(ep)
            self._mirror_status(ep, shape, phase="Serving", desired=desired)
            self._emit_event(
                ep, "ReplicaScalingUp",
                f"scale-up {observed}->{desired}: {warm} warm bind(s), "
                f"{desired - observed - warm} cold placement(s)",
                etype="Normal",
            )
            recorder.record(
                "scale", machine="inference", endpoint=req.key,
                direction="up", fleet=observed, desired=desired, warm=warm,
            )
            record_span(
                "endpoint.scale_up",
                traceparent=ep.metadata.annotations.get(
                    C.TRACEPARENT_ANNOTATION
                ),
                endpoint=ep.metadata.name,
                namespace=ep.metadata.namespace,
                from_replicas=observed,
                to_replicas=desired,
                warm_binds=warm,
            )
            return Result(requeue_after=0.05)

        self._reconcile_workload(ep, shape, replicas=shape.hosts, fleet=desired)
        self._ensure_route(ep)
        self._mirror_status(ep, shape, phase="Serving", desired=desired)
        ready_gangs = self._ready_gangs(ep, shape)
        if len(ready_gangs) >= desired:
            # full strength: any leftover scale-up claims have served their
            # bind window (the suspend idiom — pods plainly own the slices)
            self._release_claims(req.key, back_to_warm=False)
        if not ready_gangs:
            # EVERY gang lost readiness (preemption, crash): back to Loading
            # to re-form and re-verify — the repair controller never touches
            # endpoints, so this edge is the whole recovery story. A partial
            # loss stays Serving (DegradedServing condition) and the gang
            # re-places through the same level-triggered workload reconcile.
            window = self.config.serving_loading_window_s
            self._patch_annotations(
                ep,
                {
                    C.INFERENCE_STATE_ANNOTATION: STATE_LOADING,
                    C.INFERENCE_LOADING_DEADLINE_ANNOTATION: (
                        rfc3339_precise(now + window)
                    ),
                },
            )
            self._emit_event(
                ep, "EndpointDegraded",
                f"lost ALL replica readiness while Serving "
                f"({self._ready_count(ep)} hosts ready across the fleet): "
                "re-entering Loading to re-form and re-verify",
            )
            recorder.record(
                "transition", machine="inference", endpoint=req.key,
                state=STATE_LOADING, reason="readiness-lost",
            )
            return Result(requeue_after=0.05)
        return Result(requeue_after=max(
            1.0, self.config.readiness_probe_period_s * 6
        ))

    # ---------- fleet scale-down / scale-to-zero ----------

    def _replica_drain(
        self, ep: InferenceEndpoint
    ) -> Optional[Tuple[int, float]]:
        """(victim index, deadline) of an in-progress per-replica drain."""
        raw = ep.metadata.annotations.get(
            C.INFERENCE_REPLICA_DRAIN_ANNOTATION, ""
        )
        if not raw:
            return None
        try:
            data = json.loads(raw)
            return int(data["replica"]), parse_time(data["deadline"]).timestamp()
        except (ValueError, KeyError, TypeError):
            return None

    def _retire_replica(
        self, ep: InferenceEndpoint, shape: SliceShape, victim: int,
        now: float, req: Request, desired: int,
    ) -> None:
        """Drain window over: scale the victim gang away and release its
        slice back to the warm pool (the suspend idiom — released while the
        pods terminate, so the next scale-up/promotion is a pool hit).
        Reconciles to fleet=victim (NOT desired): when several replicas must
        go, each gets its own drain window — the next reconcile opens the
        next victim's."""
        pool_name = self._slice_pool_of(ep, replica=victim)
        self._reconcile_workload(
            ep, shape, replicas=shape.hosts, fleet=max(victim, 1)
        )
        released = False
        if pool_name and not ep.metadata.annotations.get(
            C.TPU_RECLAIM_ANNOTATION
        ):
            released = self.pool.release(
                pool_name, self._pool_nodes(pool_name),
                priority=endpoint_priority(ep),
            )
        self._patch_annotations(
            ep, {C.INFERENCE_REPLICA_DRAIN_ANNOTATION: None}
        )
        self._emit_event(
            ep, "ReplicaRetired",
            f"replica {victim} drained and retired"
            + ("; slice released to the warm pool" if released
               else "; slice returned to general capacity"),
            etype="Normal",
        )
        recorder.record(
            "scale", machine="inference", endpoint=req.key,
            direction="down", replica=victim, released_warm=released,
            retired=True,
        )
        record_span(
            "endpoint.scale_down",
            traceparent=ep.metadata.annotations.get(C.TRACEPARENT_ANNOTATION),
            endpoint=ep.metadata.name,
            namespace=ep.metadata.namespace,
            replica=victim,
            to_replicas=desired,
            released_warm=released,
        )
        log.info("endpoint %s retired replica %d (%s)", req.key, victim,
                 "released warm" if released else "general capacity")

    def _park_suspended(
        self, ep: InferenceEndpoint, shape: SliceShape, now: float,
        req: Request,
    ) -> Optional[Result]:
        """Scale-to-zero: every gang scales away, every slice releases warm,
        the route stays UP — the router's cold-wake (first request bumps
        desired replicas) pops the endpoint back through Pending without an
        operator in the loop."""
        pools = self._fleet_pools(ep)
        self._reconcile_workload(ep, shape, replicas=0, fleet=0)
        released = 0
        if not ep.metadata.annotations.get(C.TPU_RECLAIM_ANNOTATION):
            for pool_name in pools:
                if self.pool.release(
                    pool_name, self._pool_nodes(pool_name),
                    priority=endpoint_priority(ep),
                ):
                    released += 1
        self._patch_annotations(
            ep,
            {
                C.INFERENCE_STATE_ANNOTATION: STATE_SUSPENDED,
                C.INFERENCE_SUSPENDED_AT_ANNOTATION: rfc3339_precise(now),
                C.INFERENCE_REPLICA_DRAIN_ANNOTATION: None,
            },
        )
        self._mirror_status(ep, shape, phase="Suspended", desired=0)
        self._emit_event(
            ep, "EndpointSuspended",
            f"scale-to-zero: fleet parked, {released} slice(s) released "
            "warm; route stays up for the cold-wake",
            etype="Normal",
        )
        recorder.record(
            "transition", machine="inference", endpoint=req.key,
            state=STATE_SUSPENDED, released_warm=released,
        )
        record_span(
            "endpoint.scale_down",
            traceparent=ep.metadata.annotations.get(C.TRACEPARENT_ANNOTATION),
            endpoint=ep.metadata.name,
            namespace=ep.metadata.namespace,
            to_replicas=0,
            parked=True,
            released_warm=released,
        )
        log.info("endpoint %s suspended (scale-to-zero, %d slices warm)",
                 req.key, released)
        return Result(requeue_after=0.05)

    def _hold_suspended(
        self, ep: InferenceEndpoint, shape: SliceShape
    ) -> Result:
        """Suspended steady state: replicas 0 everywhere, route up, nothing
        to converge until something bumps desired replicas."""
        self._reconcile_workload(ep, shape, replicas=0, fleet=0)
        self._ensure_route(ep)
        self._mirror_status(ep, shape, phase="Suspended", desired=0)
        return Result(requeue_after=max(
            1.0, self.config.readiness_probe_period_s * 6
        ))

    def _fleet_pools(self, ep: InferenceEndpoint) -> List[str]:
        """Distinct slice nodepools the fleet's placed pods occupy (one per
        replica gang — a slice fits exactly one gang)."""
        from ..api.core import Node
        from ..tpu import GKE_NODEPOOL_LABEL

        pools: List[str] = []
        for p in self._pods(ep):
            if not p.spec.node_name:
                continue
            try:
                node = self.client.get(Node, "", p.spec.node_name)
            except NotFoundError:
                continue
            name = node.metadata.labels.get(GKE_NODEPOOL_LABEL, "")
            if name and name not in pools:
                pools.append(name)
        return pools

    # ---------- Draining / Terminated ----------

    def _run_drain(
        self, ep: InferenceEndpoint, shape: SliceShape, now: float, req: Request
    ) -> Optional[Result]:
        self._delete_route(ep)  # level-triggered: re-assert no traffic
        deadline_s = ep.metadata.annotations.get(
            C.INFERENCE_DRAIN_DEADLINE_ANNOTATION, ""
        )
        try:
            deadline = parse_time(deadline_s).timestamp()
        except ValueError:
            deadline = now
        if now < deadline:
            self._mirror_status(ep, shape, phase="Draining")
            return Result(requeue_after=max(0.02, min(deadline - now, 1.0)))
        return self._complete_drain(ep, shape, now, req)

    def _complete_drain(
        self, ep: InferenceEndpoint, shape: SliceShape, now: float, req: Request
    ) -> Optional[Result]:
        ann = ep.metadata.annotations
        reclaimed = ann.get(C.TPU_RECLAIM_ANNOTATION, "")
        pools = self._fleet_pools(ep)  # gather BEFORE the fleet scales away
        self._reconcile_workload(ep, shape, replicas=0)
        released = False
        if pools and not reclaimed:
            # drained endpoints release WARM like suspended notebooks: the
            # next promotion (or resume) of this shape is a pool hit — every
            # replica gang's slice, not just the first. A reclaim-forced
            # drain skips this — the requester needs the chips.
            for pool_name in pools:
                if self.pool.release(
                    pool_name, self._pool_nodes(pool_name),
                    priority=endpoint_priority(ep),
                ):
                    released = True
        else:
            self._release_claims(req.key, back_to_warm=False)
        self._patch_annotations(
            ep,
            {
                C.INFERENCE_STATE_ANNOTATION: STATE_TERMINATED,
                C.INFERENCE_DRAIN_DEADLINE_ANNOTATION: None,
            },
        )
        self._mirror_status(ep, shape, phase="Terminated")
        self._emit_event(
            ep, "EndpointTerminated",
            "drained and terminated"
            + ("; slice released to the warm pool" if released
               else "; slice returned to general capacity"),
            etype="Normal",
        )
        recorder.record(
            "transition", machine="inference", endpoint=req.key,
            state=STATE_TERMINATED, released_warm=released,
            reclaimed=bool(reclaimed),
        )
        record_span(
            "endpoint.drain",
            traceparent=ann.get(C.TRACEPARENT_ANNOTATION),
            endpoint=ep.metadata.name,
            namespace=ep.metadata.namespace,
            released_warm=released,
        )
        log.info("endpoint %s terminated (%s)", req.key,
                 "released warm" if released else "general capacity")
        return None

    # ---------- workload generation ----------

    def generate_statefulset(
        self, ep: InferenceEndpoint, shape: SliceShape, replicas: int,
        replica: int = 0,
    ) -> StatefulSet:
        """One replica GANG = one StatefulSet (its own gang-DNS headless
        service, its own slice): the fleet is N of these, not one STS with
        N*hosts pods — gang scheduling and per-replica drain both need the
        gang boundary to be a real object boundary."""
        sts = StatefulSet()
        sts.metadata.name = endpoint_statefulset_name(
            ep.metadata.name, replica
        )
        sts.metadata.namespace = ep.metadata.namespace
        sts.metadata.labels = {
            C.INFERENCE_NAME_LABEL: ep.metadata.name,
            C.INFERENCE_REPLICA_LABEL: str(replica),
        }
        sts.spec.replicas = replicas
        sts.spec.selector.match_labels = {
            C.INFERENCE_NAME_LABEL: ep.metadata.name,
            C.INFERENCE_REPLICA_LABEL: str(replica),
        }
        sts.spec.service_name = endpoint_hosts_service_name(
            ep.metadata.name, replica
        )
        sts.spec.pod_management_policy = "Parallel"

        template = sts.spec.template
        template.metadata.labels = {
            C.INFERENCE_NAME_LABEL: ep.metadata.name,
            C.INFERENCE_REPLICA_LABEL: str(replica),
        }
        template.metadata.annotations = {}
        traceparent = ep.metadata.annotations.get(C.TRACEPARENT_ANNOTATION)
        if traceparent:
            template.metadata.annotations[C.TRACEPARENT_ANNOTATION] = traceparent
        template.spec = ep.spec.template.spec.deepcopy()
        self._default_container(ep, template.spec, shape, replica)
        template.spec.node_selector.update(shape.node_selector())
        if not any(t.key == TPU_RESOURCE for t in template.spec.tolerations):
            template.spec.tolerations.append(
                Toleration(key=TPU_RESOURCE, operator="Exists",
                           effect="NoSchedule")
            )
        sts.set_owner(ep)
        return sts

    def _default_container(
        self, ep: InferenceEndpoint, podspec, shape: SliceShape,
        replica: int = 0,
    ) -> None:
        container: Optional[Container] = None
        for c in podspec.containers:
            if c.name == ep.metadata.name:
                container = c
                break
        if container is None:
            if not podspec.containers:
                podspec.containers.append(
                    Container(name=ep.metadata.name, image="")
                )
            container = podspec.containers[0]
        if not container.ports:
            container.ports = [
                ContainerPort(name="http-serving",
                              container_port=INFERENCE_PORT, protocol="TCP")
            ]
        if container.resources is None:
            container.resources = ResourceRequirements()
        container.resources.requests[TPU_RESOURCE] = str(shape.chips_per_host)
        container.resources.limits[TPU_RESOURCE] = str(shape.chips_per_host)
        existing = {e.name for e in container.env}
        for ev in tpu_env(
            shape,
            endpoint_statefulset_name(ep.metadata.name, replica),
            endpoint_hosts_service_name(ep.metadata.name, replica),
            ep.metadata.namespace,
            self.config.cluster_domain,
        ):
            if ev["name"] not in existing:
                container.set_env(ev["name"], ev["value"])
        # engine shape (serving/engine.py reads these in the pod)
        serving = ep.spec.serving
        container.set_env("SERVING_MAX_SLOTS", str(serving.max_batch_slots))
        container.set_env("SERVING_MAX_QUEUE", str(serving.max_queue_depth))
        container.set_env("SERVING_MAX_SEQ", str(serving.max_seq))
        container.set_env("SERVING_MAX_NEW", str(serving.max_new_tokens))
        container.set_env("SERVING_DECODE_BURST", str(serving.decode_burst))
        if serving.checkpoint_path:
            container.set_env("SERVING_CHECKPOINT", serving.checkpoint_path)

    def generate_service(self, ep: InferenceEndpoint) -> Service:
        svc = Service()
        svc.metadata.name = endpoint_service_name(ep.metadata.name)
        svc.metadata.namespace = ep.metadata.namespace
        svc.metadata.labels = {C.INFERENCE_NAME_LABEL: ep.metadata.name}
        svc.spec.type = "ClusterIP"
        svc.spec.selector = {C.INFERENCE_NAME_LABEL: ep.metadata.name}
        svc.spec.ports = [
            ServicePort(name="http-serving", port=80,
                        target_port=INFERENCE_PORT, protocol="TCP")
        ]
        svc.set_owner(ep)
        return svc

    def generate_hosts_service(
        self, ep: InferenceEndpoint, replica: int = 0
    ) -> Service:
        svc = Service()
        svc.metadata.name = endpoint_hosts_service_name(
            ep.metadata.name, replica
        )
        svc.metadata.namespace = ep.metadata.namespace
        svc.metadata.labels = {
            C.INFERENCE_NAME_LABEL: ep.metadata.name,
            C.INFERENCE_REPLICA_LABEL: str(replica),
        }
        svc.spec.cluster_ip = "None"
        svc.spec.selector = {
            C.INFERENCE_NAME_LABEL: ep.metadata.name,
            C.INFERENCE_REPLICA_LABEL: str(replica),
        }
        svc.spec.ports = [
            ServicePort(name="jax-coordinator", port=8476, target_port=8476),
            ServicePort(name="probe", port=self.config.probe_port,
                        target_port=self.config.probe_port),
        ]
        svc.set_owner(ep)
        return svc

    def _replica_statefulsets(
        self, ep: InferenceEndpoint
    ) -> Dict[int, StatefulSet]:
        """Index -> STS over the fleet's StatefulSets (pre-fleet objects
        without a replica label read as replica 0)."""
        out: Dict[int, StatefulSet] = {}
        for sts in self.client.list(
            StatefulSet,
            namespace=ep.metadata.namespace,
            labels={C.INFERENCE_NAME_LABEL: ep.metadata.name},
        ):
            try:
                idx = int(
                    sts.metadata.labels.get(C.INFERENCE_REPLICA_LABEL, "0")
                )
            except (TypeError, ValueError):
                idx = 0
            out[idx] = sts
        return out

    def _observed_fleet(self, ep: InferenceEndpoint) -> int:
        """The fleet size the cluster currently expresses: highest replica
        index with a scaled-up STS, plus one (0 = everything parked)."""
        active = [
            i for i, sts in self._replica_statefulsets(ep).items()
            if (sts.spec.replicas or 0) > 0
        ]
        return max(active) + 1 if active else 0

    def _reconcile_workload(
        self, ep: InferenceEndpoint, shape: SliceShape, replicas: int,
        fleet: int = 1,
    ) -> None:
        """Converge the whole fleet: ensure STS + gang-DNS service for each
        replica index < fleet (each at `replicas` pods — 0 parks the gang),
        and GC indexes >= fleet (scale to 0 first, delete once their pods
        are gone). Replica 0's objects always exist — they hold the
        pre-fleet names, so a parked endpoint still reads as 'this workload,
        scaled to zero' rather than vanishing."""
        fleet = max(1, fleet)
        existing = self._replica_statefulsets(ep)
        for idx in range(fleet):
            desired = self.generate_statefulset(ep, shape, replicas, idx)

            def attempt(desired=desired):
                try:
                    current = self.api_reader.get(
                        StatefulSet, ep.metadata.namespace,
                        desired.metadata.name,
                    )
                except NotFoundError:
                    try:
                        self.client.create(desired)
                    except AlreadyExistsError:
                        pass  # racing reconcile won; level-triggered
                    return
                changed = False
                if current.spec.replicas != desired.spec.replicas:
                    current.spec.replicas = desired.spec.replicas
                    changed = True
                if current.spec.template.to_dict() != \
                        desired.spec.template.to_dict():
                    current.spec.template = desired.spec.template
                    changed = True
                if changed:
                    self.client.update(current)

            retry_on_conflict(attempt)
        # GC retired replica gangs: scale away first (pods drain through
        # normal termination), delete the shells once empty
        for idx, sts in sorted(existing.items()):
            if idx < fleet:
                continue
            if (sts.spec.replicas or 0) > 0:
                def scale_down(sts=sts):
                    try:
                        current = self.api_reader.get(
                            StatefulSet, ep.metadata.namespace,
                            sts.metadata.name,
                        )
                    except NotFoundError:
                        return
                    if (current.spec.replicas or 0) != 0:
                        current.spec.replicas = 0
                        self.client.update(current)

                retry_on_conflict(scale_down)
            elif not self._pods(ep, replica=idx):
                for kind, name in (
                    (StatefulSet, sts.metadata.name),
                    (Service,
                     endpoint_hosts_service_name(ep.metadata.name, idx)),
                ):
                    try:
                        self.client.delete(kind, ep.metadata.namespace, name)
                    except NotFoundError:
                        pass
        services = [self.generate_service(ep)]
        services.extend(
            self.generate_hosts_service(ep, idx) for idx in range(fleet)
        )
        for svc in services:
            try:
                self.client.get(Service, ep.metadata.namespace,
                                svc.metadata.name)
            except NotFoundError:
                try:
                    self.client.create(svc)
                except AlreadyExistsError:
                    pass

    # ---------- route ----------

    def _ensure_route(self, ep: InferenceEndpoint) -> None:
        route = HTTPRoute()
        route.metadata.name = endpoint_route_name(ep)
        route.metadata.namespace = self.config.controller_namespace
        route.metadata.labels = {C.INFERENCE_NAME_LABEL: ep.metadata.name}
        route.spec.parent_refs = [
            ParentReference(
                group="gateway.networking.k8s.io",
                kind="Gateway",
                name=self.config.gateway_name,
                namespace=self.config.gateway_namespace,
            )
        ]
        route.spec.rules = [
            HTTPRouteRule(
                matches=[HTTPRouteMatch(path=HTTPPathMatch(
                    type="PathPrefix", value=self._route_path(ep),
                ))],
                backend_refs=[HTTPBackendRef(
                    kind="Service",
                    name=endpoint_service_name(ep.metadata.name),
                    namespace=ep.metadata.namespace,
                    port=80,
                )],
            )
        ]
        try:
            self.client.create(route)
        except AlreadyExistsError:
            pass  # route exists; spec is deterministic from the CR

    def _delete_route(self, ep: InferenceEndpoint) -> None:
        try:
            self.client.delete(
                HTTPRoute, self.config.controller_namespace,
                endpoint_route_name(ep),
            )
        except NotFoundError:
            pass

    @staticmethod
    def _route_path(ep: InferenceEndpoint) -> str:
        return f"/serving/{ep.metadata.namespace}/{ep.metadata.name}"

    # ---------- readiness ----------

    def _pods(
        self, ep: InferenceEndpoint, replica: Optional[int] = None
    ) -> List[Pod]:
        pods = [
            p
            for p in self.client.list(
                Pod,
                namespace=ep.metadata.namespace,
                labels={C.INFERENCE_NAME_LABEL: ep.metadata.name},
            )
            if not p.metadata.deletion_timestamp
        ]
        if replica is None:
            return pods
        return [
            p for p in pods
            if p.metadata.labels.get(C.INFERENCE_REPLICA_LABEL, "0")
            == str(replica)
        ]

    def _ready_count(self, ep: InferenceEndpoint) -> int:
        return sum(1 for p in self._pods(ep) if p.is_ready())

    def _gang_ready_counts(self, ep: InferenceEndpoint) -> Dict[int, int]:
        """Ready-pod count per replica gang (missing label = replica 0)."""
        counts: Dict[int, int] = {}
        for p in self._pods(ep):
            if not p.is_ready():
                continue
            try:
                idx = int(p.metadata.labels.get(C.INFERENCE_REPLICA_LABEL, "0"))
            except (TypeError, ValueError):
                idx = 0
            counts[idx] = counts.get(idx, 0) + 1
        return counts

    def _ready_gangs(
        self, ep: InferenceEndpoint, shape: SliceShape
    ) -> List[int]:
        """Replica indexes whose FULL gang is pod-ready — the fleet's unit
        of health (a gang missing one host serves nothing)."""
        return sorted(
            idx
            for idx, count in self._gang_ready_counts(ep).items()
            if count >= shape.hosts
        )

    def _first_ready_gang(
        self, ep: InferenceEndpoint, shape: SliceShape
    ) -> Optional[int]:
        gangs = self._ready_gangs(ep, shape)
        return gangs[0] if gangs else None

    def _hosts_ready(self, ep: InferenceEndpoint, shape: SliceShape) -> bool:
        return bool(self._ready_gangs(ep, shape))

    def _probe_urls(
        self, ep: InferenceEndpoint, shape: SliceShape, path: str,
        replica: int = 0,
    ) -> List[str]:
        sts_name = endpoint_statefulset_name(ep.metadata.name, replica)
        svc = endpoint_hosts_service_name(ep.metadata.name, replica)
        return [
            f"http://{sts_name}-{i}.{svc}.{ep.metadata.namespace}.svc."
            f"{self.config.cluster_domain}:{self.config.probe_port}{path}"
            for i in range(shape.hosts)
        ]

    def _mesh_ready(
        self, ep: InferenceEndpoint, shape: SliceShape, replica: int = 0
    ) -> bool:
        """Every host's agent reports the full device view (the notebook
        probe gate's contract, driven inline — pod-Ready alone must not
        flip an endpoint to Serving)."""
        for url in self._probe_urls(ep, shape, "/tpu/readiness",
                                    replica=replica):
            try:
                try:
                    status, body = self.http_get(url, timeout=2.0)
                except TypeError:
                    status, body = self.http_get(url)
                if status != 200:
                    return False
                report = json.loads(body.decode() or "null") or {}
                if not report.get("ready"):
                    return False
            except Exception as e:
                log.debug("readiness probe %s failed: %s", url, e)
                return False
        return True

    # ---------- status / helpers ----------

    def _mirror_status(
        self, ep: InferenceEndpoint, shape: SliceShape, phase: str,
        desired: Optional[int] = None, draining: Optional[int] = None,
    ) -> None:
        ready = self._ready_count(ep)
        gangs = self._ready_gangs(ep, shape)
        before = ep.status.to_dict()
        status = ep.status
        status.phase = phase
        status.ready_replicas = ready
        # fleet view (ISSUE 16): the router reads these — servingReplicas is
        # how many full gangs can take traffic, drainingReplicas which gangs
        # it must stop picking (route-first drain)
        status.replicas = desired if desired is not None else 0
        status.serving_replicas = len(gangs)
        status.draining_replicas = [draining] if draining is not None else []
        status.tpu = status.tpu or TPUStatus()
        status.tpu.accelerator = shape.accelerator
        status.tpu.topology = shape.topology
        status.tpu.hosts = shape.hosts
        status.tpu.hosts_ready = ready
        status.tpu.chips_per_host = shape.chips_per_host
        status.tpu.chips_expected = shape.chips
        status.tpu.mesh_ready = phase == "Serving"
        # Suspended keeps the url: the route IS up, it just cold-wakes
        status.url = self._route_path(ep) if phase in (
            "Serving", "Suspended"
        ) else ""
        self._upsert_degraded_condition(status, phase, len(gangs), desired)
        if status.to_dict() == before:
            return
        spatch = status.to_dict()
        spatch["readyReplicas"] = status.ready_replicas  # zero must be written
        try:
            # coalesced when available (runtime/coalesce.py): one PATCH per
            # endpoint per sync wave instead of one per watch event
            coalescer = getattr(self.manager, "status_coalescer", None)
            if coalescer is not None:
                coalescer.patch_status(
                    InferenceEndpoint, ep.metadata.namespace, ep.metadata.name,
                    spatch,
                )
            else:
                self.client.patch_status(
                    InferenceEndpoint, ep.metadata.namespace, ep.metadata.name,
                    spatch,
                )
        except NotFoundError:
            pass  # deleted mid-reconcile

    def _upsert_degraded_condition(
        self, status, phase: str, gangs_ready: int, desired: Optional[int],
    ) -> None:
        """DegradedServing = Serving below full fleet strength but above
        zero (a full outage re-enters Loading instead). Upsert preserves
        lastTransitionTime across unchanged statuses so alert/debug tooling
        sees when degradation STARTED, not the latest probe."""
        want = max(1, desired or 1)
        degraded = phase == "Serving" and 0 < gangs_ready < want
        now_s = rfc3339_precise(time.time())
        new_status = "True" if degraded else "False"
        reason = "ReplicaGangsDown" if degraded else "FleetAtStrength"
        message = (
            f"{gangs_ready}/{want} replica gangs healthy: serving degraded "
            "until the lost gangs re-place" if degraded
            else f"{gangs_ready}/{want} replica gangs healthy"
        )
        for cond in status.conditions:
            if cond.type == C.DEGRADED_SERVING_CONDITION:
                if (cond.status, cond.reason, cond.message) == (
                    new_status, reason, message
                ):
                    return  # unchanged: keep timestamps so status no-ops
                if cond.status != new_status:
                    cond.last_transition_time = now_s
                cond.status = new_status
                cond.reason = reason
                cond.message = message
                cond.last_probe_time = now_s
                return
        status.conditions.append(
            Condition(
                type=C.DEGRADED_SERVING_CONDITION,
                status=new_status,
                reason=reason,
                message=message,
                last_probe_time=now_s,
                last_transition_time=now_s,
            )
        )

    def _slice_pool_of(
        self, ep: InferenceEndpoint, replica: Optional[int] = None
    ) -> str:
        from ..api.core import Node
        from ..tpu import GKE_NODEPOOL_LABEL

        for p in self._pods(ep, replica=replica):
            if not p.spec.node_name:
                continue
            try:
                node = self.client.get(Node, "", p.spec.node_name)
            except NotFoundError:
                continue
            return node.metadata.labels.get(GKE_NODEPOOL_LABEL, "")
        return ""

    def _pool_nodes(self, pool: str) -> List[str]:
        from ..api.core import Node
        from ..tpu import GKE_NODEPOOL_LABEL

        return [
            n.metadata.name
            for n in self.client.list(Node)
            if n.metadata.labels.get(GKE_NODEPOOL_LABEL) == pool
        ]

    def _release_claims(self, key: str, back_to_warm: bool) -> None:
        for entry in self.pool.entries(include_unhealthy=True):
            if entry.claimed_by != key:
                continue
            if back_to_warm:
                self.pool.release(entry.pool, entry.nodes,
                                  priority=entry.priority)
            else:
                self.pool.unclaim(entry.pool)

    def _ensure_trace_root(self, ep: InferenceEndpoint) -> None:
        """First reconcile opens the `endpoint.ready` root (closed at
        Serving) and stamps its traceparent, so promotion/loading/serving
        spans — and the engine's per-request spans — join one trace."""
        if C.TRACEPARENT_ANNOTATION in ep.metadata.annotations:
            return
        root = tracing.begin_root(
            "endpoint.ready",
            key=f"endpoint:{ep.key()}",
            endpoint=ep.metadata.name,
            namespace=ep.metadata.namespace,
        )
        if root is None:
            return
        ep.metadata.annotations[C.TRACEPARENT_ANNOTATION] = root.traceparent
        self._patch_annotations(
            ep, {C.TRACEPARENT_ANNOTATION: root.traceparent}
        )

    def _close_ready_root(self, ep: InferenceEndpoint, now: float) -> None:
        traceparent = ep.metadata.annotations.get(C.TRACEPARENT_ANNOTATION)
        ctx = tracing.parse_traceparent(traceparent)
        if ctx is None:
            return
        trace_id, root_span_id = ctx
        if tracing.finish_root(trace_id, end_time=now) is None:
            # root opened in another process / lost to a restart: synthesize
            # with the annotation's own ids so the children still connect
            start = now
            try:
                start = parse_time(ep.metadata.creation_timestamp).timestamp()
            except (ValueError, TypeError):
                pass
            tracing.record_span(
                "endpoint.ready",
                trace_id=trace_id,
                span_id=root_span_id,
                start_time=start,
                end_time=now,
                endpoint=ep.metadata.name,
            )

    def _patch_annotations(self, ep: InferenceEndpoint, updates: dict) -> None:
        def attempt():
            return self.client.patch(
                InferenceEndpoint,
                ep.metadata.namespace,
                ep.metadata.name,
                {"metadata": {"annotations": updates}},
            )

        try:
            retry_on_conflict(attempt)
        except NotFoundError:
            pass  # deleted mid-transition; the delete path releases claims

    def _emit_event(
        self, ep: InferenceEndpoint, reason: str, message: str,
        etype: str = "Warning",
    ) -> None:
        emit_deduped_event(
            self.client, ep, f"{ep.metadata.name}.{reason.lower()}",
            reason=reason, message=message, etype=etype,
            api_version=ep.api_version or "kubeflow.org/v1beta1",
            kind="InferenceEndpoint",
        )


__all__ = [
    "InferenceEndpointReconciler",
    "endpoint_desired_replicas",
    "endpoint_hosts_service_name",
    "endpoint_priority",
    "endpoint_route_name",
    "endpoint_service_name",
    "endpoint_statefulset_name",
]
