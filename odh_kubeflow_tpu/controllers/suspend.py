"""Suspend/resume controller: checkpointed capacity multiplexing.

ROADMAP open item 2, the NotebookOS direction (PAPERS.md): serve many more
notebooks than chips. The culling path used to scale replicas to 0 and throw
the slice back into general capacity, so every user return paid the full cold
admission→schedule→mesh path — the north-star metric. This controller makes
the cull a SUSPEND and the return a RESUME:

State machine (durable in annotations, mirrored as Events — the same idiom
as the slice-repair machine):

    Active ──cull/stop──> Checkpointing ──acked/window──> Suspended
                                                              │ unstop
    Active <──mesh ready── Resuming <──warm claim | cold miss─┘
                              │ (bounded re-claims while the pool/capacity
                              │  recovers; a poisoned warm slice re-claims)
                              └── attempts exhausted ──> ResumeFailed
                                   (terminal-but-self-healing, like
                                    RepairFailed: ready again closes it)

- **Checkpointing**: the culler stamps `suspend-state=checkpointing`
  atomically with the stop annotation, so the core reconciler HOLDS replicas
  while every ready host's `/tpu/checkpoint` hook (probe/agent.py →
  models/checkpoint.py, orbax-acked) is driven inside a bounded window —
  with bounded, jittered per-ordinal retries (the cluster/client.py 429
  pattern), so one transient probe blip never aborts the whole suspend.
- **Suspended**: the slice's node pool is released WARM into the slice pool
  (cluster/slicepool.py) — mesh-formed, libtpu env staged — instead of torn
  down; replicas go to 0 and the chips multiplex to someone else only via
  explicit reclaim.
- **Resuming**: unstop claims a matching warm slice (pool hit — the fast
  path the `resume_vs_cold_create_p50` bench headline measures) or falls
  back to cold placement (miss); mesh-ready completes the round trip,
  re-arms the idleness clock FROM RESUME TIME (a just-resumed notebook must
  not be instantly re-culled off its pre-suspend last-activity), and feeds
  the `notebook_resume_seconds` histogram behind the resume-latency SLO.

Oversubscription policy: admitted chip demand may exceed physical chips up
to `chip_budget`. When a cold create or a resume sits unschedulable past a
grace, the reclaimer frees capacity gracefully — lowest-priority MATCHING
pool-idle warm slice first, then the lowest-priority suspend-eligible
running notebook (checkpoint-before-reclaim through this very machine) —
so pressure degrades into queueing/suspension, never RepairFailed. Canary
CRs (`reclaim-exempt` label) are never victims.
"""
from __future__ import annotations

import json
import logging
import random
import time
from typing import Dict, List, Optional, Tuple

from ..api.core import Pod, emit_deduped_event
from ..api.inference import InferenceEndpoint
from ..api.job import TPUJob
from ..api.notebook import Notebook
from ..apimachinery import (
    NotFoundError,
    now_rfc3339,
    parse_time,
    rfc3339_precise,
)
from ..cluster.client import retry_on_conflict
from ..cluster.slicepool import (
    SlicePool,
    notebook_reclaims_total,
    notebook_restore_verifications_total,
    notebook_resume_seconds,
    record_claim,
)
from ..runtime.controller import Request, Result
from ..runtime.flightrecorder import recorder
from ..runtime.manager import Manager
from ..tpu import GKE_NODEPOOL_LABEL, plan_slice, telemetry
from ..utils.tracing import record_span
from . import constants as C
from .config import Config
from .culling import HTTPGet, _default_http_get
from .inference import (
    STATE_DRAINING as EP_STATE_DRAINING,
    STATE_SERVING as EP_STATE_SERVING,
    STATE_TERMINATED as EP_STATE_TERMINATED,
    endpoint_priority,
)
from .job import (
    STATE_ADMITTED as JOB_STATE_ADMITTED,
    STATE_CHECKPOINTING as JOB_STATE_CHECKPOINTING,
    STATE_RUNNING as JOB_STATE_RUNNING,
    job_gangs,
    job_priority,
)
from .notebook import per_ordinal_probe_urls

log = logging.getLogger(__name__)

# annotation values of the suspend-state machine
STATE_CHECKPOINTING = "checkpointing"
STATE_SUSPENDED = "suspended"
STATE_RESUMING = "resuming"
STATE_RESUME_FAILED = "resume-failed"


def notebook_priority(nb: Notebook) -> int:
    """Reclaim ordering: spec.tpu.priority (higher = more important; the
    lowest-priority eligible slice is reclaimed first)."""
    if nb.spec.tpu is None:
        return 0
    try:
        return int(nb.spec.tpu.priority)
    except (TypeError, ValueError):
        return 0


def admitted_chip_demand(client, exclude_job: str = "") -> int:
    """Total admitted chip demand across ALL THREE workload classes —
    notebooks (active + suspended), non-Terminated endpoints, and ADMITTED
    jobs (Admitted/Running/Checkpointing; Pending and Preempted jobs
    re-pass the job controller's own budget gate at (re)admission before
    their demand stands, so a queue of never-admitted jobs cannot block
    notebook reclaim). The ONE budget math the reclaimer's gate and the
    job controller's queued-over-budget admission share; `exclude_job`
    (ns/name) lets the job controller count its own gangs exactly once."""
    total = 0
    for cand in client.list(Notebook):
        if cand.spec.tpu is None or not cand.spec.tpu.accelerator:
            continue
        if cand.metadata.deletion_timestamp:
            continue
        try:
            total += plan_slice(
                cand.spec.tpu.accelerator,
                cand.spec.tpu.topology,
                cand.spec.tpu.chips,
            ).chips
        except Exception as e:
            # a junk spec must not crash the budget math, but it must be
            # visible — an unplannable notebook holds zero budget
            log.debug(
                "budget math: skipping unplannable %s/%s: %s",
                cand.metadata.namespace, cand.metadata.name, e,
            )
            continue
    # the second workload class holds budget too: an admitted endpoint
    # is chip demand exactly like a notebook (Terminated ones released
    # their slice and dropped out of the demand picture)
    from .inference import resolve_endpoint_tpu

    for ep in client.list(InferenceEndpoint):
        if ep.metadata.deletion_timestamp:
            continue
        if (
            ep.metadata.annotations.get(C.INFERENCE_STATE_ANNOTATION)
            == EP_STATE_TERMINATED
        ):
            continue
        tpu = resolve_endpoint_tpu(client, ep)
        if tpu is None:
            continue
        try:
            total += plan_slice(
                tpu.accelerator, tpu.topology, tpu.chips
            ).chips
        except Exception as e:
            log.debug(
                "budget math: skipping unplannable endpoint %s/%s: %s",
                ep.metadata.namespace, ep.metadata.name, e,
            )
            continue
    # ...and the third: every gang of every ADMITTED job. Pending and
    # Preempted jobs pass through the job controller's own budget gate
    # (again, at requeue) before their demand stands — counting them here
    # would let a queue of never-admitted jobs block notebook reclaim.
    for job in client.list(TPUJob):
        if job.metadata.deletion_timestamp:
            continue
        key = f"{job.metadata.namespace}/{job.metadata.name}"
        if exclude_job and key == exclude_job:
            continue
        if job.metadata.annotations.get(C.JOB_STATE_ANNOTATION, "") not in (
            JOB_STATE_ADMITTED, JOB_STATE_RUNNING, JOB_STATE_CHECKPOINTING,
        ):
            continue
        try:
            total += sum(shape.chips for _, shape in job_gangs(job))
        except Exception as e:
            log.debug(
                "budget math: skipping unplannable job %s/%s: %s",
                job.metadata.namespace, job.metadata.name, e,
            )
            continue
    return total


class SuspendResumeController:
    def __init__(
        self,
        manager: Manager,
        config: Optional[Config] = None,
        http_get: Optional[HTTPGet] = None,
    ):
        self.manager = manager
        self.client = manager.client
        # state transitions decide on fresh reads (the cached view after our
        # own annotation writes is stale exactly in the dispatch window)
        self.api_reader = manager.api_reader
        self.config = config or Config()
        self.http_get = http_get or _default_http_get
        self.pool = SlicePool(manager.client)
        # in-memory only (the durable machine lives in annotations):
        # per-episode checkpoint acks (ordinal -> acked step), their state
        # checksums (ordinal -> digest; the restore-side verification
        # contract), and resume attempt deadlines; all re-derivable
        self._ckpt_acked: Dict[str, Dict[int, Optional[int]]] = {}
        self._ckpt_checksums: Dict[str, Dict[int, str]] = {}
        self._resume_deadline: Dict[str, float] = {}
        # requester -> last active-suspend reclaim: a short cooldown bridges
        # the victim-drained -> scheduler-caught-up gap, so one pressure
        # episode never suspends a second victim for the same slice
        self._victim_cooldown: Dict[str, float] = {}
        # the pool sweep is GLOBAL (full node scan): damped to once per
        # heartbeat interval process-wide, however many suspended notebooks
        # heartbeat — O(nodes), not O(suspended x nodes)
        self._last_sweep = 0.0

    def setup(self) -> None:
        def pod_is_labeled(ev: str, obj: dict, old: Optional[dict]) -> bool:
            return C.NOTEBOOK_NAME_LABEL in obj.get("metadata", {}).get("labels", {})

        def map_pod(obj: dict) -> List[tuple]:
            meta = obj.get("metadata", {})
            name = meta.get("labels", {}).get(C.NOTEBOOK_NAME_LABEL)
            return [(meta.get("namespace", ""), name)] if name else []

        (
            self.manager.builder("suspend-resume")
            .for_(Notebook)
            # pending pods (unschedulable -> reclaim pressure) and pod
            # readiness flips (resume completion) both re-judge the notebook
            .watches(Pod, map_pod, predicate=pod_is_labeled)
            .with_workers(self.config.max_concurrent_reconciles)
            .complete(self.reconcile)
        )

    # ---------- reconcile ----------

    def reconcile(self, req: Request) -> Optional[Result]:
        try:
            nb = self.api_reader.get(Notebook, req.namespace, req.name)
        except NotFoundError:
            # a claim held by a deleted notebook goes back to warm — a
            # phantom claim would hold the slice out of the pool forever.
            # (Gated: with the feature off no claims can exist, and a
            # node-scan per deleted notebook would tax delete storms.)
            if self.config.suspend_enabled or req.key in self._resume_deadline:
                self._release_claims(req.key, back_to_warm=True)
            self._forget(req.key)
            return None
        if nb.metadata.deletion_timestamp:
            if self.config.suspend_enabled or req.key in self._resume_deadline:
                self._release_claims(req.key, back_to_warm=True)
            self._forget(req.key)
            return None
        if nb.spec.tpu is None or not nb.spec.tpu.accelerator:
            return None  # CPU notebook: nothing to multiplex

        ann = nb.metadata.annotations
        state = ann.get(C.TPU_SUSPEND_STATE_ANNOTATION, "")
        if not state and not self.config.suspend_enabled:
            return None  # feature off and nothing in flight to drain

        now = time.time()
        # the webhook's reconciliation lock rides the SAME annotation key
        # with a sentinel value (reference idiom; cleared by the extension
        # controller once ready) — a freshly created notebook is NOT stopped,
        # and treating the lock as a stop ran a phantom suspend/resume
        # episode at birth, polluting the pool hit ratio and the
        # resume-latency histogram with bring-up time
        stopped = (
            C.STOP_ANNOTATION in ann
            and ann[C.STOP_ANNOTATION] != C.RECONCILIATION_LOCK_VALUE
        )
        shape = plan_slice(
            nb.spec.tpu.accelerator, nb.spec.tpu.topology, nb.spec.tpu.chips
        )

        if stopped:
            if not state:
                # a stop that arrived WITHOUT the culler's atomic stamp (user
                # stop, older tooling): enter checkpointing best-effort — the
                # scale-down may already be racing us, and the window logic
                # proceeds on "no ready pods" if it wins
                if (
                    self.config.suspend_enabled
                    and C.TPU_REPAIR_STATE_ANNOTATION not in ann
                ):
                    self._patch_annotations(
                        nb,
                        {C.TPU_SUSPEND_STATE_ANNOTATION: STATE_CHECKPOINTING},
                    )
                    return Result(requeue_after=0.01)
                return None
            if state == STATE_CHECKPOINTING:
                return self._run_checkpoint_window(nb, shape, now, req)
            if state in (STATE_RESUMING, STATE_RESUME_FAILED):
                # re-stopped (or re-culled) mid-resume: park back in
                # Suspended; any claimed warm slice returns to warm
                self._release_claims(req.key, back_to_warm=True, nb=nb)
                self._patch_annotations(
                    nb,
                    {
                        C.TPU_SUSPEND_STATE_ANNOTATION: STATE_SUSPENDED,
                        C.TPU_RESUME_STARTED_ANNOTATION: None,
                        C.TPU_RESUME_ATTEMPTS_ANNOTATION: None,
                    },
                )
                self._forget(req.key)
                return Result(requeue_after=0.05)
            # STATE_SUSPENDED: parked. Heartbeat keeps the pool honest (a
            # preempted warm host must not sit in the pool as a trap) and
            # re-judges on missed unstop events.
            self._sweep_pool(now)
            return Result(
                requeue_after=max(1.0, self.config.readiness_probe_period_s * 6)
            )

        # -- not stopped --
        if not state:
            # Active. The only suspend-machine work here is oversubscription
            # pressure: pods of THIS notebook sitting unschedulable trigger
            # the reclaimer (this also serves a mid-repair re-placement that
            # cannot find capacity — degrade by reclaiming, not RepairFailed).
            return self._maybe_reclaim_for(nb, shape, now, req)
        if state == STATE_CHECKPOINTING:
            # user returned before the suspend finished: abort — the slice
            # was never released, the pods never scaled away
            self._patch_annotations(nb, self._clear_updates())
            self._emit_event(
                nb, "SuspendAborted",
                "suspend aborted: notebook unstopped during the checkpoint "
                "window", etype="Normal",
            )
            self._forget(req.key)
            return None
        if state == STATE_SUSPENDED:
            return self._begin_resume(nb, shape, now, req)
        if state == STATE_RESUMING:
            return self._await_resume(nb, shape, now, req)
        if state == STATE_RESUME_FAILED:
            # terminal, but not a dead end (RepairFailed idiom): capacity or
            # the pool recovering closes the episode
            if self._resumed(nb, shape):
                return self._complete_resume(nb, now, req)
            # keep pressure on: a failed resume is exactly the unschedulable
            # shape the reclaimer exists for
            result = self._maybe_reclaim_for(nb, shape, now, req)
            if self._pending_pods(nb):
                return result or Result(requeue_after=1.0)
            return Result(requeue_after=1.0)
        log.warning("unknown suspend state %r on %s; clearing", state, req.key)
        self._patch_annotations(nb, {C.TPU_SUSPEND_STATE_ANNOTATION: None})
        return Result(requeue_after=0.05)

    # ---------- checkpoint-before-suspend ----------

    CHECKPOINT_TIMEOUT_S = 2.0

    def _run_checkpoint_window(
        self, nb: Notebook, shape, now: float, req: Request
    ) -> Result:
        ann = nb.metadata.annotations
        deadline_s = ann.get(C.TPU_SUSPEND_CHECKPOINT_DEADLINE_ANNOTATION, "")
        if not deadline_s:
            # first pass of the episode: open the window
            self._ckpt_acked.pop(req.key, None)
            deadline = now + self.config.suspend_checkpoint_window_s
            self._patch_annotations(
                nb,
                {
                    C.TPU_SUSPEND_STARTED_ANNOTATION: rfc3339_precise(now),
                    C.TPU_SUSPEND_CHECKPOINT_DEADLINE_ANNOTATION: (
                        rfc3339_precise(deadline)
                    ),
                },
            )
            recorder.record(
                "transition", machine="suspend", notebook=req.key,
                state=STATE_CHECKPOINTING,
                reclaim=bool(ann.get(C.TPU_RECLAIM_ANNOTATION)),
            )
            return Result(requeue_after=0.01)
        try:
            deadline = parse_time(deadline_s).timestamp()
        except ValueError:
            deadline = now

        pods = self._pods(nb)
        ready_ordinals = set()
        for p in pods:
            if not p.is_ready():
                continue
            try:
                ready_ordinals.add(int(p.metadata.name.rsplit("-", 1)[1]))
            except (IndexError, ValueError):
                continue
        acked = self._ckpt_acked.setdefault(req.key, {})
        checksums = self._ckpt_checksums.setdefault(req.key, {})
        pending = sorted(ready_ordinals - set(acked))
        if pending and now < deadline:
            for ordinal, ack in self._checkpoint_sweep(
                nb, shape.hosts, pending, deadline
            ):
                if ack and ack.get("saved"):
                    acked[ordinal] = ack.get("step")
                    if ack.get("checksum"):
                        checksums[ordinal] = str(ack["checksum"])
        all_acked = bool(ready_ordinals) and ready_ordinals <= set(acked)
        if not (all_acked or not ready_ordinals or now >= deadline):
            return Result(requeue_after=max(
                0.02,
                min(self.config.readiness_probe_period_s, deadline - now),
            ))

        # window closed: record the save, release the slice, park Suspended
        updates = {
            C.TPU_SUSPEND_STATE_ANNOTATION: STATE_SUSPENDED,
            C.TPU_SUSPENDED_AT_ANNOTATION: rfc3339_precise(now),
            C.TPU_SUSPEND_CHECKPOINT_DEADLINE_ANNOTATION: None,
        }
        self._ckpt_acked.pop(req.key, None)
        checksums = self._ckpt_checksums.pop(req.key, {})
        if acked:
            telemetry.slice_checkpoint_saves_total.inc(len(acked))
            steps = [s for s in acked.values() if s is not None]
            if steps:
                updates[C.TPU_CHECKPOINT_SAVED_ANNOTATION] = str(max(steps))
                # ordinal 0's digest ONLY, and only when ordinal 0 acked the
                # step being recorded: saves are per-shard (each host writes
                # what it owns), so digests are host-specific — the one
                # well-defined comparison is ordinal 0's save vs ordinal 0's
                # restore. Storing another ordinal's digest would
                # manufacture a guaranteed mismatch on multi-host slices;
                # no digest means verification reports "unverified", never
                # a false alarm.
                if acked.get(0) == max(steps) and 0 in checksums:
                    updates[C.TPU_CHECKPOINT_CHECKSUM_ANNOTATION] = (
                        checksums[0]
                    )
        reclaimed = ann.get(C.TPU_RECLAIM_ANNOTATION, "")
        pool_name = self._slice_pool_of(pods)
        released = False
        if pool_name and not reclaimed:
            # warm release: the whole point of the suspend — the slice stays
            # mesh-formed for the next resume. A reclaim-forced suspend skips
            # this: the requester that triggered it needs the chips.
            released = self.pool.release(
                pool_name,
                self._pool_nodes(pool_name),
                priority=notebook_priority(nb),
            )
        started = now
        try:
            started = parse_time(
                ann.get(C.TPU_SUSPEND_STARTED_ANNOTATION, "")
            ).timestamp()
        except ValueError:
            pass
        record_span(
            "notebook.suspend",
            traceparent=ann.get(C.TRACEPARENT_ANNOTATION),
            start_time=started,
            end_time=now,
            notebook=nb.metadata.name,
            namespace=nb.metadata.namespace,
            hosts_acked=len(acked),
            released_warm=released,
            reclaimed=bool(reclaimed),
        )
        self._patch_annotations(nb, updates)
        self._emit_event(
            nb, "NotebookSuspended",
            f"suspended after checkpoint ({len(acked)}/{shape.hosts} hosts "
            + ("acked); slice released to the warm pool" if released
               else "acked); slice returned to general capacity"),
            etype="Normal",
        )
        recorder.record(
            "transition", machine="suspend", notebook=req.key,
            state=STATE_SUSPENDED, hosts_acked=len(acked),
            released_warm=released, reclaimed=bool(reclaimed),
        )
        log.info(
            "suspended %s (%d/%d hosts checkpointed%s)",
            req.key, len(acked), shape.hosts,
            f"; {pool_name} released warm" if released else "",
        )
        return None

    def _checkpoint_sweep(
        self, nb: Notebook, hosts: int, ordinals: List[int], deadline: float
    ) -> List[Tuple[int, Optional[dict]]]:
        """Drive /tpu/checkpoint on the given ordinals concurrently, each
        with bounded jittered retries inside the window (cluster/client.py's
        429 discipline: capped sleeps, bounded attempts, then give up and let
        the next poll or the window expiry decide) — a single transient
        probe-agent blip must not abort the whole suspend."""
        from concurrent.futures import ThreadPoolExecutor

        retries = max(0, self.config.suspend_checkpoint_retries)
        base = self.config.suspend_checkpoint_backoff_s

        def probe(url: str) -> Optional[dict]:
            for attempt in range(retries + 1):
                try:
                    try:
                        status, body = self.http_get(
                            url, timeout=self.CHECKPOINT_TIMEOUT_S
                        )
                    except TypeError:  # custom http_get without timeout kwarg
                        status, body = self.http_get(url)
                    if status != 200:
                        raise ConnectionError(f"GET {url} -> {status}")
                    return json.loads(body.decode() or "null")
                except Exception as e:
                    if attempt == retries:
                        log.debug("checkpoint probe %s gave up: %s", url, e)
                        return None
                    # jittered, capped, and never past the window deadline
                    sleep = min(
                        base * (2 ** attempt) * (0.75 + 0.5 * random.random()),
                        2.0,
                        max(0.0, deadline - time.time()),
                    )
                    if sleep <= 0:
                        return None
                    time.sleep(sleep)
            return None

        urls = per_ordinal_probe_urls(
            self.client, self.config, nb, hosts, "/tpu/checkpoint"
        )
        targets = [(i, urls[i]) for i in ordinals if i < len(urls)]
        if not targets:
            return []
        with ThreadPoolExecutor(max_workers=min(16, len(targets))) as pool:
            acks = list(pool.map(probe, [u for _, u in targets]))
        return [(i, a) for (i, _), a in zip(targets, acks)]

    # ---------- resume ----------

    def _begin_resume(
        self, nb: Notebook, shape, now: float, req: Request
    ) -> Result:
        hit = self._claim_for(nb, shape, req.key)
        self._patch_annotations(
            nb,
            {
                C.TPU_SUSPEND_STATE_ANNOTATION: STATE_RESUMING,
                C.TPU_RESUME_STARTED_ANNOTATION: rfc3339_precise(now),
                C.TPU_RESUME_ATTEMPTS_ANNOTATION: "1",
            },
        )
        self._resume_deadline[req.key] = now + self._resume_backoff(1)
        recorder.record(
            "transition", machine="suspend", notebook=req.key,
            state=STATE_RESUMING, warm_hit=hit,
        )
        log.info("resuming %s (%s)", req.key,
                 "warm pool hit" if hit else "pool miss; cold placement")
        return Result(requeue_after=0.05)

    def _claim_for(self, nb: Notebook, shape, key: str) -> bool:
        """One warm-claim attempt; counts the hit/miss for the pool ratio.
        (claim() itself never picks an unhealthy pool — entries() filters
        them — so the damped sweep here is eviction bookkeeping, not the
        safety check.)"""
        self._sweep_pool(time.time())
        entry = self.pool.claim(shape.gke_accelerator, shape.topology, key)
        record_claim(entry is not None)
        return entry is not None

    def _resumed(self, nb: Notebook, shape) -> bool:
        return (
            nb.status.tpu is not None
            and nb.status.tpu.mesh_ready
            and nb.status.ready_replicas >= shape.hosts
        )

    def _await_resume(
        self, nb: Notebook, shape, now: float, req: Request
    ) -> Optional[Result]:
        if self._resumed(nb, shape):
            return self._complete_resume(nb, now, req)

        ann = nb.metadata.annotations
        attempts = int(ann.get(C.TPU_RESUME_ATTEMPTS_ANNOTATION, "1") or 1)
        deadline = self._resume_deadline.get(req.key)
        if deadline is None:
            # controller restarted mid-resume: re-derive from the durable
            # attempt counter
            deadline = now + self._resume_backoff(attempts)
            self._resume_deadline[req.key] = deadline

        # pressure valve: pods sitting unschedulable mid-resume reclaim
        # (the warm claim may have been poisoned away, or a cold fallback
        # found the cluster full)
        reclaim_result = self._maybe_reclaim_for(nb, shape, now, req)

        if now < deadline:
            return Result(requeue_after=max(
                0.02, min(deadline - now, self.config.readiness_probe_period_s)
            ))

        # one full attempt window without mesh-ready: re-claim
        attempts += 1
        if attempts > self.config.resume_max_attempts:
            return self._fail_resume(nb, now, req)
        # drop a claim that never bound (poisoned slice, raced reclaim) back
        # to warm so someone else can use it, then try fresh
        self._release_claims(req.key, back_to_warm=True, nb=nb)
        hit = self._claim_for(nb, shape, req.key)
        self._patch_annotations(
            nb, {C.TPU_RESUME_ATTEMPTS_ANNOTATION: str(attempts)}
        )
        self._resume_deadline[req.key] = now + self._resume_backoff(attempts)
        log.info(
            "resume %s still pending (attempt %d/%d, %s)",
            req.key, attempts, self.config.resume_max_attempts,
            "warm re-claim" if hit else "cold",
        )
        del reclaim_result  # pressure already applied above
        return Result(requeue_after=max(
            0.02, self._resume_deadline[req.key] - now
        ))

    def _complete_resume(
        self, nb: Notebook, now: float, req: Request
    ) -> Optional[Result]:
        ann = nb.metadata.annotations
        started = now
        try:
            started = parse_time(
                ann.get(C.TPU_RESUME_STARTED_ANNOTATION, "")
            ).timestamp()
        except ValueError:
            pass
        latency = max(0.0, now - started)
        self._verify_restore(nb, req)
        # the bind window is over: the slice is plainly owned by its pods —
        # pool marks off, so a later suspend re-releases it cleanly
        self._release_claims(req.key, back_to_warm=False, nb=nb)
        updates = self._clear_updates()
        # culling-clock contract (ISSUE 7 satellite): the idleness clock
        # re-arms FROM RESUME TIME — the preserved pre-suspend last-activity
        # would read as hours of idleness and re-cull the notebook instantly
        updates[C.LAST_ACTIVITY_ANNOTATION] = now_rfc3339()
        updates[C.LAST_ACTIVITY_CHECK_TIMESTAMP_ANNOTATION] = now_rfc3339()
        self._patch_annotations(nb, updates)
        notebook_resume_seconds.observe(latency)
        record_span(
            "notebook.resume",
            traceparent=ann.get(C.TRACEPARENT_ANNOTATION),
            start_time=started,
            end_time=now,
            notebook=nb.metadata.name,
            namespace=nb.metadata.namespace,
            latency_s=round(latency, 3),
        )
        self._emit_event(
            nb, "NotebookResumed",
            f"resumed to mesh-ready in {latency:.2f}s"
            + (f" (restoring checkpoint step "
               f"{ann.get(C.TPU_CHECKPOINT_SAVED_ANNOTATION)})"
               if ann.get(C.TPU_CHECKPOINT_SAVED_ANNOTATION) else ""),
            etype="Normal",
        )
        recorder.record(
            "transition", machine="suspend", notebook=req.key,
            state="active", resume_s=round(latency, 3),
        )
        self._forget(req.key)
        log.info("resumed %s in %.2fs", req.key, latency)
        return None

    def _verify_restore(self, nb: Notebook, req: Request) -> None:
        """Restore-side verification (ISSUE 9 satellite): the resumed
        kernel must equal the saved one. Ordinal 0's /tpu/restore ack is
        compared against the checksum the suspend-side checkpoint recorded;
        a mismatch is surfaced loudly (Warning event + counter) but never
        blocks the resume — a live-but-suspect notebook beats a wedged one,
        and the operator sees exactly which state diverged."""
        from .inference import classify_restore, probe_restore_ack

        ann = nb.metadata.annotations
        expected = ann.get(C.TPU_CHECKPOINT_CHECKSUM_ANNOTATION, "")
        if not expected:
            return  # nothing was acked with a digest: nothing to verify
        shape = plan_slice(
            nb.spec.tpu.accelerator, nb.spec.tpu.topology, nb.spec.tpu.chips
        )
        urls = per_ordinal_probe_urls(
            self.client, self.config, nb, shape.hosts, "/tpu/restore"
        )
        ack = probe_restore_ack(self.http_get, urls[0]) if urls else None
        verdict, detail = classify_restore(ack, expected)
        notebook_restore_verifications_total.inc(result=verdict)
        if verdict == "ok":
            self._emit_event(
                nb, "RestoreVerified",
                f"restored kernel verified: {detail}", etype="Normal",
            )
        elif verdict == "mismatch":
            self._emit_event(
                nb, "RestoreVerifyFailed",
                f"restored kernel does NOT equal the saved one: {detail}",
            )
            log.error("restore verification MISMATCH for %s: %s",
                      req.key, detail)

    def _fail_resume(self, nb: Notebook, now: float, req: Request) -> None:
        self._patch_annotations(
            nb, {C.TPU_SUSPEND_STATE_ANNOTATION: STATE_RESUME_FAILED}
        )
        msg = (
            f"resume abandoned after {self.config.resume_max_attempts} "
            "attempts (no warm slice bound and cold capacity never "
            "appeared); the reclaimer keeps watching — capacity returning "
            "completes the resume"
        )
        self._emit_event(nb, "ResumeFailed", msg)
        recorder.record(
            "transition", machine="suspend", notebook=req.key,
            state=STATE_RESUME_FAILED,
        )
        recorder.snapshot(
            "resume-failed", subject=req.key, client=self.client,
            notebooks=[(nb.metadata.namespace, nb.metadata.name)],
            extra={"attempts": self.config.resume_max_attempts},
        )
        self._resume_deadline.pop(req.key, None)
        log.error("resume FAILED: %s", req.key)
        return None

    def _resume_backoff(self, attempts: int) -> float:
        base = self.config.resume_timeout_s / max(
            1, self.config.resume_max_attempts
        )
        # jitter so a fleet-wide unstop (morning rush) doesn't re-claim in
        # lockstep against the draining pool
        return base * (0.85 + 0.3 * random.random())

    # ---------- oversubscription reclaim ----------

    def _maybe_reclaim_for(
        self, nb: Notebook, shape, now: float, req: Request
    ) -> Optional[Result]:
        """Free capacity for `nb` when its pods sit unschedulable: matching
        pool-idle warm slice first, then the lowest-priority suspend-eligible
        running notebook. Policy-gated by the chip budget."""
        pending = self._pending_pods(nb)
        if not pending:
            return None
        oldest = now
        for p in pending:
            try:
                oldest = min(
                    oldest, parse_time(p.metadata.creation_timestamp).timestamp()
                )
            except (ValueError, TypeError):
                pass
        grace = self.config.reclaim_pending_grace_s
        if now - oldest < grace:
            # the scheduler's capacity-freed fast path gets first shot
            return Result(requeue_after=max(0.05, grace - (now - oldest)))

        if nb.metadata.labels.get(C.TPU_RECLAIM_EXEMPT_LABEL):
            # exempt CRs (the canary) neither PAY for pressure nor CAUSE it:
            # a synthetic probe queueing in a saturated cluster is exactly
            # the signal the canary exists to measure — reclaiming a user's
            # warm slice once per probe period to serve it would convert
            # measurement into damage
            return Result(requeue_after=max(1.0, grace))

        # never reclaim anything while a matching slice is ALREADY free —
        # the window between capacity freeing and the scheduler's bind is
        # one event hop, and a reclaim pass landing inside it (or plain
        # scheduler backoff lag) would strip a warm slice or take a second
        # victim for capacity the requester is about to get
        if self._matching_capacity_free(shape):
            return Result(requeue_after=0.2)

        # one victim at a time: a reclaim-forced suspend takes a checkpoint
        # window (and a reclaim-forced endpoint drain takes its drain
        # window) to free its slice, and the requester's pods stay pending
        # the whole while — without this guard every reclaim pass in that
        # window would pick a FRESH victim and cascade for one slice (the
        # durable reclaim annotation is the in-flight marker, so the guard
        # survives controller restarts)
        for ep in self.client.list(InferenceEndpoint):
            if (
                ep.metadata.annotations.get(C.TPU_RECLAIM_ANNOTATION)
                != f"capacity-pressure:{req.key}"
            ):
                continue
            estate = ep.metadata.annotations.get(
                C.INFERENCE_STATE_ANNOTATION
            )
            still_draining = estate == EP_STATE_DRAINING or (
                estate == EP_STATE_TERMINATED
                and any(
                    True
                    for p in self.client.list(
                        Pod,
                        namespace=ep.metadata.namespace,
                        labels={
                            C.INFERENCE_NAME_LABEL: ep.metadata.name
                        },
                    )
                    if not p.metadata.deletion_timestamp
                )
            )
            if still_draining:
                return Result(requeue_after=0.2)
        for cand in self.client.list(Notebook):
            if (
                cand.metadata.annotations.get(C.TPU_RECLAIM_ANNOTATION)
                != f"capacity-pressure:{req.key}"
            ):
                continue
            cstate = cand.metadata.annotations.get(
                C.TPU_SUSPEND_STATE_ANNOTATION
            )
            still_draining = cstate == STATE_CHECKPOINTING or (
                cstate == STATE_SUSPENDED
                and any(
                    True
                    for p in self.client.list(
                        Pod,
                        namespace=cand.metadata.namespace,
                        labels={
                            C.NOTEBOOK_NAME_LABEL: cand.metadata.name
                        },
                    )
                )
            )
            if still_draining:
                return Result(requeue_after=0.2)
        for jc in self.client.list(TPUJob):
            # a job we already victimized is mid checkpoint-preempt-requeue:
            # its preempt stamp survives until the requeue clears it, so the
            # guard holds exactly as long as the slice is still coming free
            if (
                jc.metadata.annotations.get(C.JOB_PREEMPT_ANNOTATION)
                == f"capacity-pressure:{req.key}"
            ):
                return Result(requeue_after=0.2)

        budget = self.config.chip_budget
        if budget > 0 and self._admitted_chips() > budget:
            # over budget: this demand queues — reclaiming would cascade
            # suspensions to serve demand the operator never admitted
            self._emit_event(
                nb, "QueuedOverBudget",
                f"unschedulable and total admitted chip demand exceeds the "
                f"chip budget ({budget}); queued without reclaim",
            )
            return Result(requeue_after=max(1.0, grace))

        # 1) an idle warm slice of the right shape is free capacity wearing
        #    a reservation — take the lowest-priority one
        victim_entry = self.pool.reclaim_idle(
            shape.gke_accelerator, shape.topology
        )
        if victim_entry is not None:
            self._emit_event(
                nb, "SliceReclaimed",
                f"reclaimed idle warm slice {victim_entry.pool} "
                f"(priority {victim_entry.priority}) to place this notebook",
                etype="Normal",
            )
            recorder.record(
                "transition", machine="suspend", notebook=req.key,
                state="reclaim", victim=victim_entry.pool, reason="pool-idle",
            )
            recorder.snapshot(
                "reclaim", subject=req.key, client=self.client,
                notebooks=[(nb.metadata.namespace, nb.metadata.name)],
                extra={
                    "reason": "pool-idle",
                    "victim_pool": victim_entry.pool,
                    "victim_priority": victim_entry.priority,
                },
            )
            return Result(requeue_after=0.05)

        # 2) suspend (or drain) the lowest-priority eligible running
        #    workload — notebooks and Serving endpoints compete in ONE
        #    priority order (ISSUE 9 bugfix: endpoints default above
        #    interactive, and a Draining endpoint is never re-victimized)
        cooldown = max(1.0, self.config.suspend_checkpoint_window_s * 0.5)
        if now - self._victim_cooldown.get(req.key, 0.0) < cooldown:
            return Result(requeue_after=0.2)
        victim = self._pick_suspend_victim(nb, shape)
        ep_victim = self._pick_endpoint_victim(nb, shape)
        job_victim = self._pick_job_victim(nb, shape)
        # ONE ordering across all three classes: the strictly-lowest
        # priority loses; ties drain batch first (most preemptible — a job
        # requeues and resumes from its checkpoint), then suspend the
        # notebook, and an endpoint only when UNAMBIGUOUSLY the cheapest
        ranked = []
        if job_victim is not None:
            ranked.append((job_priority(job_victim), 0, "job"))
        if victim is not None:
            ranked.append((notebook_priority(victim), 1, "nb"))
        if ep_victim is not None:
            ranked.append((endpoint_priority(ep_victim), 2, "ep"))
        winner = min(ranked)[2] if ranked else None
        if winner != "nb":
            victim = None
        if winner != "ep":
            ep_victim = None
        if winner != "job":
            job_victim = None
        if job_victim is not None:
            self._victim_cooldown[req.key] = now
            jkey = f"{job_victim.metadata.namespace}/{job_victim.metadata.name}"
            self._patch_job_victim(
                job_victim,
                {C.JOB_PREEMPT_ANNOTATION: f"capacity-pressure:{req.key}"},
            )
            notebook_reclaims_total.inc(reason="job-preempt")
            self._emit_event(
                nb, "SliceReclaimed",
                f"preempting batch job {jkey} (priority "
                f"{job_priority(job_victim)}) to free capacity for "
                f"{req.key} (priority {notebook_priority(nb)}); the job "
                "checkpoints before its slice moves and requeues to resume "
                "from the saved step",
                etype="Normal",
            )
            recorder.record(
                "transition", machine="suspend", notebook=req.key,
                state="reclaim", victim=jkey, reason="job-preempt",
            )
            recorder.snapshot(
                "reclaim", subject=jkey, client=self.client,
                notebooks=[(nb.metadata.namespace, nb.metadata.name)],
                extra={
                    "reason": "job-preempt",
                    "requester": req.key,
                    "requester_priority": notebook_priority(nb),
                    "victim_priority": job_priority(job_victim),
                },
            )
            log.warning(
                "reclaim: preempting job %s (priority %d) for %s "
                "(priority %d)", jkey, job_priority(job_victim),
                req.key, notebook_priority(nb),
            )
            return Result(requeue_after=0.1)
        if ep_victim is not None:
            self._victim_cooldown[req.key] = now
            ekey = f"{ep_victim.metadata.namespace}/{ep_victim.metadata.name}"
            self._patch_endpoint_victim(
                ep_victim,
                {
                    C.STOP_ANNOTATION: now_rfc3339(),
                    C.TPU_RECLAIM_ANNOTATION: f"capacity-pressure:{req.key}",
                },
            )
            notebook_reclaims_total.inc(reason="endpoint-drain")
            self._emit_event(
                nb, "SliceReclaimed",
                f"draining serving endpoint {ekey} (priority "
                f"{endpoint_priority(ep_victim)}) to free capacity for "
                f"{req.key} (priority {notebook_priority(nb)}); in-flight "
                "requests drain bounded before the slice moves",
                etype="Normal",
            )
            recorder.record(
                "transition", machine="suspend", notebook=req.key,
                state="reclaim", victim=ekey, reason="endpoint-drain",
            )
            recorder.snapshot(
                "reclaim", subject=ekey, client=self.client,
                notebooks=[(nb.metadata.namespace, nb.metadata.name)],
                extra={
                    "reason": "endpoint-drain",
                    "requester": req.key,
                    "requester_priority": notebook_priority(nb),
                    "victim_priority": endpoint_priority(ep_victim),
                },
            )
            log.warning(
                "reclaim: draining endpoint %s (priority %d) for %s "
                "(priority %d)", ekey, endpoint_priority(ep_victim),
                req.key, notebook_priority(nb),
            )
            return Result(requeue_after=0.1)
        if victim is None:
            return Result(requeue_after=max(1.0, grace))
        self._victim_cooldown[req.key] = now
        vkey = f"{victim.metadata.namespace}/{victim.metadata.name}"
        self._patch_victim(
            victim,
            {
                C.STOP_ANNOTATION: now_rfc3339(),
                C.TPU_SUSPEND_STATE_ANNOTATION: STATE_CHECKPOINTING,
                C.TPU_RECLAIM_ANNOTATION: f"capacity-pressure:{req.key}",
            },
        )
        notebook_reclaims_total.inc(reason="suspend")
        self._emit_event(
            victim, "NotebookReclaimed",
            f"suspending (priority {notebook_priority(victim)}) to free "
            f"capacity for {req.key} (priority {notebook_priority(nb)}); "
            "state checkpoints before the slice is released",
        )
        recorder.record(
            "transition", machine="suspend", notebook=req.key,
            state="reclaim", victim=vkey, reason="suspend",
        )
        recorder.snapshot(
            "reclaim", subject=vkey, client=self.client,
            notebooks=[
                (nb.metadata.namespace, nb.metadata.name),
                (victim.metadata.namespace, victim.metadata.name),
            ],
            extra={
                "reason": "suspend",
                "requester": req.key,
                "requester_priority": notebook_priority(nb),
                "victim_priority": notebook_priority(victim),
            },
        )
        log.warning(
            "reclaim: suspending %s (priority %d) for %s (priority %d)",
            vkey, notebook_priority(victim), req.key, notebook_priority(nb),
        )
        return Result(requeue_after=0.1)

    def _pick_suspend_victim(
        self, requester: Notebook, shape
    ) -> Optional[Notebook]:
        """Lowest-priority running notebook whose slice matches the
        requester's shape and whose priority is strictly below the
        requester's. Canary/exempt CRs, stopped/suspending/repairing
        notebooks, and not-yet-ready slices are never victims."""
        my_priority = notebook_priority(requester)
        my_key = f"{requester.metadata.namespace}/{requester.metadata.name}"
        candidates: List[Tuple[int, str, Notebook]] = []
        for cand in self.client.list(Notebook):
            if cand.spec.tpu is None or not cand.spec.tpu.accelerator:
                continue
            key = f"{cand.metadata.namespace}/{cand.metadata.name}"
            if key == my_key or cand.metadata.deletion_timestamp:
                continue
            if cand.metadata.labels.get(C.TPU_RECLAIM_EXEMPT_LABEL):
                continue  # the canary measures pressure; it never pays for it
            ann = cand.metadata.annotations
            if (
                C.STOP_ANNOTATION in ann
                or ann.get(C.TPU_SUSPEND_STATE_ANNOTATION)
                or ann.get(C.TPU_REPAIR_STATE_ANNOTATION)
            ):
                continue
            if cand.status.tpu is None or not cand.status.tpu.mesh_ready:
                continue  # only a formed slice frees usable capacity
            cshape = plan_slice(
                cand.spec.tpu.accelerator,
                cand.spec.tpu.topology,
                cand.spec.tpu.chips,
            )
            if (
                cshape.gke_accelerator != shape.gke_accelerator
                or cshape.topology != shape.topology
            ):
                continue
            pri = notebook_priority(cand)
            if pri >= my_priority:
                continue
            # oldest-idle tie break: prefer the notebook idle longest. A
            # MISSING last-activity means the culler hasn't judged it yet
            # (typically just-became-ready, in active use) — that must sort
            # LAST, not first ("" < any timestamp would pick exactly the
            # wrong victim)
            last = ann.get(C.LAST_ACTIVITY_ANNOTATION, "") or "9999-12-31"
            candidates.append((pri, last, key, cand))
        if not candidates:
            return None
        candidates.sort(key=lambda t: (t[0], t[1], t[2]))
        return candidates[0][3]

    def _pick_endpoint_victim(
        self, requester: Notebook, shape
    ) -> Optional[InferenceEndpoint]:
        """Serving endpoints are reclaim victims by `spec.tpu.priority`
        exactly like notebooks — but they default ABOVE interactive
        (ENDPOINT_DEFAULT_PRIORITY), only a Serving endpoint is eligible
        (its slice is confirmed live capacity), and a Draining endpoint is
        NEVER re-victimized mid-drain (ISSUE 9 bugfix): its slice is
        already on the way out, a second stamp would only reset the drain
        window it is racing to finish."""
        from .inference import resolve_endpoint_tpu

        my_priority = notebook_priority(requester)
        candidates: List[Tuple[int, str, InferenceEndpoint]] = []
        for cand in self.client.list(InferenceEndpoint):
            if cand.metadata.deletion_timestamp:
                continue
            ann = cand.metadata.annotations
            state = ann.get(C.INFERENCE_STATE_ANNOTATION, "")
            if state != EP_STATE_SERVING:
                continue  # Draining/Terminated/Loading free nothing usable
            if C.STOP_ANNOTATION in ann:
                continue  # already winding down
            if cand.metadata.labels.get(C.TPU_RECLAIM_EXEMPT_LABEL):
                continue
            tpu = resolve_endpoint_tpu(self.client, cand)
            if tpu is None:
                continue
            try:
                cshape = plan_slice(tpu.accelerator, tpu.topology, tpu.chips)
            except Exception as e:
                log.debug("victim scan: unplannable endpoint %s/%s: %s",
                          cand.metadata.namespace, cand.metadata.name, e)
                continue
            if (
                cshape.gke_accelerator != shape.gke_accelerator
                or cshape.topology != shape.topology
            ):
                continue
            pri = endpoint_priority(cand)
            if pri >= my_priority:
                continue
            key = f"{cand.metadata.namespace}/{cand.metadata.name}"
            candidates.append((pri, key, cand))
        if not candidates:
            return None
        candidates.sort(key=lambda t: (t[0], t[1]))
        return candidates[0][2]

    def _pick_job_victim(
        self, requester: Notebook, shape
    ) -> Optional[TPUJob]:
        """Batch jobs are reclaim victims by `spec.tpu.priority` in the
        same ordering as notebooks/endpoints — but they default BELOW
        interactive (JOB_DEFAULT_PRIORITY), only a Running job is eligible
        (its slice is confirmed live capacity), and a job mid-Checkpointing
        is NEVER victimized (the Draining rule's mirror, ISSUE 10 bugfix
        sweep): its save is exactly what makes the preemption survivable,
        and a preempt stamp racing the window would re-enter it."""
        my_priority = notebook_priority(requester)
        candidates: List[Tuple[int, str, TPUJob]] = []
        for cand in self.client.list(TPUJob):
            if cand.metadata.deletion_timestamp:
                continue
            ann = cand.metadata.annotations
            state = ann.get(C.JOB_STATE_ANNOTATION, "")
            if state != JOB_STATE_RUNNING:
                continue  # Pending/Admitted/Preempted free nothing usable;
                # Checkpointing is explicitly protected mid-window
            if C.JOB_PREEMPT_ANNOTATION in ann:
                continue  # already on the way out
            if cand.metadata.labels.get(C.TPU_RECLAIM_EXEMPT_LABEL):
                continue
            try:
                gangs = job_gangs(cand)
            except Exception as e:
                log.debug("victim scan: unplannable job %s/%s: %s",
                          cand.metadata.namespace, cand.metadata.name, e)
                continue
            if not any(
                gshape.gke_accelerator == shape.gke_accelerator
                and gshape.topology == shape.topology
                for _, gshape in gangs
            ):
                continue  # no gang of this job frees the requested shape
            pri = job_priority(cand)
            if pri >= my_priority:
                continue
            key = f"{cand.metadata.namespace}/{cand.metadata.name}"
            candidates.append((pri, key, cand))
        if not candidates:
            return None
        candidates.sort(key=lambda t: (t[0], t[1]))
        return candidates[0][2]

    def _patch_job_victim(self, victim: TPUJob, updates: dict) -> None:
        def attempt():
            return self.client.patch(
                TPUJob,
                victim.metadata.namespace,
                victim.metadata.name,
                {"metadata": {"annotations": updates}},
            )

        try:
            retry_on_conflict(attempt)
        except NotFoundError:
            pass  # deleted mid-reclaim; pressure re-judges next pass

    def _patch_endpoint_victim(
        self, victim: InferenceEndpoint, updates: dict
    ) -> None:
        def attempt():
            return self.client.patch(
                InferenceEndpoint,
                victim.metadata.namespace,
                victim.metadata.name,
                {"metadata": {"annotations": updates}},
            )

        try:
            retry_on_conflict(attempt)
        except NotFoundError:
            pass  # deleted mid-reclaim; pressure re-judges next pass

    def _matching_capacity_free(self, shape) -> bool:
        """True when a whole healthy, unreserved pool of the requester's
        shape has no TPU pods on it — a gang-placeable slice the scheduler
        simply hasn't bound yet."""
        from ..api.core import Node
        from ..cluster.slicepool import POOL_STATE_ANNOTATION
        from ..tpu import (
            GKE_TPU_ACCELERATOR_LABEL,
            GKE_TPU_TOPOLOGY_LABEL,
        )

        occupied = set()
        for p in self.client.list(Pod):
            if p.spec.node_name and not p.metadata.deletion_timestamp:
                occupied.add(p.spec.node_name)
        pools: Dict[str, List] = {}
        for node in self.client.list(Node):
            labels = node.metadata.labels
            if labels.get(GKE_TPU_ACCELERATOR_LABEL) != shape.gke_accelerator:
                continue
            if labels.get(GKE_TPU_TOPOLOGY_LABEL) != shape.topology:
                continue
            pools.setdefault(
                labels.get(GKE_NODEPOOL_LABEL, node.metadata.name), []
            ).append(node)
        for nodes in pools.values():
            if len(nodes) < shape.hosts:
                continue
            free = all(
                n.metadata.name not in occupied
                and not n.metadata.annotations.get(POOL_STATE_ANNOTATION)
                # ONE health predicate with the pool (claim eligibility and
                # this free-capacity judgment must never drift apart)
                and self.pool.node_healthy(n)
                for n in nodes
            )
            if free:
                return True
        return False

    def _admitted_chips(self) -> int:
        return admitted_chip_demand(self.client)

    # ---------- helpers ----------

    def _sweep_pool(self, now: float) -> None:
        interval = max(1.0, self.config.readiness_probe_period_s * 6)
        if now - self._last_sweep < interval:
            return
        self._last_sweep = now
        self.pool.sweep()
        self.pool.refresh_gauges()

    def _pods(self, nb: Notebook) -> List[Pod]:
        return [
            p
            for p in self.client.list(
                Pod,
                namespace=nb.metadata.namespace,
                labels={C.NOTEBOOK_NAME_LABEL: nb.metadata.name},
            )
            if not p.metadata.deletion_timestamp
        ]

    def _pending_pods(self, nb: Notebook) -> List[Pod]:
        return [p for p in self._pods(nb) if not p.spec.node_name]

    def _slice_pool_of(self, pods: List[Pod]) -> str:
        """The node pool the gang occupies (gang placement guarantees one)."""
        from ..api.core import Node

        for p in pods:
            if not p.spec.node_name:
                continue
            try:
                node = self.client.get(Node, "", p.spec.node_name)
            except NotFoundError:
                continue
            return node.metadata.labels.get(GKE_NODEPOOL_LABEL, "")
        return ""

    def _pool_nodes(self, pool: str) -> List[str]:
        from ..api.core import Node

        return [
            n.metadata.name
            for n in self.client.list(Node)
            if n.metadata.labels.get(GKE_NODEPOOL_LABEL) == pool
        ]

    def _release_claims(
        self, key: str, back_to_warm: bool, nb: Optional[Notebook] = None
    ) -> None:
        """Drop (or re-warm) every pool claim held by `key`."""
        for entry in self.pool.entries(include_unhealthy=True):
            if entry.claimed_by != key:
                continue
            if back_to_warm:
                self.pool.release(
                    entry.pool, entry.nodes,
                    priority=entry.priority if nb is None
                    else notebook_priority(nb),
                )
            else:
                self.pool.unclaim(entry.pool)

    def _forget(self, key: str) -> None:
        self._ckpt_acked.pop(key, None)
        self._ckpt_checksums.pop(key, None)
        self._resume_deadline.pop(key, None)
        self._victim_cooldown.pop(key, None)

    @staticmethod
    def _clear_updates() -> dict:
        return {
            C.TPU_SUSPEND_STATE_ANNOTATION: None,
            C.TPU_SUSPEND_STARTED_ANNOTATION: None,
            C.TPU_SUSPENDED_AT_ANNOTATION: None,
            C.TPU_SUSPEND_CHECKPOINT_DEADLINE_ANNOTATION: None,
            C.TPU_RESUME_STARTED_ANNOTATION: None,
            C.TPU_RESUME_ATTEMPTS_ANNOTATION: None,
            C.TPU_RECLAIM_ANNOTATION: None,
        }

    def _patch_annotations(self, nb: Notebook, updates: dict) -> None:
        def attempt():
            return self.client.patch(
                Notebook,
                nb.metadata.namespace,
                nb.metadata.name,
                {"metadata": {"annotations": updates}},
            )

        try:
            retry_on_conflict(attempt)
        except NotFoundError:
            pass  # deleted mid-transition; the delete path forgets state

    def _patch_victim(self, victim: Notebook, updates: dict) -> None:
        self._patch_annotations(victim, updates)

    def _emit_event(
        self, nb: Notebook, reason: str, message: str, etype: str = "Warning"
    ) -> None:
        emit_deduped_event(
            self.client, nb, f"{nb.metadata.name}.{reason.lower()}",
            reason=reason, message=message, etype=etype,
            api_version=nb.api_version or "kubeflow.org/v1beta1",
            kind="Notebook",
        )
