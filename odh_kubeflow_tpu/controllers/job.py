"""TPUJob reconciler: gang-scheduled batch/RL workloads (ISSUE 10).

Opens the third workload class the ROADMAP's north star demands: Podracer-
style batch/RL training jobs (anakin: one SPMD gang; sebulba: a split
actor-gang + learner-gang co-scheduled atomically) contending for the same
chips as the interactive fleet and the serving endpoints. The reconciler
deliberately reuses the notebook stack end to end — StatefulSet + headless
per-host Service for gang DNS, the TPU scheduler's gang placement and
claimed-pool reservations, the warm slice pool, the probe agent's /tpu/*
surface, the SLO engine — rather than growing a parallel batch stack.

State machine (annotation-durable like suspend/repair/inference; declared
as data in analysis/machines.py so the conformance checker and INVCHECK
cover it from day one):

    Pending ("") ──gangs secured──> Admitted ──all hosts ready──> Running
         ^                             │ preempt                     │ cadence
         │ requeue                     v                             v
         └──────────────────────── Preempted <──preempt── Checkpointing
                                       ^                     │ acked
              host loss / reclaim ─────┘       Running <─────┤
                                                             └─> Succeeded
    Running ──backoffLimit / maxRuntime──> Failed (terminal, incident)

- **Admission is all-or-nothing gang placement.** Pending secures EVERY
  gang before anything is created: matching warm slices are claimed first
  (a suspended notebook's released slice is a batch job's fast start), the
  rest must have whole free slices. A sebulba job claims BOTH gangs
  atomically or neither — a half-placed split job would deadlock against
  another half-placed one. Demand over the chip budget queues with a
  `QueuedOverBudget` condition instead of reclaiming anything.
- **Preemption is checkpoint-first.** The oversubscription reclaimer
  (controllers/suspend.py) ranks jobs in the ONE priority ordering with
  notebooks and endpoints (batch defaults below interactive) and stamps
  `preempt-requested` instead of killing pods; this controller answers
  with a bounded Checkpointing window, records the acked step, parks
  `Preempted`, and requeues — the job resumes from the saved step, losing
  only progress since the last checkpoint. A job mid-Checkpointing is
  never re-victimized (the Draining rule's mirror).
- **Host preemption is survived the same way.** Lost readiness mid-Running
  parks the job Preempted and requeues; like endpoints, the slice-repair
  controller never touches jobs, so there is no machine fight by
  construction. Unexplained interruptions charge `backoffLimit`;
  reclaim-driven preemptions never do.
- **Progress is checkpoint acks.** The workload reports its step counter
  through the /tpu/checkpoint ack (probe/agent.py); the cadence window
  banks productive run-seconds (the `tpu_job_goodput_ratio` numerator) and
  the job Succeeds when the acked step reaches steps x completions.
"""
from __future__ import annotations

import json
import logging
import time
from typing import Dict, List, Optional, Tuple

from ..api.apps import StatefulSet
from ..api.core import (
    Container,
    Node,
    Pod,
    ResourceRequirements,
    Service,
    ServicePort,
    Toleration,
    emit_deduped_event,
)
from ..api.job import LAYOUT_SEBULBA, TPUJob
from ..api.notebook import TPUStatus
from ..apimachinery import (
    AlreadyExistsError,
    NotFoundError,
    parse_time,
    rfc3339_precise,
    sanitize_name,
)
from ..cluster.client import retry_on_conflict
from ..cluster.slicepool import POOL_STATE_ANNOTATION, SlicePool
from ..runtime import jobmetrics as JM
from ..runtime.controller import Request, Result
from ..runtime.flightrecorder import recorder
from ..runtime.manager import Manager
from ..tpu import (
    GKE_NODEPOOL_LABEL,
    GKE_TPU_ACCELERATOR_LABEL,
    GKE_TPU_TOPOLOGY_LABEL,
    SliceShape,
    TPU_RESOURCE,
    plan_slice,
    tpu_env,
)
from ..utils import tracing
from ..utils.tracing import record_span
from . import constants as C
from .conditions import upsert_condition
from .config import Config
from .culling import HTTPGet, _default_http_get

log = logging.getLogger(__name__)

# annotation values of the job machine ("" = Pending)
STATE_ADMITTED = "admitted"
STATE_RUNNING = "running"
STATE_CHECKPOINTING = "checkpointing"
STATE_PREEMPTED = "preempted"
STATE_SUCCEEDED = "succeeded"
STATE_FAILED = "failed"


def job_priority(job: TPUJob) -> int:
    """Reclaim ordering for jobs: spec.tpu.priority, with the unset default
    BELOW interactive notebooks (JOB_DEFAULT_PRIORITY) — contention
    suspends batch before it ever touches a user's session."""
    if job.spec.tpu is not None:
        try:
            explicit = int(job.spec.tpu.priority)
        except (TypeError, ValueError):
            explicit = 0
        if explicit:
            return explicit
    return C.JOB_DEFAULT_PRIORITY


def job_gangs(job: TPUJob) -> List[Tuple[str, SliceShape]]:
    """The job's gang layout as (gang name, slice shape) pairs: anakin is
    one learner gang; sebulba adds the actor gang with its OWN topology.
    Shared with the reclaimer's shape matching and the budget math."""
    gangs: List[Tuple[str, SliceShape]] = []
    if job.spec.tpu is not None and job.spec.tpu.accelerator:
        gangs.append((C.JOB_GANG_LEARNER, plan_slice(
            job.spec.tpu.accelerator, job.spec.tpu.topology,
            job.spec.tpu.chips,
        )))
    if job.spec.layout == LAYOUT_SEBULBA and job.spec.actors is not None \
            and job.spec.actors.accelerator:
        gangs.append((C.JOB_GANG_ACTORS, plan_slice(
            job.spec.actors.accelerator, job.spec.actors.topology,
            job.spec.actors.chips,
        )))
    return gangs


def job_target_step(job: TPUJob) -> int:
    """The acked step at which the job is done: the step budget runs
    `completions` times."""
    return max(1, int(job.spec.steps)) * max(1, int(job.spec.completions))


def job_statefulset_name(name: str, gang: str) -> str:
    return sanitize_name(f"{name}-{gang}", max_len=52)


def job_hosts_service_name(name: str, gang: str) -> str:
    return sanitize_name(f"{name}-{gang}-hosts", max_len=63)


class TPUJobReconciler:
    def __init__(
        self,
        manager: Manager,
        config: Optional[Config] = None,
        http_get: Optional[HTTPGet] = None,
    ):
        self.manager = manager
        self.client = manager.client
        self.api_reader = manager.api_reader
        self.config = config or Config()
        self.http_get = http_get or _default_http_get
        self.pool = SlicePool(manager.client)
        # in-memory only (the durable machine lives in annotations):
        # per-episode checkpoint acks (ordinal -> acked step); re-derivable
        self._ckpt_acked: Dict[str, Dict[int, Optional[int]]] = {}

    def setup(self) -> None:
        def pod_is_job(ev: str, obj: dict, old: Optional[dict]) -> bool:
            return C.JOB_NAME_LABEL in obj.get("metadata", {}).get(
                "labels", {}
            )

        def map_pod(obj: dict) -> List[tuple]:
            meta = obj.get("metadata", {})
            name = meta.get("labels", {}).get(C.JOB_NAME_LABEL)
            return [(meta.get("namespace", ""), name)] if name else []

        (
            self.manager.builder("tpu-job")
            .for_(TPUJob)
            .owns(StatefulSet)
            .owns(Service)
            .watches(Pod, map_pod, predicate=pod_is_job)
            .with_workers(self.config.max_concurrent_reconciles)
            .complete(self.reconcile)
        )

    # ---------- reconcile ----------

    def reconcile(self, req: Request) -> Optional[Result]:
        try:
            job = self.api_reader.get(TPUJob, req.namespace, req.name)
        except NotFoundError:
            self._release_claims(req.key, back_to_warm=True)
            self._ckpt_acked.pop(req.key, None)
            tracing.discard_root_for(f"job:{req.key}")
            return None
        if job.metadata.deletion_timestamp:
            self._release_claims(req.key, back_to_warm=True)
            self._ckpt_acked.pop(req.key, None)
            tracing.discard_root_for(f"job:{req.key}")
            return None

        gangs = job_gangs(job)
        if not gangs:
            self._emit_event(
                job, "JobInvalid",
                "no TPU spec: set spec.tpu (and spec.actors for "
                "layout=sebulba) to shape the gang(s)",
            )
            return None
        if job.spec.layout == LAYOUT_SEBULBA and len(gangs) < 2:
            self._emit_event(
                job, "JobInvalid",
                "layout=sebulba needs spec.actors: the split actor gang has "
                "no shape to co-schedule",
            )
            return None

        self._ensure_trace_root(job)
        ann = job.metadata.annotations
        state = ann.get(C.JOB_STATE_ANNOTATION, "")
        now = time.time()

        if state == STATE_PREEMPTED:
            # requeue: a fresh Pending episode resumes from the saved step.
            # The saved checkpoint step SURVIVES the clear — it is the whole
            # point of checkpoint-preempt-requeue.
            preemptions = self._int_ann(job, C.JOB_PREEMPTIONS_ANNOTATION) + 1
            self._patch_annotations(
                job,
                {
                    C.JOB_STATE_ANNOTATION: None,
                    C.JOB_PREEMPT_ANNOTATION: None,
                    C.JOB_ADMITTED_AT_ANNOTATION: None,
                    C.JOB_RUN_STARTED_AT_ANNOTATION: None,
                    C.JOB_CHECKPOINT_DEADLINE_ANNOTATION: None,
                    C.JOB_EPISODE_QUEUED_AT_ANNOTATION: rfc3339_precise(now),
                    C.JOB_PREEMPTIONS_ANNOTATION: str(preemptions),
                },
            )
            JM.tpu_job_requeues_total.inc()
            self._emit_event(
                job, "JobRequeued",
                f"requeued after preemption #{preemptions}: will resume "
                f"from checkpoint step "
                f"{ann.get(C.JOB_CHECKPOINT_STEP_ANNOTATION, '0')}",
                etype="Normal",
            )
            recorder.record(
                "transition", machine="job", job=req.key, state="pending",
                from_state=STATE_PREEMPTED, preemptions=preemptions,
            )
            record_span(
                "job.requeue",
                traceparent=ann.get(C.TRACEPARENT_ANNOTATION),
                job=job.metadata.name, namespace=job.metadata.namespace,
                resume_step=ann.get(C.JOB_CHECKPOINT_STEP_ANNOTATION, "0"),
            )
            return Result(requeue_after=0.02)
        if state in (STATE_SUCCEEDED, STATE_FAILED):
            if job.metadata.generation and job.status.phase and \
                    job.metadata.generation != job.status.observed_generation:
                # spec bump after a terminal state: user rerun/self-heal —
                # a fresh Pending episode re-converges level-triggered
                self._patch_annotations(
                    job,
                    {
                        C.JOB_STATE_ANNOTATION: None,
                        C.JOB_CHECKPOINT_STEP_ANNOTATION: None,
                        C.JOB_FAILURES_ANNOTATION: None,
                        C.JOB_PREEMPTIONS_ANNOTATION: None,
                        C.JOB_RUN_SECONDS_ANNOTATION: None,
                        C.JOB_QUEUED_AT_ANNOTATION: None,
                        C.JOB_EPISODE_QUEUED_AT_ANNOTATION: None,
                        C.JOB_FIRST_ADMITTED_AT_ANNOTATION: None,
                    },
                )
                recorder.record(
                    "transition", machine="job", job=req.key,
                    state="pending", from_state=state, reason="rerun",
                )
                return Result(requeue_after=0.02)
            # parked terminal: keep replicas at 0, nothing else to converge
            self._reconcile_workloads(job, gangs, replicas=0)
            self._mirror_status(
                job, gangs,
                phase="Succeeded" if state == STATE_SUCCEEDED else "Failed",
            )
            return None
        if state == "":
            return self._run_pending(job, gangs, now, req)
        if state == STATE_ADMITTED:
            return self._run_admitted(job, gangs, now, req)
        if state == STATE_RUNNING:
            return self._run_running(job, gangs, now, req)
        if state == STATE_CHECKPOINTING:
            return self._run_checkpoint_window(job, gangs, now, req)
        log.warning("unknown job state %r on %s; clearing", state, req.key)
        self._patch_annotations(job, {C.JOB_STATE_ANNOTATION: None})
        return Result(requeue_after=0.05)

    # ---------- Pending: all-or-nothing gang admission ----------

    def _run_pending(
        self, job: TPUJob, gangs: List[Tuple[str, SliceShape]], now: float,
        req: Request,
    ) -> Optional[Result]:
        ann = job.metadata.annotations
        if C.JOB_QUEUED_AT_ANNOTATION not in ann:
            self._patch_annotations(
                job,
                {
                    C.JOB_QUEUED_AT_ANNOTATION: rfc3339_precise(now),
                    C.JOB_EPISODE_QUEUED_AT_ANNOTATION: rfc3339_precise(now),
                },
            )
            return Result(requeue_after=0.01)
        self._mirror_status(job, gangs, phase="Pending")

        # requeue backoff: a just-preempted job re-admitting instantly would
        # race the very requester its slice was reclaimed for
        backoff = self.config.job_requeue_backoff_s
        if backoff > 0 and self._int_ann(job, C.JOB_PREEMPTIONS_ANNOTATION):
            queued = self._time_ann(
                job, C.JOB_EPISODE_QUEUED_AT_ANNOTATION, now
            )
            if now - queued < backoff:
                return Result(requeue_after=max(
                    0.02, backoff - (now - queued)
                ))

        # over-budget demand queues with a condition — reclaiming to serve
        # demand the operator never admitted would cascade suspensions
        budget = self.config.chip_budget
        if budget > 0 and self._admitted_chips_with(job, gangs) > budget:
            if self._set_queued_condition(
                job, "True", "ChipBudget",
                f"admitted chip demand exceeds the chip budget ({budget}); "
                "queued without reclaim",
            ):
                self._emit_event(
                    job, "JobQueuedOverBudget",
                    f"total admitted chip demand exceeds the chip budget "
                    f"({budget}); queued",
                )
            return Result(requeue_after=max(
                1.0, self.config.reclaim_pending_grace_s
            ))

        secured, claims = self._secure_gangs(job, gangs, req.key)
        if not secured:
            # atomicity: whatever was claimed this pass went back warm in
            # _secure_gangs; wait for capacity (write-free, so a queued job
            # quiesces instead of churning the store)
            self._set_queued_condition(
                job, "True", "WaitingForCapacity",
                "not every gang could be secured (no matching warm slice "
                "and no whole free slice); queued",
            )
            return Result(requeue_after=max(
                0.1, self.config.reclaim_pending_grace_s
            ))

        self._set_queued_condition(job, "False", "Admitted", "")
        # pin the episode's resume step BEFORE the template is generated —
        # the template env reads it, and it must not move again until the
        # next admission (a live value would roll the gang mid-run)
        resume_step = job.metadata.annotations.get(
            C.JOB_CHECKPOINT_STEP_ANNOTATION, "0"
        )
        job.metadata.annotations[C.JOB_RESUME_STEP_ANNOTATION] = resume_step
        self._reconcile_workloads(job, gangs, replicas=None)
        episode_queued = self._time_ann(
            job, C.JOB_EPISODE_QUEUED_AT_ANNOTATION, now
        )
        JM.tpu_job_queue_wait_seconds.observe(max(0.0, now - episode_queued))
        admitted_updates = {
            C.JOB_STATE_ANNOTATION: STATE_ADMITTED,
            C.JOB_ADMITTED_AT_ANNOTATION: rfc3339_precise(now),
            C.JOB_RESUME_STEP_ANNOTATION: resume_step,
        }
        if C.JOB_FIRST_ADMITTED_AT_ANNOTATION not in job.metadata.annotations:
            # the maxRuntime clock: starts at the FIRST admission and
            # survives requeues (queue wait before it is free)
            admitted_updates[C.JOB_FIRST_ADMITTED_AT_ANNOTATION] = (
                rfc3339_precise(now)
            )
            job.metadata.annotations[C.JOB_FIRST_ADMITTED_AT_ANNOTATION] = (
                admitted_updates[C.JOB_FIRST_ADMITTED_AT_ANNOTATION]
            )
        self._patch_annotations(job, admitted_updates)
        warm_gangs = sorted(claims)
        self._emit_event(
            job, "JobAdmitted",
            f"admitted: {len(gangs)} gang(s) secured "
            + (f"(warm claim: {', '.join(warm_gangs)})" if warm_gangs
               else "(cold placement)")
            + f"; resuming from step "
              f"{job.metadata.annotations.get(C.JOB_CHECKPOINT_STEP_ANNOTATION, '0')}",
            etype="Normal",
        )
        recorder.record(
            "transition", machine="job", job=req.key, state=STATE_ADMITTED,
            warm_gangs=warm_gangs,
        )
        record_span(
            "job.admit",
            traceparent=job.metadata.annotations.get(C.TRACEPARENT_ANNOTATION),
            job=job.metadata.name, namespace=job.metadata.namespace,
            warm_gangs=",".join(warm_gangs) or "none",
            queue_wait_s=round(max(0.0, now - episode_queued), 3),
        )
        log.info("job %s admitted (%s)", req.key,
                 f"warm: {warm_gangs}" if warm_gangs else "cold")
        return Result(requeue_after=0.02)

    def _secure_gangs(
        self, job: TPUJob, gangs: List[Tuple[str, SliceShape]], key: str
    ) -> Tuple[bool, Dict[str, str]]:
        """Secure EVERY gang — warm claim first, whole free slices second —
        or nothing: partial claims made this pass are released back warm
        (sebulba both-or-neither). Returns (secured, {gang: claimed pool}).

        Free slices are reserved THROUGH the pool too: the pool is parked
        warm (priority 0, the prewarm idiom) and then claimed under the
        job's key via the lead-node CAS — so two Pending jobs counting the
        same free slice resolve at the CAS, not at pod-bind time. A bare
        free-count check here would be check-then-act: both jobs admit,
        one gang never binds, and a pair of sebulba jobs reproduces
        exactly the half-placed deadlock admission exists to prevent."""
        claims: Dict[str, str] = {}
        claimed_entries = []
        # a restart mid-admission may already hold claims: match them to
        # gangs by shape instead of claiming twice
        held = [
            e for e in self.pool.entries(include_unhealthy=True)
            if e.claimed_by == key
        ]
        unsecured: List[Tuple[str, SliceShape]] = []
        for gang, shape in gangs:
            prior = next(
                (e for e in held
                 if e.accelerator == shape.gke_accelerator
                 and e.topology == shape.topology),
                None,
            )
            if prior is not None:
                held.remove(prior)
                claims[gang] = prior.pool
                # prior-pass claims unwind with this pass's on failure: a
                # crash-mid-admission must not leave a queued job pinning a
                # claimed slice forever (two such sebulba jobs holding each
                # other's needed shape would deadlock permanently)
                claimed_entries.append(prior)
                continue
            entry = self.pool.claim(shape.gke_accelerator, shape.topology, key)
            if entry is not None:
                claims[gang] = entry.pool
                claimed_entries.append(entry)
            else:
                unsecured.append((gang, shape))
        # the rest need whole free slices, distinct per gang: park-then-CAS
        # each one; a raced pool just means try the next
        parked_here: set = set()
        for gang, shape in unsecured:
            entry = None
            for pool_name, nodes in sorted(self._free_pools(
                shape.gke_accelerator, shape.topology
            ).items()):
                if not self.pool.release(pool_name, nodes, priority=0):
                    continue  # node raced away mid-park; try the next pool
                parked_here.add(pool_name)
                entry = self.pool.claim(
                    shape.gke_accelerator, shape.topology, key
                )
                if entry is not None:
                    break
                # a rival claimed the slice we just parked: it is theirs
                # now; keep walking the remaining free pools
            if entry is None:
                for e in claimed_entries:  # unwind: all-or-nothing
                    if e.pool in parked_here:
                        # free capacity we parked ourselves this pass goes
                        # BACK to general capacity — left warm it would
                        # block cold creates until an idle-reclaim
                        self.pool.unclaim(e.pool)
                    else:
                        self.pool.release(e.pool, e.nodes,
                                          priority=e.priority)
                return False, {}
            claims[gang] = entry.pool
            claimed_entries.append(entry)
        return True, claims

    def _free_pools(
        self, gke_accelerator: str, topology: str
    ) -> Dict[str, List[str]]:
        """Whole healthy, unreserved, unoccupied pools of one shape (pool
        name -> node names) — a gang-placeable slice the scheduler can
        bind."""
        occupied = {
            p.spec.node_name
            for p in self.client.list(Pod)
            if p.spec.node_name and not p.metadata.deletion_timestamp
        }
        pools: Dict[str, List[Node]] = {}
        for node in self.client.list(Node):
            labels = node.metadata.labels
            if labels.get(GKE_TPU_ACCELERATOR_LABEL) != gke_accelerator:
                continue
            if labels.get(GKE_TPU_TOPOLOGY_LABEL) != topology:
                continue
            pools.setdefault(
                labels.get(GKE_NODEPOOL_LABEL, node.metadata.name), []
            ).append(node)
        out: Dict[str, List[str]] = {}
        for pool, nodes in sorted(pools.items()):
            if all(
                n.metadata.name not in occupied
                and not n.metadata.annotations.get(POOL_STATE_ANNOTATION)
                and self.pool.node_healthy(n)
                for n in nodes
            ):
                out[pool] = [n.metadata.name for n in nodes]
        return out

    def _admitted_chips_with(
        self, job: TPUJob, gangs: List[Tuple[str, SliceShape]]
    ) -> int:
        """Total admitted chip demand INCLUDING this job's gangs — the
        budget gate; notebooks/endpoints/other jobs counted by the shared
        reclaimer math (controllers/suspend.py admitted_chip_demand)."""
        from .suspend import admitted_chip_demand

        my_key = f"{job.metadata.namespace}/{job.metadata.name}"
        return admitted_chip_demand(self.client, exclude_job=my_key) + sum(
            shape.chips for _, shape in gangs
        )

    # ---------- Admitted ----------

    def _run_admitted(
        self, job: TPUJob, gangs: List[Tuple[str, SliceShape]], now: float,
        req: Request,
    ) -> Optional[Result]:
        if C.JOB_PREEMPT_ANNOTATION in job.metadata.annotations:
            # nothing running yet: nothing to checkpoint, just park
            return self._preempt(job, gangs, now, req)
        # bind timeout: a claimed slice can still die under the gang mid-
        # bind (host loss sweeps the claim, pods stay unschedulable) — park
        # and requeue instead of wedging in Admitted forever
        bind_window = self.config.job_admission_timeout_s
        admitted_at = self._time_ann(job, C.JOB_ADMITTED_AT_ANNOTATION, now)
        if bind_window > 0 and now - admitted_at > bind_window \
                and not self._gangs_ready(job, gangs):
            self._patch_annotations(
                job, {C.JOB_PREEMPT_ANNOTATION: "bind-timeout"}
            )
            job.metadata.annotations[C.JOB_PREEMPT_ANNOTATION] = (
                "bind-timeout"
            )
            self._emit_event(
                job, "JobBindTimeout",
                f"gang(s) secured but not every host bound within "
                f"{bind_window:.0f}s; requeueing",
            )
            return self._preempt(job, gangs, now, req)
        self._reconcile_workloads(job, gangs, replicas=None)
        self._mirror_status(job, gangs, phase="Admitted")
        if self._gangs_ready(job, gangs):
            # bind window over: the slices are plainly owned by their pods
            self._release_claims(req.key, back_to_warm=False)
            self._patch_annotations(
                job,
                {
                    C.JOB_STATE_ANNOTATION: STATE_RUNNING,
                    C.JOB_RUN_STARTED_AT_ANNOTATION: rfc3339_precise(now),
                },
            )
            self._emit_event(
                job, "JobRunning",
                "every host of every gang ready; steps progressing",
                etype="Normal",
            )
            recorder.record(
                "transition", machine="job", job=req.key, state=STATE_RUNNING,
            )
            if not self._int_ann(job, C.JOB_PREEMPTIONS_ANNOTATION):
                self._close_ready_root(job, now)
            return Result(requeue_after=0.02)
        return Result(requeue_after=max(
            0.05, self.config.readiness_probe_period_s / 2
        ))

    # ---------- Running ----------

    def _run_running(
        self, job: TPUJob, gangs: List[Tuple[str, SliceShape]], now: float,
        req: Request,
    ) -> Optional[Result]:
        ann = job.metadata.annotations
        self._reconcile_workloads(job, gangs, replicas=None)
        self._mirror_status(job, gangs, phase="Running")

        if job.spec.max_runtime_s > 0 and \
                now - self._time_ann(
                    job, C.JOB_FIRST_ADMITTED_AT_ANNOTATION, now
                ) > job.spec.max_runtime_s:
            return self._fail(
                job, gangs, now, req,
                f"maxRuntime ({job.spec.max_runtime_s:.0f}s since first "
                "admission) exceeded",
            )

        if not self._gangs_ready(job, gangs):
            # host preemption / readiness lost mid-run: progress since the
            # last checkpoint is gone — park, requeue, resume from the save.
            # Unexplained losses (no preempt notice) charge backoffLimit.
            if C.JOB_PREEMPT_ANNOTATION not in ann:
                failures = self._int_ann(job, C.JOB_FAILURES_ANNOTATION) + 1
                if failures > max(0, int(job.spec.backoff_limit)):
                    return self._fail(
                        job, gangs, now, req,
                        f"backoffLimit ({job.spec.backoff_limit}) exhausted: "
                        f"{failures} unexplained interruptions",
                    )
                self._patch_annotations(
                    job, {C.JOB_FAILURES_ANNOTATION: str(failures)}
                )
            return self._preempt(job, gangs, now, req)

        if C.JOB_PREEMPT_ANNOTATION in ann or self._cadence_due(job, now):
            window = self.config.job_checkpoint_window_s
            self._ckpt_acked.pop(req.key, None)
            self._patch_annotations(
                job,
                {
                    C.JOB_STATE_ANNOTATION: STATE_CHECKPOINTING,
                    C.JOB_CHECKPOINT_DEADLINE_ANNOTATION: (
                        rfc3339_precise(now + window)
                    ),
                },
            )
            recorder.record(
                "transition", machine="job", job=req.key,
                state=STATE_CHECKPOINTING,
                preempt=C.JOB_PREEMPT_ANNOTATION in ann,
            )
            return Result(requeue_after=0.01)
        period = max(0.05, job.spec.checkpoint_period_s)
        started = self._time_ann(job, C.JOB_RUN_STARTED_AT_ANNOTATION, now)
        return Result(requeue_after=max(
            0.05,
            min(self.config.readiness_probe_period_s,
                started + period - now),
        ))

    def _cadence_due(self, job: TPUJob, now: float) -> bool:
        period = max(0.05, job.spec.checkpoint_period_s)
        started = self._time_ann(job, C.JOB_RUN_STARTED_AT_ANNOTATION, now)
        return now - started >= period

    # ---------- Checkpointing ----------

    def _run_checkpoint_window(
        self, job: TPUJob, gangs: List[Tuple[str, SliceShape]], now: float,
        req: Request,
    ) -> Optional[Result]:
        ann = job.metadata.annotations
        try:
            deadline = parse_time(
                ann.get(C.JOB_CHECKPOINT_DEADLINE_ANNOTATION, "")
            ).timestamp()
        except ValueError:
            deadline = now

        learner_shape = gangs[0][1]
        pods = self._pods(job, C.JOB_GANG_LEARNER)
        ready_ordinals = set()
        for p in pods:
            if not p.is_ready():
                continue
            try:
                ready_ordinals.add(int(p.metadata.name.rsplit("-", 1)[1]))
            except (IndexError, ValueError):
                continue
        acked = self._ckpt_acked.setdefault(req.key, {})
        pending = sorted(ready_ordinals - set(acked))
        if pending and now < deadline:
            urls = self._probe_urls(
                job, C.JOB_GANG_LEARNER, learner_shape, "/tpu/checkpoint"
            )
            for ordinal in pending:
                if ordinal >= len(urls):
                    continue
                ack = self._probe(urls[ordinal])
                if ack and ack.get("saved"):
                    acked[ordinal] = ack.get("step")
        all_acked = bool(ready_ordinals) and ready_ordinals <= set(acked)
        if not (all_acked or not ready_ordinals or now >= deadline):
            return Result(requeue_after=max(
                0.02,
                min(self.config.readiness_probe_period_s, deadline - now),
            ))
        return self._complete_checkpoint(job, gangs, now, req, acked)

    def _complete_checkpoint(
        self, job: TPUJob, gangs: List[Tuple[str, SliceShape]], now: float,
        req: Request, acked: Dict[int, Optional[int]],
    ) -> Optional[Result]:
        """Window closed: bank the save, then continue, finish, or park —
        the one function that decides where a checkpoint leads."""
        ann = job.metadata.annotations
        self._ckpt_acked.pop(req.key, None)
        saved_before = self._int_ann(job, C.JOB_CHECKPOINT_STEP_ANNOTATION)
        steps = [s for s in acked.values() if s is not None]
        # ordinal 0's ack is the canonical step (per-shard saves; the PR 9
        # lesson: cross-ordinal digests/steps are not comparable) — fall
        # back to the max only when ordinal 0 never answered
        step = acked.get(0)
        if step is None:
            step = max(steps) if steps else None
        saved = max(saved_before, int(step)) if step is not None \
            else saved_before
        updates: Dict[str, Optional[str]] = {
            C.JOB_CHECKPOINT_DEADLINE_ANNOTATION: None,
        }
        run_s = self._float_ann(job, C.JOB_RUN_SECONDS_ANNOTATION)
        if step is not None:
            updates[C.JOB_CHECKPOINT_STEP_ANNOTATION] = str(saved)
            # productive time banked ONLY when a save landed: progress
            # without a checkpoint does not survive a preemption
            started = self._time_ann(
                job, C.JOB_RUN_STARTED_AT_ANNOTATION, now
            )
            run_s += max(0.0, now - started)
            updates[C.JOB_RUN_SECONDS_ANNOTATION] = f"{run_s:.3f}"
        record_span(
            "job.checkpoint",
            traceparent=ann.get(C.TRACEPARENT_ANNOTATION),
            job=job.metadata.name, namespace=job.metadata.namespace,
            step=saved, hosts_acked=len(acked),
        )

        if C.JOB_PREEMPT_ANNOTATION in ann:
            self._patch_annotations(job, updates)
            for k, v in updates.items():  # keep the in-hand object honest
                if v is None:
                    ann.pop(k, None)
                else:
                    ann[k] = v
            return self._preempt(job, gangs, now, req)

        if step is not None and saved >= job_target_step(job):
            updates[C.JOB_STATE_ANNOTATION] = STATE_SUCCEEDED
            updates[C.JOB_RUN_STARTED_AT_ANNOTATION] = None
            self._patch_annotations(job, updates)
            for k, v in updates.items():  # keep the in-hand object honest
                if v is None:
                    ann.pop(k, None)
                else:
                    ann[k] = v
            self._teardown(job, gangs, req.key, park_warm=True)
            queued = self._time_ann(job, C.JOB_QUEUED_AT_ANNOTATION, now)
            wall = max(0.0, now - queued)
            JM.tpu_jobs_total.inc(result="succeeded")
            JM.tpu_job_completion_seconds.observe(wall)
            JM.record_job_outcome(run_s, wall)
            self._mirror_status(job, gangs, phase="Succeeded")
            self._emit_event(
                job, "JobSucceeded",
                f"completed at step {saved} in {wall:.2f}s "
                f"({run_s:.2f}s productive; "
                f"{self._int_ann(job, C.JOB_PREEMPTIONS_ANNOTATION)} "
                "preemption(s) survived)",
                etype="Normal",
            )
            recorder.record(
                "transition", machine="job", job=req.key,
                state=STATE_SUCCEEDED, step=saved,
                productive_s=round(run_s, 3), wall_s=round(wall, 3),
            )
            record_span(
                "job.run",
                traceparent=ann.get(C.TRACEPARENT_ANNOTATION),
                start_time=queued, end_time=now,
                job=job.metadata.name, namespace=job.metadata.namespace,
                step=saved, productive_s=round(run_s, 3),
            )
            log.info("job %s succeeded at step %d (%.2fs productive / "
                     "%.2fs wall)", req.key, saved, run_s, wall)
            return None

        # cadence checkpoint: keep running, cadence clock re-arms
        updates[C.JOB_STATE_ANNOTATION] = STATE_RUNNING
        updates[C.JOB_RUN_STARTED_AT_ANNOTATION] = rfc3339_precise(now)
        self._patch_annotations(job, updates)
        recorder.record(
            "transition", machine="job", job=req.key, state=STATE_RUNNING,
            step=saved, reason="cadence",
        )
        return Result(requeue_after=0.05)

    # ---------- Preempted / Failed ----------

    def _preempt(
        self, job: TPUJob, gangs: List[Tuple[str, SliceShape]], now: float,
        req: Request,
    ) -> Optional[Result]:
        ann = job.metadata.annotations
        reason = ann.get(C.JOB_PREEMPT_ANNOTATION, "")
        reclaim_forced = reason.startswith("capacity-pressure")
        # bounded label set: unknown operator-stamped reasons read as "user"
        cause = (
            "reclaim" if reclaim_forced
            else "bind-timeout" if reason == "bind-timeout"
            else "user" if reason
            else "host-loss"
        )
        # reclaim-forced: the requester needs the chips — general capacity.
        # Anything else parks warm at the JOB's priority (ISSUE 10 bugfix:
        # a priority-0 park would make the job's own slice the first
        # idle-reclaim victim, defeating the fast requeue).
        self._teardown(job, gangs, req.key, park_warm=not reclaim_forced)
        self._patch_annotations(
            job,
            {
                C.JOB_STATE_ANNOTATION: STATE_PREEMPTED,
                C.JOB_RUN_STARTED_AT_ANNOTATION: None,
                C.JOB_CHECKPOINT_DEADLINE_ANNOTATION: None,
            },
        )
        JM.tpu_job_preemptions_total.inc(cause=cause)
        self._mirror_status(job, gangs, phase="Preempted")
        self._emit_event(
            job, "JobPreempted",
            f"preempted ({cause}): checkpoint step "
            f"{ann.get(C.JOB_CHECKPOINT_STEP_ANNOTATION, '0')} saved; will "
            "requeue and resume from it",
        )
        recorder.record(
            "transition", machine="job", job=req.key, state=STATE_PREEMPTED,
            cause=cause,
            step=ann.get(C.JOB_CHECKPOINT_STEP_ANNOTATION, "0"),
        )
        record_span(
            "job.preempt",
            traceparent=ann.get(C.TRACEPARENT_ANNOTATION),
            job=job.metadata.name, namespace=job.metadata.namespace,
            cause=cause,
        )
        log.warning("job %s preempted (%s)", req.key, cause)
        return Result(requeue_after=0.05)

    def _fail(
        self, job: TPUJob, gangs: List[Tuple[str, SliceShape]], now: float,
        req: Request, message: str,
    ) -> Optional[Result]:
        self._teardown(job, gangs, req.key, park_warm=True)
        self._patch_annotations(
            job,
            {
                C.JOB_STATE_ANNOTATION: STATE_FAILED,
                C.JOB_RUN_STARTED_AT_ANNOTATION: None,
                C.JOB_CHECKPOINT_DEADLINE_ANNOTATION: None,
            },
        )
        queued = self._time_ann(job, C.JOB_QUEUED_AT_ANNOTATION, now)
        JM.tpu_jobs_total.inc(result="failed")
        JM.record_job_outcome(
            self._float_ann(job, C.JOB_RUN_SECONDS_ANNOTATION),
            max(0.0, now - queued),
        )
        self._mirror_status(job, gangs, phase="Failed")
        self._emit_event(job, "JobFailed", message)
        recorder.record(
            "transition", machine="job", job=req.key, state=STATE_FAILED,
            message=message,
        )
        recorder.snapshot(
            "job-failed", subject=req.key, client=self.client,
            extra={"message": message},
        )
        log.error("job %s FAILED: %s", req.key, message)
        return None

    def _teardown(
        self, job: TPUJob, gangs: List[Tuple[str, SliceShape]], key: str,
        park_warm: bool,
    ) -> None:
        """Scale every gang away and settle the slice pool: bound slices
        release warm at the job's priority (park_warm) or return to general
        capacity; unbound claims always go back warm."""
        pools = self._slice_pools_of(job)
        self._reconcile_workloads(job, gangs, replicas=0)
        if park_warm:
            for pool, nodes in pools.items():
                self.pool.release(pool, nodes, priority=job_priority(job))
        # claims that never bound were warm capacity all along: back to warm
        # (at their prior priority) whatever forced the teardown
        self._release_claims(key, back_to_warm=True)

    # ---------- workload generation ----------

    def generate_statefulset(
        self, job: TPUJob, gang: str, shape: SliceShape, replicas: int
    ) -> StatefulSet:
        sts = StatefulSet()
        sts.metadata.name = job_statefulset_name(job.metadata.name, gang)
        sts.metadata.namespace = job.metadata.namespace
        sts.metadata.labels = {
            C.JOB_NAME_LABEL: job.metadata.name,
            C.JOB_GANG_LABEL: gang,
        }
        sts.spec.replicas = replicas
        sts.spec.selector.match_labels = {
            C.JOB_NAME_LABEL: job.metadata.name,
            C.JOB_GANG_LABEL: gang,
        }
        sts.spec.service_name = job_hosts_service_name(
            job.metadata.name, gang
        )
        sts.spec.pod_management_policy = "Parallel"

        template = sts.spec.template
        template.metadata.labels = {
            C.JOB_NAME_LABEL: job.metadata.name,
            C.JOB_GANG_LABEL: gang,
        }
        template.metadata.annotations = {}
        traceparent = job.metadata.annotations.get(C.TRACEPARENT_ANNOTATION)
        if traceparent:
            template.metadata.annotations[C.TRACEPARENT_ANNOTATION] = (
                traceparent
            )
        template.spec = job.spec.template.spec.deepcopy()
        self._default_container(job, gang, template.spec, shape)
        template.spec.node_selector.update(shape.node_selector())
        if not any(t.key == TPU_RESOURCE for t in template.spec.tolerations):
            template.spec.tolerations.append(
                Toleration(key=TPU_RESOURCE, operator="Exists",
                           effect="NoSchedule")
            )
        sts.set_owner(job)
        return sts

    def _default_container(
        self, job: TPUJob, gang: str, podspec, shape: SliceShape
    ) -> None:
        container: Optional[Container] = None
        for c in podspec.containers:
            if c.name == job.metadata.name:
                container = c
                break
        if container is None:
            if not podspec.containers:
                podspec.containers.append(
                    Container(name=job.metadata.name, image="")
                )
            container = podspec.containers[0]
        if container.resources is None:
            container.resources = ResourceRequirements()
        container.resources.requests[TPU_RESOURCE] = str(shape.chips_per_host)
        container.resources.limits[TPU_RESOURCE] = str(shape.chips_per_host)
        existing = {e.name for e in container.env}
        for ev in tpu_env(
            shape,
            job_statefulset_name(job.metadata.name, gang),
            job_hosts_service_name(job.metadata.name, gang),
            job.metadata.namespace,
            self.config.cluster_domain,
        ):
            if ev["name"] not in existing:
                container.set_env(ev["name"], ev["value"])
        # workload contract (the training loop reads these in the pod)
        container.set_env("TPU_JOB_GANG", gang)
        container.set_env("TPU_JOB_STEPS", str(job_target_step(job)))
        # pinned per admission episode (JOB_RESUME_STEP_ANNOTATION): the
        # live checkpoint-step here would roll the gang on every cadence save
        container.set_env(
            "TPU_JOB_RESUME_STEP",
            job.metadata.annotations.get(C.JOB_RESUME_STEP_ANNOTATION, "0"),
        )

    def generate_hosts_service(self, job: TPUJob, gang: str) -> Service:
        svc = Service()
        svc.metadata.name = job_hosts_service_name(job.metadata.name, gang)
        svc.metadata.namespace = job.metadata.namespace
        svc.metadata.labels = {
            C.JOB_NAME_LABEL: job.metadata.name,
            C.JOB_GANG_LABEL: gang,
        }
        svc.spec.cluster_ip = "None"
        svc.spec.selector = {
            C.JOB_NAME_LABEL: job.metadata.name,
            C.JOB_GANG_LABEL: gang,
        }
        svc.spec.ports = [
            ServicePort(name="jax-coordinator", port=8476, target_port=8476),
            ServicePort(name="probe", port=self.config.probe_port,
                        target_port=self.config.probe_port),
        ]
        svc.set_owner(job)
        return svc

    def _reconcile_workloads(
        self, job: TPUJob, gangs: List[Tuple[str, SliceShape]],
        replicas: Optional[int],
    ) -> None:
        """Converge one STS + headless gang-DNS Service per gang; replicas
        None = each gang's host count (the running shape), 0 = scaled away."""
        for gang, shape in gangs:
            desired = self.generate_statefulset(
                job, gang, shape,
                shape.hosts if replicas is None else replicas,
            )

            def attempt(desired=desired):
                try:
                    current = self.api_reader.get(
                        StatefulSet, job.metadata.namespace,
                        desired.metadata.name,
                    )
                except NotFoundError:
                    try:
                        self.client.create(desired)
                    except AlreadyExistsError:
                        pass  # racing reconcile won; level-triggered
                    return
                changed = False
                if current.spec.replicas != desired.spec.replicas:
                    current.spec.replicas = desired.spec.replicas
                    changed = True
                if current.spec.template.to_dict() != \
                        desired.spec.template.to_dict():
                    current.spec.template = desired.spec.template
                    changed = True
                if changed:
                    self.client.update(current)

            retry_on_conflict(attempt)
            svc = self.generate_hosts_service(job, gang)
            try:
                self.client.get(Service, job.metadata.namespace,
                                svc.metadata.name)
            except NotFoundError:
                try:
                    self.client.create(svc)
                except AlreadyExistsError:
                    pass

    # ---------- readiness / probing ----------

    def _pods(self, job: TPUJob, gang: Optional[str] = None) -> List[Pod]:
        labels = {C.JOB_NAME_LABEL: job.metadata.name}
        if gang:
            labels[C.JOB_GANG_LABEL] = gang
        return [
            p
            for p in self.client.list(
                Pod, namespace=job.metadata.namespace, labels=labels
            )
            if not p.metadata.deletion_timestamp
        ]

    def _gangs_ready(
        self, job: TPUJob, gangs: List[Tuple[str, SliceShape]]
    ) -> bool:
        for gang, shape in gangs:
            ready = sum(1 for p in self._pods(job, gang) if p.is_ready())
            if ready < shape.hosts:
                return False
        return True

    def _ready_count(self, job: TPUJob) -> int:
        return sum(1 for p in self._pods(job) if p.is_ready())

    def _probe_urls(
        self, job: TPUJob, gang: str, shape: SliceShape, path: str
    ) -> List[str]:
        sts_name = job_statefulset_name(job.metadata.name, gang)
        svc = job_hosts_service_name(job.metadata.name, gang)
        return [
            f"http://{sts_name}-{i}.{svc}.{job.metadata.namespace}.svc."
            f"{self.config.cluster_domain}:{self.config.probe_port}{path}"
            for i in range(shape.hosts)
        ]

    CHECKPOINT_TIMEOUT_S = 2.0

    def _probe(self, url: str) -> Optional[dict]:
        try:
            try:
                status, body = self.http_get(
                    url, timeout=self.CHECKPOINT_TIMEOUT_S
                )
            except TypeError:  # custom http_get without timeout kwarg
                status, body = self.http_get(url)
            if status != 200:
                raise ConnectionError(f"GET {url} -> {status}")
            return json.loads(body.decode() or "null")
        except Exception as e:
            log.debug("job checkpoint probe %s failed: %s", url, e)
            return None

    # ---------- pools / claims ----------

    def _slice_pools_of(self, job: TPUJob) -> Dict[str, List[str]]:
        """pool name -> node names for every pool the job's gangs occupy."""
        pools: Dict[str, List[str]] = {}
        names = set()
        for p in self._pods(job):
            if not p.spec.node_name:
                continue
            try:
                node = self.client.get(Node, "", p.spec.node_name)
            except NotFoundError:
                continue
            names.add(node.metadata.labels.get(GKE_NODEPOOL_LABEL, ""))
        names.discard("")
        for node in self.client.list(Node):
            pool = node.metadata.labels.get(GKE_NODEPOOL_LABEL, "")
            if pool in names:
                pools.setdefault(pool, []).append(node.metadata.name)
        return pools

    def _release_claims(self, key: str, back_to_warm: bool) -> None:
        for entry in self.pool.entries(include_unhealthy=True):
            if entry.claimed_by != key:
                continue
            if back_to_warm:
                self.pool.release(entry.pool, entry.nodes,
                                  priority=entry.priority)
            else:
                self.pool.unclaim(entry.pool)

    # ---------- status / helpers ----------

    def _mirror_status(
        self, job: TPUJob, gangs: List[Tuple[str, SliceShape]], phase: str
    ) -> None:
        learner_shape = gangs[0][1]
        ready = self._ready_count(job)
        before = job.status.to_dict()
        status = job.status
        status.phase = phase
        status.ready_replicas = ready
        status.completed_steps = self._int_ann(
            job, C.JOB_CHECKPOINT_STEP_ANNOTATION
        )
        status.preemptions = self._int_ann(job, C.JOB_PREEMPTIONS_ANNOTATION)
        status.failures = self._int_ann(job, C.JOB_FAILURES_ANNOTATION)
        status.observed_generation = job.metadata.generation
        status.tpu = status.tpu or TPUStatus()
        status.tpu.accelerator = learner_shape.accelerator
        status.tpu.topology = learner_shape.topology
        status.tpu.hosts = sum(s.hosts for _, s in gangs)
        status.tpu.hosts_ready = ready
        status.tpu.chips_per_host = learner_shape.chips_per_host
        status.tpu.chips_expected = sum(s.chips for _, s in gangs)
        status.tpu.mesh_ready = phase == "Running"
        if status.to_dict() == before:
            return
        spatch = status.to_dict()
        spatch["readyReplicas"] = status.ready_replicas  # zero must be written
        try:
            # coalesced when available (runtime/coalesce.py): one PATCH per
            # job per sync wave instead of one per watch event
            coalescer = getattr(self.manager, "status_coalescer", None)
            if coalescer is not None:
                coalescer.patch_status(
                    TPUJob, job.metadata.namespace, job.metadata.name, spatch
                )
            else:
                self.client.patch_status(
                    TPUJob, job.metadata.namespace, job.metadata.name, spatch
                )
        except NotFoundError:
            pass  # deleted mid-reconcile

    def _set_queued_condition(
        self, job: TPUJob, status: str, reason: str, message: str
    ) -> bool:
        """Upsert the QueuedOverBudget condition; write-free when nothing
        changed (a queued job must quiesce, not churn the store)."""
        if not upsert_condition(
            job.status.conditions, C.JOB_QUEUED_CONDITION, status, reason,
            message,
        ):
            return False
        try:
            self.client.patch_status(
                TPUJob, job.metadata.namespace, job.metadata.name,
                {"conditions": [c.to_dict() for c in job.status.conditions]},
            )
        except NotFoundError:
            pass
        return True

    def _ensure_trace_root(self, job: TPUJob) -> None:
        """First reconcile opens the `job.ready` root (closed at the first
        Running) and stamps its traceparent, so admission/checkpoint/
        preempt/requeue spans join one trace."""
        if C.TRACEPARENT_ANNOTATION in job.metadata.annotations:
            return
        root = tracing.begin_root(
            "job.ready",
            key=f"job:{job.key()}",
            job=job.metadata.name,
            namespace=job.metadata.namespace,
        )
        if root is None:
            return
        job.metadata.annotations[C.TRACEPARENT_ANNOTATION] = root.traceparent
        self._patch_annotations(
            job, {C.TRACEPARENT_ANNOTATION: root.traceparent}
        )

    def _close_ready_root(self, job: TPUJob, now: float) -> None:
        traceparent = job.metadata.annotations.get(C.TRACEPARENT_ANNOTATION)
        ctx = tracing.parse_traceparent(traceparent)
        if ctx is None:
            return
        trace_id, root_span_id = ctx
        if tracing.finish_root(trace_id, end_time=now) is None:
            start = now
            try:
                start = parse_time(
                    job.metadata.creation_timestamp
                ).timestamp()
            except (ValueError, TypeError):
                pass
            tracing.record_span(
                "job.ready",
                trace_id=trace_id,
                span_id=root_span_id,
                start_time=start,
                end_time=now,
                job=job.metadata.name,
            )

    def _int_ann(self, job: TPUJob, key: str) -> int:
        try:
            return int(job.metadata.annotations.get(key, "0") or 0)
        except ValueError:
            return 0

    def _float_ann(self, job: TPUJob, key: str) -> float:
        try:
            return float(job.metadata.annotations.get(key, "0") or 0)
        except ValueError:
            return 0.0

    def _time_ann(self, job: TPUJob, key: str, default: float) -> float:
        try:
            return parse_time(
                job.metadata.annotations.get(key, "")
            ).timestamp()
        except (ValueError, TypeError):
            return default

    def _patch_annotations(self, job: TPUJob, updates: dict) -> None:
        def attempt():
            return self.client.patch(
                TPUJob,
                job.metadata.namespace,
                job.metadata.name,
                {"metadata": {"annotations": updates}},
            )

        try:
            retry_on_conflict(attempt)
        except NotFoundError:
            pass  # deleted mid-transition; the delete path releases claims

    def _emit_event(
        self, job: TPUJob, reason: str, message: str, etype: str = "Warning"
    ) -> None:
        emit_deduped_event(
            self.client, job, f"{job.metadata.name}.{reason.lower()}",
            reason=reason, message=message, etype=etype,
            api_version=job.api_version or "kubeflow.org/v1beta1",
            kind="TPUJob",
        )


__all__ = [
    "TPUJobReconciler",
    "job_gangs",
    "job_priority",
    "job_statefulset_name",
    "job_target_step",
]
