"""Annotation/label contracts of the notebook stack.

Keys are kept byte-identical to the reference where they are user-facing
contracts (stop/culling state machine, restart, update-pending, auth) so CRs
and tooling written for the reference keep working (reference
pkg/culler/culler.go:40-41, odh notebook_controller.go:56-79,
notebook_webhook.go constants)."""

# -- stop / culling state machine --
STOP_ANNOTATION = "kubeflow-resource-stopped"
RECONCILIATION_LOCK_VALUE = "odh-notebook-controller-lock"
LAST_ACTIVITY_ANNOTATION = "notebooks.kubeflow.org/last-activity"
LAST_ACTIVITY_CHECK_TIMESTAMP_ANNOTATION = (
    "notebooks.kubeflow.org/last_activity_check_timestamp"
)

# -- core reconciler --
NOTEBOOK_NAME_LABEL = "notebook-name"
NOTEBOOK_RESTART_ANNOTATION = "notebooks.opendatahub.io/notebook-restart"
NOTEBOOK_PORT = 8888
NOTEBOOK_PORT_NAME = "http-notebook"  # service port name (Istio/mesh RBAC relies on it)
DEFAULT_WORKING_DIR = "/home/jovyan"
DEFAULT_FS_GROUP = 100
PREFIX_ENV = "NB_PREFIX"

# -- webhook / extension --
UPDATE_PENDING_ANNOTATION = "notebooks.opendatahub.io/update-pending"
INJECT_AUTH_ANNOTATION = "notebooks.opendatahub.io/inject-auth"
IMAGE_SELECTION_ANNOTATION = "notebooks.opendatahub.io/last-image-selection"
IMAGE_NAMESPACE_ANNOTATION = "notebooks.opendatahub.io/workbench-image-namespace"
AUTH_SIDECAR_CPU_REQUEST_ANNOTATION = "notebooks.opendatahub.io/auth-sidecar-cpu-request"
AUTH_SIDECAR_MEMORY_REQUEST_ANNOTATION = (
    "notebooks.opendatahub.io/auth-sidecar-memory-request"
)
AUTH_SIDECAR_CPU_LIMIT_ANNOTATION = "notebooks.opendatahub.io/auth-sidecar-cpu-limit"
AUTH_SIDECAR_MEMORY_LIMIT_ANNOTATION = "notebooks.opendatahub.io/auth-sidecar-memory-limit"
FEAST_LABEL = "opendatahub.io/feast-integration"
RUNTIME_IMAGE_LABEL = "opendatahub.io/runtime-image"

# -- observability --
# W3C traceparent of the readiness trace, stamped by the webhook at CREATE
# and copied into the pod template so every actor on the CR-submit ->
# jax.devices()-ready path (reconciler, kubelet, probe gate) joins ONE trace
from ..utils.tracing import TRACEPARENT_ANNOTATION  # noqa: E402,F401  (canonical home)

# -- slice repair (controllers/slice_repair.py) --
# The durable repair state machine lives in annotations (SURVEY §5: the API
# server is the database), mirrored into conditions for humans:
#   Ready -> Degraded (fault detected; checkpoint-before-evict window)
#         -> Repairing (gang evicted; all-or-nothing re-placement)
#         -> Ready (repaired)  |  RepairFailed (attempts exhausted; terminal)
TPU_REPAIR_STATE_ANNOTATION = "notebooks.tpu.kubeflow.org/repair-state"
TPU_REPAIR_STARTED_ANNOTATION = "notebooks.tpu.kubeflow.org/repair-started"
TPU_REPAIR_ATTEMPTS_ANNOTATION = "notebooks.tpu.kubeflow.org/repair-attempts"
TPU_REPAIR_CAUSE_ANNOTATION = "notebooks.tpu.kubeflow.org/repair-cause"
# checkpoint-before-evict contract: the repair controller stamps the window
# deadline here BEFORE evicting the gang; the in-pod agent's /tpu/checkpoint
# hook (probe/agent.py -> models/checkpoint.py) is driven inside that window,
# and the last acked step is recorded for the resumed workload to restore
TPU_CHECKPOINT_REQUEST_ANNOTATION = "notebooks.tpu.kubeflow.org/checkpoint-before-evict"
TPU_CHECKPOINT_SAVED_ANNOTATION = "notebooks.tpu.kubeflow.org/checkpoint-saved"

# condition types on NotebookStatus (owned by probe_status / slice_repair /
# the alert manager; the core reconciler's pod-condition mirror preserves
# these)
TPU_HEALTHY_CONDITION = "TPUHealthy"
TPU_DEGRADED_CONDITION = "Degraded"
# stamped by the alert manager (runtime/alerts.py) on the worst offenders
# while a burn-rate alert fires; cleared (reason Recovered) at resolution
SLO_DEGRADED_CONDITION = "DegradedSLO"

# -- suspend / resume (controllers/suspend.py) --
# The capacity-multiplexing state machine, annotation-durable like the repair
# machine above:
#   Active -> Checkpointing (cull/stop with state saved before the scale-down)
#          -> Suspended (slice released to the warm pool; replicas 0)
#          -> Resuming (unstop: warm-pool claim or cold fallback)
#          -> Active (mesh ready again)  |  ResumeFailed (attempts exhausted)
TPU_SUSPEND_STATE_ANNOTATION = "notebooks.tpu.kubeflow.org/suspend-state"
TPU_SUSPEND_STARTED_ANNOTATION = "notebooks.tpu.kubeflow.org/suspend-started"
TPU_SUSPENDED_AT_ANNOTATION = "notebooks.tpu.kubeflow.org/suspended-at"
TPU_RESUME_STARTED_ANNOTATION = "notebooks.tpu.kubeflow.org/resume-started"
TPU_RESUME_ATTEMPTS_ANNOTATION = "notebooks.tpu.kubeflow.org/resume-attempts"
# checkpoint deadline of the suspend path (the repair path has its own key
# above; two concurrent windows must not clobber each other's deadline)
TPU_SUSPEND_CHECKPOINT_DEADLINE_ANNOTATION = (
    "notebooks.tpu.kubeflow.org/suspend-checkpoint-deadline"
)
# stamped (with the reclaim reason) when a suspend was FORCED by the
# oversubscription reclaimer rather than idleness: the suspend path then
# returns the slice to general capacity instead of the warm pool — the
# requester that triggered the reclaim needs the chips
TPU_RECLAIM_ANNOTATION = "notebooks.tpu.kubeflow.org/reclaimed"
# never a reclaim victim: the SLO canary (runtime/prober.py) stamps this on
# its CRs — suspending the prober would blind the very signal that detects
# the pressure incident
TPU_RECLAIM_EXEMPT_LABEL = "notebooks.tpu.kubeflow.org/reclaim-exempt"

# -- inference serving (controllers/inference.py) --
# The promotion state machine, annotation-durable like the suspend/repair
# machines above (declared as data in analysis/machines.py):
#   Pending ("") -> Loading (pods ready; checkpoint restore + verification)
#                -> Serving (verified; route live)  |  LoadFailed (terminal)
#   Serving/Loading/Pending --stop--> Draining (route torn down; bounded
#   drain window) -> Terminated (replicas 0; slice released warm)
INFERENCE_STATE_ANNOTATION = "inference.tpu.kubeflow.org/endpoint-state"
INFERENCE_LOADING_DEADLINE_ANNOTATION = (
    "inference.tpu.kubeflow.org/loading-deadline"
)
INFERENCE_DRAIN_DEADLINE_ANNOTATION = "inference.tpu.kubeflow.org/drain-deadline"
# stamped at promotion time with the source notebook's ns/name so the
# endpoint's warm claim, checkpoint lineage, and trace all name their origin
INFERENCE_PROMOTED_FROM_ANNOTATION = "inference.tpu.kubeflow.org/promoted-from"
# pod -> owning InferenceEndpoint (the serving analog of notebook-name: the
# scheduler's claimed-pool owner check and the sim probe agent both key on it)
INFERENCE_NAME_LABEL = "inference-endpoint-name"
# -- serving fleet (ISSUE 16) --
# pod -> replica index within the endpoint's fleet: readiness is counted PER
# replica gang (a gang is ready only when all its hosts are), while every pod
# still carries INFERENCE_NAME_LABEL so the slicepool claim owner stays ns/name
INFERENCE_REPLICA_LABEL = "inference-endpoint-replica"
# the autoscaler's output channel (the HPA analog): runtime/autoscaler.py
# writes the desired replica count HERE, controllers/inference.py clamps it
# into autoscaling.{min,max} and reconciles toward it — single-writer
# ownership of INFERENCE_STATE_ANNOTATION stays with the inference controller
INFERENCE_DESIRED_REPLICAS_ANNOTATION = (
    "inference.tpu.kubeflow.org/desired-replicas"
)
# route-first per-replica drain (scale-down): JSON {"replica": i, "deadline":
# rfc3339} stamped when the controller picks a scale-down victim; the router
# stops sending it traffic (status.draining_replicas mirrors it), in-flight
# requests get the bounded window, then the gang scales away and its slice
# releases warm. Cleared at retire (or when the scale-down is withdrawn)
INFERENCE_REPLICA_DRAIN_ANNOTATION = (
    "inference.tpu.kubeflow.org/replica-draining"
)
# scale-to-zero park marker: stamped when the autoscaler parks the fleet
# (endpoint-state -> suspended, route left up); ANY writer clearing it (the
# router's cold-wake, an operator) pops the endpoint back to Pending
INFERENCE_SUSPENDED_AT_ANNOTATION = "inference.tpu.kubeflow.org/suspended-at"
# status condition while the fleet is degraded (>=1 but < desired replicas
# healthy): the endpoint keeps Serving — partial capacity is not an outage —
# but humans and the alert surface see the reduced strength
DEGRADED_SERVING_CONDITION = "DegradedServing"
# Serving endpoints default ABOVE interactive notebooks in the reclaim
# ordering (ISSUE 9 bugfix): a spec.tpu.priority of 0 on an endpoint reads
# as this value, so an idle notebook is always suspended before live traffic
ENDPOINT_DEFAULT_PRIORITY = 10

# -- batch/RL jobs (controllers/job.py, ISSUE 10) --
# The gang-scheduled job state machine, annotation-durable like the
# suspend/repair/inference machines above (declared as data in
# analysis/machines.py):
#   Pending ("") -> Admitted (gangs secured: warm claim(s) or free capacity;
#                   sebulba claims BOTH gangs atomically or neither)
#             -> Running (every host of every gang ready; steps progress)
#             -> Checkpointing (cadence or preempt: /tpu/checkpoint driven,
#                acked step recorded) -> Running | Succeeded | Preempted
#   Running --host loss--> Preempted --requeue--> Pending (resume from the
#   saved step); Failed (backoffLimit / maxRuntime) is terminal + incident
JOB_STATE_ANNOTATION = "jobs.tpu.kubeflow.org/job-state"
# last ACKED checkpoint step — the durable resume point; survives requeues
JOB_CHECKPOINT_STEP_ANNOTATION = "jobs.tpu.kubeflow.org/checkpoint-step"
JOB_CHECKPOINT_DEADLINE_ANNOTATION = (
    "jobs.tpu.kubeflow.org/checkpoint-deadline"
)
# stamped by the oversubscription reclaimer ("capacity-pressure:<ns/name>")
# or an operator ("user"): the job controller answers with
# checkpoint-before-preempt; capacity-pressure preempts release the slice to
# general capacity (the requester needs the chips), anything else parks warm
JOB_PREEMPT_ANNOTATION = "jobs.tpu.kubeflow.org/preempt-requested"
JOB_QUEUED_AT_ANNOTATION = "jobs.tpu.kubeflow.org/queued-at"  # first submit
# current episode's queue entry (reset per requeue; feeds the queue-wait
# histogram episode by episode)
JOB_EPISODE_QUEUED_AT_ANNOTATION = "jobs.tpu.kubeflow.org/episode-queued-at"
JOB_ADMITTED_AT_ANNOTATION = "jobs.tpu.kubeflow.org/admitted-at"
# first admission EVER (survives requeues, reset only on terminal rerun):
# the spec.maxRuntimeS clock starts here — queue wait before the first
# admission is free, parked/requeued time after it is not
JOB_FIRST_ADMITTED_AT_ANNOTATION = "jobs.tpu.kubeflow.org/first-admitted-at"
# the checkpoint step this EPISODE resumed from, pinned at admission: the
# pod template's TPU_JOB_RESUME_STEP reads this, never the live
# checkpoint-step — a cadence save mid-run must not mutate the template
# and roll the very gang it just checkpointed
JOB_RESUME_STEP_ANNOTATION = "jobs.tpu.kubeflow.org/resume-step"
JOB_RUN_STARTED_AT_ANNOTATION = "jobs.tpu.kubeflow.org/run-started"
# productive seconds banked at checkpoint acks (progress that SURVIVES a
# preemption); the job_goodput_ratio numerator
JOB_RUN_SECONDS_ANNOTATION = "jobs.tpu.kubeflow.org/run-seconds"
JOB_PREEMPTIONS_ANNOTATION = "jobs.tpu.kubeflow.org/preemptions"
JOB_FAILURES_ANNOTATION = "jobs.tpu.kubeflow.org/failures"
# pod -> owning TPUJob (the batch analog of notebook-name: the scheduler's
# claimed-pool owner check and the sim probe agent both key on it) + which
# gang of a sebulba job the pod belongs to
JOB_NAME_LABEL = "tpu-job-name"
JOB_GANG_LABEL = "tpu-job-gang"
JOB_GANG_LEARNER = "learner"
JOB_GANG_ACTORS = "actors"
# batch defaults BELOW interactive notebooks in the reclaim ordering: an
# unset spec.tpu.priority on a job reads as this value, so contention
# suspends a batch job before it ever touches a notebook or an endpoint
JOB_DEFAULT_PRIORITY = -10
# status condition set while a job queues over the chip budget
JOB_QUEUED_CONDITION = "QueuedOverBudget"

# -- checkpoint restore verification (ISSUE 9 satellite) --
# checksum of the state the checkpoint hook saved (probe agent ack); after
# resume — and after endpoint Loading — the /tpu/restore probe's checksum is
# compared against this, so "the restored kernel equals the saved one" is
# asserted end-to-end instead of assumed
TPU_CHECKPOINT_CHECKSUM_ANNOTATION = (
    "notebooks.tpu.kubeflow.org/checkpoint-checksum"
)

# -- TPU-native additions --
TPU_SLICE_POOL_LABEL = "notebooks.tpu.kubeflow.org/slice-pool"
# stamped on Events the mirror controller creates, and checked on ingest, so
# a mirrored Event is never re-mirrored into an infinite loop
TPU_MIRRORED_EVENT_ANNOTATION = "notebooks.tpu.kubeflow.org/mirrored"
TPU_PROBE_PORT = 8889  # in-pod probe agent (readiness + utilization + activity)

# -- finalizers (extension controller) --
ROUTE_FINALIZER = "notebooks.tpu.kubeflow.org/route-cleanup"
REFERENCE_GRANT_FINALIZER = "notebooks.tpu.kubeflow.org/referencegrant-cleanup"
AUTH_BINDING_FINALIZER = "notebooks.tpu.kubeflow.org/auth-binding-cleanup"
