"""Probe-status controller: device-visibility-gated slice readiness.

SURVEY §7 hard part (a), made real: "when is a slice ready?" is answered by
the in-pod probe contract, not by pod phase. Every ordinal's agent serves
GET /tpu/readiness -> {"chips_visible", "chips_expected", "ready"}
(probe/agent.py:181-189); this controller polls all hosts and owns the
device-level slice of NotebookStatus.tpu:

- chips_visible  = SUM of per-host reported chips (a host whose libtpu sees
  2 of 4 chips contributes 2 — pod-Ready alone never inflates this),
- mesh_ready     = every host reports ready (visible >= expected) AND every
  pod is Ready,
- first_ready_time + the notebook_slice_ready_seconds histogram fire at THAT
  moment — so the north-star metric (Notebook CR -> jax.devices() ready)
  measures device visibility, not kubelet bookkeeping.

The reconciler is requeue-driven at a fixed cadence like the culler
(reference culling_controller.go:86-203's RequeueAfter pattern); the pod-fact
fields (hosts_ready, chips_expected, ...) stay owned by the core reconciler
(controllers/notebook.py) and both writers preserve each other's fields.
"""
from __future__ import annotations

import json
import logging
import time
from typing import List, Optional, Tuple

from ..api.core import Pod
from ..api.notebook import Notebook, TPUStatus
from ..apimachinery import NotFoundError, now_rfc3339, parse_time
from ..cluster.client import retry_on_conflict
from ..runtime.controller import Request, Result
from ..runtime.flightrecorder import recorder
from ..runtime.manager import Manager
from ..tpu import plan_slice
from ..utils import tracing
from . import constants as C
from .conditions import get_condition, write_condition
from .config import Config
from .culling import HTTPGet, _default_http_get
from .metrics import NotebookMetrics
from .notebook import per_ordinal_probe_urls

log = logging.getLogger(__name__)


class ProbeStatusController:
    def __init__(
        self,
        manager: Manager,
        config: Optional[Config] = None,
        http_get: Optional[HTTPGet] = None,
        metrics: Optional[NotebookMetrics] = None,
    ):
        self.manager = manager
        self.client = manager.client
        # fresh reads for read-modify-write (manager.client may serve a
        # just-stale informer cache)
        self.api_reader = manager.api_reader
        self.config = config or Config()
        self.http_get = http_get or _default_http_get
        self.metrics = metrics or NotebookMetrics(manager.metrics)

    def setup(self) -> None:
        self.manager.builder("probe-status").for_(Notebook).with_workers(
            self.config.max_concurrent_reconciles
        ).complete(self.reconcile)

    # ---------- probing ----------

    def readiness_urls(self, nb: Notebook, hosts: int) -> List[str]:
        """One /tpu/readiness endpoint per ordinal (shared addressing with
        the culler's utilization probe: per_ordinal_probe_urls)."""
        return per_ordinal_probe_urls(
            self.client, self.config, nb, hosts, "/tpu/readiness"
        )

    PROBE_TIMEOUT_S = 2.0

    def collect_reports(self, nb: Notebook, hosts: int) -> List[Optional[dict]]:
        """Per-ordinal readiness reports; None for unreachable hosts.

        Probes run concurrently with a short timeout: the controller has one
        worker shared across all notebooks, and bring-up is exactly when DNS
        blackholes — N sequential 10s timeouts would starve every other
        slice's readiness detection."""
        from concurrent.futures import ThreadPoolExecutor

        def probe(url: str) -> Optional[dict]:
            try:
                try:
                    status, body = self.http_get(url, timeout=self.PROBE_TIMEOUT_S)
                except TypeError:  # custom http_get without timeout kwarg
                    status, body = self.http_get(url)
                if status != 200:
                    raise ConnectionError(f"GET {url} -> {status}")
                return json.loads(body.decode() or "null")
            except Exception:
                return None

        urls = self.readiness_urls(nb, hosts)
        if not urls:
            return []
        with ThreadPoolExecutor(max_workers=min(16, len(urls))) as pool:
            reports = list(pool.map(probe, urls))
        unreachable = sum(1 for r in reports if r is None)
        if unreachable:
            self.metrics.probe_unreachable_total.inc(unreachable)
        return reports

    # ---------- reconcile ----------

    def reconcile(self, req: Request) -> Optional[Result]:
        period_s = self.config.readiness_probe_period_s
        try:
            nb = self.client.get(Notebook, req.namespace, req.name)
        except NotFoundError:
            return None
        if nb.metadata.deletion_timestamp:
            return None
        if nb.spec.tpu is None or not nb.spec.tpu.accelerator:
            return None  # CPU notebook: no device gate
        if C.STOP_ANNOTATION in nb.metadata.annotations:
            # stopped slices have no devices; clear the gate but keep
            # first_ready_time (it anchors the FIRST bring-up latency). The
            # health verdict goes Unknown — a stale False must not read as a
            # live fault when the notebook is unstopped (the slice-repair
            # controller only acts on an affirmative False)
            if get_condition(nb, C.TPU_HEALTHY_CONDITION) is not None:
                write_condition(
                    self.client, self.api_reader, nb,
                    C.TPU_HEALTHY_CONDITION, "Unknown", "Stopped",
                    "notebook stopped; no devices to probe",
                )
            self._write(nb, chips_visible=0, mesh_ready=False, newly_ready=False)
            return None

        shape = plan_slice(
            nb.spec.tpu.accelerator, nb.spec.tpu.topology, nb.spec.tpu.chips
        )
        pods = [
            p
            for p in self.client.list(
                Pod,
                namespace=nb.metadata.namespace,
                labels={C.NOTEBOOK_NAME_LABEL: nb.metadata.name},
            )
            if not p.metadata.deletion_timestamp
        ]
        ready_pods = sum(1 for p in pods if p.is_ready())

        tpu_pub = nb.status.tpu
        if ready_pods < shape.hosts and not (
            tpu_pub and (tpu_pub.mesh_ready or tpu_pub.chips_visible)
        ):
            # Pods still coming up AND nothing is published as up: probing
            # every ordinal now mostly hits unreachable agents, and under a
            # create storm those wasted probe cycles are real contention
            # (every notebook event during bring-up re-triggered a full
            # probe sweep). Wait for the pod facts — the pod-Ready event
            # chain re-enqueues this notebook — with the periodic requeue
            # as the backstop. A DEGRADED slice (mesh_ready or chips
            # currently published) deliberately falls through: the probe
            # sweep is what downgrades the gate and the chip count after a
            # host loss or restart.
            return Result(requeue_after=period_s)

        # one timing source for BOTH consumers of the sweep window: the
        # sweep-duration histogram and the probe.first_healthy trace span
        probe_t0 = time.time()
        reports = self.collect_reports(nb, shape.hosts)
        probe_t1 = time.time()
        if reports:
            self.metrics.probe_sweep_seconds.observe(probe_t1 - probe_t0)
        chips_visible = sum(int(r.get("chips_visible", 0)) for r in reports if r)
        hosts_reporting_ready = sum(1 for r in reports if r and r.get("ready"))
        mesh_ready = (
            shape.hosts > 0
            and hosts_reporting_ready == shape.hosts
            and ready_pods == shape.hosts
            # gate on the PUBLISHED pod facts too: the core reconciler's
            # ready_replicas mirror must land before the device gate flips,
            # so observers never see mesh_ready=True with a stale
            # ready_replicas (the mirror's write re-enqueues this notebook,
            # so waiting costs one event hop, not a poll period)
            and nb.status.ready_replicas >= shape.hosts
        )

        # device-health aggregation -> the TPUHealthy condition (the slice-
        # repair controller's detection signal). Judged only once the slice
        # has been ready at least once (or is ready right now): during FIRST
        # bring-up an unreachable agent is normal, not a fault — the mesh
        # gate owns bring-up, TPUHealthy owns degradation-after-ready.
        if mesh_ready or (nb.status.tpu and nb.status.tpu.first_ready_time):
            healthy, reason, message = self._device_health(
                reports, shape, ready_pods
            )
            write_condition(
                self.client,
                self.api_reader,
                nb,
                C.TPU_HEALTHY_CONDITION,
                "True" if healthy else "False",
                reason,
                message,
            )

        newly_ready = mesh_ready and not (
            nb.status.tpu and nb.status.tpu.first_ready_time
        )
        # flight-recorder sample on gate FLIPS only (a steady-state sweep is
        # not evidence): the mesh going un-ready after first-ready is the
        # leading edge of every degradation incident
        was_ready = bool(nb.status.tpu and nb.status.tpu.mesh_ready)
        if mesh_ready != was_ready:
            recorder.record(
                "mesh", notebook=req.key, ready=mesh_ready,
                chips_visible=chips_visible, hosts_ready=ready_pods,
            )
        newly_ready = self._write(nb, chips_visible, mesh_ready, newly_ready)
        if newly_ready:
            # observe only after the write persisted (double-count guard)
            try:
                created = parse_time(nb.metadata.creation_timestamp).timestamp()
                self.metrics.slice_ready_seconds.observe(time.time() - created)
            except (ValueError, TypeError):
                pass
            self._record_ready_trace(nb, shape, chips_visible, probe_t0, probe_t1)
            log.info(
                "slice ready: %s (%d chips over %d hosts)",
                req.key,
                chips_visible,
                shape.hosts,
            )
        # keep polling until the mesh gate is green; afterwards stay on a slow
        # heartbeat so chip loss (e.g. a host losing devices) is re-detected
        return Result(requeue_after=period_s if not mesh_ready else period_s * 6)

    # ---------- device health (the TPUHealthy verdict) ----------

    @staticmethod
    def _device_health(
        reports: List[Optional[dict]], shape, ready_pods: int
    ) -> Tuple[bool, str, str]:
        """(healthy, reason, message) from one probe sweep. Precedence:
        unreachable hosts (preempted/crashed — the most urgent) > degraded
        ICI links > missing chips; healthy only when every host reported and
        every device checked out."""
        unreachable = sum(1 for r in reports if r is None)
        if unreachable or ready_pods < shape.hosts or len(reports) < shape.hosts:
            down = max(unreachable, shape.hosts - ready_pods)
            return (
                False,
                "HostUnreachable",
                f"{down}/{shape.hosts} hosts unreachable or not ready",
            )
        ici_hosts = [i for i, r in enumerate(reports) if r.get("ici_degraded")]
        if ici_hosts:
            return (
                False,
                "ICIDegraded",
                f"hosts {ici_hosts} report degraded ICI links",
            )
        missing = 0
        dead: List[str] = []  # "ordinal/device" ids from per-device health
        for i, r in enumerate(reports):
            failed = r.get("chips_failed")
            if failed is None:
                failed = max(
                    0,
                    int(r.get("chips_expected", 0)) - int(r.get("chips_visible", 0)),
                )
            missing += int(failed)
            for d in r.get("device_health") or []:
                if not d.get("healthy", True):
                    dead.append(f"{i}/{d.get('id')}")
        if missing:
            message = f"{missing} expected chips not visible"
            if dead:
                message += f" (dead devices host/id: {', '.join(dead[:8])})"
            return False, "ChipFailure", message
        return True, "AllDevicesHealthy", ""

    # ---------- readiness trace (terminal spans + root closure) ----------

    def _record_ready_trace(
        self, nb: Notebook, shape, chips_visible: int, probe_t0: float, probe_t1: float
    ) -> None:
        """First mesh-ready: record `probe.first_healthy` (the sweep that saw
        every host ready) and the terminal `jax.devices.ready` marker, then
        close the `notebook.ready` root the webhook opened — synthesizing it
        from creationTimestamp when the root lives in another process."""
        traceparent = nb.metadata.annotations.get(C.TRACEPARENT_ANNOTATION)
        ctx = tracing.parse_traceparent(traceparent)
        if ctx is None:
            return
        trace_id, root_span_id = ctx
        now = time.time()
        tracing.record_span(
            "probe.first_healthy",
            traceparent=traceparent,
            start_time=probe_t0,
            end_time=probe_t1,
            notebook=nb.metadata.name,
            hosts=shape.hosts,
        )
        tracing.record_span(
            "jax.devices.ready",
            traceparent=traceparent,
            start_time=now,
            end_time=now,
            notebook=nb.metadata.name,
            chips_visible=chips_visible,
        )
        if tracing.finish_root(trace_id, end_time=now, chips=chips_visible) is None:
            # root opened elsewhere (remote-mode webhook) or lost to a
            # restart: synthesize it with the annotation's OWN span id so the
            # children recorded against it still connect
            start = now
            try:
                start = parse_time(nb.metadata.creation_timestamp).timestamp()
            except (ValueError, TypeError):
                pass
            tracing.record_span(
                "notebook.ready",
                trace_id=trace_id,
                span_id=root_span_id,
                start_time=start,
                end_time=now,
                notebook=nb.metadata.name,
                namespace=nb.metadata.namespace,
                chips=chips_visible,
            )

    # ---------- status write (owns ONLY the device-gate fields) ----------

    def _write(
        self, nb: Notebook, chips_visible: int, mesh_ready: bool, newly_ready: bool
    ) -> bool:
        """Publish the device-gate fields; returns whether first_ready_time
        was set by THIS call (the metric-observe gate)."""
        # no-op pre-check against the (cache-served) object in hand: steady-
        # state heartbeat cycles then cost only the probe HTTP GETs, not an
        # API write per notebook per cycle. A stale cache that hides a
        # needed write self-heals: the event that updates the cache
        # re-enqueues this notebook (level-triggered).
        tpu = nb.status.tpu
        if (
            tpu is not None
            and tpu.chips_visible == chips_visible
            and tpu.mesh_ready == mesh_ready
            and not (newly_ready and not tpu.first_ready_time)
        ):
            return False

        if not newly_ready:
            # common path: merge-PATCH of the device-gate fields only
            # (disjoint ownership with the core reconciler's mirror — see
            # notebook.py _update_status): one request, no RMW loop
            try:
                self.client.patch_status(
                    Notebook, nb.metadata.namespace, nb.metadata.name,
                    {"tpu": {"chipsVisible": int(chips_visible),
                             "meshReady": bool(mesh_ready)}},
                )
            except NotFoundError:
                pass  # deleted mid-reconcile
            return False

        # first-ready transition (once per notebook lifetime): the anchor
        # field is SET-ONCE, and the cached nb may lag our own earlier
        # write — decide on a FRESH read under conflict retry so a racing
        # reconcile can neither move the anchor nor double-observe the
        # slice-ready metric
        def attempt() -> bool:
            cur = self.api_reader.get(
                Notebook, nb.metadata.namespace, nb.metadata.name
            )
            tpu = cur.status.tpu or TPUStatus()
            first = not tpu.first_ready_time
            changed = (
                first
                or tpu.chips_visible != chips_visible
                or tpu.mesh_ready != mesh_ready
            )
            tpu.chips_visible = chips_visible
            tpu.mesh_ready = mesh_ready
            if first:
                tpu.first_ready_time = now_rfc3339()
            if changed:
                cur.status.tpu = tpu
                self.client.update_status(cur)
            return first

        try:
            return retry_on_conflict(attempt)
        except NotFoundError:
            return False  # deleted mid-reconcile
