"""TPU workbench extension reconciler.

Re-design of the reference's OpenshiftNotebookReconciler + satellite-object
builders (reference odh-notebook-controller/controllers/notebook_controller.go
:178-497, notebook_route.go, notebook_referencegrant.go,
notebook_kube_rbac_auth.go, notebook_network.go, notebook_rbac.go,
notebook_dspa_secret.go, notebook_runtime.go) with OpenShift-isms swapped for
GKE/Gateway-API equivalents:

- Gateway-API HTTPRoute in the CENTRAL namespace (cross-ns backendRef to the
  user-ns Service) + one shared ReferenceGrant per user namespace,
- auth sidecar satellites: ServiceAccount, :8443 Service, SAR ConfigMap, and
  the cluster-scoped auth-delegator ClusterRoleBinding (finalizer-cleaned:
  cross-namespace/cluster-scoped objects can't ride owner-ref GC),
- per-notebook NetworkPolicies (notebook port from the controller namespace
  only; auth port open; probe port open to the controller namespace),
- CA-bundle ConfigMap assembly (controller-ns source + cluster roots),
- runtime-images ConfigMap sync and pipeline RBAC/secret wiring,
- **reconciliation-lock removal**: the final step that lets the core
  reconciler scale the StatefulSet 0 -> hosts (the webhook<->controller
  handshake, reference RemoveReconciliationLock :143-174).
"""
from __future__ import annotations

import json
import logging
import time
from typing import List, Optional

from ..api.core import ConfigMap, Secret, Service, ServiceAccount, ServicePort
from ..api.gateway import (
    GATEWAY_V1,
    HTTPBackendRef,
    HTTPPathMatch,
    HTTPRoute,
    HTTPRouteMatch,
    HTTPRouteRule,
    ParentReference,
    ReferenceGrant,
    ReferenceGrantFrom,
    ReferenceGrantSpec,
    ReferenceGrantTo,
)
from ..api.networking import (
    NetworkPolicy,
    NetworkPolicyIngressRule,
    NetworkPolicyPeer,
    NetworkPolicyPort,
)
from ..api.notebook import Notebook
from ..api.rbac import ClusterRoleBinding, Role, RoleBinding, RoleRef, Subject
from ..apimachinery import (
    AlreadyExistsError,
    LabelSelector,
    NotFoundError,
    parse_time,
    sanitize_name,
)
from ..cluster.client import retry_on_conflict
from ..runtime.controller import Request, Result
from ..runtime.manager import Manager
from . import constants as C
from .config import Config
from .webhook import AUTH_PROXY_PORT, CA_BUNDLE_CONFIGMAP

log = logging.getLogger(__name__)

NOTEBOOK_NAMESPACE_LABEL = "notebook-namespace"
REFERENCE_GRANT_NAME = "notebook-httproute-access"
RUNTIME_IMAGES_CONFIGMAP = "pipeline-runtime-images"
CA_SOURCE_CONFIGMAP = "odh-trusted-ca-bundle"
KUBE_ROOT_CA_CONFIGMAP = "kube-root-ca.crt"
PIPELINE_SERVER_SECRET = "pipeline-server-config"
ELYRA_SECRET_NAME = "ds-pipeline-config"
PIPELINE_ROLE_NAME = "ds-pipeline-user-access-dspa"

FINALIZERS = (C.ROUTE_FINALIZER, C.REFERENCE_GRANT_FINALIZER, C.AUTH_BINDING_FINALIZER)


def route_name(nb: Notebook) -> str:
    return sanitize_name(f"nb-{nb.metadata.namespace}-{nb.metadata.name}")


def auth_service_name(nb_name: str) -> str:
    return f"{nb_name}-kube-rbac-proxy"


def auth_binding_name(nb: Notebook) -> str:
    return sanitize_name(
        f"{nb.metadata.name}-rbac-{nb.metadata.namespace}-auth-delegator"
    )


class TPUWorkbenchReconciler:
    def __init__(self, manager: Manager, config: Optional[Config] = None):
        self.manager = manager
        self.client = manager.client
        self.api_reader = manager.api_reader
        self.config = config or Config()
        # auth-sweep bookkeeping (cleanup_auth_objects): the epoch is taken
        # at CONSTRUCTION (manager boot), so only notebooks that pre-date
        # this manager get the leaked-binding sweep. Taking it lazily at the
        # first cleanup call put it AFTER a create storm's CREATEs, making
        # every storm notebook "pre-existing" — 4 blind DELETEs each,
        # exactly during the storm (round-5 loadtest profile). Floored to
        # the second because creationTimestamp has 1 s resolution: a
        # notebook created in the manager's boot second must compare as
        # NOT-pre-existing (the trade: pre-existing notebooks from that same
        # wall-clock second skip the sweep until the next manager restart).
        self._auth_swept: set = set()
        self._sweep_epoch = float(int(time.time()))

    def setup(self) -> None:
        def map_route(obj: dict) -> List[tuple]:
            labels = obj.get("metadata", {}).get("labels", {})
            name = labels.get(C.NOTEBOOK_NAME_LABEL)
            ns = labels.get(NOTEBOOK_NAMESPACE_LABEL)
            return [(ns, name)] if name and ns else []

        def map_ca_source(obj: dict) -> List[tuple]:
            meta = obj.get("metadata", {})
            name, ns = meta.get("name"), meta.get("namespace", "")
            if name == CA_SOURCE_CONFIGMAP and ns == self.config.controller_namespace:
                # the central custom bundle affects every notebook
                return [
                    (nb.metadata.namespace, nb.metadata.name)
                    for nb in self.client.list(Notebook)
                ]
            if name in (KUBE_ROOT_CA_CONFIGMAP, CA_BUNDLE_CONFIGMAP):
                # namespace-local sources only touch that namespace's notebooks
                return [
                    (nb.metadata.namespace, nb.metadata.name)
                    for nb in self.client.list(Notebook, namespace=ns)
                ]
            return []

        (
            self.manager.builder("tpu-workbench")
            .for_(Notebook)
            .owns(ServiceAccount)
            .owns(Service)
            .owns(Secret)
            .owns(ConfigMap)
            .owns(NetworkPolicy)
            .owns(RoleBinding)
            .watches(HTTPRoute, map_route)
            .watches(ConfigMap, map_ca_source)
            # no reconciles keyed off grants — the watch exists to give the
            # cached client a ReferenceGrant informer (the shared per-ns
            # grant is existence-prechecked on every reconcile)
            .watches(ReferenceGrant, lambda obj: [])
            .with_workers(self.config.max_concurrent_reconciles)
            .complete(self.reconcile)
        )

    # ================= reconcile =================

    def reconcile(self, req: Request) -> Optional[Result]:
        try:
            nb = self.client.get(Notebook, req.namespace, req.name)
        except NotFoundError:
            return None

        if nb.metadata.deletion_timestamp:
            self._finalize(nb)
            return None

        self._ensure_finalizers(nb)
        self.reconcile_cert_configmap(nb)
        self.reconcile_network_policies(nb)
        self.reconcile_runtime_images(nb)
        if self.config.set_pipeline_rbac:
            self.reconcile_pipeline_rbac(nb)
        if self.config.set_pipeline_secret:
            self.reconcile_elyra_secret(nb)
        self.reconcile_reference_grant(nb)

        auth = nb.metadata.annotations.get(C.INJECT_AUTH_ANNOTATION) == "true"
        if auth:
            self.reconcile_auth_objects(nb)
        else:
            self.cleanup_auth_objects(nb)
        # route setup is a named phase of the readiness trace (the webhook's
        # reconciliation lock holds replicas at 0 until this controller is
        # done, so route time is on the bring-up critical path)
        from ..utils.tracing import reconcile_tracer

        with reconcile_tracer.start_span(
            "reconcile.route",
            traceparent=nb.metadata.annotations.get(C.TRACEPARENT_ANNOTATION),
            notebook=nb.metadata.name,
        ):
            self.reconcile_httproute(nb, auth=auth)

        self.remove_reconciliation_lock(nb)
        return None

    # ================= finalizers / deletion =================

    def _ensure_finalizers(self, nb: Notebook) -> None:
        missing = [f for f in FINALIZERS if f not in nb.metadata.finalizers]
        if not missing:
            return

        def attempt():
            cur = self.api_reader.get(Notebook, nb.metadata.namespace, nb.metadata.name)
            for f in FINALIZERS:
                if f not in cur.metadata.finalizers:
                    cur.metadata.finalizers.append(f)
            return self.client.update(cur)

        retry_on_conflict(attempt)

    def _finalize(self, nb: Notebook) -> None:
        """Deletion path (reference :194-369): tear down the cross-namespace /
        cluster-scoped satellites owner refs can't reach, then drop finalizers."""
        errors: List[str] = []
        if C.ROUTE_FINALIZER in nb.metadata.finalizers:
            try:
                self.client.delete(
                    HTTPRoute, self.config.controller_namespace, route_name(nb)
                )
            except NotFoundError:
                pass
            except Exception as e:  # keep finalizing; retry via requeue
                errors.append(f"httproute: {e}")
        if C.REFERENCE_GRANT_FINALIZER in nb.metadata.finalizers:
            try:
                self._delete_reference_grant_if_last(nb)
            except Exception as e:
                errors.append(f"referencegrant: {e}")
        if C.AUTH_BINDING_FINALIZER in nb.metadata.finalizers:
            try:
                self.client.delete(ClusterRoleBinding, "", auth_binding_name(nb))
            except NotFoundError:
                pass
            except Exception as e:
                errors.append(f"clusterrolebinding: {e}")
        if errors:
            raise RuntimeError("finalization incomplete: " + "; ".join(errors))

        def drop():
            cur = self.api_reader.get(Notebook, nb.metadata.namespace, nb.metadata.name)
            cur.metadata.finalizers = [
                f for f in cur.metadata.finalizers if f not in FINALIZERS
            ]
            return self.client.update(cur)

        try:
            retry_on_conflict(drop)
        except NotFoundError:
            pass

    def _delete_reference_grant_if_last(self, nb: Notebook) -> None:
        others = [
            n
            for n in self.client.list(Notebook, namespace=nb.metadata.namespace)
            if n.metadata.name != nb.metadata.name and not n.metadata.deletion_timestamp
        ]
        if others:
            return
        try:
            self.client.delete(
                ReferenceGrant, nb.metadata.namespace, REFERENCE_GRANT_NAME
            )
        except NotFoundError:
            pass

    # ================= CA bundle =================

    def reconcile_cert_configmap(self, nb: Notebook) -> None:
        """Assemble workbench-trusted-ca-bundle from the controller-ns custom
        bundle + the cluster root CA (reference CreateNotebookCertConfigMap
        :504-606, incl. light PEM validation)."""
        parts: List[str] = []
        for ns, name, key in (
            (self.config.controller_namespace, CA_SOURCE_CONFIGMAP, "ca-bundle.crt"),
            (nb.metadata.namespace, KUBE_ROOT_CA_CONFIGMAP, "ca.crt"),
        ):
            try:
                cm = self.client.get(ConfigMap, ns, name)
            except NotFoundError:
                continue
            pem = cm.data.get(key, "")
            if pem and "BEGIN CERTIFICATE" in pem:
                parts.append(pem.strip())
        if not parts:
            # all CA sources gone: prune the stale bundle (reference
            # UnsetNotebookCertConfig :639-704 analog), don't freeze it.
            # Cached existence pre-check: no CA sources AND no bundle (the
            # common bare-cluster case) must not cost a DELETE per reconcile.
            try:
                self.client.get(ConfigMap, nb.metadata.namespace, CA_BUNDLE_CONFIGMAP)
            except NotFoundError:
                return
            try:
                self.client.delete(
                    ConfigMap, nb.metadata.namespace, CA_BUNDLE_CONFIGMAP
                )
            except NotFoundError:
                pass
            return
        desired_data = {"ca-bundle.crt": "\n".join(parts) + "\n"}

        # cached no-op pre-check: bundle already equal -> zero API requests
        try:
            if self.client.get(
                ConfigMap, nb.metadata.namespace, CA_BUNDLE_CONFIGMAP
            ).data == desired_data:
                return
        except NotFoundError:
            pass

        def attempt():
            # shared per-namespace object, multiple concurrent reconcilers:
            # fresh read + conflict retry (a cached RV here 409s uncaught)
            try:
                cur = self.api_reader.get(
                    ConfigMap, nb.metadata.namespace, CA_BUNDLE_CONFIGMAP
                )
            except NotFoundError:
                cm = ConfigMap()
                cm.metadata.name = CA_BUNDLE_CONFIGMAP
                cm.metadata.namespace = nb.metadata.namespace
                cm.metadata.labels = {"app.kubernetes.io/part-of": "tpu-notebooks"}
                cm.data = desired_data
                self._create(cm)
                return
            if cur.data != desired_data:
                cur.data = desired_data
                self.client.update(cur)

        retry_on_conflict(attempt)

    # ================= network policies =================

    def reconcile_network_policies(self, nb: Notebook) -> None:
        """Reference NewNotebookNetworkPolicy/NewKubeRbacProxyNetworkPolicy
        (:132-211) + a TPU-native rule: the probe port is reachable from the
        controller namespace only (the culler probes it)."""
        ctrl_ns_peer = NetworkPolicyPeer(
            namespace_selector=LabelSelector(
                match_labels={"kubernetes.io/metadata.name": self.config.controller_namespace}
            )
        )
        # the Gateway dataplane forwards user traffic from its own namespace —
        # without this peer the HTTPRoute path is dead for non-auth notebooks.
        # In auth mode the gateway must ONLY reach the kube-rbac-proxy (:8443);
        # admitting it to :8888 would let any route attached to the shared
        # Gateway bypass the SubjectAccessReview.
        auth = nb.metadata.annotations.get(C.INJECT_AUTH_ANNOTATION) == "true"
        gateway_ns_peer = NetworkPolicyPeer(
            namespace_selector=LabelSelector(
                match_labels={"kubernetes.io/metadata.name": self.config.gateway_namespace}
            )
        )
        ctrl = NetworkPolicy()
        ctrl.metadata.name = f"{nb.metadata.name}-ctrl-np"
        ctrl.metadata.namespace = nb.metadata.namespace
        ctrl.spec.pod_selector = LabelSelector(
            match_labels={C.NOTEBOOK_NAME_LABEL: nb.metadata.name}
        )
        ctrl.spec.policy_types = ["Ingress"]
        ctrl.spec.ingress = [
            NetworkPolicyIngressRule(
                ports=[NetworkPolicyPort(protocol="TCP", port=C.NOTEBOOK_PORT)],
                from_=[ctrl_ns_peer] if auth else [ctrl_ns_peer, gateway_ns_peer],
            ),
            NetworkPolicyIngressRule(
                ports=[NetworkPolicyPort(protocol="TCP", port=self.config.probe_port)],
                from_=[ctrl_ns_peer],
            ),
            # slice-internal traffic (jax.distributed coordinator + ICI setup)
            NetworkPolicyIngressRule(
                ports=[NetworkPolicyPort(protocol="TCP", port=8476)],
                from_=[
                    NetworkPolicyPeer(
                        pod_selector=LabelSelector(
                            match_labels={C.NOTEBOOK_NAME_LABEL: nb.metadata.name}
                        )
                    )
                ],
            ),
        ]
        ctrl.set_owner(nb)
        self._create_or_replace_spec(ctrl)

        if nb.metadata.annotations.get(C.INJECT_AUTH_ANNOTATION) == "true":
            auth_np = NetworkPolicy()
            auth_np.metadata.name = f"{nb.metadata.name}-kube-rbac-proxy-np"
            auth_np.metadata.namespace = nb.metadata.namespace
            auth_np.spec.pod_selector = LabelSelector(
                match_labels={C.NOTEBOOK_NAME_LABEL: nb.metadata.name}
            )
            auth_np.spec.policy_types = ["Ingress"]
            auth_np.spec.ingress = [
                NetworkPolicyIngressRule(
                    ports=[NetworkPolicyPort(protocol="TCP", port=AUTH_PROXY_PORT)]
                )
            ]
            auth_np.set_owner(nb)
            self._create_or_replace_spec(auth_np)

    # ================= runtime images =================

    def reconcile_runtime_images(self, nb: Notebook) -> None:
        """Sync ConfigMaps labeled runtime-image in the controller ns into a
        per-user-ns `pipeline-runtime-images` ConfigMap (ImageStream-list
        analog, reference notebook_runtime.go:43-152)."""
        sync_runtime_images(
            self.client, self.config, nb.metadata.namespace,
            fresh=self.api_reader,
        )

    # ================= pipeline RBAC + Elyra =================

    def reconcile_pipeline_rbac(self, nb: Notebook) -> None:
        """RoleBinding elyra-pipelines-{name} -> Role ds-pipeline-user-access-
        dspa, only if the Role exists (reference notebook_rbac.go:89-154)."""
        try:
            self.client.get(Role, nb.metadata.namespace, PIPELINE_ROLE_NAME)
        except NotFoundError:
            return
        rb = RoleBinding()
        rb.metadata.name = f"elyra-pipelines-{nb.metadata.name}"
        rb.metadata.namespace = nb.metadata.namespace
        rb.role_ref = RoleRef(kind="Role", name=PIPELINE_ROLE_NAME)
        rb.subjects = [
            Subject(
                kind="ServiceAccount",
                name=nb.metadata.name,
                namespace=nb.metadata.namespace,
            )
        ]
        rb.set_owner(nb)
        self._create(rb)

    def reconcile_elyra_secret(self, nb: Notebook) -> None:
        """Render the Elyra runtime config Secret (`ds-pipeline-config`,
        odh_dsp.json). Extraction order mirrors the reference
        (notebook_dspa_secret.go:106-148,189-371): the namespace's DSPA CR
        (endpoints + object-storage creds from its S3 secret, public endpoint
        from the Gateway hostname) first, the flat `pipeline-server-config`
        Secret as the no-DSPA fallback."""
        sync_elyra_secret(
            self.client, self.config, nb.metadata.namespace,
            fresh=self.api_reader,
        )

    # ================= routing =================

    def reconcile_reference_grant(self, nb: Notebook) -> None:
        """One shared grant per user namespace: HTTPRoute(central ns) ->
        Service(user ns) (reference notebook_referencegrant.go:39-126)."""
        grant = ReferenceGrant()
        grant.metadata.name = REFERENCE_GRANT_NAME
        grant.metadata.namespace = nb.metadata.namespace
        grant.spec = ReferenceGrantSpec(
            from_=[
                ReferenceGrantFrom(
                    group="gateway.networking.k8s.io",
                    kind="HTTPRoute",
                    namespace=self.config.controller_namespace,
                )
            ],
            to=[ReferenceGrantTo(group="", kind="Service")],
        )
        # cached existence pre-check (the grant's spec is static): the
        # informer registered in setup() makes this free, so N notebooks in
        # a namespace cost ONE create + the storm-window races instead of a
        # blind 409 POST per reconcile (round-5 loadtest: 56 wasted writes
        # at 25 notebooks)
        try:
            self.client.get(
                ReferenceGrant, nb.metadata.namespace, REFERENCE_GRANT_NAME
            )
            return
        except NotFoundError:
            pass
        try:
            self.client.create(grant)
        except AlreadyExistsError:
            pass

    def reconcile_httproute(self, nb: Notebook, auth: bool) -> None:
        """Central-namespace HTTPRoute with cross-ns backendRef; auth mode
        retargets the backend to the kube-rbac-proxy service (reference
        notebook_route.go:50-218 + EnsureConflictingHTTPRouteAbsent :269-324,
        which here is a plain retarget since the route name is shared)."""
        route = HTTPRoute()
        route.metadata.name = route_name(nb)
        route.metadata.namespace = self.config.controller_namespace
        route.metadata.labels = {
            C.NOTEBOOK_NAME_LABEL: nb.metadata.name,
            NOTEBOOK_NAMESPACE_LABEL: nb.metadata.namespace,
        }
        if auth:
            backend = HTTPBackendRef(
                kind="Service",
                name=auth_service_name(nb.metadata.name),
                namespace=nb.metadata.namespace,
                port=AUTH_PROXY_PORT,
            )
        else:
            backend = HTTPBackendRef(
                kind="Service",
                name=nb.metadata.name,
                namespace=nb.metadata.namespace,
                port=80,
            )
        route.spec.parent_refs = [
            ParentReference(
                group="gateway.networking.k8s.io",
                kind="Gateway",
                name=self.config.gateway_name,
                namespace=self.config.gateway_namespace,
            )
        ]
        route.spec.rules = [
            HTTPRouteRule(
                matches=[
                    HTTPRouteMatch(
                        path=HTTPPathMatch(
                            type="PathPrefix",
                            value=f"/notebook/{nb.metadata.namespace}/{nb.metadata.name}",
                        )
                    )
                ],
                backend_refs=[backend],
            )
        ]
        # no owner ref: cross-namespace — label-matched, finalizer-cleaned
        self._create_or_replace_spec(route)

    # ================= auth satellites =================

    def reconcile_auth_objects(self, nb: Notebook) -> None:
        """ServiceAccount + :8443 Service + SAR ConfigMap + cluster-scoped
        auth-delegator binding (reference notebook_kube_rbac_auth.go)."""
        sa = ServiceAccount()
        sa.metadata.name = nb.metadata.name
        sa.metadata.namespace = nb.metadata.namespace
        sa.set_owner(nb)
        self._create(sa)

        svc = Service()
        svc.metadata.name = auth_service_name(nb.metadata.name)
        svc.metadata.namespace = nb.metadata.namespace
        svc.metadata.annotations = {
            # cert-manager serving cert (the OpenShift serving-cert analog)
            "cert-manager.io/issuer": "cluster-ca",
            "cert-manager.io/secret-name": f"{nb.metadata.name}-tls",
        }
        svc.spec.selector = {C.NOTEBOOK_NAME_LABEL: nb.metadata.name}
        svc.spec.ports = [
            ServicePort(name="https", port=AUTH_PROXY_PORT, target_port=AUTH_PROXY_PORT)
        ]
        svc.set_owner(nb)
        self._create(svc)

        sar = {
            "authorization": {
                "resourceAttributes": {
                    "apiGroup": "kubeflow.org",
                    "resource": "notebooks",
                    "name": nb.metadata.name,
                    "namespace": nb.metadata.namespace,
                    "verb": "get",
                }
            }
        }
        cm = ConfigMap()
        cm.metadata.name = f"{nb.metadata.name}-kube-rbac-proxy-config"
        cm.metadata.namespace = nb.metadata.namespace
        cm.data = {"config-file.yaml": json.dumps(sar, sort_keys=True)}
        cm.set_owner(nb)
        self._create_or_replace_spec(cm, field="data")

        crb = ClusterRoleBinding()
        crb.metadata.name = auth_binding_name(nb)
        crb.role_ref = RoleRef(kind="ClusterRole", name="system:auth-delegator")
        crb.subjects = [
            Subject(
                kind="ServiceAccount",
                name=nb.metadata.name,
                namespace=nb.metadata.namespace,
            )
        ]
        # cluster-scoped: no owner ref possible -> AUTH_BINDING_FINALIZER cleans
        try:
            self.client.create(crb)
        except AlreadyExistsError:
            pass

    def cleanup_auth_objects(self, nb: Notebook) -> None:
        """Auth switched off: revoke the delegator binding and remove the
        orphan proxy Service/ConfigMap (the SA stays — it's the pod identity).
        Leaving the ClusterRoleBinding would keep tokenreview rights forever.

        Gated on the CACHED proxy Service/ConfigMap: for the (default)
        never-auth notebook this is a pure no-op and must not cost four
        blind DELETEs per reconcile. When either cached marker exists the
        full sweep runs — including the (unwatched, cluster-scoped)
        ClusterRoleBinding, which is why the markers are the WATCHED kinds.
        Because a marker can disappear while the CRB survives (a partially
        failed earlier sweep), the FIRST reconcile of each notebook per
        manager lifetime always runs the full sweep — leaked bindings are
        reaped at the next manager start or notebook event, without paying
        per-reconcile cluster-scoped reads."""
        swept = self._auth_swept
        key = (nb.metadata.namespace, nb.metadata.name, nb.metadata.uid)
        first_sweep = key not in swept
        if first_sweep:
            # only PRE-EXISTING notebooks can carry leftovers from a
            # previous manager's partial sweep; ones created under this
            # manager skip straight to the marker gate (a startup sweep for
            # every fresh create would land exactly during create storms)
            try:
                created = parse_time(nb.metadata.creation_timestamp).timestamp()
                first_sweep = created < self._sweep_epoch
            except (ValueError, TypeError):
                pass
        marker_present = first_sweep
        if not marker_present:
            for cls, ns, name in (
                (Service, nb.metadata.namespace, auth_service_name(nb.metadata.name)),
                (ConfigMap, nb.metadata.namespace,
                 f"{nb.metadata.name}-kube-rbac-proxy-config"),
            ):
                try:
                    self.client.get(cls, ns, name)
                    marker_present = True
                    break
                except NotFoundError:
                    pass
        if not marker_present:
            swept.add(key)
            return
        for cls, ns, name in (
            (ClusterRoleBinding, "", auth_binding_name(nb)),
            (Service, nb.metadata.namespace, auth_service_name(nb.metadata.name)),
            (ConfigMap, nb.metadata.namespace, f"{nb.metadata.name}-kube-rbac-proxy-config"),
            (NetworkPolicy, nb.metadata.namespace, f"{nb.metadata.name}-kube-rbac-proxy-np"),
        ):
            try:
                self.client.delete(cls, ns, name)
            except NotFoundError:
                pass
        # only a COMPLETED sweep retires the one-shot: a transient delete
        # failure above raises out of reconcile, and the requeue re-enters
        # with first_sweep still true (else a leaked CRB would survive the
        # manager's whole lifetime)
        swept.add(key)

    # ================= the lock =================

    def remove_reconciliation_lock(self, nb: Notebook) -> None:
        """The handshake's last step: only the webhook's lock value is
        removed — a user's own stop annotation is never touched (reference
        RemoveReconciliationLock :143-174 patches it to null with retries)."""
        if nb.metadata.annotations.get(C.STOP_ANNOTATION) != C.RECONCILIATION_LOCK_VALUE:
            return

        def attempt():
            cur = self.api_reader.get(Notebook, nb.metadata.namespace, nb.metadata.name)
            if cur.metadata.annotations.get(C.STOP_ANNOTATION) != C.RECONCILIATION_LOCK_VALUE:
                return cur
            return self.client.patch(
                Notebook,
                nb.metadata.namespace,
                nb.metadata.name,
                {"metadata": {"annotations": {C.STOP_ANNOTATION: None}}},
            )

        retry_on_conflict(attempt)

    # ================= helpers =================

    def _create(self, obj) -> None:
        try:
            self.client.create(obj)
        except AlreadyExistsError:
            pass

    def _create_or_replace_spec(self, desired, field: str = "spec") -> None:
        cls = type(desired)

        def as_dict(v):
            return v.to_dict() if hasattr(v, "to_dict") else v

        # cached pre-checks (round-5 loadtest: the fresh-read attempts below
        # were ~130 GETs at 25 notebooks): already-converged -> zero
        # requests; cache-absent -> straight create. Both stale-cache races
        # resolve level-triggered: a stale "absent" lands in
        # AlreadyExistsError and falls through to the RMW; a stale
        # "converged" skip is re-enqueued by the event that updates the
        # cache.
        try:
            cached = self.client.get(
                cls, desired.metadata.namespace, desired.metadata.name
            )
            if as_dict(getattr(cached, field)) == as_dict(getattr(desired, field)) and (
                not desired.metadata.labels
                or cached.metadata.labels == desired.metadata.labels
            ):
                return
        except NotFoundError:
            try:
                self.client.create(desired)
                return
            except AlreadyExistsError:
                pass  # racing reconcile or stale cache: fall through to RMW

        def attempt():
            try:
                # fresh read: a cached RV straight after our own write 409s
                cur = self.api_reader.get(
                    cls, desired.metadata.namespace, desired.metadata.name
                )
            except NotFoundError:
                self._create(desired)
                return
            des_val = getattr(desired, field)
            changed = False
            if as_dict(getattr(cur, field)) != as_dict(des_val):
                setattr(cur, field, des_val)
                changed = True
            if desired.metadata.labels and cur.metadata.labels != desired.metadata.labels:
                cur.metadata.labels = desired.metadata.labels
                changed = True
            if changed:
                self.client.update(cur)

        retry_on_conflict(attempt)


def _format_key_name(display_name: str) -> str:
    """'Tensorflow 2.x' -> 'tensorflow_2.x.json' (reference formatKeyName
    :174-182)."""
    sanitized = display_name.lower().replace(" ", "_").replace("/", "_")
    return f"{sanitized}.json"


# ---------------------------------------------------------------------------
# Shared sync helpers: the webhook syncs these at admission (so a notebook's
# FIRST pod already mounts them — reference notebook_webhook.go:400-429) and
# the extension controller keeps them fresh afterwards.
# ---------------------------------------------------------------------------


def _build_runtime_images(client, config) -> dict:
    sources = client.list(
        ConfigMap,
        namespace=config.controller_namespace,
        labels={C.RUNTIME_IMAGE_LABEL: "true"},
    )
    data = {}
    for src in sources:
        for display_name, meta_json in sorted(src.data.items()):
            key = _format_key_name(display_name)
            try:
                meta = json.loads(meta_json)
            except ValueError:
                continue
            data[key] = json.dumps(meta, sort_keys=True)
    return data


def sync_runtime_images(client, config, namespace: str, fresh=None) -> bool:
    """Build/refresh the per-namespace `pipeline-runtime-images` ConfigMap
    from runtime-image sources in the controller namespace (ImageStream-list
    analog, reference notebook_runtime.go:43-152). Returns True when the
    catalog exists after the sync.

    Read/write split: `client` may serve STALE reads (the webhook's
    TTLReadClient memo, the extension's informer cache) and is used only for
    no-op detection — the common paths (no sources + no catalog; catalog
    already converged) cost zero fresh requests. Every WRITE decision
    re-derives from `fresh` (api_reader / the memo's inner client) under
    conflict retry, so a stale read can never update with a dead
    resourceVersion or prune a live catalog off a stale-empty source list."""
    fresh = fresh or getattr(client, "fresh", client)
    data = _build_runtime_images(client, config)
    if not data:
        try:
            client.get(ConfigMap, namespace, RUNTIME_IMAGES_CONFIGMAP)
        except NotFoundError:
            return False  # common case: nothing configured, no write
        # delete decision: a live catalog must only be pruned when the FRESH
        # source list is really empty (a memoized/cached empty list is not
        # evidence)
        def prune_attempt() -> bool:
            fresh_data = _build_runtime_images(fresh, config)
            if fresh_data:
                _apply_runtime_images(fresh, namespace, fresh_data)
                return True
            try:
                fresh.delete(ConfigMap, namespace, RUNTIME_IMAGES_CONFIGMAP)
            except NotFoundError:
                pass
            return False

        return retry_on_conflict(prune_attempt)
    # no-op pre-check on the (possibly stale) cached view
    try:
        if client.get(ConfigMap, namespace, RUNTIME_IMAGES_CONFIGMAP).data == data:
            return True
    except NotFoundError:
        pass

    # write decision: REBUILD the content from fresh sources too — writing
    # the cached-derived `data` could roll a newer catalog back to the memo
    # window's stale source list
    def write_attempt() -> bool:
        fresh_data = _build_runtime_images(fresh, config)
        if not fresh_data:
            try:
                fresh.delete(ConfigMap, namespace, RUNTIME_IMAGES_CONFIGMAP)
            except NotFoundError:
                pass
            return False
        _apply_runtime_images(fresh, namespace, fresh_data)
        return True

    return retry_on_conflict(write_attempt)


def _apply_runtime_images(fresh, namespace: str, data: dict) -> None:
    try:
        cur = fresh.get(ConfigMap, namespace, RUNTIME_IMAGES_CONFIGMAP)
        if cur.data != data:
            cur.data = data
            fresh.update(cur)
    except NotFoundError:
        cm = ConfigMap()
        cm.metadata.name = RUNTIME_IMAGES_CONFIGMAP
        cm.metadata.namespace = namespace
        cm.data = data
        try:
            fresh.create(cm)
        except AlreadyExistsError:
            pass  # racing writer; level-triggered convergence


def _gateway_public_hostname(client, config) -> str:
    """Public endpoint hostname: the data-science Gateway's listener hostname
    (reference getHostnameForPublicEndpoint, notebook_dspa_secret.go:106-148;
    its OpenShift-Route fallback maps here to the flat secret fallback in
    sync_elyra_secret)."""
    from ..api.gateway import Gateway

    try:
        gw = client.get(Gateway, config.gateway_namespace, config.gateway_name)
    except NotFoundError:
        return ""
    for listener in gw.spec.listeners:
        if listener.hostname:
            return listener.hostname
    return ""


def sync_elyra_secret(client, config, namespace: str, fresh=None) -> bool:
    """Render the `ds-pipeline-config` Secret (Elyra KFP runtime config,
    odh_dsp.json). DSPA-first, exactly like the reference
    (notebook_dspa_secret.go:189-371): endpoints derive from the namespace's
    DSPA CR, object-storage credentials from its S3 secret, the public
    endpoint from the Gateway hostname; without a DSPA, the flat
    `pipeline-server-config` Secret in the controller namespace supplies the
    fields. Returns True when the Secret exists after the sync.

    Same read/write split as sync_runtime_images: possibly-stale `client`
    reads drive the no-op pre-check only; the write path RE-DERIVES the
    desired content from `fresh` inside the conflict retry (writing
    cached-derived content could roll a newer render back)."""
    fresh = fresh or getattr(client, "fresh", client)
    derived = _derive_elyra_config(client, config, namespace)
    if derived is None:
        return False
    owner, desired = derived

    # no-op pre-check on the (possibly stale) cached view
    try:
        cached = client.get(Secret, namespace, ELYRA_SECRET_NAME)
        if cached.string_data == desired and (
            owner is None or cached.owned_by(owner)
        ):
            return True
    except NotFoundError:
        pass

    def attempt() -> bool:
        fresh_derived = _derive_elyra_config(fresh, config, namespace)
        if fresh_derived is None:
            return False  # sources vanished since the cached read: no write
        f_owner, f_desired = fresh_derived
        try:
            cur = fresh.get(Secret, namespace, ELYRA_SECRET_NAME)
        except NotFoundError:
            secret = Secret()
            secret.metadata.name = ELYRA_SECRET_NAME
            secret.metadata.namespace = namespace
            secret.string_data = f_desired
            secret.type = "Opaque"
            if f_owner is not None:
                # owned by the DSPA, as the reference's secret is (:280-371)
                secret.set_owner(f_owner, controller=False)
            try:
                fresh.create(secret)
            except AlreadyExistsError:
                pass
            return True
        changed = False
        if cur.string_data != f_desired:
            cur.string_data = f_desired
            changed = True
        if f_owner is not None and not cur.owned_by(f_owner):
            # a DSPA that appeared after the secret was first rendered must
            # still own it (GC on DSPA deletion — reference :280-371)
            cur.set_owner(f_owner, controller=False)
            changed = True
        if changed:
            fresh.update(cur)
        return True

    return retry_on_conflict(attempt)


def _derive_elyra_config(client, config, namespace: str):
    """The Elyra render half of sync_elyra_secret: (owner, desired data) or
    None when no pipeline config source exists. Pure reads — callable
    against either the cached or the fresh client."""
    from ..api.dspa import DSPA_NAME, DataSciencePipelinesApplication

    owner = None
    meta: Optional[dict] = None
    try:
        dspa = client.get(DataSciencePipelinesApplication, namespace, DSPA_NAME)
    except NotFoundError:
        dspa = None
    if dspa is not None:
        owner = dspa
        cos_endpoint = cos_bucket = cos_user = cos_password = ""
        storage = dspa.spec.object_storage
        ext = storage.external_storage if storage else None
        if ext is not None:
            scheme = ext.scheme or "https"
            cos_endpoint = f"{scheme}://{ext.host}" if ext.host else ""
            cos_bucket = ext.bucket
            creds = ext.s3_credentials_secret
            if creds is not None and creds.secret_name:
                try:
                    s3 = client.get(Secret, namespace, creds.secret_name)
                    blob = dict(s3.string_data or {})
                    cos_user = blob.get(creds.access_key or "accesskey", "")
                    cos_password = blob.get(creds.secret_key or "secretkey", "")
                except NotFoundError:
                    pass
        api_endpoint = (
            f"https://ds-pipeline-{DSPA_NAME}.{namespace}.svc."
            f"{config.cluster_domain}:8443"
        )
        hostname = _gateway_public_hostname(client, config)
        public_api_endpoint = (
            f"https://{hostname}/pipeline/{namespace}/{DSPA_NAME}" if hostname else ""
        )
        if not public_api_endpoint:
            # Route-fallback analog: the flat secret may carry an externally
            # published endpoint when no Gateway hostname is set
            try:
                flat = client.get(
                    Secret, config.controller_namespace, PIPELINE_SERVER_SECRET
                )
                public_api_endpoint = flat.string_data.get("public_api_endpoint", "")
            except NotFoundError:
                pass
        meta = {
            "api_endpoint": api_endpoint,
            "public_api_endpoint": public_api_endpoint,
            "cos_endpoint": cos_endpoint,
            "cos_bucket": cos_bucket,
            "cos_username": cos_user,
            "cos_password": cos_password,
        }
    else:
        try:
            src = client.get(
                Secret, config.controller_namespace, PIPELINE_SERVER_SECRET
            )
        except NotFoundError:
            return None
        meta = {
            "api_endpoint": src.string_data.get("api_endpoint", ""),
            "public_api_endpoint": src.string_data.get("public_api_endpoint", ""),
            "cos_endpoint": src.string_data.get("cos_endpoint", ""),
            "cos_bucket": src.string_data.get("cos_bucket", ""),
            "cos_username": src.string_data.get("cos_username", ""),
            "cos_password": src.string_data.get("cos_password", ""),
        }

    cfg = {
        "display_name": "Data Science Pipeline",
        "schema_name": "kfp",
        "metadata": {
            "tags": [],
            "display_name": "Data Science Pipeline",
            "engine": "Argo",
            "auth_type": "KUBERNETES_SERVICE_ACCOUNT_TOKEN",
            "cos_auth_type": "KUBERNETES_SECRET",
            "cos_secret": ELYRA_SECRET_NAME,
            "runtime_type": "KUBEFLOW_PIPELINES",
            **meta,
        },
    }
    desired = {"odh_dsp.json": json.dumps(cfg, sort_keys=True)}
    return owner, desired
