"""Core Notebook reconciler: Notebook CR -> StatefulSet + Service(s) + status.

TPU-native re-design of the reference's NotebookReconciler
(reference components/notebook-controller/controllers/notebook_controller.go:
Reconcile :93-297, generateStatefulSet :433-523, generateService :525-552,
updateNotebookStatus :299-374, setPrefixEnvVar :417-431):

- `spec.tpu` drives the slice: replicas = hosts (the reference hard-wires 1),
  `google.com/tpu` requests at chips-per-host granularity, GKE accelerator/
  topology node selectors, and a headless per-host Service for stable pod DNS
  (the jax.distributed coordinator address),
- the stop annotation (`kubeflow-resource-stopped`) scales to 0 — culling a
  TPU notebook frees the WHOLE slice,
- status mirrors pod conditions/container state like the reference, plus
  `status.tpu` slice bring-up (hosts ready / chips visible / mesh ready),
- the restart annotation deletes all ordinal pods, not just {name}-0.
"""
from __future__ import annotations

import logging
from typing import List, Optional

from ..api.apps import StatefulSet
from ..api.core import (
    Container,
    ContainerPort,
    Event,
    ObjectReference,
    Pod,
    PodSecurityContext,
    ResourceRequirements,
    Service,
    ServicePort,
    Toleration,
)
from ..api.notebook import Notebook, TPUStatus
from ..apimachinery import (
    AlreadyExistsError,
    Condition,
    NotFoundError,
    now_rfc3339,
    sanitize_name,
)
from ..cluster.client import retry_on_conflict
from ..runtime.controller import Request, Result
from ..runtime.manager import Manager
from ..tpu import SliceShape, TPU_RESOURCE, plan_slice, tpu_env, ordinal_env
from ..utils import tracing
from ..utils.tracing import reconcile_tracer
from . import constants as C
from .conditions import REPAIR_OWNED_CONDITIONS
from .config import Config
from .metrics import NotebookMetrics

log = logging.getLogger(__name__)


def statefulset_name(nb_name: str) -> str:
    """Deterministic 52-char clamp (truncate + hash) where the reference
    switches to generateName `nb-` past 52 chars (notebook_controller.go:
    58-59): pod ordinals append `-N` and the name must stay a valid DNS
    label — multi-host coordinator addressing depends on it. Deterministic
    (unlike generateName) so level-triggered reconciles converge."""
    return sanitize_name(nb_name, max_len=52)


def hosts_service_name(nb_name: str) -> str:
    # a DNS label itself (pod DNS is {pod}.{svc}.{ns}.svc...): clamp at 63
    return sanitize_name(f"{nb_name}-hosts", max_len=63)


def per_ordinal_probe_urls(
    client, config, nb: Notebook, hosts: int, path: str
) -> List[str]:
    """One agent endpoint per ordinal over per-pod DNS — shared by the
    culler's /tpu/utilization probe and the readiness gate's /tpu/readiness
    probe so addressing fixes land once. Rides the StatefulSet's ACTUAL
    serviceName (immutable in real k8s: an STS created before a rename keeps
    its old headless svc), falling back to the derived name."""
    svc = hosts_service_name(nb.metadata.name)
    sts_name = statefulset_name(nb.metadata.name)
    try:
        sts = client.get(StatefulSet, nb.metadata.namespace, sts_name)
        if sts.spec.service_name:
            svc = sts.spec.service_name
    except NotFoundError:
        pass
    return [
        f"http://{sts_name}-{i}.{svc}.{nb.metadata.namespace}.svc."
        f"{config.cluster_domain}:{config.probe_port}{path}"
        for i in range(hosts)
    ]


class NotebookReconciler:
    def __init__(self, manager: Manager, config: Optional[Config] = None,
                 metrics: Optional[NotebookMetrics] = None):
        self.manager = manager
        self.client = manager.client
        self.api_reader = manager.api_reader
        self.config = config or Config()
        self.metrics = metrics or NotebookMetrics(manager.metrics, manager.client)

    def setup(self) -> None:
        def pod_is_labeled(ev: str, obj: dict, old: Optional[dict]) -> bool:
            # predNBPodIsLabeled analog (reference notebook_controller.go:740-751)
            return C.NOTEBOOK_NAME_LABEL in obj.get("metadata", {}).get("labels", {})

        def map_pod(obj: dict) -> List[tuple]:
            meta = obj.get("metadata", {})
            name = meta.get("labels", {}).get(C.NOTEBOOK_NAME_LABEL)
            return [(meta.get("namespace", ""), name)] if name else []

        (
            self.manager.builder("notebook")
            .for_(Notebook)
            .owns(StatefulSet)
            .owns(Service)
            .watches(Pod, map_pod, predicate=pod_is_labeled)
            .with_workers(self.config.max_concurrent_reconciles)
            .complete(self.reconcile)
        )

    # ---------- generation ----------

    def plan(self, nb: Notebook) -> Optional[SliceShape]:
        if nb.spec.tpu is None or not nb.spec.tpu.accelerator:
            return None
        return plan_slice(
            nb.spec.tpu.accelerator, nb.spec.tpu.topology, nb.spec.tpu.chips
        )

    def generate_statefulset(self, nb: Notebook, shape: Optional[SliceShape]) -> StatefulSet:
        sts = StatefulSet()
        sts.metadata.name = statefulset_name(nb.metadata.name)
        sts.metadata.namespace = nb.metadata.namespace
        sts.metadata.labels = {C.NOTEBOOK_NAME_LABEL: nb.metadata.name}

        stopped = C.STOP_ANNOTATION in nb.metadata.annotations
        if (
            stopped
            and nb.metadata.annotations.get(C.TPU_SUSPEND_STATE_ANNOTATION)
            == "checkpointing"
        ):
            # checkpoint-before-suspend window (controllers/suspend.py): the
            # stop is real but the scale-down waits — every ready host's
            # /tpu/checkpoint hook must be driven while the pods still exist.
            # The suspend controller flips the state to "suspended" (bounded
            # window), and THEN replicas go to 0.
            stopped = False
        hosts = shape.hosts if shape else 1
        sts.spec.replicas = 0 if stopped else hosts
        sts.spec.selector.match_labels = {C.NOTEBOOK_NAME_LABEL: nb.metadata.name}
        # Always the headless service: per-pod DNS records only exist behind a
        # headless Service, and the culler's TPU probe needs {name}-0.{svc}
        # even for single-host slices (a ClusterIP service can't resolve pods)
        sts.spec.service_name = hosts_service_name(nb.metadata.name)
        sts.spec.pod_management_policy = "Parallel"  # slice hosts boot together

        template = sts.spec.template
        template.metadata.labels = {C.NOTEBOOK_NAME_LABEL: nb.metadata.name}
        template.metadata.annotations = {}
        # propagate the readiness trace to the pods: the kubelet (sim) and
        # the in-pod probe agent join the trace via this annotation
        traceparent = nb.metadata.annotations.get(C.TRACEPARENT_ANNOTATION)
        if traceparent:
            template.metadata.annotations[C.TRACEPARENT_ANNOTATION] = traceparent
        template.spec = nb.spec.template.spec.deepcopy()
        self._default_container(nb, template.spec, shape)

        if self.config.add_fsgroup:
            if template.spec.security_context is None:
                template.spec.security_context = PodSecurityContext()
            if template.spec.security_context.fs_group is None:
                template.spec.security_context.fs_group = C.DEFAULT_FS_GROUP

        if shape is not None:
            template.spec.node_selector.update(shape.node_selector())
            if not any(t.key == TPU_RESOURCE for t in template.spec.tolerations):
                template.spec.tolerations.append(
                    Toleration(key=TPU_RESOURCE, operator="Exists", effect="NoSchedule")
                )
        sts.set_owner(nb)
        return sts

    def _default_container(
        self, nb: Notebook, podspec, shape: Optional[SliceShape]
    ) -> None:
        """Defaulting the reference applies to the primary container
        (notebook_controller.go:493-521), plus the TPU resource binding."""
        container: Optional[Container] = None
        for c in podspec.containers:
            if c.name == nb.metadata.name:
                container = c
                break
        if container is None:
            if not podspec.containers:
                podspec.containers.append(Container(name=nb.metadata.name, image=""))
            container = podspec.containers[0]

        if not container.working_dir:
            container.working_dir = C.DEFAULT_WORKING_DIR
        if not container.ports:
            container.ports = [
                ContainerPort(
                    name="notebook-port", container_port=C.NOTEBOOK_PORT, protocol="TCP"
                )
            ]
        container.set_env(
            C.PREFIX_ENV, f"/notebook/{nb.metadata.namespace}/{nb.metadata.name}"
        )

        if shape is not None:
            if container.resources is None:
                container.resources = ResourceRequirements()
            container.resources.requests[TPU_RESOURCE] = str(shape.chips_per_host)
            container.resources.limits[TPU_RESOURCE] = str(shape.chips_per_host)
            svc = hosts_service_name(nb.metadata.name)
            existing = {e.name for e in container.env}
            for ev in tpu_env(
                shape,
                statefulset_name(nb.metadata.name),  # pod DNS rides the STS name
                svc,
                nb.metadata.namespace,
                self.config.cluster_domain,
                runtime=(nb.spec.tpu.runtime or "jax") if nb.spec.tpu else "jax",
            ):
                if ev["name"] not in existing:
                    container.set_env(ev["name"], ev["value"])
            if shape.multi_host and "TPU_WORKER_ID" not in existing:
                from ..api.core import EnvVar, EnvVarSource

                for od in ordinal_env():
                    if not container.get_env(od["name"]):
                        container.env.append(
                            EnvVar(
                                name=od["name"],
                                value_from=EnvVarSource.from_dict(od["valueFrom"]),
                            )
                        )

    def generate_service(self, nb: Notebook) -> Service:
        svc = Service()
        svc.metadata.name = nb.metadata.name
        svc.metadata.namespace = nb.metadata.namespace
        svc.metadata.labels = {C.NOTEBOOK_NAME_LABEL: nb.metadata.name}
        svc.spec.type = "ClusterIP"
        svc.spec.selector = {C.NOTEBOOK_NAME_LABEL: nb.metadata.name}
        svc.spec.ports = [
            ServicePort(
                name=C.NOTEBOOK_PORT_NAME,
                port=80,
                target_port=C.NOTEBOOK_PORT,
                protocol="TCP",
            )
        ]
        svc.set_owner(nb)
        return svc

    def generate_hosts_service(self, nb: Notebook) -> Service:
        """Headless Service: stable {pod}.{svc} DNS for every slice host —
        the jax.distributed coordinator contract."""
        svc = Service()
        svc.metadata.name = hosts_service_name(nb.metadata.name)
        svc.metadata.namespace = nb.metadata.namespace
        svc.metadata.labels = {C.NOTEBOOK_NAME_LABEL: nb.metadata.name}
        svc.spec.cluster_ip = "None"
        svc.spec.selector = {C.NOTEBOOK_NAME_LABEL: nb.metadata.name}
        svc.spec.ports = [
            ServicePort(name="jax-coordinator", port=8476, target_port=8476),
            ServicePort(name="probe", port=self.config.probe_port,
                        target_port=self.config.probe_port),
        ]
        svc.set_owner(nb)
        return svc

    # ---------- reconcile ----------

    def reconcile(self, req: Request) -> Optional[Result]:
        try:
            nb = self.client.get(Notebook, req.namespace, req.name)
        except NotFoundError:
            # the CR is gone: close the readiness root the webhook opened
            # under this key, or a deleted-before-ready notebook leaks its
            # root until capacity eviction (tracing_roots_evicted_total
            # reason="deleted" counts these)
            tracing.discard_root_for(req.key)
            return None
        if nb.metadata.deletion_timestamp:
            tracing.discard_root_for(req.key)
            return None

        shape = self.plan(nb)
        # per-phase child spans of the readiness trace (annotation-carried):
        # one reconcile = one `reconcile.notebook` span with STS/service/
        # status children, so bench.py can decompose where bring-up time goes
        traceparent = nb.metadata.annotations.get(C.TRACEPARENT_ANNOTATION)
        with reconcile_tracer.start_span(
            "reconcile.notebook", traceparent=traceparent,
            notebook=nb.metadata.name, namespace=nb.metadata.namespace,
        ):
            with reconcile_tracer.start_span("reconcile.statefulset"):
                self._reconcile_statefulset(nb, shape)
            with reconcile_tracer.start_span("reconcile.service"):
                self._reconcile_service(nb, self.generate_service(nb))
                self._reconcile_service(nb, self.generate_hosts_service(nb))
            with reconcile_tracer.start_span("reconcile.status"):
                self._update_status(nb, shape)
            self._handle_restart(nb)
        return None

    def _reconcile_statefulset(self, nb: Notebook, shape: Optional[SliceShape]) -> None:
        desired = self.generate_statefulset(nb, shape)

        def sts_diff(current) -> bool:
            return (
                current.metadata.labels != desired.metadata.labels
                or current.spec.replicas != desired.spec.replicas
                or current.spec.template.to_dict() != desired.spec.template.to_dict()
            )

        # cached no-op pre-check (controller-runtime reads through the cache
        # here): a steady-state reconcile costs zero API requests. Cache lag
        # is level-triggered-safe — the event that updates the cache
        # re-enqueues the notebook.
        try:
            if not sts_diff(self.client.get(
                StatefulSet, nb.metadata.namespace, desired.metadata.name
            )):
                return
        except NotFoundError:
            # cache-absent -> straight create, skipping the fresh-read
            # attempt (first reconcile of every notebook; a stale cache
            # lands in AlreadyExists and falls through to the RMW)
            try:
                self.client.create(desired)
                self.metrics.notebook_create_total.inc()
                return
            except AlreadyExistsError:
                pass
            except Exception:
                self.metrics.notebook_create_failed_total.inc()
                raise

        def attempt():
            try:
                # FRESH read: the cached view after our own create/update is
                # stale exactly in the write-to-informer-dispatch window
                current = self.api_reader.get(
                    StatefulSet, nb.metadata.namespace, desired.metadata.name
                )
            except NotFoundError:
                try:
                    self.client.create(desired)
                    self.metrics.notebook_create_total.inc()
                except AlreadyExistsError:
                    return  # a racing reconcile created it: converged
                except Exception:
                    self.metrics.notebook_create_failed_total.inc()
                    raise
                return
            # CopyStatefulSetFields semantics (reference common/
            # reconcilehelper/util.go:107-160): labels/annotations/replicas/
            # template copied over
            changed = False
            if current.metadata.labels != desired.metadata.labels:
                current.metadata.labels = desired.metadata.labels
                changed = True
            if current.spec.replicas != desired.spec.replicas:
                current.spec.replicas = desired.spec.replicas
                changed = True
            if current.spec.template.to_dict() != desired.spec.template.to_dict():
                current.spec.template = desired.spec.template
                changed = True
            if changed:
                self.client.update(current)

        retry_on_conflict(attempt)

    def _reconcile_service(self, nb: Notebook, desired: Service) -> None:
        def svc_diff(current) -> bool:
            return (
                current.metadata.labels != desired.metadata.labels
                or current.spec.selector != desired.spec.selector
                or [p.to_dict() for p in current.spec.ports]
                != [p.to_dict() for p in desired.spec.ports]
            )

        # cached no-op pre-check (see _reconcile_statefulset)
        try:
            if not svc_diff(self.client.get(
                Service, nb.metadata.namespace, desired.metadata.name
            )):
                return
        except NotFoundError:
            try:
                self.client.create(desired)
                return
            except AlreadyExistsError:
                pass  # stale cache or racing reconcile: RMW below

        def attempt():
            try:
                current = self.api_reader.get(
                    Service, nb.metadata.namespace, desired.metadata.name
                )
            except NotFoundError:
                try:
                    self.client.create(desired)
                except AlreadyExistsError:
                    pass  # racing reconcile won; level-triggered convergence
                return
            # CopyServiceFields: keep clusterIP, copy selector/ports/labels
            changed = False
            if current.metadata.labels != desired.metadata.labels:
                current.metadata.labels = desired.metadata.labels
                changed = True
            if current.spec.selector != desired.spec.selector:
                current.spec.selector = desired.spec.selector
                changed = True
            if [p.to_dict() for p in current.spec.ports] != [
                p.to_dict() for p in desired.spec.ports
            ]:
                current.spec.ports = desired.spec.ports
                changed = True
            if changed:
                self.client.update(current)

        retry_on_conflict(attempt)

    def _update_status(self, nb: Notebook, shape: Optional[SliceShape]) -> None:
        # CACHED reads build the candidate status (the reference's status
        # mirroring reads pods/STS through mgr.GetClient()'s cache too);
        # level-triggered reconciles make cache lag self-healing — the event
        # that updates the cache re-enqueues this notebook. The write path
        # below still read-modify-writes against a FRESH read, and skips the
        # API entirely when the cached object already carries the candidate
        # status (under a create storm this is the difference between ~3
        # uncached reads per event and none).
        try:
            sts = self.client.get(
                StatefulSet, nb.metadata.namespace, statefulset_name(nb.metadata.name)
            )
        except NotFoundError:
            return
        pods = [
            p
            for p in self.client.list(
                Pod,
                namespace=nb.metadata.namespace,
                labels={C.NOTEBOOK_NAME_LABEL: nb.metadata.name},
            )
            if not p.metadata.deletion_timestamp
        ]
        ready_pods = sum(1 for p in pods if p.is_ready())

        before = nb.status.to_dict()  # pre-mutation snapshot for the no-op check
        status = nb.status
        # ready_replicas derives from the CACHED pod set rather than
        # sts.status.readyReplicas (the reference copies the latter,
        # notebook_controller.go:299-313): the value is identical — the STS
        # controller computes it from the same pods — but pod-derived is one
        # event hop fresher during bring-up (pod-ready -> mirror directly,
        # instead of pod-ready -> STS status write -> mirror; measured
        # ~300 ms of storm-time informer backlog on that extra hop, which
        # the mesh_ready gate would otherwise serialize onto every slice)
        status.ready_replicas = min(
            ready_pods,
            sts.spec.replicas if sts.spec.replicas is not None else ready_pods,
        )

        # mirror pod 0 (PodCondToNotebookCond analog, :376-415)
        pod0 = next(
            (
                p
                for p in pods
                if p.metadata.name == f"{statefulset_name(nb.metadata.name)}-0"
            ),
            None,
        )
        if pod0 is not None:
            # the pod-condition mirror must not stomp the repair stack's
            # conditions (TPUHealthy/Degraded — probe_status + slice_repair
            # own those; see controllers/conditions.py)
            preserved = [
                c
                for c in status.conditions
                if c.type in REPAIR_OWNED_CONDITIONS
            ]
            status.conditions = [
                Condition(
                    type=c.type,
                    status=c.status,
                    reason=c.reason,
                    message=c.message,
                    last_probe_time=c.last_probe_time,
                    last_transition_time=c.last_transition_time,
                )
                for c in pod0.status.conditions
            ] + preserved
            primary = next(
                (
                    cs
                    for cs in pod0.status.container_statuses
                    if cs.name == nb.metadata.name
                ),
                None,
            ) or (pod0.status.container_statuses[0] if pod0.status.container_statuses else None)
            if primary is not None:
                status.container_state = primary.state

        if shape is not None:
            status.tpu = status.tpu or TPUStatus()
            status.tpu.accelerator = shape.accelerator
            status.tpu.topology = shape.topology
            status.tpu.hosts = shape.hosts
            status.tpu.chips_per_host = shape.chips_per_host
            status.tpu.chips_expected = shape.chips
            status.tpu.hosts_ready = ready_pods
            # chips_visible / mesh_ready / first_ready_time are OWNED by the
            # device-visibility gate (controllers/probe_status.py): pod-Ready
            # alone must never flip them — a host whose libtpu sees 2 of 4
            # chips keeps mesh_ready false even with every pod Ready

        # no-op pre-check against the object in hand (cache-served): the
        # mirroring above never touches the probe controller's fields, so if
        # the candidate equals the pre-mutation snapshot, the write — one
        # API call — can be skipped entirely
        if status.to_dict() == before:
            return

        # merge-PATCH of this controller's OWNED fields only: one request,
        # no read-modify-write loop, conflict-free against the probe
        # controller by construction (disjoint ownership — its
        # chipsVisible/meshReady/firstReadyTime never appear in this patch,
        # so the server-side merge preserves them)
        spatch = status.to_dict()
        tpu_patch = spatch.get("tpu")
        if tpu_patch is not None:
            for k in ("chipsVisible", "meshReady", "firstReadyTime"):
                tpu_patch.pop(k, None)
            # zero must be WRITTEN, not omitted: to_dict's omitempty drops
            # hostsReady=0, so a drained slice's stored non-zero count could
            # never converge — the no-op pre-check then failed on every
            # pass and each content-identical patch still bumped
            # resourceVersion, re-enqueueing this notebook in a ~165/s
            # write loop for as long as it stayed suspended (found by the
            # ISSUE 9 promotion drive when the loop's spans flooded the
            # trace ring)
            tpu_patch["hostsReady"] = status.tpu.hosts_ready
        spatch["readyReplicas"] = status.ready_replicas  # same zero contract
        if "containerState" not in spatch:
            spatch["containerState"] = None  # explicit null deletes (pod gone)
        try:
            # route through the status coalescer when the manager carries one
            # (runtime/coalesce.py): adjacent mirror patches in one sync wave
            # batch into a single PATCH, owned zeros/nulls preserved
            coalescer = getattr(self.manager, "status_coalescer", None)
            if coalescer is not None:
                coalescer.patch_status(
                    Notebook, nb.metadata.namespace, nb.metadata.name, spatch
                )
            else:
                self.client.patch_status(
                    Notebook, nb.metadata.namespace, nb.metadata.name, spatch
                )
        except NotFoundError:
            pass  # deleted mid-reconcile

    def _handle_restart(self, nb: Notebook) -> None:
        """notebooks.opendatahub.io/notebook-restart handling (reference
        notebook_controller.go:262-294), generalized to all ordinals."""
        if nb.metadata.annotations.get(C.NOTEBOOK_RESTART_ANNOTATION) != "true":
            return
        for pod in self.client.list(
            Pod,
            namespace=nb.metadata.namespace,
            labels={C.NOTEBOOK_NAME_LABEL: nb.metadata.name},
        ):
            try:
                self.client.delete(Pod, pod.metadata.namespace, pod.metadata.name)
            except NotFoundError:
                pass

        def clear():
            self.client.patch(
                Notebook,
                nb.metadata.namespace,
                nb.metadata.name,
                {"metadata": {"annotations": {C.NOTEBOOK_RESTART_ANNOTATION: None}}},
            )

        retry_on_conflict(clear)


class EventMirrorController:
    """Re-emits pod/StatefulSet events onto the owning Notebook CR so users
    see scheduling/image failures on the CR itself (reference folds this into
    the main Reconcile at notebook_controller.go:98-126; a dedicated
    controller is the cleaner factoring)."""

    def __init__(self, manager: Manager):
        self.manager = manager
        self.client = manager.client

    def setup(self) -> None:
        def is_workload_event(ev: str, obj: dict, old: Optional[dict]) -> bool:
            inv = obj.get("involvedObject", {})
            return inv.get("kind") in ("Pod", "StatefulSet") and not obj.get(
                "metadata", {}
            ).get("annotations", {}).get(C.TPU_MIRRORED_EVENT_ANNOTATION)

        (
            self.manager.builder("event-mirror")
            .for_(Event, predicate=is_workload_event)
            .complete(self.reconcile)
        )

    def _notebook_for(self, inv: ObjectReference) -> Optional[Notebook]:
        """nbNameFromInvolvedObject analog (reference :705-729)."""
        if inv.kind == "Pod":
            try:
                pod = self.client.get(Pod, inv.namespace, inv.name)
            except NotFoundError:
                return None
            nb_name = pod.metadata.labels.get(C.NOTEBOOK_NAME_LABEL)
        elif inv.kind == "StatefulSet":
            nb_name = inv.name
        else:
            return None
        if not nb_name:
            return None
        try:
            nb = self.client.get(Notebook, inv.namespace, nb_name)
        except NotFoundError:
            return None
        return nb

    def reconcile(self, req: Request) -> Optional[Result]:
        try:
            ev = self.client.get(Event, req.namespace, req.name)
        except NotFoundError:
            return None
        if ev.metadata.annotations.get(C.TPU_MIRRORED_EVENT_ANNOTATION):
            return None
        if ev.involved_object.kind not in ("Pod", "StatefulSet"):
            return None
        nb = self._notebook_for(ev.involved_object)
        if nb is None:
            return None
        mirrored = Event()
        mirrored.metadata.name = f"{nb.metadata.name}.{ev.metadata.uid[:8]}"
        mirrored.metadata.namespace = nb.metadata.namespace
        mirrored.metadata.annotations = {C.TPU_MIRRORED_EVENT_ANNOTATION: "true"}
        mirrored.involved_object = ObjectReference(
            api_version=nb.api_version or "kubeflow.org/v1beta1",
            kind="Notebook",
            name=nb.metadata.name,
            namespace=nb.metadata.namespace,
            uid=nb.metadata.uid,
        )
        mirrored.reason = ev.reason
        mirrored.message = ev.message
        mirrored.type = ev.type
        mirrored.count = ev.count
        mirrored.last_timestamp = ev.last_timestamp or now_rfc3339()
        try:
            self.client.create(mirrored)
        except AlreadyExistsError:
            # source event recurred (count bumped): keep the mirror current
            try:
                self.client.patch(
                    Event,
                    mirrored.metadata.namespace,
                    mirrored.metadata.name,
                    {
                        "count": ev.count,
                        "message": ev.message,
                        "lastTimestamp": mirrored.last_timestamp,
                    },
                )
            except NotFoundError:
                pass  # event-GC race: mirror TTL'd between create and patch
        return None
