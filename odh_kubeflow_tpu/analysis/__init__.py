"""Operator-lint: AST invariant checks for the control plane.

The Go reference inherits `go vet`, `golangci-lint` and `-race` for free;
this package is the Python reproduction's equivalent correctness-tooling
layer. A small framework (`framework.py`) walks the package, parses every
module once, and runs pluggable checkers that enforce operator-specific
invariants the generic linters cannot know about:

- ``cache-mutation``   objects read from an informer cache must be
                       deep-copied before any in-place write
                       (checkers/cache_mutation.py)
- ``lock-discipline``  no sleeps / network / callback dispatch / re-entrant
                       acquisition inside a ``with lock:`` body, plus a
                       global lock-acquisition-order cycle check
                       (checkers/lock_discipline.py)
- ``swallowed-exception``  no bare/blind except in reconcile, webhook or
                       probe paths (checkers/exceptions.py)
- ``metric-convention`` / ``annotation-convention``  Prometheus naming and
                       constants.py-sourced annotation keys
                       (checkers/conventions.py)

Intentional exceptions are recorded inline with ``# lint: disable=<check>``
pragmas (comma-separated check names, or ``all``); `ci/analysis.sh` runs the
whole pass and fails on any unsuppressed finding. The runtime half of the
tooling — the instrumented lock + cache write barrier that turns chaos runs
into race runs — lives in `odh_kubeflow_tpu/utils/racecheck.py`.
"""
from .framework import (  # noqa: F401
    Checker,
    Finding,
    ModuleInfo,
    all_checkers,
    run_analysis,
    run_on_source,
)
from .metric_rules import check_registry  # noqa: F401
