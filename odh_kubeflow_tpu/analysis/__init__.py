"""Operator-lint: AST invariant checks for the control plane.

The Go reference inherits `go vet`, `golangci-lint` and `-race` for free;
this package is the Python reproduction's equivalent correctness-tooling
layer. A small framework (`framework.py`) walks the package, parses every
module once, and runs pluggable checkers that enforce operator-specific
invariants the generic linters cannot know about:

- ``cache-mutation``   objects read from an informer cache must be
                       deep-copied before any in-place write
                       (checkers/cache_mutation.py)
- ``lock-discipline``  no sleeps / network / callback dispatch / re-entrant
                       acquisition inside a ``with lock:`` body, plus a
                       global lock-acquisition-order cycle check
                       (checkers/lock_discipline.py)
- ``swallowed-exception``  no bare/blind except in reconcile, webhook or
                       probe paths (checkers/exceptions.py)
- ``metric-convention`` / ``annotation-convention``  Prometheus naming and
                       constants.py-sourced annotation keys (+ dead
                       ``*_ANNOTATION`` constants) (checkers/conventions.py)
- ``machine-conformance``  every write of a state annotation matches a
                       transition declared in `machines.py` — the three
                       annotation-durable machines as data
                       (checkers/machine_conformance.py)
- ``retrace-hazard`` / ``host-transfer`` / ``donation-discipline`` /
  ``psum-axis``        the jaxlint family: data-plane compile-cache
                       hygiene, host-sync surfaces inside declared hot
                       regions (`hotregions.py`), buffer-donation
                       discipline, and collective-axis sanity
                       (checkers/jaxlint.py)
- ``rbac-coverage`` / ``crd-schema-drift`` / ``env-contract`` /
  ``flow-schema-coverage``  the deploylint family: client calls vs the
                       declared RBAC (both directions, with an optional
                       runtime surface artifact), committed CRD manifests
                       vs the generators, os.environ reads vs the
                       ENV_CONTRACT registry vs the manifests, and
                       flow_context/webhook literals vs the committed
                       FlowSchemas/webhook config — all through the shared
                       deployment-surface contract (`deploysurface.py`)
                       (checkers/deploylint.py)

Intentional exceptions are recorded inline with ``# lint: disable=<check>``
pragmas (comma-separated check names, or ``all``) and budgeted in
`ci/pragma_allowlist.txt`; `ci/analysis.sh` runs the whole pass and fails on
any unsuppressed finding or unreviewed pragma. The runtime half of the
tooling — the instrumented lock + cache write barrier that turns chaos runs
into race runs (`utils/racecheck.py`), the INVCHECK store-write invariant
monitor (`utils/invcheck.py`), the JAXGUARD compile/transfer/donation guard
(`utils/jaxguard.py`, sharing `hotregions.py` with the jaxlint checkers),
the DEPLOYGUARD RBAC/flow-identity guard (`utils/deployguard.py`, sharing
`deploysurface.py` with the deploylint checkers),
and the systematic interleaving explorer (`explore.py`) — shares the
machine/region specs with the static checkers.
"""
from .framework import (  # noqa: F401
    Checker,
    Finding,
    ModuleInfo,
    all_checkers,
    run_analysis,
    run_on_source,
)
from .metric_rules import check_registry  # noqa: F401
