"""lock-discipline: what may not happen inside a ``with lock:`` body, plus a
global lock-acquisition-order cycle check.

The Go reference gets `-race` and deadlock-on-timeout panics for free; here
17 modules take locks across the informer/workqueue/apiserver/kubelet/probe
paths with nothing watching. Two checkers share one lexical model:

`LockDisciplineChecker` (per-module):
- no `time.sleep` under a lock (a sleeping holder stalls every contender —
  the classic tail-latency multiplier),
- no network/blocking I/O calls under a lock (`urlopen`, `http_get`,
  `_get_json`, sockets, subprocess),
- no callback/handler dispatch under a lock (a handler is arbitrary foreign
  code: it can try to take another lock and close an inversion cycle),
- no re-entrant acquisition of a non-reentrant lock: a nested `with` on the
  same lock, or a call to a same-class method that takes the lock the
  caller already holds (threading.Lock self-deadlocks; only RLock and
  Condition are re-entrant).

`LockOrderChecker` (whole-package): builds the static acquisition graph —
an edge A -> B for every `with A:` body that lexically nests `with B:` or
calls a same-class method that takes B — and reports every cycle. A cycle
is a potential ABBA deadlock even if chaos runs have never hit it; the
runtime twin (utils/racecheck.py) checks the same property on the ACTUAL
acquisition order under RACECHECK=1.

Lock identity is `ClassName.attr` for `self.X` locks and `module.name` for
globals — instances of the same class share a node, so hierarchical
same-class locking shows up as a self-edge (ignored: that is re-entrancy,
the discipline checker's job, not ordering's).

`Condition.wait()` is exempt everywhere: wait releases the lock.
"""
from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..framework import Checker, Finding, ModuleInfo
from ._util import dotted_name, is_lock_expr, terminal_name

SLEEP_RE = re.compile(r"^(time\.)?sleep$")
# dotted-name fragments that mean "this call leaves the process"
NETWORK_FRAGMENTS = (
    "urlopen", "urlretrieve", "http_get", "_get_json", "getresponse",
    "create_connection", "subprocess.", "requests.", "socket.socket",
)
HANDLER_CALL_RE = re.compile(r"(^|_)(handler|callback|cb|hook)s?$")
HANDLER_ITER_RE = re.compile(r"(^|_)(handlers|callbacks|listeners|subscribers|hooks)$")
# threading factory -> reentrancy. Condition's default inner lock is an
# RLock; racecheck factories mirror the same split.
LOCK_FACTORIES = {
    "Lock": "Lock",
    "RLock": "RLock",
    "Condition": "Condition",
    "make_lock": "Lock",
    "make_rlock": "RLock",
}


def _lock_factory_kind(value: ast.AST) -> Optional[str]:
    """`threading.Lock()` -> "Lock", `racecheck.make_rlock(...)` -> "RLock"."""
    if not isinstance(value, ast.Call):
        return None
    name = terminal_name(value.func)
    return LOCK_FACTORIES.get(name or "")


class _ClassInfo:
    def __init__(self, name: str):
        self.name = name
        # lock attr ("self._lock" dotted) -> "Lock" | "RLock" | "Condition"
        self.lock_kinds: Dict[str, str] = {}
        # method name -> set of lock dotted names it acquires lexically
        self.method_locks: Dict[str, Set[str]] = {}
        # method name -> True if it dispatches a callback/handler anywhere in
        # its body (so a call to it under a lock is transitively dispatch)
        self.method_dispatches: Dict[str, bool] = {}


def _scan_classes(tree: ast.AST) -> Dict[str, _ClassInfo]:
    classes: Dict[str, _ClassInfo] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        info = _ClassInfo(node.name)
        classes[node.name] = info
        for method in node.body:
            if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            acquired: Set[str] = set()
            dispatches = False
            for sub in ast.walk(method):
                if isinstance(sub, ast.Assign):
                    kind = _lock_factory_kind(sub.value)
                    if kind:
                        for target in sub.targets:
                            dn = dotted_name(target)
                            if dn and dn.startswith("self."):
                                info.lock_kinds[dn] = kind
                if isinstance(sub, ast.With):
                    for item in sub.items:
                        dn = dotted_name(item.context_expr)
                        if dn and dn.startswith("self.") and is_lock_expr(item.context_expr):
                            acquired.add(dn)
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and HANDLER_CALL_RE.search(sub.func.attr)
                ):
                    dispatches = True
            info.method_locks[method.name] = acquired
            info.method_dispatches[method.name] = dispatches
    return classes


def _module_label(path: str) -> str:
    return Path(path).stem


class _WalkContext:
    """Lexical walk of one function: tracks the stack of held locks and the
    enclosing class, emitting discipline findings and order-graph edges."""

    def __init__(
        self,
        path: str,
        cls: Optional[_ClassInfo],
        classes: Dict[str, _ClassInfo],
        edges: Dict[Tuple[str, str], Tuple[str, int]],
        findings: List[Finding],
    ):
        self.path = path
        self.cls = cls
        self.classes = classes
        self.edges = edges
        self.findings = findings
        self.held: List[Tuple[str, str]] = []  # (dotted expr, graph node id)

    def _flag(self, line: int, message: str) -> None:
        self.findings.append(
            Finding(check="lock-discipline", path=self.path, line=line, message=message)
        )

    def _node_id(self, dotted: str) -> str:
        if dotted.startswith("self.") and self.cls is not None:
            return f"{self.cls.name}.{dotted[len('self.'):]}"
        return f"{_module_label(self.path)}.{dotted}"

    def _lock_kind(self, dotted: str) -> Optional[str]:
        if dotted.startswith("self.") and self.cls is not None:
            return self.cls.lock_kinds.get(dotted)
        return None

    def _add_edge(self, outer: str, inner: str, line: int) -> None:
        if outer == inner:
            return  # re-entrancy, not ordering
        self.edges.setdefault((outer, inner), (self.path, line))

    def walk_stmts(self, stmts: Iterable[ast.stmt]) -> None:
        for stmt in stmts:
            self.walk(stmt)

    def walk(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)):
            return  # does not run under the enclosing lock
        if isinstance(node, ast.With):
            lock_items = [
                (item, dotted_name(item.context_expr))
                for item in node.items
                if is_lock_expr(item.context_expr)
            ]
            entered = 0
            for item, dotted in lock_items:
                if dotted is None:
                    continue
                node_id = self._node_id(dotted)
                kind = self._lock_kind(dotted)
                for held_dotted, held_id in self.held:
                    if held_dotted == dotted:
                        if kind in ("RLock", "Condition"):
                            continue
                        self._flag(
                            node.lineno,
                            f"re-entrant acquisition of non-reentrant lock "
                            f"{dotted} (already held; threading.Lock "
                            f"self-deadlocks here)",
                        )
                    else:
                        self._add_edge(held_id, node_id, node.lineno)
                self.held.append((dotted, node_id))
                entered += 1
            for item in node.items:  # context expressions evaluate pre-lock
                if not is_lock_expr(item.context_expr):
                    self.walk(item.context_expr)
            self.walk_stmts(node.body)
            if entered:
                del self.held[len(self.held) - entered:]
            return
        if isinstance(node, ast.Call) and self.held:
            self._check_call(node)
        if isinstance(node, ast.For) and self.held:
            iter_name = terminal_name(node.iter) or ""
            iter_call_recv = (
                terminal_name(node.iter.func.value)
                if isinstance(node.iter, ast.Call)
                and isinstance(node.iter.func, ast.Attribute)
                and isinstance(node.iter.func.value, (ast.Name, ast.Attribute))
                else None
            )
            handlerish = HANDLER_ITER_RE.search(iter_name) or (
                iter_call_recv and HANDLER_ITER_RE.search(iter_call_recv)
            )
            if handlerish and isinstance(node.target, ast.Name):
                target = node.target.id
                for sub in ast.walk(node):
                    if (
                        isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Name)
                        and sub.func.id == target
                    ):
                        self._flag(
                            sub.lineno,
                            f"callback {target!r} (from {iter_name or iter_call_recv}) "
                            f"dispatched while holding {self.held[-1][0]} — foreign "
                            f"code under a lock can close a deadlock cycle",
                        )
        for child in ast.iter_child_nodes(node):
            self.walk(child)

    def _check_call(self, node: ast.Call) -> None:
        dotted = dotted_name(node.func) or ""
        short = terminal_name(node.func) or ""
        held_expr = self.held[-1][0]
        if short in ("wait", "wait_for"):
            return  # Condition.wait releases the lock
        if SLEEP_RE.match(dotted) or SLEEP_RE.match(short):
            self._flag(
                node.lineno,
                f"time.sleep while holding {held_expr} — every contender "
                f"stalls for the full sleep",
            )
            return
        for fragment in NETWORK_FRAGMENTS:
            if fragment in dotted:
                self._flag(
                    node.lineno,
                    f"blocking I/O call {dotted}() while holding {held_expr}",
                )
                return
        if HANDLER_CALL_RE.search(short):
            # `wh.handler(req)` or a bare `handler(...)` — either way foreign
            # code is running with our lock held
            self._flag(
                node.lineno,
                f"callback dispatch {dotted}() while holding {held_expr}",
            )
        # same-class method call that re-acquires a held non-reentrant lock,
        # and order edges for the locks it does acquire
        if (
            isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "self"
            and self.cls is not None
        ):
            if self.cls.method_dispatches.get(node.func.attr):
                self._flag(
                    node.lineno,
                    f"call to self.{node.func.attr}() while holding "
                    f"{held_expr} — the callee dispatches callbacks, so "
                    f"foreign code runs under this lock",
                )
            callee_locks = self.cls.method_locks.get(node.func.attr, set())
            for callee_lock in callee_locks:
                kind = self.cls.lock_kinds.get(callee_lock)
                for held_dotted, held_id in self.held:
                    if held_dotted == callee_lock:
                        if kind in ("RLock", "Condition"):
                            continue
                        self._flag(
                            node.lineno,
                            f"call to self.{node.func.attr}() re-acquires "
                            f"non-reentrant lock {callee_lock} already held here",
                        )
                    else:
                        self._add_edge(
                            held_id, self._node_id(callee_lock), node.lineno
                        )


class LockDisciplineChecker(Checker):
    name = "lock-discipline"

    def __init__(self) -> None:
        # acquisition-order edges harvested during the SAME walk that finds
        # discipline violations; a paired LockOrderChecker consumes them so
        # the package is walked once, not twice
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        self._walk_module(module, self.edges, findings)
        return findings

    @staticmethod
    def _walk_module(
        module: ModuleInfo,
        edges: Dict[Tuple[str, str], Tuple[str, int]],
        findings: List[Finding],
    ) -> None:
        classes = _scan_classes(module.tree)

        def visit_scope(body: Iterable[ast.stmt], cls: Optional[_ClassInfo]) -> None:
            for stmt in body:
                if isinstance(stmt, ast.ClassDef):
                    visit_scope(stmt.body, classes.get(stmt.name))
                elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    ctx = _WalkContext(module.path, cls, classes, edges, findings)
                    ctx.walk_stmts(stmt.body)
                    # nested defs: fresh context (no lock held at def time)
                    for sub in ast.walk(stmt):
                        if isinstance(
                            sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                        ) and sub is not stmt:
                            inner = _WalkContext(
                                module.path, cls, classes, edges, findings
                            )
                            inner.walk_stmts(sub.body)
                else:
                    ctx = _WalkContext(module.path, cls, classes, edges, findings)
                    ctx.walk(stmt)

        assert isinstance(module.tree, ast.Module)
        visit_scope(module.tree.body, None)


class LockOrderChecker(Checker):
    """Whole-package static lock-order graph; cycles reported in finish().

    Pass `shared` (the run's LockDisciplineChecker) to reuse the edges its
    walk already harvested; standalone (tests, --check lock-order) it walks
    the modules itself."""

    name = "lock-order"

    def __init__(self, shared: Optional[LockDisciplineChecker] = None) -> None:
        self._shared = shared
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = (
            shared.edges if shared is not None else {}
        )

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        if self._shared is None:
            findings: List[Finding] = []  # discipline findings discarded here
            LockDisciplineChecker._walk_module(module, self.edges, findings)
        return ()

    def finish(self) -> Iterable[Finding]:
        graph: Dict[str, List[str]] = {}
        for (a, b) in self.edges:
            graph.setdefault(a, []).append(b)
        findings: List[Finding] = []
        reported: Set[frozenset] = set()
        for start in sorted(graph):
            cycle = self._find_cycle(graph, start)
            if not cycle:
                continue
            key = frozenset(cycle)
            if key in reported:
                continue
            reported.add(key)
            first_edge = (cycle[0], cycle[1 % len(cycle)])
            path, line = self.edges.get(first_edge, ("<unknown>", 0))
            findings.append(
                Finding(
                    check="lock-order",
                    path=path,
                    line=line,
                    message=(
                        "lock acquisition order cycle: "
                        + " -> ".join(cycle + [cycle[0]])
                        + " (potential ABBA deadlock)"
                    ),
                )
            )
        return findings

    @staticmethod
    def _find_cycle(graph: Dict[str, List[str]], start: str) -> Optional[List[str]]:
        stack: List[Tuple[str, List[str]]] = [(start, [start])]
        seen: Set[str] = set()
        while stack:
            node, path = stack.pop()
            for nxt in graph.get(node, []):
                if nxt == start:
                    return path
                if nxt in seen:
                    continue
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
        return None
