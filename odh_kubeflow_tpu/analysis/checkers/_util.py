"""Shared AST helpers for the checkers."""
from __future__ import annotations

import ast
import re
from typing import Iterable, Optional

# terminal attribute/variable names that denote a lock-like object. `cond`
# covers threading.Condition (it IS a lock for discipline purposes).
LOCK_NAME_RE = re.compile(r"(^|_)(lock|cond|mutex)$")


def dotted_name(node: ast.AST) -> Optional[str]:
    """`self._cache.get` -> "self._cache.get"; None for non-name chains."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def terminal_name(node: ast.AST) -> Optional[str]:
    """The last identifier of a name chain: `self._lock` -> "_lock"."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def is_lock_expr(node: ast.AST) -> bool:
    """Heuristic: a with-item (or call receiver) is a lock if its terminal
    name looks lock-ish. Covers every lock in this codebase (`_lock`,
    `_cond`, `_serve_lock`, `_roots_lock`, ...) without type inference."""
    name = terminal_name(node)
    return bool(name and LOCK_NAME_RE.search(name))


def walk_body(stmts: Iterable[ast.stmt]) -> Iterable[ast.AST]:
    """Walk statements without descending into nested function/class defs —
    a `def` inside a `with lock:` body does not RUN under the lock."""
    stack = list(stmts)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
            ):
                continue
            stack.append(child)


def base_name(node: ast.AST) -> Optional[str]:
    """The root variable of a subscript/attribute chain:
    `obj["metadata"]["labels"]` -> "obj"; `self.x[0]` -> "self.x"."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        if isinstance(node, ast.Attribute):
            # stop at `self.<attr>`: return the dotted prefix
            dn = dotted_name(node)
            if dn is not None:
                return dn
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None
