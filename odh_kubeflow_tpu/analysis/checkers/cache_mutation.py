"""no-cache-mutation: objects read from an informer cache (or any read memo)
must be deep-copied before any in-place write.

This is the classic controller-runtime bug class: a reconciler mutates the
object the informer's lister handed out, silently corrupting the shared
cache for every other reader — no error, just a cluster view that drifts
from etcd until the next relist. The Go ecosystem catches it with
deep-copy-gen conventions and runtime mutation detectors
(`client-go`'s `mutation_detector.go`, `-race`); statically we approximate
with a per-function taint pass:

- SEEDS: calls `<recv>.get(...)` / `<recv>.list(...)` / `<recv>.values()` /
  `<recv>.items()` and subscripts `<recv>[key]` where the receiver's
  terminal name looks cache-ish (`_cache`, `cache`, `inf`, `informer`,
  `*_memo`). Iterating a seed taints the loop target.
- LAUNDER: `copy.deepcopy(x)`, `x.deepcopy()`, or rebinding the name.
- FLAG: any in-place write through a tainted name — subscript/attribute
  assignment, `del`, augmented assignment, or a mutating method call
  (`update`, `pop`, `setdefault`, `append`, ...), including through
  subscript chains (`obj["metadata"]["labels"][k] = v`).

The cache CONTAINER itself is exempt: `self._cache[key] = obj` is the
informer (the owner) managing its own storage, which is legal; the invariant
protects objects handed OUT of it. The runtime twin of this checker is the
RACECHECK=1 write barrier in utils/racecheck.py, which catches the dynamic
escapes (handler callbacks, cross-module flows) this lexical pass cannot.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List

from ..framework import Checker, Finding, ModuleInfo
from ._util import base_name, terminal_name

CACHE_RECV_RE = re.compile(r"(^|_)(cache|caches|memo|memos|inf|informer)$|_memo$|_cache$")
READ_METHODS = {"get", "list", "values", "items"}
MUTATORS = {
    "update", "pop", "popitem", "setdefault", "clear",
    "append", "extend", "insert", "remove", "sort", "reverse",
}
LAUNDER_CALLS = {"deepcopy"}  # copy.deepcopy(x) / x.deepcopy()


def _is_cache_read(node: ast.AST) -> bool:
    """`self._cache.get(k)`, `inf.list(...)`, `self._cache[k]` ..."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        if node.func.attr in READ_METHODS:
            recv = terminal_name(node.func.value)
            return bool(recv and CACHE_RECV_RE.search(recv))
    if isinstance(node, ast.Subscript):
        recv = terminal_name(node.value)
        return bool(recv and CACHE_RECV_RE.search(recv))
    return False


def _is_launder(node: ast.AST) -> bool:
    if isinstance(node, ast.Call):
        name = None
        if isinstance(node.func, ast.Attribute):
            name = node.func.attr
        elif isinstance(node.func, ast.Name):
            name = node.func.id
        return name in LAUNDER_CALLS
    return False


class _FunctionTaint(ast.NodeVisitor):
    """Single forward pass over one function body, in textual order. Taint is
    a name -> seed-line map; joins are ignored (any path that taints, taints
    — conservative in the flagging direction, permissive on rebinds)."""

    def __init__(self, path: str):
        self.path = path
        self.taint: Dict[str, int] = {}
        self.findings: List[Finding] = []

    # -- taint sources / kills --

    def _names_of_target(self, target: ast.AST) -> List[str]:
        if isinstance(target, ast.Name):
            return [target.id]
        if isinstance(target, (ast.Tuple, ast.List)):
            out: List[str] = []
            for elt in target.elts:
                out.extend(self._names_of_target(elt))
            return out
        return []

    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)  # flags mutations on the RHS/targets first
        value = node.value
        tainted_value = _is_cache_read(value) or (
            isinstance(value, ast.Name) and value.id in self.taint
        )
        for target in node.targets:
            self._check_mutation(target, node.lineno)
            for name in self._names_of_target(target):
                if _is_launder(value):
                    self.taint.pop(name, None)
                elif tainted_value:
                    self.taint[name] = node.lineno
                else:
                    self.taint.pop(name, None)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self.generic_visit(node)
        if node.value is None:
            return
        if isinstance(node.target, ast.Name):
            if _is_cache_read(node.value):
                self.taint[node.target.id] = node.lineno
            else:
                self.taint.pop(node.target.id, None)

    def visit_For(self, node: ast.For) -> None:
        iter_tainted = _is_cache_read(node.iter) or (
            isinstance(node.iter, ast.Name) and node.iter.id in self.taint
        )
        if iter_tainted:
            for name in self._names_of_target(node.target):
                self.taint[name] = node.lineno
        self.generic_visit(node)

    # -- mutation sinks --

    def _check_mutation(self, target: ast.AST, lineno: int) -> None:
        if isinstance(target, (ast.Subscript, ast.Attribute)):
            base = base_name(target.value)
            if base in self.taint:
                self.findings.append(
                    Finding(
                        check="cache-mutation",
                        path=self.path,
                        line=lineno,
                        message=(
                            f"in-place write through {base!r} (read from a cache "
                            f"at line {self.taint[base]}) without copy.deepcopy()"
                        ),
                    )
                )

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_mutation(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for target in node.targets:
            self._check_mutation(target, node.lineno)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute) and node.func.attr in MUTATORS:
            base = base_name(node.func.value)
            if base in self.taint:
                self.findings.append(
                    Finding(
                        check="cache-mutation",
                        path=self.path,
                        line=node.lineno,
                        message=(
                            f"mutating call .{node.func.attr}() through {base!r} "
                            f"(read from a cache at line {self.taint[base]}) "
                            f"without copy.deepcopy()"
                        ),
                    )
                )
        self.generic_visit(node)

    # nested defs get their own fresh pass (run by the checker); don't let
    # this one descend into them with the enclosing scope's taint
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        pass

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass


class CacheMutationChecker(Checker):
    name = "cache-mutation"

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visitor = _FunctionTaint(module.path)
                for stmt in node.body:
                    visitor.visit(stmt)
                findings.extend(visitor.findings)
        return findings
