"""metric-convention / annotation-convention: names are contracts.

Metric families and annotation keys outlive any one release — dashboards,
alerts, and users' CRs bind to them. Two checkers keep them centralized and
well-formed:

`MetricConventionChecker`: every `registry.counter/gauge/histogram(...)`
registration site must use a literal name that passes the shared Prometheus
rules in analysis/metric_rules.py (valid charset, counters end in `_total`,
non-empty help, valid label names, no reserved `le`). Literal-only is itself
a rule: a computed metric name cannot be grepped, alerted on, or linted.

`AnnotationConventionChecker`: the operator's own annotation/label keys
(`notebooks.kubeflow.org/...`, `notebooks.opendatahub.io/...`,
`opendatahub.io/...`, `kubeflow-resource-stopped`) may only be spelled out
in controllers/constants.py (and utils/tracing.py, the traceparent key's
canonical home). Everywhere else must import the constant — the reference
keeps these byte-identical to upstream, and a typo'd inline key silently
breaks the stop/culling state machine rather than failing loudly.

The checker's `finish()` pass additionally flags DEAD `*_ANNOTATION`
constants: a key defined in constants.py that no other module reads is
either a leftover from a removed feature (delete it) or — worse — a
contract someone believes is honored while nothing writes or reads it
(ISSUE 8 satellite; first catch: TPU_IDLE_ANNOTATION, which nothing ever
consumed — the culler reads last_busy from the probe JSON).
"""
from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Tuple

from ..framework import Checker, Finding, ModuleInfo
from ..metric_rules import check_metric
from ._util import terminal_name

REGISTRY_RECV_RE = re.compile(r"(^|_)(registry|metrics)$")
REGISTRATION_METHODS = {"counter", "gauge", "histogram"}

# the operator's own key namespaces (external contract keys like
# cert-manager.io/* are other controllers' constants, not ours)
ANNOTATION_KEY_RE = re.compile(
    r"^(notebooks\.(kubeflow\.org|opendatahub\.io|tpu\.kubeflow\.org)"
    r"|opendatahub\.io)/[A-Za-z0-9_.\-]+$"
    r"|^kubeflow-resource-stopped$"
)
ANNOTATION_HOMES = ("constants.py", "tracing.py")


def _literal_str(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class MetricConventionChecker(Checker):
    name = "metric-convention"

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in REGISTRATION_METHODS
            ):
                continue
            recv = terminal_name(node.func.value) or ""
            if not REGISTRY_RECV_RE.search(recv):
                continue
            name = _literal_str(node.args[0] if node.args else None)
            if name is None:
                findings.append(
                    Finding(
                        check=self.name,
                        path=module.path,
                        line=node.lineno,
                        message=f"metric name passed to .{node.func.attr}() "
                        "must be a string literal (computed names cannot be "
                        "grepped, alerted on, or linted)",
                    )
                )
                continue
            help_node = node.args[1] if len(node.args) > 1 else None
            for kw in node.keywords:
                if kw.arg == "help_":
                    help_node = kw.value
            help_text = _literal_str(help_node)
            if help_node is None:
                help_text = ""  # registration default: empty help
            # labels: third positional (Registry.counter(name, help_, labels))
            # or the `labels=` keyword — both spellings are live in-tree
            labels_node = node.args[2] if len(node.args) > 2 else None
            for kw in node.keywords:
                if kw.arg == "labels":
                    labels_node = kw.value
            labels: List[str] = []
            if isinstance(labels_node, (ast.Tuple, ast.List)):
                labels = [
                    v for v in (_literal_str(e) for e in labels_node.elts)
                    if v is not None
                ]
            for violation in check_metric(
                name, node.func.attr, help_text, labels
            ):
                findings.append(
                    Finding(
                        check=self.name,
                        path=module.path,
                        line=node.lineno,
                        message=violation,
                    )
                )
        return findings


class AnnotationConventionChecker(Checker):
    name = "annotation-convention"

    def __init__(self) -> None:
        # constants.py `*_ANNOTATION` definitions and the names read
        # anywhere else, for the dead-constant finish() pass. Only armed
        # when the real constants module is in the scan set, so fixture
        # runs on a lone snippet stay silent.
        self._defined: Dict[str, Tuple[str, int]] = {}
        self._read: set = set()

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        basename = Path(module.path).name
        if basename == "constants.py" and "controllers" in Path(module.path).parts:
            for node in ast.iter_child_nodes(module.tree):
                if not isinstance(node, ast.Assign):
                    continue
                for target in node.targets:
                    if isinstance(target, ast.Name) and \
                            target.id.endswith("_ANNOTATION"):
                        self._defined[target.id] = (module.path, node.lineno)
        else:
            for node in ast.walk(module.tree):
                if isinstance(node, ast.Attribute):
                    self._read.add(node.attr)
                elif isinstance(node, ast.Name):
                    self._read.add(node.id)
        if basename in ANNOTATION_HOMES:
            return []
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Constant) and isinstance(node.value, str)):
                continue
            if ANNOTATION_KEY_RE.match(node.value):
                findings.append(
                    Finding(
                        check=self.name,
                        path=module.path,
                        line=node.lineno,
                        message=f"operator annotation/label key {node.value!r} "
                        "spelled inline — import it from "
                        "controllers/constants.py (one typo here silently "
                        "breaks the culling/stop state machine)",
                    )
                )
        return findings

    def finish(self) -> Iterable[Finding]:
        if not self._read:
            # constants.py scanned alone (a single-file --check run): with
            # no reader module in the scan set, "nothing reads it" would be
            # vacuously true for every constant — stay silent
            return
        for name, (path, line) in sorted(self._defined.items()):
            if name not in self._read:
                yield Finding(
                    check=self.name,
                    path=path,
                    line=line,
                    message=f"dead annotation constant {name}: no module "
                    "reads it — delete it, or the feature that honored "
                    "this contract is gone while the key suggests "
                    "otherwise",
                )
