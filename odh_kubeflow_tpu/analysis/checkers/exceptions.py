"""swallowed-exception: no bare/blind except in reconcile, webhook or probe
paths.

A reconciler that swallows an exception converts a retryable failure into
silent state drift: the workqueue never backs off, the status never reports
the error, and the operator looks healthy while doing nothing. Two shapes
are flagged, scoped to the control-plane paths where they are dangerous
(controllers/, probe/, webhook modules — plus any function named
reconcile*):

- bare ``except:`` — catches SystemExit/KeyboardInterrupt too; always wrong,
- blind ``except Exception:`` whose body is only ``pass``/``continue``/``...``
  — no log, no fallback value, no re-raise; the error evaporates.

A handler that assigns a fallback (``terminals = []``) or logs is NOT
flagged: degrading with a recorded decision is the pattern the reference
uses, and the point is to force the decision to be visible.
"""
from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable, List

from ..framework import Checker, Finding, ModuleInfo

SCOPED_DIRS = {"controllers", "probe"}


def _in_scope(path: str) -> bool:
    if path == "<fixture>":
        return True
    parts = Path(path).parts
    if SCOPED_DIRS & set(parts):
        return True
    return "webhook" in Path(path).name


def _is_blind_body(body: List[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring / `...`
        return False
    return True


def _catches_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    for sub in types:
        if isinstance(sub, ast.Name) and sub.id in ("Exception", "BaseException"):
            return True
        if isinstance(sub, ast.Attribute) and sub.attr in ("Exception", "BaseException"):
            return True
    return False


def _reconcile_handlers(tree: ast.AST) -> List[ast.ExceptHandler]:
    """Except handlers lexically inside any reconcile* function — reconcile
    paths are in scope wherever the module lives (runtime/, cluster/, ...)."""
    out: List[ast.ExceptHandler] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
            node.name.startswith("reconcile") or node.name.startswith("_reconcile")
        ):
            out.extend(
                sub for sub in ast.walk(node) if isinstance(sub, ast.ExceptHandler)
            )
    return out


class SwallowedExceptionChecker(Checker):
    name = "swallowed-exception"

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        if _in_scope(module.path):
            handlers: List[ast.ExceptHandler] = [
                node
                for node in ast.walk(module.tree)
                if isinstance(node, ast.ExceptHandler)
            ]
        else:
            handlers = _reconcile_handlers(module.tree)
        findings: List[Finding] = []
        for node in handlers:
            if node.type is None:
                findings.append(
                    Finding(
                        check=self.name,
                        path=module.path,
                        line=node.lineno,
                        message="bare `except:` in a control-plane path "
                        "(catches SystemExit/KeyboardInterrupt too) — name "
                        "the exception and handle or log it",
                    )
                )
            elif _catches_broad(node) and _is_blind_body(node.body):
                findings.append(
                    Finding(
                        check=self.name,
                        path=module.path,
                        line=node.lineno,
                        message="blind `except Exception: pass` in a "
                        "control-plane path — the error evaporates; log it, "
                        "assign a fallback, or re-raise",
                    )
                )
        return findings
