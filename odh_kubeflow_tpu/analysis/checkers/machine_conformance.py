"""machine-conformance: state-machine writes must match analysis/machines.py.

The three annotation-durable machines (suspend, slice-repair, culling/stop)
are declared as data in `analysis/machines.py`. This checker AST-extracts
every WRITE of a machine's state annotation from the scanned modules —

    {C.TPU_SUSPEND_STATE_ANNOTATION: STATE_SUSPENDED}      # patch dict
    updates[C.STOP_ANNOTATION] = now_rfc3339()             # subscript store
    annotations.setdefault(C.STOP_ANNOTATION, C.RECON...)  # setdefault

— and flags:

- writes from a module with no declared transition for that machine
  (non-owning writer: a fourth controller quietly joining a two-writer
  contract is exactly how lifecycle races are born),
- writes whose target state is not declared, or whose (function, target)
  pair matches no declared transition (a drifted transition),
- declared transitions whose implementing function no longer writes that
  state (spec drift the other way), checked only when the owning module is
  actually in the scan set,
- spec-level dead ends: unreachable declared states, terminal states with
  neither a self-heal path nor an incident bundle, and — for transitions
  entering a terminal `incident` state — a `via` function that never calls
  `recorder.snapshot(...)`.

A `finish()` pass also asserts the REPAIR_OWNED_CONDITIONS drift contract:
the tuple in controllers/conditions.py must cover EXACTLY the condition
types the repair/suspend/SLO machines pass to `write_condition` — a
condition written but not mirror-preserved gets stomped by the pod-condition
mirror; a preserved-but-never-written type is a dead entry.
"""
from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..framework import Checker, Finding, ModuleInfo
from ..machines import MACHINES, MachineSpec, machine_for_annotation, spec_errors

# where the machine specs live, for spec-level findings
_SPEC_PATH = "odh_kubeflow_tpu/analysis/machines.py"

# constants.py values resolved lazily (Attribute writes like
# C.RECONCILIATION_LOCK_VALUE need the literal value to classify the state)
_CONST_VALUES: Optional[Dict[str, str]] = None


def _const_values() -> Dict[str, str]:
    global _CONST_VALUES
    if _CONST_VALUES is None:
        from ...controllers import constants as C

        _CONST_VALUES = {
            name: value
            for name, value in vars(C).items()
            if isinstance(value, str) and not name.startswith("_")
        }
    return _CONST_VALUES


def _annotation_const(node: ast.AST) -> Optional[str]:
    """The constants.py NAME a key expression references (C.X / constants.X
    / bare X from `from .constants import X`)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


class _Write:
    __slots__ = ("spec", "module", "function", "value", "dynamic", "line")

    def __init__(self, spec: MachineSpec, module: str, function: str,
                 value: Optional[str], dynamic: bool, line: int):
        self.spec = spec
        self.module = module
        self.function = function
        self.value = value
        self.dynamic = dynamic
        self.line = line


class MachineConformanceChecker(Checker):
    name = "machine-conformance"

    def __init__(self) -> None:
        # (machine name, via) pairs implemented somewhere in the scan set,
        # and which owner modules were actually scanned — drift checks only
        # fire for machines whose owners are present (fixture runs on a
        # single snippet must not report the whole real tree as missing)
        self._implemented: Set[Tuple[str, str, str]] = set()
        self._scanned_modules: Set[str] = set()
        self._condition_writes: Dict[str, Tuple[str, int]] = {}
        self._owned_conditions: Optional[List[Tuple[str, int]]] = None
        self._conditions_path: Optional[str] = None

    # ---------- per-module ----------

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        basename = Path(module.path).name
        self._scanned_modules.add(basename)
        findings: List[Finding] = []
        consts = self._module_string_constants(module.tree)

        for func_name, node, key_node, value_node in self._write_sites(module.tree):
            const_name = _annotation_const(key_node)
            if const_name is None:
                continue
            spec = machine_for_annotation(const_name)
            if spec is None:
                continue
            write = self._classify(
                spec, basename, func_name, value_node, consts, node.lineno
            )
            findings.extend(self._judge(module, write))
        if basename == "conditions.py":
            self._harvest_owned_conditions(module)
        self._harvest_condition_writes(module)
        return findings

    def _write_sites(self, tree: ast.AST):
        """Yield (enclosing function, node, key expr, value expr) for every
        annotation-write shape in the module."""
        func_of: Dict[ast.AST, str] = {}

        def walk(node: ast.AST, func: str) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and func == "<module>":
                # nested defs (retry closures like `attempt`) attribute to
                # the named method that owns them — the transition's `via`
                func = node.name
            func_of[node] = func
            for child in ast.iter_child_nodes(node):
                walk(child, func)

        walk(tree, "<module>")
        for node in ast.walk(tree):
            func = func_of.get(node, "<module>")
            if isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if k is not None:
                        yield func, node, k, v
            elif isinstance(node, ast.Assign):
                for target in node.targets:
                    if isinstance(target, ast.Subscript):
                        yield func, node, target.slice, node.value
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "setdefault"
                and len(node.args) >= 2
            ):
                yield func, node, node.args[0], node.args[1]

    @staticmethod
    def _module_string_constants(tree: ast.AST) -> Dict[str, str]:
        """Module-level NAME = "literal" assignments (STATE_* values)."""
        out: Dict[str, str] = {}
        for node in ast.iter_child_nodes(tree):
            if not isinstance(node, ast.Assign):
                continue
            if not (isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)):
                continue
            for target in node.targets:
                if isinstance(target, ast.Name):
                    out[target.id] = node.value.value
        return out

    def _classify(
        self,
        spec: MachineSpec,
        module: str,
        func: str,
        value_node: ast.AST,
        consts: Dict[str, str],
        line: int,
    ) -> _Write:
        value: Optional[str] = None
        dynamic = False
        if isinstance(value_node, ast.Constant):
            if value_node.value is None:
                value = ""
            elif isinstance(value_node.value, str):
                value = value_node.value
            else:
                dynamic = True
        elif isinstance(value_node, ast.Name) and value_node.id in consts:
            value = consts[value_node.id]
        elif isinstance(value_node, ast.Attribute) \
                and value_node.attr in _const_values():
            value = _const_values()[value_node.attr]
        else:
            dynamic = True
        return _Write(spec, module, func, value, dynamic, line)

    def _judge(self, module: ModuleInfo, w: _Write) -> Iterable[Finding]:
        spec = w.spec
        via = f"{w.module}:{w.function}"
        state = spec.classify_value(w.value, dynamic=w.dynamic)
        if state is None:
            if w.dynamic:
                msg = (
                    f"{spec.name} machine: computed value written to "
                    f"{spec.annotation} in {via} — states must be literal "
                    "(a computed state cannot be checked against the spec)"
                )
            else:
                msg = (
                    f"{spec.name} machine: {via} writes undeclared state "
                    f"{w.value!r} (declared: "
                    f"{sorted(s.name or '(absent)' for s in spec.states)}; "
                    "declare it in analysis/machines.py or fix the write)"
                )
            yield Finding(self.name, module.path, w.line, msg)
            return
        declared_vias = {t.via for t in spec.transitions if t.via}
        if all(not v.startswith(w.module + ":") for v in declared_vias):
            yield Finding(
                self.name, module.path, w.line,
                f"{spec.name} machine: {w.module} writes {spec.annotation} "
                f"but is not a declared writer (owners: "
                f"{', '.join(spec.writer_modules())}) — declare the "
                "transition in analysis/machines.py or route the write "
                "through the owning controller",
            )
            return
        matching = [
            t for t in spec.transitions if t.via == via and t.dst == state
        ]
        if not matching:
            yield Finding(
                self.name, module.path, w.line,
                f"{spec.name} machine: transition to "
                f"{state or '(cleared)'!r} in {via} is not declared in "
                "analysis/machines.py — a drifted transition (declare it, "
                "with its legal source states, or fix the write)",
            )
            return
        self._implemented.add((spec.name, via, state))
        # incident contract: a transition into a terminal incident state
        # must snapshot a flight-recorder bundle from its via function
        st = spec.state(state)
        if st is not None and st.terminal and st.incident:
            if not self._function_snapshots(module.tree, w.function):
                yield Finding(
                    self.name, module.path, w.line,
                    f"{spec.name} machine: {via} enters terminal state "
                    f"{state!r} without a recorder.snapshot(...) incident "
                    "bundle — a dead end with no evidence trail",
                )

    @staticmethod
    def _function_snapshots(tree: ast.AST, func_name: str) -> bool:
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == func_name:
                for sub in ast.walk(node):
                    if (
                        isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "snapshot"
                    ):
                        return True
        return False

    # ---------- REPAIR_OWNED_CONDITIONS drift ----------

    def _harvest_owned_conditions(self, module: ModuleInfo) -> None:
        self._conditions_path = module.path
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Assign):
                continue
            if not any(
                isinstance(t, ast.Name) and t.id == "REPAIR_OWNED_CONDITIONS"
                for t in node.targets
            ):
                continue
            if isinstance(node.value, (ast.Tuple, ast.List)):
                self._owned_conditions = [
                    (name, node.lineno)
                    for name in (
                        _annotation_const(e) for e in node.value.elts
                    )
                    if name is not None
                ]

    def _harvest_condition_writes(self, module: ModuleInfo) -> None:
        """Condition-type constants passed to write_condition(...) — the
        mirror-preservation contract's write side."""
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else ""
            )
            if name != "write_condition" or len(node.args) < 4:
                continue
            ctype = _annotation_const(node.args[3])
            if ctype and ctype.isupper():
                self._condition_writes.setdefault(
                    ctype, (module.path, node.lineno)
                )

    # ---------- cross-module ----------

    def finish(self) -> Iterable[Finding]:
        findings: List[Finding] = []
        for spec in MACHINES:
            for err in spec_errors(spec):
                findings.append(Finding(self.name, _SPEC_PATH, 1, err))
            # drift the other way: a declared transition nobody implements.
            # Only judged when the via module itself was scanned — a
            # single-fixture run must not report the whole tree missing.
            for t in spec.transitions:
                if t.via is None:
                    continue
                via_module = t.via.split(":", 1)[0]
                if via_module not in self._scanned_modules:
                    continue
                if (spec.name, t.via, t.dst) not in self._implemented:
                    findings.append(Finding(
                        self.name, _SPEC_PATH, 1,
                        f"{spec.name} machine: declared transition "
                        f"{t.src or 'rest'!r}->{t.dst or 'rest'!r} via "
                        f"{t.via} has no matching write in {via_module} — "
                        "the spec drifted from the code",
                    ))
        # conditions drift (only when conditions.py was in the scan set AND
        # the writing modules were too — the package-level pass)
        if self._owned_conditions is not None and \
                "slice_repair.py" in self._scanned_modules:
            owned = {name for name, _ in self._owned_conditions}
            written = set(self._condition_writes)
            path = self._conditions_path or "controllers/conditions.py"
            line = self._owned_conditions[0][1] if self._owned_conditions else 1
            for name in sorted(written - owned):
                wpath, wline = self._condition_writes[name]
                findings.append(Finding(
                    self.name, wpath, wline,
                    f"condition {name} is written via write_condition but "
                    "missing from REPAIR_OWNED_CONDITIONS — the pod-"
                    "condition mirror will stomp it on the next rebuild",
                ))
            for name in sorted(owned - written):
                findings.append(Finding(
                    self.name, path, line,
                    f"REPAIR_OWNED_CONDITIONS entry {name} is never passed "
                    "to write_condition — a dead preservation entry (remove "
                    "it, or the machine that owned it lost its write)",
                ))
        return findings
