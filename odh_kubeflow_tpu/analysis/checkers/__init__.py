"""Checker registry: one place that knows every checker class."""
from __future__ import annotations

from typing import List

from ..framework import Checker
from .cache_mutation import CacheMutationChecker
from .conventions import AnnotationConventionChecker, MetricConventionChecker
from .deploylint import (
    CrdSchemaDriftChecker,
    EnvContractChecker,
    FlowSchemaCoverageChecker,
    RbacCoverageChecker,
)
from .exceptions import SwallowedExceptionChecker
from .jaxlint import (
    DonationDisciplineChecker,
    HostTransferChecker,
    PsumAxisChecker,
    RetraceHazardChecker,
)
from .lock_discipline import LockDisciplineChecker, LockOrderChecker
from .machine_conformance import MachineConformanceChecker


def make_checkers() -> List[Checker]:
    discipline = LockDisciplineChecker()
    return [
        CacheMutationChecker(),
        discipline,
        # shares discipline's walk: edges are harvested once, cycles
        # reported at finish()
        LockOrderChecker(shared=discipline),
        SwallowedExceptionChecker(),
        MetricConventionChecker(),
        AnnotationConventionChecker(),
        MachineConformanceChecker(),
        # the jaxlint family (ISSUE 12): data-plane compilation/transfer/
        # donation discipline; psum-axis judges cross-module at finish()
        RetraceHazardChecker(),
        HostTransferChecker(),
        DonationDisciplineChecker(),
        PsumAxisChecker(),
        # the deploylint family (ISSUE 14): deployment-surface conformance
        # against the analysis/deploysurface.py contract (runtime twin:
        # utils/deployguard.py)
        RbacCoverageChecker(),
        CrdSchemaDriftChecker(),
        EnvContractChecker(),
        FlowSchemaCoverageChecker(),
    ]
