"""jaxlint: the data-plane discipline checkers (ISSUE 12).

Four checkers over the jax-facing modules, catching the silent-perf-killer
classes that never fail a test — they just move the token-latency SLO:

- ``retrace-hazard``       jit caches remade per call (jit inside a loop,
                           ``jax.jit(f)(x)``, ``jax.jit(lambda ...)`` in a
                           function body), non-hashable static arguments,
                           and shape-derived Python values fed to a static
                           position (one compile PER DISTINCT VALUE).
- ``host-transfer``        device->host sync surfaces (``.item()``,
                           ``jax.device_get``, ``np.array/asarray``,
                           ``float/int/bool`` over device expressions,
                           branching on device values) inside a declared
                           hot region (analysis/hotregions.py) or any
                           same-module function it reaches.
- ``donation-discipline``  a jitted fn overwriting a buffer parameter
                           (``dynamic_update_slice`` / ``.at[...].set``)
                           without donating it — XLA must then keep both
                           copies live; and donated arguments read after
                           the call (they are deleted).
- ``psum-axis``            collective axis names must be axes some module
                           actually declares (mesh ``AXES`` tuples,
                           ``Mesh(..., axis_names=...)``) — a cross-module
                           finish() pass, since ``parallel/ring_attention``
                           uses axes ``parallel/mesh`` declares.

The runtime twin is `utils/jaxguard.py`; the two share the hot-region
registry the way machine-conformance and INVCHECK share `machines.py`.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .. import hotregions
from ..framework import Checker, Finding, ModuleInfo
from ._util import dotted_name, terminal_name

_JIT_DOTTED = {"jax.jit", "jit", "jax.pjit", "pjit", "jaxguard.jit"}
_PARTIAL_DOTTED = {"partial", "functools.partial"}


def _as_jit_call(node: ast.AST) -> Optional[ast.Call]:
    """The Call carrying the jit kwargs if `node` is ``jax.jit(...)`` or
    ``partial(jax.jit, ...)``; None otherwise."""
    if not isinstance(node, ast.Call):
        return None
    dn = dotted_name(node.func)
    if dn in _JIT_DOTTED:
        return node
    if dn in _PARTIAL_DOTTED and node.args and dotted_name(node.args[0]) in _JIT_DOTTED:
        return node
    return None


def _literal_strings(node: ast.AST) -> List[str]:
    """String constants in `node` (a Constant or a Tuple/List of them)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append(elt.value)
        return out
    return []


def _literal_ints(node: ast.AST) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [
            elt.value
            for elt in node.elts
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int)
        ]
    return []


@dataclass
class JitSpec:
    """One in-module jit-decorated function: parameter layout + which
    positions/names are static and which are donated."""

    fn: ast.FunctionDef
    params: List[str]
    static_pos: Set[int] = field(default_factory=set)
    static_names: Set[str] = field(default_factory=set)
    donate_pos: Set[int] = field(default_factory=set)

    def static_positions(self) -> Set[int]:
        out = set(self.static_pos)
        for name in self.static_names:
            if name in self.params:
                out.add(self.params.index(name))
        return out


def _jit_specs(tree: ast.AST) -> Dict[str, JitSpec]:
    """Terminal name -> JitSpec for every jit-decorated def in the module."""
    specs: Dict[str, JitSpec] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for deco in node.decorator_list:
            call = _as_jit_call(deco)
            if call is None and dotted_name(deco) not in _JIT_DOTTED:
                continue
            spec = JitSpec(
                fn=node, params=[a.arg for a in node.args.args]
            )
            for kw in (call.keywords if call is not None else []):
                if kw.arg == "static_argnums":
                    spec.static_pos.update(_literal_ints(kw.value))
                elif kw.arg == "static_argnames":
                    spec.static_names.update(_literal_strings(kw.value))
                elif kw.arg == "donate_argnums":
                    spec.donate_pos.update(_literal_ints(kw.value))
                elif kw.arg == "donate_argnames":
                    for name in _literal_strings(kw.value):
                        if name in spec.params:
                            spec.donate_pos.add(spec.params.index(name))
            specs[node.name] = spec
            break
    return specs


def _contains_device_call(node: ast.AST) -> bool:
    """Does the expression contain a jnp./jax./lax. call — i.e. does
    evaluating it force a device value into a host context?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            dn = dotted_name(sub.func)
            if dn and (
                dn.startswith("jnp.") or dn.startswith("jax.")
                or dn.startswith("lax.")
            ):
                return True
    return False


# ---------------------------------------------------------------------------
# retrace-hazard
# ---------------------------------------------------------------------------


class RetraceHazardChecker(Checker):
    """Compile-cache hygiene: the cache must be keyed by shapes the caller
    actually cycles through, and must be MADE exactly once."""

    name = "retrace-hazard"

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        seen_lines: Set[int] = set()

        def flag(line: int, message: str) -> None:
            if line in seen_lines:
                return
            seen_lines.add(line)
            findings.append(Finding(self.name, module.path, line, message))

        specs = _jit_specs(module.tree)

        # 1. jit created inside a loop body: the callable AND its compile
        # cache are remade per iteration
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.For, ast.While)):
                continue
            for sub in ast.walk(node):
                if sub is node:
                    continue
                if _as_jit_call(sub) is not None:
                    flag(
                        sub.lineno,
                        "jax.jit inside a loop body — the jitted callable "
                        "(and its compile cache) is remade every iteration; "
                        "hoist it out of the loop",
                    )
                elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for deco in sub.decorator_list:
                        if (
                            _as_jit_call(deco) is not None
                            or dotted_name(deco) in _JIT_DOTTED
                        ):
                            flag(
                                sub.lineno,
                                f"@jax.jit def {sub.name} inside a loop "
                                "body — a fresh function (and cache) per "
                                "iteration; define it once outside",
                            )

        for node in ast.walk(module.tree):
            call = _as_jit_call(node)
            if call is None:
                continue
            # 2. jax.jit(f)(x): compile cache created and thrown away per call
            # (walk parents cheaply: look for Call whose func IS this call)
            # handled below via the parent scan
            # 3. jit over a lambda inside a function body: fresh callable
            # identity per invocation of the enclosing function
            target = call.args[-1] if call.args else None
            if isinstance(target, ast.Lambda):
                flag(
                    call.lineno,
                    "jax.jit over a lambda — a fresh callable identity "
                    "(and compile cache) every time this line runs; name "
                    "the function at module scope",
                )

        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call) and _as_jit_call(node.func) is not None:
                flag(
                    node.lineno,
                    "jax.jit(...)(args) — the jitted wrapper (and its "
                    "compile cache) is created per call and never reused; "
                    "bind the jitted callable once",
                )

        # 4 + 5: call-site checks against in-module jitted fns
        for fndef in ast.walk(module.tree):
            if not isinstance(fndef, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            tainted = self._shape_tainted(fndef)
            for node in ast.walk(fndef):
                if not isinstance(node, ast.Call):
                    continue
                callee = terminal_name(node.func)
                spec = specs.get(callee or "")
                if spec is None or spec.fn is fndef:
                    continue
                static = spec.static_positions()
                for idx, arg in enumerate(node.args):
                    if idx in static:
                        self._check_static_arg(arg, flag, tainted)
                for kw in node.keywords:
                    if kw.arg in spec.static_names or (
                        kw.arg in spec.params
                        and spec.params.index(kw.arg) in static
                    ):
                        self._check_static_arg(kw.value, flag, tainted)
        return findings

    def _check_static_arg(self, arg: ast.AST, flag, tainted: Set[str]) -> None:
        if isinstance(arg, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                            ast.DictComp, ast.SetComp)):
            flag(
                arg.lineno,
                "non-hashable value at a static jit position — jax hashes "
                "static args to key the compile cache; pass a tuple or a "
                "frozen/hashable config object",
            )
            return
        shape_derived = isinstance(arg, ast.Name) and arg.id in tainted
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Call) and terminal_name(sub.func) == "len":
                shape_derived = True
            if isinstance(sub, ast.Attribute) and sub.attr == "shape":
                shape_derived = True
            if isinstance(sub, ast.Name) and sub.id in tainted:
                shape_derived = True
        if shape_derived:
            flag(
                arg.lineno,
                "shape-derived Python value at a static jit position — one "
                "compile PER DISTINCT VALUE; pad to a bounded shape family "
                "or pragma with the rationale if per-shape compiles are "
                "the design",
            )

    @staticmethod
    def _shape_tainted(fndef: ast.AST) -> Set[str]:
        """Names in `fndef` bound (transitively) from `.shape` / `len()`
        expressions — the Python-scalar values that retrace per value when
        fed to a static position."""
        tainted: Set[str] = set()
        assigns: List[Tuple[List[str], ast.AST]] = []
        for node in ast.walk(fndef):
            if isinstance(node, ast.Assign):
                # only true Store targets: `self._x[i] = ...` must not taint
                # `self`/`i` (their ctx is Load inside the subscript)
                names = [
                    t.id
                    for tgt in node.targets
                    for t in ast.walk(tgt)
                    if isinstance(t, ast.Name) and isinstance(t.ctx, ast.Store)
                ]
                assigns.append((names, node.value))
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                if isinstance(node.target, ast.Name):
                    assigns.append(([node.target.id], node.value))
        for _ in range(3):  # short transitive closure
            changed = False
            for names, value in assigns:
                hit = False
                for sub in ast.walk(value):
                    if isinstance(sub, ast.Attribute) and sub.attr == "shape":
                        hit = True
                    elif isinstance(sub, ast.Call) and terminal_name(sub.func) == "len":
                        hit = True
                    elif isinstance(sub, ast.Name) and sub.id in tainted:
                        hit = True
                if hit and not set(names) <= tainted:
                    tainted.update(names)
                    changed = True
            if not changed:
                break
        return tainted


# ---------------------------------------------------------------------------
# host-transfer
# ---------------------------------------------------------------------------

_NP_TRANSFER = {
    "np.array", "np.asarray", "numpy.array", "numpy.asarray",
}


class HostTransferChecker(Checker):
    """Device->host sync surfaces inside a declared hot region or any
    same-module function it reaches. A sync in the decode loop serializes
    the device pipeline on the host round trip — the per-token dispatch
    floor continuous batching exists to amortize."""

    name = "host-transfer"

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        roots = hotregions.hot_functions_for(module.path)
        if not roots:
            return ()
        funcs = self._module_functions(module.tree)
        reach = self._reachable(roots, funcs)
        findings: List[Finding] = []
        seen_lines: Set[int] = set()

        def flag(line: int, message: str) -> None:
            if line in seen_lines:
                return
            seen_lines.add(line)
            findings.append(Finding(self.name, module.path, line, message))

        for qualname in sorted(reach):
            fndef = funcs[qualname]
            origin = reach[qualname]
            where = (
                f"hot region {origin.name!r}"
                if qualname in roots
                else f"reached from hot region {origin.name!r}"
            )
            for node in ast.walk(fndef):
                if isinstance(node, ast.Call):
                    dn = dotted_name(node.func) or ""
                    tn = terminal_name(node.func) or ""
                    if tn == "item" and isinstance(node.func, ast.Attribute):
                        flag(node.lineno,
                             f".item() in {where} — a blocking device->host "
                             "sync per call; batch the fetch after the region")
                    elif tn == "device_get":
                        flag(node.lineno,
                             f"jax.device_get in {where} — a blocking host "
                             "sync; batch into ONE post-region drain "
                             "(or pragma the intentional one)")
                    elif dn in _NP_TRANSFER:
                        flag(node.lineno,
                             f"{dn} in {where} — materializes the device "
                             "value on host; keep the value on device or "
                             "use .copy() on an already-fetched array")
                    elif tn in ("float", "int", "bool") and node.args and any(
                        _contains_device_call(a) for a in node.args
                    ):
                        flag(node.lineno,
                             f"{tn}() over a device expression in {where} — "
                             "an implicit blocking transfer")
                elif isinstance(node, (ast.If, ast.While)):
                    if _contains_device_call(node.test):
                        flag(node.test.lineno,
                             f"branching on a device value in {where} — "
                             "implicit bool() is a blocking transfer; fold "
                             "the predicate into the compiled program (e.g. "
                             "jnp.where) or fetch it in the batched drain")
        return findings

    @staticmethod
    def _module_functions(tree: ast.AST) -> Dict[str, ast.FunctionDef]:
        """Qualname (`Class.method` / bare fn) -> def node, one level of
        class nesting (all this codebase has)."""
        out: Dict[str, ast.FunctionDef] = {}
        for node in ast.iter_child_nodes(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out[node.name] = node
            elif isinstance(node, ast.ClassDef):
                for sub in ast.iter_child_nodes(node):
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        out[f"{node.name}.{sub.name}"] = sub
        return out

    @staticmethod
    def _reachable(
        roots: Dict[str, "hotregions.HotRegion"],
        funcs: Dict[str, ast.FunctionDef],
    ) -> Dict[str, "hotregions.HotRegion"]:
        """Roots plus same-module callees reachable from them (edges by
        terminal call name: `self._emit(...)` reaches `Cls._emit`)."""
        by_terminal: Dict[str, List[str]] = {}
        for qualname in funcs:
            by_terminal.setdefault(qualname.rsplit(".", 1)[-1], []).append(qualname)
        out: Dict[str, hotregions.HotRegion] = {}
        work = [
            (qualname, region)
            for qualname, region in roots.items()
            if qualname in funcs
        ]
        while work:
            qualname, region = work.pop()
            if qualname in out:
                continue
            out[qualname] = region
            for node in ast.walk(funcs[qualname]):
                if isinstance(node, ast.Call):
                    tn = terminal_name(node.func)
                    for callee in by_terminal.get(tn or "", []):
                        if callee not in out:
                            work.append((callee, region))
        return out


# ---------------------------------------------------------------------------
# donation-discipline
# ---------------------------------------------------------------------------

_AT_MUTATORS = {"set", "add", "multiply", "divide", "min", "max", "mul"}


class DonationDisciplineChecker(Checker):
    """Jitted fns that overwrite a buffer parameter without donating it
    (XLA keeps both copies live — for a KV cache that's double HBM), and
    donated arguments read after the call (deleted buffers)."""

    name = "donation-discipline"

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        specs = _jit_specs(module.tree)
        for spec in specs.values():
            overwritten = self._overwritten_params(spec)
            for param in sorted(overwritten):
                pos = spec.params.index(param)
                if pos not in spec.donate_pos:
                    findings.append(Finding(
                        self.name, module.path, spec.fn.lineno,
                        f"jitted {spec.fn.name!r} overwrites buffer "
                        f"parameter {param!r} (position {pos}) without "
                        f"donate_argnums — XLA must keep input AND output "
                        "copies live; donate the buffer so the update "
                        "aliases in place",
                    ))
        findings.extend(self._reads_after_donation(module, specs))
        return findings

    @staticmethod
    def _overwritten_params(spec: JitSpec) -> Set[str]:
        """Params whose (transitively-derived) values are written via
        dynamic_update_slice / .at[...].set inside the function body.
        Propagation covers assignments and for-loop unpacking over
        zip/enumerate of tainted values — the per-layer cache idiom."""
        origins: Dict[str, Set[str]] = {p: {p} for p in spec.params}

        def expr_origins(node: ast.AST) -> Set[str]:
            out: Set[str] = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name) and sub.id in origins:
                    out |= origins[sub.id]
            return out

        def bind(targets: Sequence[ast.AST], value: ast.AST) -> bool:
            src = expr_origins(value)
            if not src:
                return False
            changed = False
            for tgt in targets:
                for t in ast.walk(tgt):
                    if isinstance(t, ast.Name):
                        if not src <= origins.get(t.id, set()):
                            origins[t.id] = origins.get(t.id, set()) | src
                            changed = True
            return changed

        body_nodes = [
            n for n in ast.walk(spec.fn)
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            or n is spec.fn
        ]
        for _ in range(3):
            changed = False
            for node in body_nodes:
                if isinstance(node, ast.Assign):
                    changed |= bind(node.targets, node.value)
                elif isinstance(node, ast.For):
                    changed |= bind([node.target], node.iter)
            if not changed:
                break

        overwritten: Set[str] = set()
        for node in body_nodes:
            if not isinstance(node, ast.Call):
                continue
            tn = terminal_name(node.func)
            if tn == "dynamic_update_slice" and node.args:
                overwritten |= expr_origins(node.args[0])
            elif (
                tn in _AT_MUTATORS
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Subscript)
                and isinstance(node.func.value.value, ast.Attribute)
                and node.func.value.value.attr == "at"
            ):
                overwritten |= expr_origins(node.func.value.value.value)
        return overwritten & set(spec.params)

    def _reads_after_donation(
        self, module: ModuleInfo, specs: Dict[str, JitSpec]
    ) -> List[Finding]:
        findings: List[Finding] = []
        for fndef in ast.walk(module.tree):
            if not isinstance(fndef, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for node in ast.walk(fndef):
                if not isinstance(node, ast.Call):
                    continue
                spec = specs.get(terminal_name(node.func) or "")
                if spec is None or not spec.donate_pos or spec.fn is fndef:
                    continue
                for pos in sorted(spec.donate_pos):
                    if pos >= len(node.args):
                        continue
                    donated = dotted_name(node.args[pos])
                    if donated is None:
                        continue
                    line = self._read_after(fndef, node, donated)
                    if line is not None:
                        findings.append(Finding(
                            self.name, module.path, line,
                            f"{donated!r} is read after being donated to "
                            f"{spec.fn.name!r} (position {pos}) — the "
                            "buffer is deleted by the call; rebind the "
                            "result or stop donating",
                        ))
        return findings

    @staticmethod
    def _read_after(
        fndef: ast.AST, call: ast.Call, donated: str
    ) -> Optional[int]:
        """Line of the first Load of `donated` after the donating call,
        unless the name is rebound first (including by the call's own
        enclosing assignment)."""
        call_end = getattr(call, "end_lineno", call.lineno)
        first_load: Optional[int] = None
        first_store: Optional[int] = None
        for node in ast.walk(fndef):
            dn = dotted_name(node) if isinstance(node, (ast.Name, ast.Attribute)) else None
            if dn != donated:
                continue
            ctx = getattr(node, "ctx", None)
            if isinstance(ctx, ast.Store):
                # the donating call's own assignment target rebinds on the
                # statement line(s) the call spans
                lineno = node.lineno
                if lineno >= call.lineno and (
                    first_store is None or lineno < first_store
                ):
                    first_store = lineno
            elif isinstance(ctx, ast.Load) and node.lineno > call_end:
                if first_load is None or node.lineno < first_load:
                    first_load = node.lineno
        if first_load is None:
            return None
        if first_store is not None and first_store <= first_load:
            return None
        return first_load


# ---------------------------------------------------------------------------
# psum-axis
# ---------------------------------------------------------------------------

_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "psum_scatter", "axis_index", "axis_size",
}
_AXES_ASSIGN_NAMES = {"AXES", "MESH_AXES", "axis_names"}


class PsumAxisChecker(Checker):
    """Collective axis-name literals must be axes some scanned module
    declares (mesh AXES tuples / Mesh(axis_names=...)). Cross-module: uses
    are collected per module, judged once at finish() against the union of
    declared axes — `ring_attention`'s "sp" default is legal because
    `parallel/mesh.py` declares it."""

    name = "psum-axis"

    def __init__(self) -> None:
        self.declared: Set[str] = set()
        self.uses: List[Tuple[str, int, str]] = []  # (path, line, axis)

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if (
                        isinstance(tgt, ast.Name)
                        and tgt.id in _AXES_ASSIGN_NAMES
                    ):
                        self.declared.update(_literal_strings(node.value))
            elif isinstance(node, ast.Call):
                tn = terminal_name(node.func) or ""
                if tn == "Mesh" or tn == "make_mesh":
                    if len(node.args) >= 2:
                        self.declared.update(_literal_strings(node.args[1]))
                for kw in node.keywords:
                    if kw.arg == "axis_names":
                        self.declared.update(_literal_strings(kw.value))
                if tn in _COLLECTIVES:
                    for arg in node.args[1:] if tn not in (
                        "axis_index", "axis_size"
                    ) else node.args:
                        for axis in _literal_strings(arg):
                            self.uses.append((module.path, arg.lineno, axis))
                    for kw in node.keywords:
                        if kw.arg in ("axis_name", "axis", "axis_names"):
                            for axis in _literal_strings(kw.value):
                                self.uses.append(
                                    (module.path, kw.value.lineno, axis)
                                )
                elif tn in ("pmap", "shard_map", "xmap"):
                    for kw in node.keywords:
                        if kw.arg in ("axis_name", "axis_names"):
                            for axis in _literal_strings(kw.value):
                                self.uses.append(
                                    (module.path, kw.value.lineno, axis)
                                )
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                args = node.args
                defaults = args.defaults
                params = args.args[len(args.args) - len(defaults):]
                for param, default in zip(params, defaults):
                    if param.arg in ("axis_name", "axis_names"):
                        for axis in _literal_strings(default):
                            self.uses.append(
                                (module.path, default.lineno, axis)
                            )
        return ()

    def finish(self) -> Iterable[Finding]:
        if not self.declared:
            # nothing in the scanned tree declares mesh axes (fixture runs
            # over non-parallel modules): no basis to judge uses
            return ()
        findings = []
        for path, line, axis in self.uses:
            if axis not in self.declared:
                findings.append(Finding(
                    self.name, path, line,
                    f"collective axis {axis!r} is not a declared mesh axis "
                    f"(declared: {sorted(self.declared)}) — the collective "
                    "would fail (or silently no-op under a 1-sized rename) "
                    "at the call site's mesh",
                ))
        return findings
