"""deploylint: the deployment-surface conformance family (ISSUE 14).

Four checkers proving the committed deploy/ surface and the code agree,
all reading the ONE contract in analysis/deploysurface.py (whose runtime
twin is utils/deployguard.py):

- rbac-coverage       every client verb×kind the manager issues is granted
                      by deploy/manifests.py cluster_role(), and no granted
                      rule is exercised by nothing (stale RBAC);
- crd-schema-drift    the CRDs deploy/crdgen.py derives from the api/
                      dataclasses match the committed deploy/base/
                      manifests.yaml byte-for-structure;
- env-contract        every os.environ read package-wide resolves to a
                      declared knob in controllers/config.py ENV_CONTRACT;
                      dead knobs and manifest-knob drift are findings;
- flow-schema-coverage  every flow name the code enters classifies onto a
                      non-default PriorityLevel, declared flows are
                      entered, and served webhook paths match the
                      generated registration.

Attribution (rbac-coverage): only deploysurface.is_manager_module() paths
count — the sim-cluster actors (kubelet/scheduler/statefulset) model other
identities. Kinds are resolved through local bindings (assignments, loop
targets, parameter annotations, intra-module helper returns); calls whose
kind stays dynamic are recorded per-verb and left to DEPLOYGUARD, which
sees the live (flow, verb, kind) stream.
"""
from __future__ import annotations

import ast
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .. import deploysurface as ds
from ..framework import Checker, Finding, ModuleInfo

_CLIENT_RECEIVERS = ("client", "api_reader")


def _norm(path: str) -> str:
    return path.replace("\\", "/")


def _receiver_name(func: ast.Attribute) -> str:
    node = func.value
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _is_client_receiver(name: str) -> bool:
    return name in _CLIENT_RECEIVERS or name.endswith("_client")


def _str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _scope_split(body: Sequence[ast.stmt]) -> Tuple[List[ast.AST], List[ast.AST]]:
    """Walk a scope's statements, NOT descending into nested def/async def
    (those are their own scopes, returned separately). Lambdas stay in the
    enclosing scope — `retry_on_conflict(lambda: client.update(nb))` must
    resolve against the enclosing bindings."""
    nodes: List[ast.AST] = []
    nested: List[ast.AST] = []
    stack: List[ast.AST] = list(body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            nested.append(n)
            continue
        nodes.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return nodes, nested


class RbacCoverageChecker(Checker):
    """Manager client traffic ⊆ declared RBAC, and declared RBAC ⊆ traffic."""

    name = "rbac-coverage"

    def __init__(self) -> None:
        # (group, resource, verb) -> first (path, line) exercising it
        self._usage: Dict[Tuple[str, str, str], Tuple[str, int]] = {}
        # verbs issued at call sites whose kind stayed dynamic
        self._dynamic_verbs: Set[str] = set()
        # (group, resource) -> (path, line) of the generator rule literal
        self._rule_sites: Dict[Tuple[str, str], Tuple[str, int]] = {}
        self._manifests_scanned = False
        self._reported: Set[Tuple[str, str, str, str]] = set()
        # test/CLI hooks: a --deploy-surface artifact (set of 4-tuples), a
        # synthetic RBAC table, and a gate override for fixture runs
        self.surface: Optional[Set[Tuple[str, str, str, str]]] = None
        self.rbac_override: Optional[Dict[Tuple[str, str], Any]] = None
        self.force_stale = False

    def _granted(self) -> Dict[Tuple[str, str], Any]:
        if self.rbac_override is not None:
            return self.rbac_override
        return ds.declared_rbac()

    # -- generator harvest (stale findings anchor at the rule literal) --

    def _harvest_rules(self, module: ModuleInfo) -> None:
        self._manifests_scanned = True
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Dict):
                continue
            keys = {_str_const(k) for k in node.keys}
            if not {"apiGroups", "resources", "verbs"} <= keys:
                continue
            try:
                rule = ast.literal_eval(node)
            except (ValueError, SyntaxError):
                continue
            for group in rule.get("apiGroups", []):
                for resource in rule.get("resources", []):
                    self._rule_sites.setdefault(
                        (group, resource), (module.path, node.lineno)
                    )

    # -- kind resolution --

    @staticmethod
    def _method_returns(tree: ast.AST) -> Dict[str, Set[str]]:
        """helper name -> kinds it returns via `return Cls(...)` — resolves
        the extension.py `self._create(self._rolebinding(...))` idiom."""
        out: Dict[str, Set[str]] = {}
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for sub in ast.walk(node):
                if not (isinstance(sub, ast.Return) and isinstance(sub.value, ast.Call)):
                    continue
                f = sub.value.func
                if isinstance(f, ast.Name) and f.id in ds.KIND_RESOURCES:
                    out.setdefault(node.name, set()).add(f.id)
        return out

    def _wrapper_methods(self, tree: ast.AST) -> Dict[str, List[Tuple[str, int]]]:
        """helper name -> [(client method, param index)] for helpers that
        forward a parameter straight into a client call (`def _create(self,
        obj): ... self.client.create(obj)`) — the call SITE carries the kind."""
        out: Dict[str, List[Tuple[str, int]]] = {}
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            params = [a.arg for a in node.args.args if a.arg != "self"]
            if not params:
                continue
            for sub in ast.walk(node):
                if not (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in ds.CLIENT_VERBS
                    and _is_client_receiver(_receiver_name(sub.func))
                    and sub.args
                    and isinstance(sub.args[0], ast.Name)
                    and sub.args[0].id in params
                ):
                    continue
                out.setdefault(node.name, []).append(
                    (sub.func.attr, params.index(sub.args[0].id))
                )
        return out

    def _expr_kinds(
        self,
        node: ast.AST,
        env: Dict[str, Set[str]],
        returns: Dict[str, Set[str]],
    ) -> Set[str]:
        if isinstance(node, ast.Name):
            if node.id in ds.KIND_RESOURCES:
                return {node.id}
            return set(env.get(node.id, ()))
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name):
                if f.id in ds.KIND_RESOURCES:
                    return {f.id}
                if f.id in returns:
                    return set(returns[f.id])
            if isinstance(f, ast.Attribute):
                if f.attr == "deepcopy" and node.args:
                    return self._expr_kinds(node.args[0], env, returns)
                if (
                    f.attr in ("get", "list")
                    and _is_client_receiver(_receiver_name(f))
                    and node.args
                ):
                    return self._expr_kinds(node.args[0], env, returns)
                if f.attr in returns:
                    return set(returns[f.attr])
        return set()

    def _bindings(
        self,
        nodes: Iterable[ast.AST],
        env: Dict[str, Set[str]],
        returns: Dict[str, Set[str]],
    ) -> None:
        for n in nodes:
            if isinstance(n, ast.Assign) and len(n.targets) == 1:
                t = n.targets[0]
                if isinstance(t, ast.Name):
                    kinds = self._expr_kinds(n.value, env, returns)
                    if kinds:
                        env.setdefault(t.id, set()).update(kinds)
            elif isinstance(n, ast.AnnAssign) and isinstance(n.target, ast.Name):
                if (
                    isinstance(n.annotation, ast.Name)
                    and n.annotation.id in ds.KIND_RESOURCES
                ):
                    env.setdefault(n.target.id, set()).add(n.annotation.id)
            elif isinstance(n, ast.For):
                if isinstance(n.target, ast.Name):
                    kinds = self._expr_kinds(n.iter, env, returns)
                    if kinds:
                        env.setdefault(n.target.id, set()).update(kinds)
                elif isinstance(n.target, ast.Tuple) and isinstance(
                    n.iter, (ast.Tuple, ast.List)
                ):
                    # `for cls, ns, name in ((Service, ...), (ConfigMap, ...))`
                    for j, elt in enumerate(n.target.elts):
                        if not isinstance(elt, ast.Name):
                            continue
                        for row in n.iter.elts:
                            if isinstance(row, (ast.Tuple, ast.List)) and j < len(
                                row.elts
                            ):
                                cell = row.elts[j]
                                if (
                                    isinstance(cell, ast.Name)
                                    and cell.id in ds.KIND_RESOURCES
                                ):
                                    env.setdefault(elt.id, set()).add(cell.id)

    # -- per-module pass --

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        path = _norm(module.path)
        if path.endswith("deploy/manifests.py"):
            self._harvest_rules(module)
        if not ds.is_manager_module(path):
            return []
        findings: List[Finding] = []
        returns = self._method_returns(module.tree)
        wrappers = self._wrapper_methods(module.tree)
        self._scope(
            module.tree.body, {}, module, returns, wrappers, set(), findings
        )
        return findings

    def _scope(
        self,
        body: Sequence[ast.stmt],
        env: Dict[str, Set[str]],
        module: ModuleInfo,
        returns: Dict[str, Set[str]],
        wrappers: Dict[str, List[Tuple[str, int]]],
        wrapper_params: Set[str],
        findings: List[Finding],
    ) -> None:
        nodes, nested = _scope_split(body)
        self._bindings(nodes, env, returns)
        for n in nodes:
            if isinstance(n, ast.Call):
                self._handle_call(
                    n, env, module, returns, wrappers, wrapper_params, findings
                )
        for fn in nested:
            assert isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
            fenv = dict(env)
            params = {a.arg for a in fn.args.args if a.arg != "self"}
            for a in fn.args.args:
                if (
                    a.annotation is not None
                    and isinstance(a.annotation, ast.Name)
                    and a.annotation.id in ds.KIND_RESOURCES
                ):
                    fenv.setdefault(a.arg, set()).add(a.annotation.id)
            fw = params if fn.name in wrappers else set()
            self._scope(fn.body, fenv, module, returns, wrappers, fw, findings)

    def _handle_call(
        self,
        call: ast.Call,
        env: Dict[str, Set[str]],
        module: ModuleInfo,
        returns: Dict[str, Set[str]],
        wrappers: Dict[str, List[Tuple[str, int]]],
        wrapper_params: Set[str],
        findings: List[Finding],
    ) -> None:
        f = call.func
        if not isinstance(f, ast.Attribute):
            if (  # module-level wrapper called by bare name
                isinstance(f, ast.Name) and f.id in wrappers
            ):
                for method, pidx in wrappers[f.id]:
                    arg = call.args[pidx] if pidx < len(call.args) else None
                    kinds = (
                        self._expr_kinds(arg, env, returns) if arg is not None else set()
                    )
                    self._record(method, kinds, module, call.lineno, findings)
            return
        # informer registration: .for_/.owns/.watches(Cls) = get+list+watch
        if f.attr in ds.WATCH_METHODS and call.args:
            kinds = self._expr_kinds(call.args[0], env, returns)
            for verb in ds.WATCH_VERBS:
                self._record(verb, kinds, module, call.lineno, findings)
            return
        if f.attr in wrappers and not _is_client_receiver(_receiver_name(f)):
            for method, pidx in wrappers[f.attr]:
                arg = call.args[pidx] if pidx < len(call.args) else None
                kinds = (
                    self._expr_kinds(arg, env, returns) if arg is not None else set()
                )
                self._record(method, kinds, module, call.lineno, findings)
            return
        if f.attr not in ds.CLIENT_VERBS:
            return
        if not _is_client_receiver(_receiver_name(f)):
            return
        if not call.args:
            return
        arg = call.args[0]
        if isinstance(arg, ast.Name) and arg.id in wrapper_params:
            # this IS a wrapper body's forwarding call; its sites carry the kind
            return
        kinds = self._expr_kinds(arg, env, returns)
        self._record(f.attr, kinds, module, call.lineno, findings)

    def _record(
        self,
        method: str,
        kinds: Set[str],
        module: ModuleInfo,
        line: int,
        findings: List[Finding],
    ) -> None:
        verb_sub = ds.CLIENT_VERBS.get(method)
        verb = verb_sub[0] if verb_sub else method
        if not kinds:
            self._dynamic_verbs.add(verb)
            return
        granted = self._granted()
        for kind in sorted(kinds):
            req = ds.required_rbac(method if verb_sub else "get", kind)
            if verb_sub is None:
                req = (ds.KIND_RESOURCES[kind][0], ds.KIND_RESOURCES[kind][1], verb)
            if req is None:
                continue
            group, resource, v = req
            self._usage.setdefault((group, resource, v), (module.path, line))
            if v in granted.get((group, resource), ()):
                continue
            key = (module.path, group, resource, v)
            if key in self._reported:
                continue
            self._reported.add(key)
            findings.append(
                Finding(
                    self.name,
                    module.path,
                    line,
                    f"issues {method} {kind} but verb {v!r} on "
                    f"{group or 'core'}/{resource} is not granted to the "
                    "manager ServiceAccount (deploy/manifests.py "
                    "cluster_role()) — grant it or move the call off the "
                    "manager's identity",
                )
            )

    # -- stale direction --

    def finish(self) -> Iterable[Finding]:
        if not (self._manifests_scanned or self.force_stale):
            return []
        findings: List[Finding] = []
        surface_resources = (
            ds.exercised_resources_from_surface(self.surface)
            if self.surface is not None
            else None
        )
        for (group, resource), verbs in sorted(self._granted().items()):
            if (group, resource) in ds.RBAC_EXEMPTIONS:
                continue
            if any((group, resource, v) in self._usage for v in verbs):
                continue
            if surface_resources is not None and (group, resource) in surface_resources:
                continue
            dyn = set(verbs) & self._dynamic_verbs
            if dyn and surface_resources is None:
                # a dynamic-kind call could exercise it; only a runtime
                # surface artifact (--deploy-surface) can settle that
                continue
            path, line = self._rule_sites.get(
                (group, resource), ("odh_kubeflow_tpu/deploy/manifests.py", 1)
            )
            confidence = (
                " (runtime surface artifact confirms: never exercised)"
                if surface_resources is not None
                else ""
            )
            findings.append(
                Finding(
                    self.name,
                    path,
                    line,
                    f"stale RBAC: rule grants {sorted(verbs)} on "
                    f"{group or 'core'}/{resource} but no manager code "
                    f"exercises it{confidence} — drop the rule or add a "
                    "reviewed exemption in analysis/deploysurface.py",
                )
            )
        return findings


class CrdSchemaDriftChecker(Checker):
    """deploy/crdgen.py output == committed deploy/base/manifests.yaml CRDs."""

    name = "crd-schema-drift"
    MAX_PATHS_PER_CRD = 12

    def __init__(self) -> None:
        self._crdgen_path: Optional[str] = None
        # test hook: point at a doctored committed tree
        self.manifests_path: Optional[str] = None

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        if _norm(module.path).endswith("deploy/crdgen.py"):
            self._crdgen_path = module.path
        return []

    @classmethod
    def _diff(cls, want: Any, got: Any, prefix: str, out: List[str]) -> None:
        if len(out) >= cls.MAX_PATHS_PER_CRD:
            return
        if isinstance(want, dict) and isinstance(got, dict):
            for key in sorted(set(want) | set(got)):
                p = f"{prefix}.{key}" if prefix else str(key)
                if key not in got:
                    out.append(f"{p}: missing from committed manifest")
                elif key not in want:
                    out.append(f"{p}: only in committed manifest")
                else:
                    cls._diff(want[key], got[key], p, out)
                if len(out) >= cls.MAX_PATHS_PER_CRD:
                    return
        elif isinstance(want, list) and isinstance(got, list):
            if len(want) != len(got):
                out.append(f"{prefix}: {len(want)} generated vs {len(got)} committed entries")
                return
            for i, (w, g) in enumerate(zip(want, got)):
                cls._diff(w, g, f"{prefix}[{i}]", out)
                if len(out) >= cls.MAX_PATHS_PER_CRD:
                    return
        elif want != got:
            out.append(f"{prefix}: generated {want!r} vs committed {got!r}")

    def finish(self) -> Iterable[Finding]:
        if self._crdgen_path is None:
            return []
        import yaml

        import odh_kubeflow_tpu.deploy as deploy_pkg
        from ...deploy.crdgen import (
            inference_endpoint_crd,
            notebook_crd,
            tpu_job_crd,
        )

        # the committed tree lives at the REPO root (deploy/base/...), not
        # inside the package — ci/build_manifests.sh generates it there
        repo_root = Path(deploy_pkg.__file__).resolve().parent.parent.parent
        manifests = Path(
            self.manifests_path
            or repo_root / "deploy" / "base" / "manifests.yaml"
        )
        findings: List[Finding] = []
        anchor = self._crdgen_path
        if not manifests.exists():
            return [
                Finding(
                    self.name,
                    anchor,
                    1,
                    f"committed manifest tree missing: {manifests} — run "
                    "python -m odh_kubeflow_tpu.deploy generate --root deploy",
                )
            ]
        committed = {
            doc["metadata"]["name"]: doc
            for doc in yaml.safe_load_all(manifests.read_text())
            if isinstance(doc, dict)
            and doc.get("kind") == "CustomResourceDefinition"
        }
        generated = {
            crd["metadata"]["name"]: crd
            for crd in (notebook_crd(), inference_endpoint_crd(), tpu_job_crd())
        }
        for name in sorted(set(generated) | set(committed)):
            if name not in committed:
                findings.append(
                    Finding(
                        self.name,
                        anchor,
                        1,
                        f"CRD {name} is generated by crdgen but absent from "
                        f"{manifests} — regenerate the deploy tree",
                    )
                )
                continue
            if name not in generated:
                findings.append(
                    Finding(
                        self.name,
                        anchor,
                        1,
                        f"CRD {name} is committed in {manifests} but no "
                        "crdgen function produces it",
                    )
                )
                continue
            diffs: List[str] = []
            self._diff(generated[name], committed[name], "", diffs)
            for d in diffs:
                findings.append(
                    Finding(
                        self.name,
                        anchor,
                        1,
                        f"CRD {name} drifted from the api/ dataclasses: {d} "
                        "— regenerate with python -m odh_kubeflow_tpu.deploy "
                        "generate --root deploy",
                    )
                )
        return findings


class EnvContractChecker(Checker):
    """Every os.environ read resolves to a declared ENV_CONTRACT knob."""

    name = "env-contract"

    def __init__(self) -> None:
        self._reads: Dict[str, Tuple[str, int]] = {}  # name -> first site
        self._config_path: Optional[str] = None
        self._knob_lines: Dict[str, int] = {}
        # test hooks
        self.declared_override: Optional[Dict[str, Any]] = None
        self.manifest_names_override: Optional[Set[str]] = None
        self.force_finish = False

    def _declared(self) -> Dict[str, Any]:
        if self.declared_override is not None:
            return self.declared_override
        return ds.declared_env()

    def _manifest_names(self) -> Set[str]:
        if self.manifest_names_override is not None:
            return set(self.manifest_names_override)
        return set(ds.manifest_env_names())

    # -- env-read extraction --

    @staticmethod
    def _is_os_environ(node: ast.AST) -> bool:
        return (
            isinstance(node, ast.Attribute)
            and node.attr == "environ"
            and isinstance(node.value, ast.Name)
            and node.value.id == "os"
        )

    @classmethod
    def _aliases_environ(cls, node: ast.AST) -> bool:
        """Is this assignment VALUE the environ mapping itself (`os.environ`,
        `environ if ... else os.environ`, `environ or os.environ`)? A call
        RESULT like `os.environ.get(...)` is a plain string, not an alias."""
        if cls._is_os_environ(node):
            return True
        if isinstance(node, ast.IfExp):
            return cls._aliases_environ(node.body) or cls._aliases_environ(node.orelse)
        if isinstance(node, ast.BoolOp):
            return any(cls._aliases_environ(v) for v in node.values)
        return False

    def _module_reads(self, module: ModuleInfo) -> List[Tuple[str, int]]:
        tree = module.tree
        aliases: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name) and self._aliases_environ(node.value):
                    aliases.add(t.id)

        def env_receiver(node: ast.AST) -> bool:
            return self._is_os_environ(node) or (
                isinstance(node, ast.Name) and node.id in aliases
            )

        reads: List[Tuple[str, int]] = []
        # wrapper name -> param names whose value is the env key
        wrappers: Dict[str, Set[str]] = {}

        def key_exprs(node: ast.AST) -> Iterable[Tuple[ast.AST, int]]:
            """(key expression, line) of every env read under `node`."""
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    f = sub.func
                    if (
                        isinstance(f, ast.Attribute)
                        and f.attr in ("get", "setdefault")
                        and env_receiver(f.value)
                        and sub.args
                    ):
                        yield sub.args[0], sub.lineno
                    elif (
                        isinstance(f, ast.Attribute)
                        and f.attr == "getenv"
                        and isinstance(f.value, ast.Name)
                        and f.value.id == "os"
                        and sub.args
                    ):
                        yield sub.args[0], sub.lineno
                elif isinstance(sub, ast.Subscript) and env_receiver(sub.value):
                    key = sub.slice
                    if isinstance(key, ast.Index):  # pragma: no cover (py<3.9)
                        key = key.value  # type: ignore[attr-defined]
                    yield key, sub.lineno
                elif (
                    isinstance(sub, ast.Compare)
                    and len(sub.ops) == 1
                    and isinstance(sub.ops[0], (ast.In, ast.NotIn))
                    and len(sub.comparators) == 1
                    and env_receiver(sub.comparators[0])
                ):
                    yield sub.left, sub.lineno

        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            params = {a.arg for a in node.args.args}
            for key, _line in key_exprs(node):
                if isinstance(key, ast.Name) and key.id in params:
                    wrappers.setdefault(node.name, set()).add(key.id)

        for key, line in key_exprs(tree):
            name = _str_const(key)
            if name is not None:
                reads.append((name, line))
            # non-literal keys that aren't wrapper params are a documented
            # blind spot; DEPLOYGUARD has no env analog, so keep them rare

        # literal call sites of env-key wrappers (_env_bool("DEV", ...))
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)):
                continue
            wrapper = wrappers.get(node.func.id)
            if not wrapper or not node.args:
                continue
            name = _str_const(node.args[0])
            if name is not None:
                reads.append((name, node.lineno))
        return reads

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        path = _norm(module.path)
        if path.endswith("controllers/config.py"):
            self._config_path = module.path
            for node in ast.walk(module.tree):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "EnvKnob"
                ):
                    name = None
                    if node.args:
                        name = _str_const(node.args[0])
                    for kw in node.keywords:
                        if kw.arg == "name":
                            name = _str_const(kw.value)
                    if name:
                        self._knob_lines[name] = node.lineno
        findings: List[Finding] = []
        declared = self._declared()
        seen_here: Set[str] = set()
        for name, line in self._module_reads(module):
            self._reads.setdefault(name, (module.path, line))
            if name in declared or name in seen_here:
                continue
            seen_here.add(name)
            findings.append(
                Finding(
                    self.name,
                    module.path,
                    line,
                    f"os.environ read of {name!r} is not declared in "
                    "ENV_CONTRACT (controllers/config.py) — declare the knob "
                    "(name, default, consumer, doc) or drop the read",
                )
            )
        return findings

    def finish(self) -> Iterable[Finding]:
        if self._config_path is None and not self.force_finish:
            return []
        findings: List[Finding] = []
        anchor = self._config_path or "odh_kubeflow_tpu/controllers/config.py"
        declared = self._declared()
        manifest_names = self._manifest_names()
        for name, knob in sorted(declared.items()):
            line = self._knob_lines.get(name, 1)
            if name not in self._reads:
                findings.append(
                    Finding(
                        self.name,
                        anchor,
                        line,
                        f"dead knob: ENV_CONTRACT declares {name!r} but "
                        "nothing in the package reads it — drop the entry or "
                        "wire the consumer",
                    )
                )
            if getattr(knob, "manifest", False) and name not in manifest_names:
                findings.append(
                    Finding(
                        self.name,
                        anchor,
                        line,
                        f"knob {name!r} is declared manifest=True but the "
                        "generated Deployment env stanza / culler ConfigMap "
                        "(deploy/manifests.py) does not carry it",
                    )
                )
        for name in sorted(manifest_names - set(declared)):
            findings.append(
                Finding(
                    self.name,
                    anchor,
                    1,
                    f"generated manifests ship env {name!r} but ENV_CONTRACT "
                    "does not declare it — the deployed knob would be dead "
                    "on arrival",
                )
            )
        return findings


class FlowSchemaCoverageChecker(Checker):
    """Entered flows classify non-default; declared flows are entered;
    served webhook paths match the generated registration."""

    name = "flow-schema-coverage"

    def __init__(self) -> None:
        self._entered: Dict[str, Tuple[str, int]] = {}
        self._declared_flow_lines: Dict[str, int] = {}
        self._flowcontrol_path: Optional[str] = None
        self._main_scanned = False
        self._served_paths: Dict[str, Tuple[str, int]] = {}
        self._fc = None
        # test hooks
        self.webhook_paths_override: Optional[Set[str]] = None

    def _controller(self):
        if self._fc is None:
            from ...cluster.flowcontrol import FlowController

            self._fc = FlowController()
        return self._fc

    def _declared_webhook_paths(self) -> Set[str]:
        if self.webhook_paths_override is not None:
            return set(self.webhook_paths_override)
        return set(ds.declared_webhook_paths())

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        path = _norm(module.path)
        findings: List[Finding] = []
        if path.endswith("cluster/flowcontrol.py"):
            self._flowcontrol_path = module.path
            for node in ast.walk(module.tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "FlowSchema"
                ):
                    continue
                exprs: List[ast.AST] = []
                for kw in node.keywords:
                    if kw.arg == "flows":
                        exprs.append(kw.value)
                for e in exprs:
                    if isinstance(e, (ast.Tuple, ast.List)):
                        for elt in e.elts:
                            name = _str_const(elt)
                            if name:
                                self._declared_flow_lines.setdefault(
                                    name, elt.lineno
                                )
            return findings
        if path.endswith("odh_kubeflow_tpu/main.py"):
            self._main_scanned = True
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Attribute)
                    and node.targets[0].attr == "flow"
                ):
                    # `elector_client.flow = LEADER_ELECTION_FLOW` — a
                    # per-client flow override is an entry point too
                    name = _str_const(node.value)
                    if name is None and isinstance(node.value, ast.Name):
                        if node.value.id == "LEADER_ELECTION_FLOW":
                            name = "leader-election"
                    if name:
                        self._entered.setdefault(name, (module.path, node.lineno))
                continue
            f = node.func
            flow_name: Optional[str] = None
            if (
                isinstance(f, ast.Attribute)
                and f.attr == "builder"
                and node.args
            ):
                flow_name = _str_const(node.args[0])
            elif (
                (isinstance(f, ast.Name) and f.id == "flow_context")
                or (isinstance(f, ast.Attribute) and f.attr == "flow_context")
            ) and node.args:
                flow_name = _str_const(node.args[0])
            if flow_name:
                self._entered.setdefault(flow_name, (module.path, node.lineno))
                level = self._controller().classify(flow_name)
                if level.name == "default":
                    findings.append(
                        Finding(
                            self.name,
                            module.path,
                            node.lineno,
                            f"flow {flow_name!r} enters flow_context but "
                            "classifies onto the default PriorityLevel — add "
                            "it to a FlowSchema in cluster/flowcontrol.py so "
                            "overload sheds it deliberately",
                        )
                    )
                continue
            if (
                isinstance(f, ast.Attribute)
                and f.attr == "register"
                and node.args
            ):
                served = _str_const(node.args[0])
                if served and served.startswith(("/mutate", "/validate")):
                    self._served_paths.setdefault(served, (module.path, node.lineno))
                    if served not in self._declared_webhook_paths():
                        findings.append(
                            Finding(
                                self.name,
                                module.path,
                                node.lineno,
                                f"webhook path {served!r} is served but absent "
                                "from the generated "
                                "MutatingWebhookConfiguration "
                                "(deploy/manifests.py) — the API server would "
                                "never call it",
                            )
                        )
        return findings

    def finish(self) -> Iterable[Finding]:
        findings: List[Finding] = []
        if self._flowcontrol_path is not None:
            for name, line in sorted(self._declared_flow_lines.items()):
                if name not in self._entered:
                    findings.append(
                        Finding(
                            self.name,
                            self._flowcontrol_path,
                            line,
                            f"FlowSchema names flow {name!r} but nothing "
                            "enters it (no builder/flow_context/client.flow "
                            "site) — stale schema or a controller missing "
                            "its flow identity",
                        )
                    )
        if self._main_scanned:
            for path in sorted(self._declared_webhook_paths() - set(self._served_paths)):
                findings.append(
                    Finding(
                        self.name,
                        "odh_kubeflow_tpu/main.py",
                        1,
                        f"generated MutatingWebhookConfiguration points at "
                        f"{path!r} but no server.register() serves it — CR "
                        "writes would fail closed (failurePolicy: Fail)",
                    )
                )
        return findings


def make_deploylint_checkers() -> List[Checker]:
    return [
        RbacCoverageChecker(),
        CrdSchemaDriftChecker(),
        EnvContractChecker(),
        FlowSchemaCoverageChecker(),
    ]
