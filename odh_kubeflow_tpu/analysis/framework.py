"""Checker framework: module loading, pragma suppression, finding report.

One `ast.parse` per module, shared by every checker; checkers are small
classes with a per-module `check()` and an optional cross-module `finish()`
(the lock-order graph needs the whole package before it can report cycles).

Suppression is comment-driven so exceptions live next to the code they
excuse:

    self._handlers.append(handler)  # lint: disable=lock-discipline

- ``# lint: disable=<check>[,<check>...]`` suppresses those checks on that
  physical line (the line a finding is reported at).
- ``# lint: disable-file=<check>`` anywhere in the file suppresses the check
  for the whole module (used for fixture files that exist to be ugly).
- ``all`` matches every check.
"""
from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

_PRAGMA_RE = re.compile(r"#\s*lint:\s*disable(?P<scope>-file)?\s*=\s*(?P<checks>[A-Za-z0-9_,\- ]+)")


@dataclass(frozen=True)
class Finding:
    check: str
    path: str  # repo-relative where possible (stable in CI output)
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.check}] {self.message}"


@dataclass
class ModuleInfo:
    path: str
    source: str
    tree: ast.AST
    # physical line -> set of check names disabled on that line
    line_pragmas: Dict[int, Set[str]] = field(default_factory=dict)
    file_pragmas: Set[str] = field(default_factory=set)

    @classmethod
    def parse(cls, path: str, source: Optional[str] = None) -> "ModuleInfo":
        if source is None:
            source = Path(path).read_text()
        tree = ast.parse(source, filename=path)
        info = cls(path=path, source=source, tree=tree)
        # pragmas come from real COMMENT tokens only — a regex over raw lines
        # would arm suppressions written inside string literals/docstrings
        # (e.g. a fixture or log template containing the pragma text)
        try:
            tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
        except (tokenize.TokenError, IndentationError):  # pragma: no cover
            tokens = []  # ast.parse succeeded, so this is near-unreachable
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _PRAGMA_RE.search(tok.string)
            if not m:
                continue
            checks = {c.strip() for c in m.group("checks").split(",") if c.strip()}
            if m.group("scope"):
                info.file_pragmas |= checks
            else:
                info.line_pragmas.setdefault(tok.start[0], set()).update(checks)
        return info

    def suppressed(self, finding: Finding) -> bool:
        if {"all", finding.check} & self.file_pragmas:
            return True
        on_line = self.line_pragmas.get(finding.line, set())
        return bool({"all", finding.check} & on_line)


class Checker:
    """Base checker: subclass, set `name`, implement `check(module)`."""

    name = "checker"

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        raise NotImplementedError

    def finish(self) -> Iterable[Finding]:
        """Cross-module findings, after every module has been checked."""
        return ()


def all_checkers() -> List[Checker]:
    """Fresh instances of every registered checker (stateful finish() passes
    must not leak graph state between runs)."""
    from .checkers import make_checkers

    return make_checkers()


def _iter_py_files(root: Path) -> Iterable[Path]:
    for path in sorted(root.rglob("*.py")):
        if "__pycache__" in path.parts or "_native" in path.parts:
            continue
        yield path


def _iter_modules(paths: Sequence[str]) -> Iterator[ModuleInfo]:
    """Parse every .py under `paths` (files or directories), cwd-relative
    where possible — the ONE iteration both the analysis pass and the
    pragma budget share, so they can never scan different trees."""
    for p in paths:
        root = Path(p)
        files = [root] if root.is_file() else list(_iter_py_files(root))
        for f in files:
            try:
                rel = str(f.relative_to(Path.cwd()))
            except ValueError:
                rel = str(f)
            yield ModuleInfo.parse(rel)


def run_analysis(
    paths: Sequence[str],
    checkers: Optional[Sequence[Checker]] = None,
    include_suppressed: bool = False,
) -> List[Finding]:
    """Run checkers over every .py under `paths` (files or directories).

    Returns unsuppressed findings sorted by (path, line). Pass
    `include_suppressed=True` to audit what the pragmas are hiding."""
    checkers = list(checkers) if checkers is not None else all_checkers()
    findings: List[Finding] = []
    modules: List[ModuleInfo] = list(_iter_modules(paths))
    if not modules:
        # a mistyped path (or a runner invoked from the wrong cwd) must not
        # turn the lint gate into a vacuous green
        raise FileNotFoundError(
            f"analysis found no Python modules under {list(paths)!r} "
            f"(cwd: {Path.cwd()})"
        )
    for module in modules:
        for checker in checkers:
            for finding in checker.check(module):
                if include_suppressed or not module.suppressed(finding):
                    findings.append(finding)
    by_path = {m.path: m for m in modules}
    for checker in checkers:
        for finding in checker.finish():
            module = by_path.get(finding.path)
            if include_suppressed or module is None or not module.suppressed(finding):
                findings.append(finding)
    return sorted(findings, key=lambda f: (f.path, f.line, f.check))


def collect_pragmas(paths: Sequence[str]) -> Dict[Tuple[str, str], int]:
    """(path, check) -> pragma count over every module under `paths` — the
    pragma BUDGET the ci/analysis.sh gate holds against the committed
    allowlist. Counts are per-line-occurrence (a file pragma counts once):
    adding an unreviewed `# lint: disable` anywhere fails CI even when the
    file already had one for the same check."""
    out: Dict[Tuple[str, str], int] = {}
    for info in _iter_modules(paths):
        for checks in info.line_pragmas.values():
            for check in checks:
                out[(info.path, check)] = out.get((info.path, check), 0) + 1
        for check in info.file_pragmas:
            out[(info.path, check)] = out.get((info.path, check), 0) + 1
    return out


def render_pragma_allowlist(budget: Dict[Tuple[str, str], int]) -> str:
    lines = [
        "# Reviewed `# lint: disable` pragma budget (ci/analysis.sh gate).",
        "# Regenerate after a REVIEWED change with:",
        "#   python -m odh_kubeflow_tpu.analysis --pragma-update ci/pragma_allowlist.txt",
        "# format: path<TAB>check<TAB>count",
    ]
    for (path, check), count in sorted(budget.items()):
        lines.append(f"{path}\t{check}\t{count}")
    return "\n".join(lines) + "\n"


def parse_pragma_allowlist(text: str) -> Dict[Tuple[str, str], int]:
    out: Dict[Tuple[str, str], int] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.split("\t")
        if len(parts) != 3:
            raise ValueError(f"malformed allowlist line: {line!r}")
        out[(parts[0], parts[1])] = int(parts[2])
    return out


def pragma_budget_violations(
    budget: Dict[Tuple[str, str], int],
    allowlist: Dict[Tuple[str, str], int],
) -> List[str]:
    """New/expanded pragmas fail; shrinkage only nags (an overly-generous
    allowlist is stale, not dangerous)."""
    problems = []
    for (path, check), count in sorted(budget.items()):
        allowed = allowlist.get((path, check), 0)
        if count > allowed:
            problems.append(
                f"{path}: {count} `# lint: disable={check}` pragma(s), "
                f"allowlist permits {allowed} — a new suppression needs "
                "review (then --pragma-update)"
            )
    return problems


def run_on_source(
    source: str, checkers: Sequence[Checker], path: str = "<fixture>"
) -> List[Finding]:
    """Run checkers over an in-memory snippet — the test-fixture entry point."""
    module = ModuleInfo.parse(path, source=source)
    findings: List[Finding] = []
    for checker in checkers:
        for finding in checker.check(module):
            if not module.suppressed(finding):
                findings.append(finding)
    for checker in checkers:
        for finding in checker.finish():
            if not module.suppressed(finding):
                findings.append(finding)
    return sorted(findings, key=lambda f: (f.path, f.line, f.check))
