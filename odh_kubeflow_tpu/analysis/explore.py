"""Systematic interleaving explorer for the three state machines (ISSUE 8).

The dynamic half of the verification subsystem: drive the REAL controllers
(culling, suspend/resume, slice-repair — not models of them) one reconcile
at a time against an in-process Store, and let a deterministic DPOR-lite
scheduler enumerate interleavings of those steps with fault-injector ops
(host preemption/restore), a rival pool-CAS attempt, and the cluster model's
pod lifecycle — asserting the global invariants (utils/invcheck.py) after
EVERY store write and the steady-state contracts at quiescence.

Scheduler model (CHESS-style bounded search):

- every operation is atomic (one reconcile call / one fault op); the unit
  of interleaving is the operation, so intra-reconcile TOCTOU windows are
  deliberately out of scope — those are the RACECHECK lane's job,
- an op that runs without changing the store resourceVersion is QUIESCED
  until someone else makes progress; a leaf (fully-explored schedule) is
  reached when every repeatable op is quiesced and every one-shot op ran,
- switching away from an actor that still has work counts as a PREEMPTION;
  schedules are enumerated exhaustively within (max_depth, max_preemptions)
  and deduplicated by a normalized state hash (uids/resourceVersions/
  timestamps stripped), so permutations of no-op steps collapse,
- everything is seeded and wall-clock-free in its CONTROL FLOW (controller
  backoff windows are configured far past the run, retry jitter damped), so
  a violating schedule replays exactly and minimizes by greedy delta
  reduction — the finding ships the minimized interleaving trace.

Known-bad mutants (the explorer must be able to FAIL, or a green run means
nothing): `skip-checkpoint` suspends straight past the checkpoint window
(no checkpoint-saved evidence), `cas-blind` claims warm slices while
ignoring the lead-node CAS. `explore_mutant()` deterministically reproduces
each as a minimized trace; tests/test_explore.py pins both.
"""
# this module IS the verification harness: the scenario setup writes state
# annotations as premises, the unstop op models the user's kubectl patch,
# and the mutants exist to violate the machine contract on purpose — the
# machine-conformance checker must not police its own test bench
# lint: disable-file=machine-conformance
from __future__ import annotations

import copy
import hashlib
import itertools
import json
import random
import re
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..api.core import Container, Node, Pod
from ..api.notebook import Notebook, TPUSpec, TPUStatus
from ..apimachinery import Condition, NotFoundError
from ..cluster.slicepool import (
    POOL_CLAIMED_BY_ANNOTATION,
    POOL_PRIORITY_ANNOTATION,
    POOL_SINCE_ANNOTATION,
    POOL_STATE_ANNOTATION,
    POOL_STATE_WARM,
    SlicePool,
)
from ..cluster.store import Store
from ..controllers import constants as C
from ..controllers.config import Config
from ..controllers.culling import CullingReconciler
from ..controllers.slice_repair import SliceRepairController
from ..controllers.suspend import SuspendResumeController
from ..runtime.controller import Request
from ..runtime.manager import Manager
from ..tpu import (
    GKE_NODEPOOL_LABEL,
    GKE_TPU_ACCELERATOR_LABEL,
    GKE_TPU_TOPOLOGY_LABEL,
    plan_slice,
)
from ..utils import invcheck

NS = "explore"
OLD_TS = "2020-01-01T00:00:00Z"

# control flow must not depend on wall clock: cull thresholds zero (always
# idle, always due), checkpoint windows generous (the fake agents ack on
# the first sweep, so windows close by all-acked, never by deadline), and
# retry/backoff deadlines far past any run (waiting states quiesce instead
# of burning attempts)
EXPLORE_CONFIG = Config(
    enable_culling=True,
    suspend_enabled=True,
    cull_idle_time_min=0.0,
    idleness_check_period_min=0.0,
    readiness_probe_period_s=1000.0,
    suspend_checkpoint_window_s=600.0,
    suspend_checkpoint_retries=0,
    suspend_checkpoint_backoff_s=0.0,
    resume_timeout_s=36000.0,
    resume_max_attempts=10,
    reclaim_pending_grace_s=0.0,
    checkpoint_window_s=600.0,
    repair_max_attempts=1000,
    repair_backoff_s=36000.0,
    repair_backoff_max_s=36000.0,
    # job machine (ISSUE 10): generous checkpoint window (acks instant, so
    # windows close by all-acked), cadence never fires on wall clock (the
    # preempt op is the only checkpoint trigger), requeue backoff off,
    # bind timeout far past the run (a threshold that can lapse mid-
    # exploration makes schedules irreproducible)
    job_checkpoint_window_s=600.0,
    job_requeue_backoff_s=0.0,
    job_admission_timeout_s=36000.0,
)


def fake_http_get(url: str, timeout: float = 0.0) -> Tuple[int, bytes]:
    """Deterministic in-pod agent: kernels idle since the epoch of boredom,
    TPU duty cycle zero, checkpoint hooks ack instantly."""
    if "/api/kernels" in url:
        body = [{"execution_state": "idle", "last_activity": OLD_TS}]
    elif "/api/terminals" in url:
        body = []
    elif "/tpu/utilization" in url:
        body = {"duty_cycle": 0.0, "last_busy": OLD_TS}
    elif "/tpu/checkpoint" in url:
        body = {"saved": True, "step": 100}
    else:
        body = {}
    return 200, json.dumps(body).encode()


# ---------------------------------------------------------------------------
# mutants — the seeded known-bad fixtures (ISSUE 8 satellite)
# ---------------------------------------------------------------------------


class SkipCheckpointSuspendController(SuspendResumeController):
    """MUTANT: the suspend transition skips the checkpoint window — straight
    checkpointing -> suspended with no /tpu/checkpoint sweep and no
    checkpoint-saved record. Violates the `checkpoint-before-suspend`
    invariant on the first suspend of a notebook with live ready hosts."""

    def _run_checkpoint_window(self, nb, shape, now, req):
        from ..apimachinery import rfc3339_precise

        pods = self._pods(nb)
        pool_name = self._slice_pool_of(pods)
        if pool_name and not nb.metadata.annotations.get(C.TPU_RECLAIM_ANNOTATION):
            self.pool.release(pool_name, self._pool_nodes(pool_name))
        self._patch_annotations(
            nb,
            {
                C.TPU_SUSPEND_STATE_ANNOTATION: "suspended",
                C.TPU_SUSPENDED_AT_ANNOTATION: rfc3339_precise(now),
                C.TPU_SUSPEND_CHECKPOINT_DEADLINE_ANNOTATION: None,
            },
        )
        return None


class CASBlindSlicePool(SlicePool):
    """MUTANT: pool claims ignore the lead-node CAS — a blind re-read-and-
    overwrite on Conflict, no expect_state guard. Two racing claimants both
    'win'; the second steals the first's claim, which the `pool-claim-cas`
    invariant catches at the stealing write."""

    def _stamp(self, node_name, updates, expect_state=SlicePool._ANY_STATE):
        from ..apimachinery import ConflictError

        for _ in range(3):
            try:
                node = self.client.get(Node, "", node_name)
            except NotFoundError:
                return False
            # BUG under test: no expect_state re-judge, conflicts ignored
            for key, value in updates.items():
                if value is None:
                    node.metadata.annotations.pop(key, None)
                else:
                    node.metadata.annotations[key] = value
            try:
                self.client.update(node)
                return True
            except ConflictError:
                continue
            except NotFoundError:
                return False
        return False

    def claim(self, gke_accelerator, topology, notebook_key):
        # ...and claims don't even require warm (the entries() filter is the
        # polite half of the contract this mutant discards)
        for entry in self.entries(include_unhealthy=True):
            if entry.accelerator != gke_accelerator or entry.topology != topology:
                continue
            for name in entry.nodes:
                self._stamp(name, {
                    POOL_STATE_ANNOTATION: "claimed",
                    POOL_CLAIMED_BY_ANNOTATION: notebook_key,
                })
            return entry
        return None


# ---------------------------------------------------------------------------
# the world: real controllers over a bare store, plus a deterministic
# cluster model standing in for scheduler/statefulset/kubelet
# ---------------------------------------------------------------------------


@dataclass
class Op:
    name: str
    fn: Callable[["World"], None]
    once: bool = False
    after: Optional[str] = None  # one-shot ordering (restore needs preempt)


class World:
    """One freshly-built scenario. Snapshot/restore is what makes DFS over
    interleavings affordable: store buckets are dicts of canonical-JSON
    strings and controller scratch state is a handful of small dicts."""

    def __init__(self, suspend_cls=SuspendResumeController,
                 pool_cls=None, chip_budget: int = 8):
        self.monitor = invcheck.Monitor(
            extra={
                "checkpoint-before-suspend":
                    invcheck.check_checkpoint_before_suspend,
            },
            collect=True,
            chip_budget=chip_budget,
        )
        # python backend: snapshot/restore reaches into _PyBucket._objs.
        # The collecting monitor is injected explicitly so an ambient
        # INVCHECK=1 cannot swap in a raising one mid-construction.
        self.store = Store(backend="python", invariants=self.monitor)
        self.manager = Manager(self.store, cached_reads=False)
        self.client = self.manager.client
        cfg = EXPLORE_CONFIG
        self.culler = CullingReconciler(self.manager, cfg, http_get=fake_http_get)
        self.suspend = suspend_cls(self.manager, cfg, http_get=fake_http_get)
        self.repair = SliceRepairController(self.manager, cfg, http_get=fake_http_get)
        self.repair.unreachable_dwell_s = 1e9  # taints only; no probe dwell
        if pool_cls is not None:
            self.suspend.pool = pool_cls(self.manager.client)
        self.rival_pool = (pool_cls or SlicePool)(self.manager.client)
        self.shape = plan_slice("v5e", "2x2", 0)
        self._setup()
        # the scenario's initial objects are a premise, not transitions —
        # anything the setup writes tripped is discarded before ops run
        self.monitor.reset()

    # ---------- initial scenario ----------

    def _add_node(self, name: str, pool: str) -> None:
        node = Node()
        node.metadata.name = name
        node.metadata.labels.update({
            GKE_NODEPOOL_LABEL: pool,
            GKE_TPU_ACCELERATOR_LABEL: self.shape.gke_accelerator,
            GKE_TPU_TOPOLOGY_LABEL: self.shape.topology,
        })
        node.status.capacity["google.com/tpu"] = str(self.shape.chips_per_host)
        node.status.conditions.append(Condition(type="Ready", status="True"))
        self.client.create(node)

    def _add_nb(self, name: str, annotations: Dict[str, str]) -> None:
        nb = Notebook()
        nb.metadata.name = name
        nb.metadata.namespace = NS
        nb.metadata.annotations.update(annotations)
        nb.spec.template.spec.containers = [Container(name=name, image="jax:1")]
        nb.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2")
        self.client.create(nb)

    def _setup(self) -> None:
        self._add_node("node-a", "pool-a")
        self._add_node("node-b", "pool-b")
        # nb1: active and idle on pool-a — the culler's next victim
        self._add_nb("nb1", {
            C.LAST_ACTIVITY_ANNOTATION: OLD_TS,
            C.LAST_ACTIVITY_CHECK_TIMESTAMP_ANNOTATION: OLD_TS,
        })
        self._bind("nb1", "node-a")
        # nb2: already suspended warm on pool-b — resumes when unstopped
        self._add_nb("nb2", {
            C.STOP_ANNOTATION: OLD_TS,
            C.TPU_SUSPEND_STATE_ANNOTATION: "suspended",
            C.TPU_SUSPENDED_AT_ANNOTATION: OLD_TS,
            C.TPU_CHECKPOINT_SAVED_ANNOTATION: "100",
        })
        node = self.client.get(Node, "", "node-b")
        node.metadata.annotations.update({
            POOL_STATE_ANNOTATION: POOL_STATE_WARM,
            POOL_SINCE_ANNOTATION: OLD_TS,
            POOL_PRIORITY_ANNOTATION: "0",
        })
        self.client.update(node)

    # ---------- cluster model (scheduler + statefulset + kubelet) ----------

    def _pods(self, name: str) -> List[Pod]:
        return [
            p for p in self.client.list(
                Pod, namespace=NS, labels={C.NOTEBOOK_NAME_LABEL: name}
            )
            if not p.metadata.deletion_timestamp
        ]

    def _node_free_for(self, node: Node, nb_key: str) -> bool:
        if any(t.get("key") for t in node.spec.get("taints", [])):
            return False
        if any(c.type == "Ready" and c.status == "False"
               for c in node.status.conditions):
            return False
        state = node.metadata.annotations.get(POOL_STATE_ANNOTATION)
        if state == POOL_STATE_WARM:
            return False  # reserved: the scheduler places nobody here
        if state == "claimed" and node.metadata.annotations.get(
                POOL_CLAIMED_BY_ANNOTATION) != nb_key:
            return False
        occupied = {
            p.spec.node_name
            for p in self.client.list(Pod)
            if p.spec.node_name and not p.metadata.deletion_timestamp
        }
        return node.metadata.name not in occupied

    def _bind(self, name: str, node_name: str) -> None:
        pod = Pod()
        pod.metadata.name = f"{name}-0"
        pod.metadata.namespace = NS
        pod.metadata.labels[C.NOTEBOOK_NAME_LABEL] = name
        pod.spec.node_name = node_name
        pod.status.phase = "Running"
        pod.status.conditions.append(Condition(type="Ready", status="True"))
        self.client.create(pod)
        self._mirror_status(name)

    def _mirror_status(self, name: str) -> None:
        try:
            nb = self.client.get(Notebook, NS, name)
        except NotFoundError:
            return
        pods = self._pods(name)
        ready = sum(1 for p in pods if p.is_ready())
        mesh = ready >= self.shape.hosts
        tpu = nb.status.tpu or TPUStatus()
        if (nb.status.ready_replicas, tpu.mesh_ready) == (ready, mesh):
            return
        tpu.mesh_ready = mesh
        tpu.chips_visible = self.shape.chips if mesh else 0
        nb.status.ready_replicas = ready
        nb.status.tpu = tpu
        if mesh:
            from ..controllers.conditions import upsert_condition

            upsert_condition(
                nb.status.conditions, C.TPU_HEALTHY_CONDITION, "True",
                "AllDevicesHealthy", "",
            )
        self.client.update_status(nb)

    def cluster_step(self, name: str) -> None:
        """One deterministic pass of the cluster side for one notebook:
        scale down a suspended slice, place/bind a wanted pod (honoring
        warm/claimed pool reservations), mirror pod facts into status."""
        try:
            nb = self.client.get(Notebook, NS, name)
        except NotFoundError:
            return
        ann = nb.metadata.annotations
        stopped = (
            C.STOP_ANNOTATION in ann
            and ann[C.STOP_ANNOTATION] != C.RECONCILIATION_LOCK_VALUE
        )
        state = ann.get(C.TPU_SUSPEND_STATE_ANNOTATION, "")
        # notebook.py's replica hold: checkpointing keeps the slice up
        desired = 0 if (stopped and state != "checkpointing") else 1
        pods = self._pods(name)
        if desired == 0:
            for p in pods:
                self.client.delete(Pod, NS, p.metadata.name)
            if pods:
                self._mirror_status(name)
            return
        if not pods:
            nb_key = f"{NS}/{name}"
            for node in sorted(self.client.list(Node),
                               key=lambda n: n.metadata.name):
                if self._node_free_for(node, nb_key):
                    self._bind(name, node.metadata.name)
                    return
            # no capacity: a pending pod is the reclaimer's pressure signal
            pod = Pod()
            pod.metadata.name = f"{name}-0"
            pod.metadata.namespace = NS
            pod.metadata.labels[C.NOTEBOOK_NAME_LABEL] = name
            self.client.create(pod)
            return
        pending = [p for p in pods if not p.spec.node_name]
        nb_key = f"{NS}/{name}"
        for p in pending:
            for node in sorted(self.client.list(Node),
                               key=lambda n: n.metadata.name):
                if self._node_free_for(node, nb_key):
                    p.spec.node_name = node.metadata.name
                    p = self.client.update(p)
                    # status is a subresource: Ready must land separately
                    p.status.phase = "Running"
                    p.status.conditions = [Condition(type="Ready", status="True")]
                    self.client.update_status(p)
                    break
        self._mirror_status(name)

    # ---------- fault / scripted ops ----------

    def preempt(self, node_name: str) -> None:
        from ..cluster.faults import PREEMPTION_TAINT_KEY

        node = self.client.get(Node, "", node_name)
        taints = node.spec.setdefault("taints", [])
        taints.append({"key": PREEMPTION_TAINT_KEY, "effect": "NoSchedule"})
        node = self.client.update(node)
        for cond in node.status.conditions:
            if cond.type == "Ready":
                cond.status = "False"
        self.client.update_status(node)

    def restore(self, node_name: str) -> None:
        node = self.client.get(Node, "", node_name)
        node.spec["taints"] = []
        node = self.client.update(node)
        for cond in node.status.conditions:
            if cond.type == "Ready":
                cond.status = "True"
        self.client.update_status(node)

    def unstop(self, name: str) -> None:
        self.client.patch(
            Notebook, NS, name,
            {"metadata": {"annotations": {C.STOP_ANNOTATION: None}}},
        )

    def rival_cas(self) -> None:
        """A racing resume: claim any matching slice, then abandon the bind
        and return it warm — the CAS-contention probe. With the honest pool
        the loser backs off cleanly; the cas-blind mutant steals instead."""
        entry = self.rival_pool.claim(
            self.shape.gke_accelerator, self.shape.topology, f"{NS}/rival"
        )
        if entry is not None:
            self.rival_pool.release(entry.pool, entry.nodes)

    # ---------- op table ----------

    def ops(self) -> List[Op]:
        def reconcile(ctrl, name):
            return lambda w: ctrl.reconcile(Request(namespace=NS, name=name))

        return [
            Op("cull-1", reconcile(self.culler, "nb1")),
            Op("suspend-1", reconcile(self.suspend, "nb1")),
            Op("suspend-2", reconcile(self.suspend, "nb2")),
            Op("repair-1", reconcile(self.repair, "nb1")),
            # one cluster actor (scheduler/sts/kubelet act as one serialized
            # control loop here — pod-level sub-interleavings are the
            # RACECHECK soaks' territory)
            Op("cluster", lambda w: (w.cluster_step("nb1"),
                                     w.cluster_step("nb2"))),
            Op("unstop-2", lambda w: w.unstop("nb2"), once=True),
            Op("preempt-a", lambda w: w.preempt("node-a"), once=True),
            Op("restore-a", lambda w: w.restore("node-a"), once=True,
               after="preempt-a"),
            Op("rival-cas", lambda w: w.rival_cas(), once=True),
        ]

    # ---------- snapshot / restore ----------

    def snapshot(self) -> dict:
        return {
            "buckets": {
                skey: dict(bucket._objs)
                for skey, bucket in self.store._objects.items()
            },
            "last_rv": self.store._last_rv,
            "violations": len(self.monitor.violations),
            "suspend": copy.deepcopy({
                "acked": self.suspend._ckpt_acked,
                "deadline": self.suspend._resume_deadline,
                "cooldown": self.suspend._victim_cooldown,
                "sweep": self.suspend._last_sweep,
            }),
            "repair": copy.deepcopy({
                "seen": self.repair._last_seen,
                "next": self.repair._next_attempt,
                "evicted": self.repair._evicted_at,
                "acked": self.repair._ckpt_acked,
                "in_repair": self.repair._in_repair,
            }),
        }

    def restore_snapshot(self, snap: dict) -> None:
        self.store._objects = {}
        for skey, objs in snap["buckets"].items():
            bucket = self.store._bucket(*skey)
            bucket._objs = dict(objs)
        self.store._last_rv = snap["last_rv"]
        self.store._rv = itertools.count(snap["last_rv"] + 1)
        self.store._history.clear()
        self.store._history_dropped_rv.clear()
        # resourceVersions are REUSED across sibling branches after a
        # restore, so an rv-keyed hash from another branch would lie
        self._hash_cache = (-1, "")
        del self.monitor.violations[snap["violations"]:]
        s = copy.deepcopy(snap["suspend"])
        self.suspend._ckpt_acked = s["acked"]
        self.suspend._resume_deadline = s["deadline"]
        self.suspend._victim_cooldown = s["cooldown"]
        self.suspend._last_sweep = s["sweep"]
        r = copy.deepcopy(snap["repair"])
        self.repair._last_seen = r["seen"]
        self.repair._next_attempt = r["next"]
        self.repair._evicted_at = r["evicted"]
        self.repair._ckpt_acked = r["acked"]
        self.repair._in_repair = r["in_repair"]

    # ---------- normalized state hash ----------

    def scratch_token(self) -> Tuple:
        """Controller in-memory scratch, normalized for the memo key: two
        schedules are only equivalent when the controllers REMEMBER the
        same things, not just when the store matches (a parked backoff
        deadline vs none changes what the next reconcile does). Wall-clock
        floats reduce to presence — within a run every deadline is either
        unset or far-future by construction. (repair._last_seen is
        deliberately absent: it only feeds the goodput integrator, never a
        branch.)"""
        def keyed(d: Dict) -> Tuple:
            return tuple(sorted(d))

        return (
            tuple(sorted(
                (k, tuple(sorted(v.items())))
                for k, v in self.suspend._ckpt_acked.items()
            )),
            keyed(self.suspend._resume_deadline),
            keyed(self.suspend._victim_cooldown),
            bool(self.suspend._last_sweep),
            tuple(sorted(
                (k, tuple(sorted(v.items())))
                for k, v in self.repair._ckpt_acked.items()
            )),
            keyed(self.repair._next_attempt),
            keyed(self.repair._evicted_at),
            tuple(sorted(self.repair._in_repair)),
        )

    _TS_RE = re.compile(
        r"\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}(\.\d+)?(Z|\+00:00)?|\d+\.\d+"
    )
    _hash_cache: Tuple[int, str] = (-1, "")

    def state_hash(self) -> str:
        # the store is the only hashed state, so the hash is valid as long
        # as the last resourceVersion is (no-op drains re-use it for free)
        if self._hash_cache[0] == self.store._last_rv:
            return self._hash_cache[1]
        view = []
        for (av, kind) in sorted(self.store._objects):
            if kind == "Event":
                continue  # dedup counters/timestamps, not machine state
            for key, raw in sorted(self.store._objects[(av, kind)]._objs.items()):
                obj = json.loads(raw)
                meta = obj.get("metadata", {})
                for f in ("uid", "resourceVersion", "creationTimestamp",
                          "generation"):
                    meta.pop(f, None)
                view.append((kind, key, obj))
        text = self._TS_RE.sub("<t>", json.dumps(view, sort_keys=True))
        digest = hashlib.sha256(text.encode()).hexdigest()
        self._hash_cache = (self.store._last_rv, digest)
        return digest


class JobWorld(World):
    """World + the third workload class (ISSUE 10): a batch TPUJob whose
    admission warm-claims the suspended nb2's slice, so nb2's resume is a
    pool miss that pressures the reclaimer into the job — the full
    job-vs-suspend-vs-reclaim interleaving space (warm-claim admission,
    checkpoint-before-preempt, requeue, re-admission) driven through the
    REAL TPUJobReconciler and the REAL reclaimer.

    `churn_ops` adds the base world's cull/suspend actors for nb1 on top —
    the full three-actor churn space (the slow tier; the tight default
    keeps nb1 as static occupancy so the tier-1 run exhausts in seconds)."""

    def __init__(self, churn_ops: bool = False, **kw):
        super().__init__(**kw)
        from ..controllers.job import TPUJobReconciler

        self.churn_ops = churn_ops
        self.job = TPUJobReconciler(
            self.manager, EXPLORE_CONFIG, http_get=fake_http_get
        )
        self._add_job("job1")

    def _add_job(self, name: str) -> None:
        from ..api.job import TPUJob

        job = TPUJob()
        job.metadata.name = name
        job.metadata.namespace = NS
        job.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2")
        job.spec.template.spec.containers = [
            Container(name=name, image="jax:1")
        ]
        job.spec.steps = 1000  # never completes inside a run (step acks 100)
        job.spec.checkpoint_period_s = 36000.0  # cadence never fires
        self.client.create(job)
        self.monitor.reset()  # premise, not a transition

    def job_cluster_step(self, name: str) -> None:
        """The cluster model's job half: one learner-gang pod keyed by the
        job state annotation, honoring warm/claimed pool reservations under
        the job's OWN claim key."""
        from ..api.job import TPUJob

        try:
            job = self.client.get(TPUJob, NS, name)
        except NotFoundError:
            return
        state = job.metadata.annotations.get(C.JOB_STATE_ANNOTATION, "")
        desired = 1 if state in ("admitted", "running", "checkpointing") \
            else 0
        pods = [
            p for p in self.client.list(
                Pod, namespace=NS, labels={C.JOB_NAME_LABEL: name}
            )
            if not p.metadata.deletion_timestamp
        ]
        if desired == 0:
            for p in pods:
                self.client.delete(Pod, NS, p.metadata.name)
            return
        job_key = f"{NS}/{name}"
        if not pods:
            pod = Pod()
            pod.metadata.name = f"{name}-{C.JOB_GANG_LEARNER}-0"
            pod.metadata.namespace = NS
            pod.metadata.labels[C.JOB_NAME_LABEL] = name
            pod.metadata.labels[C.JOB_GANG_LABEL] = C.JOB_GANG_LEARNER
            for node in sorted(self.client.list(Node),
                               key=lambda n: n.metadata.name):
                if self._node_free_for(node, job_key):
                    pod.spec.node_name = node.metadata.name
                    break
            self.client.create(pod)
            if pod.spec.node_name:
                placed = self.client.get(Pod, NS, pod.metadata.name)
                placed.status.phase = "Running"
                placed.status.conditions = [
                    Condition(type="Ready", status="True")
                ]
                self.client.update_status(placed)
            return
        for p in pods:
            if p.spec.node_name:
                continue
            for node in sorted(self.client.list(Node),
                               key=lambda n: n.metadata.name):
                if self._node_free_for(node, job_key):
                    p.spec.node_name = node.metadata.name
                    p = self.client.update(p)
                    p.status.phase = "Running"
                    p.status.conditions = [
                        Condition(type="Ready", status="True")
                    ]
                    self.client.update_status(p)
                    break

    def ops(self) -> List[Op]:
        def reconcile(ctrl, name):
            return lambda w: ctrl.reconcile(Request(namespace=NS, name=name))

        # the job space drops the repair/fault/rival ops (that cross product
        # is the base World's territory) and adds the job actor: the
        # reclaimer preempt rides the REAL suspend-2 reconcile once nb2's
        # resume finds its warm slice claimed away by the job's admission
        ops = [
            Op("suspend-2", reconcile(self.suspend, "nb2")),
            Op("job-1", reconcile(self.job, "job1")),
            Op("cluster", lambda w: (w.cluster_step("nb1"),
                                     w.cluster_step("nb2"),
                                     w.job_cluster_step("job1"))),
            Op("unstop-2", lambda w: w.unstop("nb2"), once=True),
        ]
        if self.churn_ops:
            ops[0:0] = [
                Op("cull-1", reconcile(self.culler, "nb1")),
                Op("suspend-1", reconcile(self.suspend, "nb1")),
            ]
        return ops

    def snapshot(self) -> dict:
        snap = super().snapshot()
        snap["job"] = copy.deepcopy({"acked": self.job._ckpt_acked})
        return snap

    def restore_snapshot(self, snap: dict) -> None:
        super().restore_snapshot(snap)
        self.job._ckpt_acked = copy.deepcopy(snap["job"])["acked"]

    def scratch_token(self) -> Tuple:
        return super().scratch_token() + (
            tuple(sorted(
                (k, tuple(sorted(v.items())))
                for k, v in self.job._ckpt_acked.items()
            )),
        )


# ---------------------------------------------------------------------------
# steady-state (quiescence) contracts
# ---------------------------------------------------------------------------


def steady_violations(world: World) -> List[invcheck.InvariantViolation]:
    """Judged at a leaf, after every actor quiesced: the transient windows
    level-triggered controllers are allowed are OVER, so exclusion, stuck
    states, condition/state consistency, and phantom claims are now hard."""
    out: List[invcheck.InvariantViolation] = []

    def v(name: str, detail: str) -> None:
        out.append(invcheck.InvariantViolation(name, detail))

    from ..api.job import TPUJob

    notebooks = world.client.list(Notebook, namespace=NS)
    jobs = world.client.list(TPUJob, namespace=NS)
    keys = {f"{nb.metadata.namespace}/{nb.metadata.name}" for nb in notebooks}
    keys |= {f"{j.metadata.namespace}/{j.metadata.name}" for j in jobs}
    for j in jobs:
        jkey = f"{NS}/{j.metadata.name}"
        jstate = j.metadata.annotations.get(C.JOB_STATE_ANNOTATION, "")
        # legitimate parks: queued Pending (""), a long Running stretch
        # (cadence is wall-clock), and the terminal states. Admitted /
        # Checkpointing / Preempted must always advance — an actor out of
        # work with a job wedged there is exactly the silent-stuck bug the
        # requeue contract exists to prevent.
        if jstate not in ("", "running", "succeeded", "failed"):
            v("stuck-state",
              f"{jkey} quiesced in non-parked job state {jstate!r} — every "
              "actor is out of work and nothing will ever advance it")
    for nb in notebooks:
        ann = nb.metadata.annotations
        key = f"{NS}/{nb.metadata.name}"
        sus = ann.get(C.TPU_SUSPEND_STATE_ANNOTATION, "")
        rep = ann.get(C.TPU_REPAIR_STATE_ANNOTATION, "")
        if sus and rep:
            v("machine-exclusion",
              f"{key} owned by BOTH machines at quiescence "
              f"(suspend={sus!r}, repair={rep!r})")
        if sus not in ("", "suspended") or rep:
            v("stuck-state",
              f"{key} quiesced in non-parked state "
              f"(suspend={sus!r}, repair={rep!r}) — every actor is out of "
              "work and nothing will ever advance it")
        deg = next((c for c in nb.status.conditions
                    if c.type == C.TPU_DEGRADED_CONDITION), None)
        if not rep and deg is not None and deg.status == "True":
            v("condition-consistency",
              f"{key}: Degraded=True ({deg.reason}) but the repair machine "
              "is at rest")
    for node in world.client.list(Node):
        ann = node.metadata.annotations
        state = ann.get(POOL_STATE_ANNOTATION)
        if state is None:
            continue
        pods_here = [
            p for p in world.client.list(Pod)
            if p.spec.node_name == node.metadata.name
            and not p.metadata.deletion_timestamp
        ]
        if state == POOL_STATE_WARM and pods_here:
            v("pool-consistency",
              f"node {node.metadata.name} is warm-reserved but hosts "
              f"{len(pods_here)} pod(s)")
        if state == "claimed":
            claimant = ann.get(POOL_CLAIMED_BY_ANNOTATION, "")
            if claimant not in keys:
                v("pool-consistency",
                  f"node {node.metadata.name} quiesced claimed by "
                  f"{claimant!r}, which does not exist — a phantom claim "
                  "holding the slice out of the pool forever")
    # chip-accounting attribution (ISSUE 17): the ledger's conservation
    # contract depends on classify() being exhaustive and exclusive — every
    # TPU node maps to exactly ONE valid (class, phase) bucket in every
    # reachable quiesced state, so no chip-second can ever go unattributed
    # or be double-counted regardless of which interleaving produced the
    # state. The wall-clock half (sum == chips x dt) is the INVCHECK-armed
    # runtime check; THIS half is interleaving coverage.
    from ..runtime.accounting import PHASES, ChipAccountant

    accountant = ChipAccountant(world.client, clock=lambda: 0.0)
    try:
        attrs = accountant.classify(now=0.0)
    except Exception as e:  # classification must never throw on a real state
        v("accounting-attribution",
          f"classify() raised on a quiesced reachable state: {e!r}")
        attrs = []
    from ..tpu import TPU_RESOURCE
    tpu_nodes = {
        n.metadata.name
        for n in world.client.list(Node)
        if int(n.status.capacity.get(TPU_RESOURCE, "0") or 0) > 0
    }
    seen: Dict[str, int] = {}
    for a in attrs:
        seen[a.node] = seen.get(a.node, 0) + 1
        if a.phase not in PHASES:
            v("accounting-attribution",
              f"node {a.node} attributed to unknown phase {a.phase!r}")
    for name, count in seen.items():
        if count > 1:
            v("accounting-attribution",
              f"node {name} attributed {count} times in one pass — its "
              "chip-seconds would be double-counted")
    missing = tpu_nodes - set(seen)
    if missing:
        v("accounting-attribution",
          f"TPU node(s) {sorted(missing)} unattributed — their "
          "chip-seconds would leak from the conservation ledger")
    return out


# ---------------------------------------------------------------------------
# the explorer
# ---------------------------------------------------------------------------


@dataclass
class Violation:
    invariant: str
    detail: str
    trace: Tuple[str, ...]


@dataclass
class ExplorationResult:
    schedules: int = 0  # leaves fully explored to quiescence
    visited: int = 0  # scheduler decision points
    pruned: int = 0  # memo hits
    truncated: int = 0  # paths cut by max_depth (0 == exhaustive)
    exhausted: bool = False  # frontier fully drained within budgets
    violations: List[Violation] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return self.exhausted and not self.violations and not self.truncated


class Explorer:
    def __init__(
        self,
        world_factory: Callable[[], World] = World,
        max_depth: int = 64,
        max_preemptions: int = 1,
        max_visited: int = 200_000,
        seed: int = 0,
        stop_on_violation: bool = False,
    ):
        self.world_factory = world_factory
        self.max_depth = max_depth
        self.max_preemptions = max_preemptions
        self.max_visited = max_visited
        self.seed = seed
        self.stop_on_violation = stop_on_violation

    def explore(self) -> ExplorationResult:
        world = self.world_factory()
        ops = world.ops()
        order = list(range(len(ops)))
        random.Random(self.seed).shuffle(order)
        result = ExplorationResult()
        memo = set()
        self._dfs(world, ops, order, (), frozenset(), frozenset(),
                  None, 0, (-1, -1), memo, result)
        result.exhausted = result.visited < self.max_visited
        return result

    def _dfs(self, world, ops, order, trace, quiesced, done_once,
             last_actor, preemptions, drain, memo, result) -> bool:
        """Returns True to abort the whole search (budget / stop-on-hit)."""
        result.visited += 1
        if result.visited >= self.max_visited:
            return True
        enabled = []
        for pos, idx in enumerate(order):
            op = ops[idx]
            if op.once and op.name in done_once:
                continue
            if op.after is not None and op.after not in done_once:
                continue
            if not op.once and op.name in quiesced:
                continue
            enabled.append((pos, idx))
        if not enabled:
            # leaf: a fully-quiesced schedule
            result.schedules += 1
            for violation in steady_violations(world):
                result.violations.append(Violation(
                    violation.invariant, violation.detail, trace))
                if self.stop_on_violation:
                    return True
            return False
        if len(trace) >= self.max_depth:
            result.truncated += 1
            return False
        drain_start, last_idle_pos = drain
        n = len(order)
        for pos, idx in enabled:
            op = ops[idx]
            cost = int(
                last_actor is not None
                and op.name != last_actor
                and last_actor not in quiesced
                and not self._is_done_once(ops, last_actor, done_once)
            )
            if preemptions + cost > self.max_preemptions:
                continue
            snap = world.snapshot()
            violations_before = len(world.monitor.violations)
            rv_before = world.store._last_rv
            try:
                op.fn(world)
                failure = None
            except Exception as e:  # a crashed reconcile is itself a finding
                failure = e
            progress = world.store._last_rv != rv_before
            # canonical drain order: consecutive no-op runs commute, so of
            # their permutations only the one ascending CYCLICALLY from the
            # drain's first idle op is explored — judged after the run
            # (whether an op progresses cannot be known in advance);
            # progressing ops are never constrained
            if (
                not progress
                and drain_start >= 0
                and (pos - drain_start) % n < (last_idle_pos - drain_start) % n
            ):
                world.restore_snapshot(snap)
                continue
            new_trace = trace + (op.name,)
            aborted = False
            if failure is not None:
                result.violations.append(Violation(
                    "op-exception", f"{op.name} raised {failure!r}", new_trace))
                aborted = self.stop_on_violation
            for violation in world.monitor.violations[violations_before:]:
                result.violations.append(Violation(
                    violation.invariant, violation.detail, new_trace))
                if self.stop_on_violation:
                    aborted = True
            if aborted:
                return True
            next_quiesced = (
                frozenset() if progress
                else quiesced | ({op.name} if not op.once else frozenset())
            )
            next_done = done_once | ({op.name} if op.once else frozenset())
            next_drain = (
                (-1, -1) if progress
                else (drain_start if drain_start >= 0 else pos, pos)
            )
            key = (world.state_hash(), world.scratch_token(), next_quiesced,
                   next_done, op.name, preemptions + cost, next_drain)
            if key in memo:
                result.pruned += 1
            else:
                memo.add(key)
                if self._dfs(world, ops, order, new_trace, next_quiesced,
                             next_done, op.name, preemptions + cost,
                             next_drain, memo, result):
                    return True
            world.restore_snapshot(snap)
        return False

    @staticmethod
    def _is_done_once(ops, name, done_once) -> bool:
        return name in done_once

    # ---------- replay + minimization ----------

    def replay(self, trace: Sequence[str]) -> List[Violation]:
        world = self.world_factory()
        ops = {op.name: op for op in world.ops()}
        out: List[Violation] = []
        for i, name in enumerate(trace):
            before = len(world.monitor.violations)
            try:
                ops[name].fn(world)
            except Exception as e:
                out.append(Violation("op-exception", f"{name} raised {e!r}",
                                     tuple(trace[: i + 1])))
            for violation in world.monitor.violations[before:]:
                out.append(Violation(violation.invariant, violation.detail,
                                     tuple(trace[: i + 1])))
        return out

    def minimize(self, trace: Sequence[str], invariant: str) -> Tuple[str, ...]:
        """Greedy delta reduction: drop every op whose removal still
        reproduces the invariant; deterministic, so the minimized trace is
        stable across runs (the test pins it)."""
        current = list(trace)

        def reproduces(candidate: List[str]) -> bool:
            return any(v.invariant == invariant
                       for v in self.replay(candidate))

        changed = True
        while changed:
            changed = False
            i = 0
            while i < len(current):
                candidate = current[:i] + current[i + 1:]
                if reproduces(candidate):
                    current = candidate
                    changed = True
                else:
                    i += 1
        return tuple(current)


# ---------------------------------------------------------------------------
# entry points (tests + `python -m odh_kubeflow_tpu.analysis --explore`)
# ---------------------------------------------------------------------------

MUTANTS: Dict[str, Callable[[], World]] = {
    "skip-checkpoint": lambda: World(
        suspend_cls=SkipCheckpointSuspendController),
    "cas-blind": lambda: World(pool_cls=CASBlindSlicePool),
}
MUTANT_INVARIANT = {
    "skip-checkpoint": "checkpoint-before-suspend",
    "cas-blind": "pool-claim-cas",
}


def explore_default(max_preemptions: int = 0, seed: int = 0,
                    max_visited: int = 200_000) -> ExplorationResult:
    """The acceptance run: bounded-exhaustive over the suspend x repair x
    reclaim interleaving space with the SHIPPED controllers — must come
    back exhausted, un-truncated, and violation-free. max_preemptions=0
    still interleaves every actor ordering at every quiescence point
    (~40 s); 1 adds an arbitrary preemptive switch anywhere (~3 min, the
    slow-marked soak tier)."""
    return Explorer(World, max_preemptions=max_preemptions, seed=seed,
                    max_visited=max_visited).explore()


def explore_jobs(max_preemptions: int = 0, seed: int = 0,
                 max_visited: int = 200_000,
                 churn_ops: bool = False) -> ExplorationResult:
    """ISSUE 10 acceptance: bounded-exhaustive over the job-vs-suspend-vs-
    reclaim interleaving space (JobWorld: warm-claim admission steals the
    suspended notebook's slice, the resume pressures the REAL reclaimer
    into checkpoint-preempting the REAL job controller, the job requeues) —
    must come back exhausted, un-truncated, and violation-free. The default
    space exhausts in seconds; churn_ops=True adds the interactive
    cull/suspend actors (the slow tier, ~2 min)."""
    return Explorer(lambda: JobWorld(churn_ops=churn_ops),
                    max_preemptions=max_preemptions, seed=seed,
                    max_visited=max_visited).explore()


def explore_mutant(name: str, seed: int = 0) -> Tuple[Violation, Tuple[str, ...]]:
    """Deterministically reproduce a seeded known-bad mutant: first
    violating schedule, then the minimized replayable trace."""
    explorer = Explorer(MUTANTS[name], max_preemptions=1, seed=seed,
                        stop_on_violation=True)
    result = explorer.explore()
    target = MUTANT_INVARIANT[name]
    hits = [v for v in result.violations if v.invariant == target]
    if not hits:
        raise AssertionError(
            f"mutant {name!r} produced no {target} violation "
            f"({len(result.violations)} other violations, "
            f"{result.schedules} schedules)"
        )
    first = hits[0]
    minimized = explorer.minimize(first.trace, target)
    return first, minimized


def overhead_ratio(n: int = 300) -> Tuple[float, float]:
    """(per-write seconds off, on): the INVCHECK calm-path cost probe the
    acceptance bound (<10% per reconcile) is measured from — a reconcile-
    shaped loop of annotation patches against a bare store."""
    def loop(store: Store) -> float:
        client = Manager(store, cached_reads=False).client
        nb = Notebook()
        nb.metadata.name = "calm"
        nb.metadata.namespace = NS
        nb.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2")
        client.create(nb)
        t0 = time.perf_counter()
        for i in range(n):
            client.patch(Notebook, NS, "calm", {
                "metadata": {"annotations": {
                    C.LAST_ACTIVITY_CHECK_TIMESTAMP_ANNOTATION: f"t{i}",
                }},
            })
        return time.perf_counter() - t0

    def bare_store() -> Store:
        store = Store(backend="python")
        store.invariants = None  # ambient INVCHECK must not skew "off"
        return store

    off = min(loop(bare_store()) for _ in range(3))
    on = min(
        loop(Store(backend="python", invariants=invcheck.Monitor()))
        for _ in range(3)
    )
    return off / n, on / n
