"""The declared deployment surface — the ONE contract deploylint + DEPLOYGUARD share.

The machines.py/hotregions.py pattern applied to the deployment surface
itself: this module declares what the committed manifests promise (RBAC
verbs per resource, webhook paths, env knobs, flow schemas), the static
checkers (analysis/checkers/deploylint.py) prove the code agrees at lint
time, and the runtime twin (utils/deployguard.py) proves the live request
stream agrees under the chaos soaks.

Three layers of truth, kept honest against each other:

- the *generator* (deploy/manifests.py) is authoritative for what RBAC the
  manager's ServiceAccount is granted — `declared_rbac()` calls it, so the
  contract can never drift from what `generate` writes;
- the *scheme* kinds map onto RBAC (group, resource) pairs via
  `KIND_RESOURCES` — the table the AST pass and the runtime guard both use
  to turn a typed-client call into an RBAC requirement;
- `ci/build_manifests.sh --check` pins the committed YAML to the generator,
  closing the loop (generator == committed == code).

Import-light: constants only at module scope; everything touching
deploy/manifests.py or controllers/config.py resolves lazily.
"""
from __future__ import annotations

import re
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

# ---------------------------------------------------------------------------
# kinds -> RBAC (apiGroup, resource) — every kind the scheme registers
# ---------------------------------------------------------------------------

KIND_RESOURCES: Dict[str, Tuple[str, str]] = {
    "Notebook": ("kubeflow.org", "notebooks"),
    "InferenceEndpoint": ("kubeflow.org", "inferenceendpoints"),
    "TPUJob": ("kubeflow.org", "tpujobs"),
    "StatefulSet": ("apps", "statefulsets"),
    "Deployment": ("apps", "deployments"),
    "Lease": ("coordination.k8s.io", "leases"),
    "Gateway": ("gateway.networking.k8s.io", "gateways"),
    "HTTPRoute": ("gateway.networking.k8s.io", "httproutes"),
    "ReferenceGrant": ("gateway.networking.k8s.io", "referencegrants"),
    "NetworkPolicy": ("networking.k8s.io", "networkpolicies"),
    "Role": ("rbac.authorization.k8s.io", "roles"),
    "RoleBinding": ("rbac.authorization.k8s.io", "rolebindings"),
    "ClusterRoleBinding": ("rbac.authorization.k8s.io", "clusterrolebindings"),
    "MutatingWebhookConfiguration": (
        "admissionregistration.k8s.io",
        "mutatingwebhookconfigurations",
    ),
    "DataSciencePipelinesApplication": (
        "datasciencepipelinesapplications.opendatahub.io",
        "datasciencepipelinesapplications",
    ),
    "ConfigMap": ("", "configmaps"),
    "Event": ("", "events"),
    "Namespace": ("", "namespaces"),
    "Node": ("", "nodes"),
    "PersistentVolumeClaim": ("", "persistentvolumeclaims"),
    "Pod": ("", "pods"),
    "Secret": ("", "secrets"),
    "Service": ("", "services"),
    "ServiceAccount": ("", "serviceaccounts"),
}

# typed-client method -> (RBAC verb, subresource). update_status/patch_status
# hit `<resource>/status`; everything else hits the main resource.
CLIENT_VERBS: Dict[str, Tuple[str, str]] = {
    "create": ("create", ""),
    "get": ("get", ""),
    "list": ("list", ""),
    "update": ("update", ""),
    "update_status": ("update", "status"),
    "patch": ("patch", ""),
    "patch_status": ("patch", "status"),
    "delete": ("delete", ""),
}

# informer registration (runtime/builder): a watched kind is read via
# list+watch (+get on cache misses through the api_reader)
WATCH_METHODS = ("for_", "owns", "watches")
WATCH_VERBS = ("get", "list", "watch")


def required_rbac(method: str, kind: str) -> Optional[Tuple[str, str, str]]:
    """(apiGroup, resource[, /status], verb) one typed-client call needs,
    or None when the kind is outside the declared contract."""
    if kind not in KIND_RESOURCES or method not in CLIENT_VERBS:
        return None
    group, resource = KIND_RESOURCES[kind]
    verb, sub = CLIENT_VERBS[method]
    return (group, f"{resource}/{sub}" if sub else resource, verb)


# ---------------------------------------------------------------------------
# attribution: which modules run under the manager's ServiceAccount
# ---------------------------------------------------------------------------

# Everything here issues API requests AS the manager in a real deployment.
# The sim-cluster actors (cluster/kubelet.py, scheduler.py, statefulset.py,
# sim.py) model node agents / kube controllers with their OWN identities, so
# their traffic never counts against the manager's RBAC.
_MANAGER_MODULE_RE = re.compile(
    r"odh_kubeflow_tpu/(?:"
    r"controllers/[^/]+\.py"
    r"|runtime/[^/]+\.py"
    r"|cluster/slicepool\.py"
    r"|api/core\.py"
    r"|main\.py"
    r")$"
)


def is_manager_module(path: str) -> bool:
    return bool(_MANAGER_MODULE_RE.search(path.replace("\\", "/")))


# flows owned by the manager's controllers (runtime/controller.py enters
# flow_context(name) around every reconcile) plus the canary prober. Traffic
# on these flows is DEPLOYGUARD-enforced against declared_rbac(); everything
# else (sim actors, loadtest drivers, bare test clients) is record-only.
MANAGER_FLOWS: FrozenSet[str] = frozenset(
    {
        "notebook",
        "event-mirror",
        "tpu-workbench",
        "probe-status",
        "culling",
        "slice-repair",
        "suspend-resume",
        "inference-endpoint",
        "tpu-job",
        "canary",
        # ISSUE 16: the autoscaler sweep and the router's cold-wake patch
        # are manager traffic — RBAC-enforced like every controller flow
        "endpoint-autoscaler",
        "token-router",
    }
)

# ---------------------------------------------------------------------------
# reviewed exemptions: granted-but-not-code-exercised RBAC that is still
# required by the deployed shape. Keyed (apiGroup, resource) -> rationale;
# the stale-rule direction of rbac-coverage skips these.
# ---------------------------------------------------------------------------

RBAC_EXEMPTIONS: Dict[Tuple[str, str], str] = {
    ("authorization.k8s.io", "subjectaccessreviews"): (
        "issued by the kube-rbac-proxy sidecar under the same "
        "ServiceAccount, not by manager code"
    ),
    ("kubeflow.org", "notebooks/finalizers"): (
        "OwnerReferencesPermissionEnforcement needs finalizers update even "
        "though code writes finalizers through the main resource"
    ),
    ("kubeflow.org", "inferenceendpoints/finalizers"): (
        "OwnerReferencesPermissionEnforcement needs finalizers update even "
        "though code writes finalizers through the main resource"
    ),
    ("kubeflow.org", "tpujobs/finalizers"): (
        "OwnerReferencesPermissionEnforcement needs finalizers update even "
        "though code writes finalizers through the main resource"
    ),
}


# ---------------------------------------------------------------------------
# lazy views over the generator + env registry (the authoritative halves)
# ---------------------------------------------------------------------------

_rbac_cache: Optional[Dict[Tuple[str, str], FrozenSet[str]]] = None


def declared_rbac() -> Dict[Tuple[str, str], FrozenSet[str]]:
    """(apiGroup, resource) -> granted verbs, straight from the generator
    (deploy/manifests.py cluster_role()) — the same dict `generate` writes,
    so the contract cannot drift from the committed manifests once
    ci/build_manifests.sh --check pins those to the generator."""
    global _rbac_cache
    if _rbac_cache is None:
        from ..deploy.manifests import cluster_role

        out: Dict[Tuple[str, str], Set[str]] = {}
        for rule in cluster_role()["rules"]:
            for group in rule["apiGroups"]:
                for resource in rule["resources"]:
                    out.setdefault((group, resource), set()).update(rule["verbs"])
        _rbac_cache = {k: frozenset(v) for k, v in out.items()}
    return _rbac_cache


def rbac_allows(method: str, kind: str) -> Tuple[bool, str]:
    """Does declared RBAC cover one typed-client call? Returns (ok, detail);
    kinds outside the contract are (False, why) — the runtime guard turns
    that into a drift error on manager flows."""
    req = required_rbac(method, kind)
    if req is None:
        return False, (
            f"kind {kind!r} is outside the declared deployment contract "
            "(analysis/deploysurface.py KIND_RESOURCES)"
        )
    group, resource, verb = req
    granted = declared_rbac().get((group, resource), frozenset())
    if verb in granted:
        return True, ""
    return False, (
        f"verb {verb!r} on {group or 'core'}/{resource} is not granted to "
        "the manager ServiceAccount (deploy/manifests.py cluster_role())"
    )


def declared_webhook_paths() -> FrozenSet[str]:
    """Every clientConfig path the generated webhook registration points at."""
    from ..deploy.manifests import mutating_webhook_configuration

    paths = set()
    for wh in mutating_webhook_configuration("ns")["webhooks"]:
        path = wh.get("clientConfig", {}).get("service", {}).get("path")
        if path:
            paths.add(path)
    return frozenset(paths)


def declared_env() -> Dict[str, object]:
    """name -> EnvKnob from the ENV_CONTRACT registry (controllers/config.py)."""
    from ..controllers.config import ENV_CONTRACT

    return {knob.name: knob for knob in ENV_CONTRACT}


def manifest_env_names() -> FrozenSet[str]:
    """Env names the generated Deployment stanza + culler ConfigMap carry."""
    from ..deploy.manifests import culler_config, manager_deployment

    names: Set[str] = set()
    dep = manager_deployment("ns", "img", "proxy-img")
    for container in dep["spec"]["template"]["spec"]["containers"]:
        for entry in container.get("env", []):
            names.add(entry["name"])
    names.update(culler_config("ns")["data"].keys())
    return frozenset(names)


def surface_tuples_from_artifact(data: object) -> Set[Tuple[str, str, str, str]]:
    """Normalize a --deploy-surface artifact (utils/deployguard.py dump) to
    {(flow, method, kind, subresource)} tuples."""
    out: Set[Tuple[str, str, str, str]] = set()
    if isinstance(data, dict):
        data = data.get("surface", [])
    for entry in data or []:
        if isinstance(entry, dict):
            out.add(
                (
                    str(entry.get("flow", "")),
                    str(entry.get("method", "")),
                    str(entry.get("kind", "")),
                    str(entry.get("subresource", "")),
                )
            )
        elif isinstance(entry, (list, tuple)) and len(entry) == 4:
            out.add(tuple(str(x) for x in entry))  # type: ignore[arg-type]
    return out


def exercised_resources_from_surface(
    surface: Set[Tuple[str, str, str, str]],
) -> Set[Tuple[str, str]]:
    """(apiGroup, resource) pairs the recorded runtime surface touched."""
    out: Set[Tuple[str, str]] = set()
    for _flow, method, kind, _sub in surface:
        req = required_rbac(method, kind)
        if req is not None:
            out.add((req[0], req[1]))
    return out
