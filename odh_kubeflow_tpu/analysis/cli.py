"""`python -m odh_kubeflow_tpu.analysis` — the ci/analysis.sh entry point.

    python -m odh_kubeflow_tpu.analysis odh_kubeflow_tpu      # full pass
    python -m odh_kubeflow_tpu.analysis --check lock-discipline path/
    python -m odh_kubeflow_tpu.analysis --include-suppressed  # audit pragmas
    python -m odh_kubeflow_tpu.analysis --registry-lint       # live-registry
                                    # naming rules (ci/metrics_lint.sh lane)
    python -m odh_kubeflow_tpu.analysis --slo-lint            # SLO/alert defs
                                    # vs live registry (ci/slo_lint.sh lane)

Exit status: 0 = no unsuppressed findings, 1 = findings, 2 = usage error.
"""
from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import List, Optional

from .framework import all_checkers, run_analysis


def _registry_lint() -> int:
    """Import every metric-registration site, then lint the live global
    registry — the Python half metrics_lint.sh delegates to."""
    import odh_kubeflow_tpu.cluster.slicepool  # noqa: F401
    import odh_kubeflow_tpu.runtime.controller  # noqa: F401
    import odh_kubeflow_tpu.runtime.metrics as m
    import odh_kubeflow_tpu.runtime.workqueue  # noqa: F401
    import odh_kubeflow_tpu.tpu.telemetry  # noqa: F401
    from odh_kubeflow_tpu.controllers.metrics import NotebookMetrics

    from .metric_rules import check_registry

    NotebookMetrics(m.global_registry)  # controller series register in __init__
    violations = check_registry(m.global_registry)
    if violations:
        print("metrics lint FAILED:")
        for v in violations:
            print(f"  - {v}")
        return 1
    text = m.global_registry.render()
    print(
        f"metrics lint OK: {len(m.global_registry._metrics)} families, "
        f"{len(text.splitlines())} exposition lines"
    )
    return 0


def _slo_lint() -> int:
    """Import every metric-registration site plus the SLO/alert/prober
    definitions, then lint the definitions against the live registry — the
    ci/slo_lint.sh entry (metric_rules.check_slo_definitions is the one
    source of truth, like the registry lint)."""
    import odh_kubeflow_tpu.cluster.slicepool  # noqa: F401  (pool + resume)
    import odh_kubeflow_tpu.runtime.controller  # noqa: F401
    import odh_kubeflow_tpu.runtime.flightrecorder  # noqa: F401
    import odh_kubeflow_tpu.runtime.metrics as m
    import odh_kubeflow_tpu.runtime.prober  # noqa: F401  (canary families)
    import odh_kubeflow_tpu.tpu.telemetry  # noqa: F401
    from odh_kubeflow_tpu.controllers.metrics import NotebookMetrics
    from odh_kubeflow_tpu.runtime.alerts import default_rules
    from odh_kubeflow_tpu.runtime.slo import default_slos

    from .metric_rules import check_slo_definitions

    NotebookMetrics(m.global_registry)  # controller series register in __init__
    slos = default_slos()
    rules = default_rules(slos)
    violations = check_slo_definitions(slos, rules, m.global_registry)
    if violations:
        print("slo lint FAILED:")
        for v in violations:
            print(f"  - {v}")
        return 1
    print(
        f"slo lint OK: {len(slos)} SLOs, {len(rules)} alert rules, every "
        "referenced metric registered"
    )
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m odh_kubeflow_tpu.analysis",
        description="Operator-lint: AST invariant checks for the control plane",
    )
    parser.add_argument("paths", nargs="*", default=[], help="files or directories")
    parser.add_argument(
        "--check", action="append", default=None,
        help="run only this checker (repeatable)",
    )
    parser.add_argument(
        "--include-suppressed", action="store_true",
        help="show findings hidden by `# lint: disable=` pragmas",
    )
    parser.add_argument(
        "--list-checks", action="store_true", help="list checker names and exit"
    )
    parser.add_argument(
        "--registry-lint", action="store_true",
        help="lint the live metrics registry instead of source files",
    )
    parser.add_argument(
        "--slo-lint", action="store_true",
        help="lint SLO/alert-rule definitions against the live registry "
        "(the ci/slo_lint.sh lane)",
    )
    args = parser.parse_args(argv)

    if args.list_checks:
        for checker in all_checkers():
            print(checker.name)
        return 0
    if args.registry_lint:
        return _registry_lint()
    if args.slo_lint:
        return _slo_lint()

    if args.paths:
        paths = args.paths
    else:
        # resolve the default from the installed package location, not the
        # cwd — `python -m odh_kubeflow_tpu.analysis` must scan the same
        # tree no matter where it is invoked from
        import odh_kubeflow_tpu

        paths = [str(Path(odh_kubeflow_tpu.__file__).parent)]
    checkers = all_checkers()
    if args.check:
        known = {c.name for c in checkers}
        unknown = set(args.check) - known
        if unknown:
            print(f"unknown checker(s): {', '.join(sorted(unknown))}", file=sys.stderr)
            print(f"available: {', '.join(sorted(known))}", file=sys.stderr)
            return 2
        selected = set(args.check)
        checkers = [c for c in checkers if c.name in selected]
        if "lock-order" in selected and "lock-discipline" not in selected:
            # lock-order normally piggybacks on lock-discipline's walk; run
            # standalone when discipline was filtered out
            from .checkers.lock_discipline import LockOrderChecker

            checkers = [
                LockOrderChecker() if c.name == "lock-order" else c
                for c in checkers
            ]

    findings = run_analysis(
        paths, checkers=checkers, include_suppressed=args.include_suppressed
    )
    for finding in findings:
        print(finding)
    if findings:
        print(f"\n{len(findings)} finding(s)")
        return 1
    print("analysis OK: no findings")
    return 0


if __name__ == "__main__":
    sys.exit(main())
