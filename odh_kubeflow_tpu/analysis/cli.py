"""`python -m odh_kubeflow_tpu.analysis` — the ci/analysis.sh entry point.

    python -m odh_kubeflow_tpu.analysis odh_kubeflow_tpu      # full pass
    python -m odh_kubeflow_tpu.analysis --check lock-discipline path/
    python -m odh_kubeflow_tpu.analysis --include-suppressed  # audit pragmas
    python -m odh_kubeflow_tpu.analysis --registry-lint       # live-registry
                                    # naming rules (ci/metrics_lint.sh lane)
    python -m odh_kubeflow_tpu.analysis --slo-lint            # SLO/alert defs
                                    # vs live registry (ci/slo_lint.sh lane)
    python -m odh_kubeflow_tpu.analysis --pragma-gate ci/pragma_allowlist.txt
                                    # fail on unreviewed `# lint: disable`
    python -m odh_kubeflow_tpu.analysis --pragma-update ci/pragma_allowlist.txt
    python -m odh_kubeflow_tpu.analysis --machines-doc        # render the
                                    # machine specs (ARCHITECTURE round 9)
    python -m odh_kubeflow_tpu.analysis --explore             # bounded
                                    # exhaustive interleaving run (ISSUE 8)
    python -m odh_kubeflow_tpu.analysis --check retrace-hazard \
        --check host-transfer --check donation-discipline \
        --check psum-axis odh_kubeflow_tpu
                                    # the jaxlint data-plane family
                                    # (ci/analysis.sh --jax lane, ISSUE 12)
    python -m odh_kubeflow_tpu.analysis --check rbac-coverage \
        --check crd-schema-drift --check env-contract \
        --check flow-schema-coverage [--deploy-surface surface.json] \
        odh_kubeflow_tpu            # the deploylint deployment-surface
                                    # family (ci/analysis.sh --deploy lane,
                                    # ISSUE 14)

Exit status: 0 = no unsuppressed findings, 1 = findings, 2 = usage error.
"""
from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import List, Optional

from .framework import (
    all_checkers,
    collect_pragmas,
    parse_pragma_allowlist,
    pragma_budget_violations,
    render_pragma_allowlist,
    run_analysis,
)


def _registry_lint() -> int:
    """Import every metric-registration site, then lint the live global
    registry — the Python half metrics_lint.sh delegates to."""
    import odh_kubeflow_tpu.cluster.slicepool  # noqa: F401
    import odh_kubeflow_tpu.runtime.accounting  # noqa: F401  (fleet ledger)
    import odh_kubeflow_tpu.runtime.controller  # noqa: F401
    import odh_kubeflow_tpu.runtime.jobmetrics  # noqa: F401  (TPUJob series)
    import odh_kubeflow_tpu.runtime.metrics as m
    import odh_kubeflow_tpu.runtime.prober  # noqa: F401  (canary families)
    import odh_kubeflow_tpu.runtime.workqueue  # noqa: F401
    import odh_kubeflow_tpu.serving.metrics  # noqa: F401  (inference families)
    import odh_kubeflow_tpu.tpu.telemetry  # noqa: F401
    import odh_kubeflow_tpu.utils.profiler  # noqa: F401  (PROFILE=1 families)
    from odh_kubeflow_tpu.controllers.metrics import NotebookMetrics

    from .metric_rules import check_registry

    NotebookMetrics(m.global_registry)  # controller series register in __init__
    violations = check_registry(m.global_registry)
    if violations:
        print("metrics lint FAILED:")
        for v in violations:
            print(f"  - {v}")
        return 1
    text = m.global_registry.render()
    print(
        f"metrics lint OK: {len(m.global_registry._metrics)} families, "
        f"{len(text.splitlines())} exposition lines"
    )
    return 0


def _slo_lint() -> int:
    """Import every metric-registration site plus the SLO/alert/prober
    definitions, then lint the definitions against the live registry — the
    ci/slo_lint.sh entry (metric_rules.check_slo_definitions is the one
    source of truth, like the registry lint)."""
    import odh_kubeflow_tpu.cluster.slicepool  # noqa: F401  (pool + resume)
    import odh_kubeflow_tpu.runtime.controller  # noqa: F401
    import odh_kubeflow_tpu.runtime.flightrecorder  # noqa: F401
    import odh_kubeflow_tpu.runtime.metrics as m
    import odh_kubeflow_tpu.runtime.prober  # noqa: F401  (canary families)
    import odh_kubeflow_tpu.tpu.telemetry  # noqa: F401
    import odh_kubeflow_tpu.utils.profiler  # noqa: F401  (PROFILE=1 families)
    from odh_kubeflow_tpu.controllers.metrics import NotebookMetrics
    from odh_kubeflow_tpu.runtime.alerts import default_rules
    from odh_kubeflow_tpu.runtime.slo import default_slos

    from .metric_rules import check_slo_definitions

    NotebookMetrics(m.global_registry)  # controller series register in __init__
    slos = default_slos()
    rules = default_rules(slos)
    violations = check_slo_definitions(slos, rules, m.global_registry)
    if violations:
        print("slo lint FAILED:")
        for v in violations:
            print(f"  - {v}")
        return 1
    print(
        f"slo lint OK: {len(slos)} SLOs, {len(rules)} alert rules, every "
        "referenced metric registered"
    )
    return 0


def _default_paths() -> List[str]:
    # resolve from the installed package location, not the cwd — the same
    # tree is scanned no matter where the command is invoked from
    import odh_kubeflow_tpu

    return [str(Path(odh_kubeflow_tpu.__file__).parent)]


def _pragma_gate(paths: List[str], allowlist_path: str, update: bool) -> int:
    if update and paths:
        # an update from a subset of the tree would silently DROP every
        # reviewed entry outside it — the allowlist is whole-tree only
        print(
            "--pragma-update rebuilds the allowlist for the WHOLE tree; "
            "explicit paths would drop reviewed entries outside them — "
            "run it without path arguments",
            file=sys.stderr,
        )
        return 2
    # the committed allowlist stores repo-root-relative paths; normalize the
    # collected keys the same way so the gate is cwd-independent (the repo
    # root is derived from the installed package, never from cwd)
    import odh_kubeflow_tpu

    repo_root = Path(odh_kubeflow_tpu.__file__).resolve().parent.parent
    raw_budget = collect_pragmas(paths or _default_paths())
    budget = {}
    for (path, check), count in raw_budget.items():
        resolved = Path(path).resolve()
        try:
            path = str(resolved.relative_to(repo_root))
        except ValueError:
            path = str(resolved)
        budget[(path, check)] = budget.get((path, check), 0) + count
    if update:
        Path(allowlist_path).write_text(render_pragma_allowlist(budget))
        print(f"pragma allowlist updated: {len(budget)} (path, check) "
              f"entries -> {allowlist_path}")
        return 0
    try:
        allowlist = parse_pragma_allowlist(Path(allowlist_path).read_text())
    except FileNotFoundError:
        print(f"pragma gate FAILED: allowlist {allowlist_path} missing "
              "(generate it with --pragma-update)", file=sys.stderr)
        return 1
    problems = pragma_budget_violations(budget, allowlist)
    if problems:
        print("pragma gate FAILED (unreviewed suppressions):")
        for p in problems:
            print(f"  - {p}")
        return 1
    stale = sum(
        1 for key, allowed in allowlist.items() if budget.get(key, 0) < allowed
    )
    print(
        f"pragma gate OK: {sum(budget.values())} pragma(s) across "
        f"{len(budget)} (path, check) entries, all reviewed"
        + (f" ({stale} allowlist entr{'y' if stale == 1 else 'ies'} stale — "
           "refresh with --pragma-update)" if stale else "")
    )
    return 0


def _explore() -> int:
    """The bounded-exhaustive interleaving run over the shipped
    controllers (the --machines lane's dynamic half)."""
    import logging

    logging.disable(logging.CRITICAL)
    from .explore import explore_default, explore_jobs

    ok = True
    for name, run in (("default", explore_default), ("jobs", explore_jobs)):
        result = run()
        print(
            f"explorer[{name}]: {result.schedules} quiesced schedules, "
            f"{result.visited} scheduler states ({result.pruned} pruned), "
            f"truncated={result.truncated}, exhausted={result.exhausted}"
        )
        for v in result.violations:
            print(f"  VIOLATION [{v.invariant}] {v.detail}")
            print(f"    trace: {' -> '.join(v.trace)}")
        ok = ok and result.ok
    if not ok:
        print("explorer FAILED: interleaving space not clean/exhausted")
        return 1
    print("explorer OK: zero invariant violations over the explored spaces")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m odh_kubeflow_tpu.analysis",
        description="Operator-lint: AST invariant checks for the control plane",
    )
    parser.add_argument("paths", nargs="*", default=[], help="files or directories")
    parser.add_argument(
        "--check", action="append", default=None,
        help="run only this checker (repeatable)",
    )
    parser.add_argument(
        "--include-suppressed", action="store_true",
        help="show findings hidden by `# lint: disable=` pragmas",
    )
    parser.add_argument(
        "--list-checks", action="store_true", help="list checker names and exit"
    )
    parser.add_argument(
        "--registry-lint", action="store_true",
        help="lint the live metrics registry instead of source files",
    )
    parser.add_argument(
        "--slo-lint", action="store_true",
        help="lint SLO/alert-rule definitions against the live registry "
        "(the ci/slo_lint.sh lane)",
    )
    parser.add_argument(
        "--pragma-gate", metavar="ALLOWLIST",
        help="fail when the tree carries `# lint: disable` pragmas beyond "
        "the committed allowlist (ci/pragma_allowlist.txt)",
    )
    parser.add_argument(
        "--pragma-update", metavar="ALLOWLIST",
        help="rewrite the pragma allowlist from the current tree (after "
        "review)",
    )
    parser.add_argument(
        "--deploy-surface", metavar="ARTIFACT",
        help="JSON surface artifact recorded by DEPLOYGUARD "
        "(DEPLOYGUARD_SURFACE_OUT) — gives rbac-coverage runtime confidence "
        "when flagging stale rules",
    )
    parser.add_argument(
        "--machines-doc", action="store_true",
        help="render the state-machine specs (analysis/machines.py) as the "
        "markdown contract ARCHITECTURE.md embeds",
    )
    parser.add_argument(
        "--explore", action="store_true",
        help="run the bounded exhaustive interleaving exploration over the "
        "shipped controllers (analysis/explore.py)",
    )
    args = parser.parse_args(argv)

    if args.list_checks:
        for checker in all_checkers():
            print(checker.name)
        return 0
    if args.registry_lint:
        return _registry_lint()
    if args.slo_lint:
        return _slo_lint()
    if args.pragma_gate or args.pragma_update:
        return _pragma_gate(
            args.paths,
            args.pragma_update or args.pragma_gate,
            update=bool(args.pragma_update),
        )
    if args.machines_doc:
        from .machines import render_markdown

        print(render_markdown())
        return 0
    if args.explore:
        return _explore()

    paths = args.paths or _default_paths()
    checkers = all_checkers()
    if args.check:
        known = {c.name for c in checkers}
        unknown = set(args.check) - known
        if unknown:
            print(f"unknown checker(s): {', '.join(sorted(unknown))}", file=sys.stderr)
            print(f"available: {', '.join(sorted(known))}", file=sys.stderr)
            return 2
        selected = set(args.check)
        checkers = [c for c in checkers if c.name in selected]
        if "lock-order" in selected and "lock-discipline" not in selected:
            # lock-order normally piggybacks on lock-discipline's walk; run
            # standalone when discipline was filtered out
            from .checkers.lock_discipline import LockOrderChecker

            checkers = [
                LockOrderChecker() if c.name == "lock-order" else c
                for c in checkers
            ]

    if args.deploy_surface:
        import json

        from .deploysurface import surface_tuples_from_artifact

        surface = surface_tuples_from_artifact(
            json.loads(Path(args.deploy_surface).read_text())
        )
        for c in checkers:
            if c.name == "rbac-coverage":
                c.surface = surface  # type: ignore[attr-defined]

    findings = run_analysis(
        paths, checkers=checkers, include_suppressed=args.include_suppressed
    )
    for finding in findings:
        print(finding)
    if findings:
        print(f"\n{len(findings)} finding(s)")
        return 1
    print("analysis OK: no findings")
    return 0


if __name__ == "__main__":
    sys.exit(main())
