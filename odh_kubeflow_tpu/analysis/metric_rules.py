"""Prometheus naming rules — the ONE source of truth shared by the static
AST checker (checkers/conventions.py) and the runtime registry lint that
`ci/metrics_lint.sh` delegates to.

These started life as an inline grep in metrics_lint.sh; the rules are
byte-for-byte the same here so the lane's contract did not change when the
shell script became a thin wrapper.
"""
from __future__ import annotations

import re
from typing import List, Optional, Sequence

METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Documented observation range (lo_s, hi_s) per histogram family — the ONE
# place bucket coverage is declared (ISSUE 15). The rule: the family's
# first bucket must sit at or below lo, its last finite bucket at or above
# hi, and at least 3 boundaries must land inside the range (resolution).
# Seconds-scale defaults silently collapse ms-scale phase timings into one
# bucket (the bug this lint exists for: tpu_decode_step_duration_seconds
# shared the train-step buckets while a v5e decode step lands ~0.5-1ms).
# Every registered histogram MUST appear here — an undeclared family is a
# lint violation, so a new metric can't dodge the coverage question.
HISTOGRAM_RANGES = {
    "notebook_slice_ready_seconds": (0.1, 300.0),
    "notebook_probe_sweep_seconds": (0.001, 10.0),
    "notebook_resume_seconds": (0.05, 300.0),
    "flowcontrol_wait_seconds": (0.001, 60.0),
    # sim-mode reconciles land sub-ms (ISSUE 20 audit: the old 1ms low end
    # saturated the first bucket, making queue-wait p50s unreadable)
    "workqueue_queue_duration_seconds": (0.0001, 60.0),
    "controller_reconcile_duration_seconds": (0.0001, 60.0),
    # CPPROFILE=1 control-plane profiler families (runtime/cpprofile.py):
    # queue-wait/work share the sub-ms reconcile range; takeover phases run
    # from sub-ms (no-op lease acquire in sim) to tens of seconds (relist
    # at population under a real apiserver)
    "cp_queue_wait_seconds": (0.0001, 60.0),
    "cp_reconcile_work_seconds": (0.0001, 60.0),
    "cp_takeover_phase_seconds": (0.001, 60.0),
    "canary_probe_latency_seconds": (0.1, 300.0),
    "tpu_job_queue_wait_seconds": (0.05, 1800.0),
    "tpu_job_completion_seconds": (0.5, 7200.0),
    "tpu_train_step_duration_seconds": (0.001, 30.0),
    # a v5e decode step is sub-ms/token (BENCH_r05: 10k tok/s single-slot);
    # the CPU sim stretches to seconds — the range spans both
    "tpu_decode_step_duration_seconds": (0.0005, 30.0),
    "tpu_slice_repair_duration_seconds": (0.1, 600.0),
    "inference_ttft_seconds": (0.001, 10.0),
    "inference_token_latency_seconds": (0.0005, 2.5),
    # routing overhead: sub-ms pick in steady state, stretching toward the
    # retry-budget cap (jittered backoffs) when replicas shed or fail
    "inference_router_added_latency_seconds": (0.0005, 1.0),
    "profile_phase_seconds": (0.0001, 2.5),
    "profile_region_seconds": (0.0005, 30.0),
    "profile_compile_seconds": (0.001, 60.0),
}


def check_histogram_buckets(name: str, buckets: Sequence[float]) -> List[str]:
    """Bucket-coverage lint for one histogram family: its declared buckets
    must bracket the documented observation range with usable resolution."""
    rng = HISTOGRAM_RANGES.get(name)
    if rng is None:
        return [
            f"{name}: histogram has no documented observation range — "
            f"declare (lo_s, hi_s) in HISTOGRAM_RANGES (metric_rules.py) "
            f"so bucket coverage is lintable"
        ]
    lo, hi = rng
    violations: List[str] = []
    finite = sorted(b for b in buckets if b != float("inf"))
    if not finite:
        return [f"{name}: histogram with no finite buckets"]
    if finite[0] > lo:
        violations.append(
            f"{name}: first bucket {finite[0]}s is above the documented "
            f"low end {lo}s — observations below it are indistinguishable"
        )
    if finite[-1] < hi:
        violations.append(
            f"{name}: last finite bucket {finite[-1]}s is below the "
            f"documented high end {hi}s — the top of the range collapses "
            f"into +Inf"
        )
    inside = [b for b in finite if lo <= b <= hi]
    if len(inside) < 3:
        violations.append(
            f"{name}: only {len(inside)} bucket boundary(ies) inside the "
            f"documented range [{lo}, {hi}]s — no usable resolution"
        )
    return violations


def check_metric(
    name: str,
    type_name: str,
    help_text: Optional[str],
    label_names: Sequence[str] = (),
) -> List[str]:
    """Violation strings for one metric family (empty = compliant)."""
    violations: List[str] = []
    if not METRIC_NAME_RE.match(name):
        violations.append(f"{name}: invalid metric name")
    if type_name == "counter" and not name.endswith("_total"):
        violations.append(f"{name}: counter without _total suffix")
    if help_text is not None and not help_text.strip():
        violations.append(f"{name}: empty help string")
    for label in label_names:
        if not LABEL_NAME_RE.match(label) or label == "le":
            violations.append(f"{name}: invalid label name {label!r}")
    return violations


def check_slo_definitions(slos, rules, registry) -> List[str]:
    """Lint SLO + alert-rule definitions against the LIVE registry — the
    ci/slo_lint.sh contract (same one-source-of-truth pattern as
    check_registry): every metric an indicator references must be a
    registered family of the right type, latency thresholds must sit on a
    real bucket boundary, and every alert rule must reference a defined SLO
    and known windows."""
    from odh_kubeflow_tpu.runtime.metrics import Counter, Gauge, Histogram
    from odh_kubeflow_tpu.runtime.slo import (
        EventRatioIndicator,
        GaugeIndicator,
        LatencyIndicator,
        WINDOW_SECONDS,
    )

    violations: List[str] = []
    names = set()
    for slo in slos:
        names.add(slo.name)
        if not (0.0 < slo.objective < 1.0):
            violations.append(
                f"slo {slo.name}: objective {slo.objective} outside (0, 1)"
            )
        for metric_name in slo.metric_names():
            if registry.get(metric_name) is None:
                violations.append(
                    f"slo {slo.name}: references unregistered metric "
                    f"{metric_name!r}"
                )
        indicator = slo.indicator
        if isinstance(indicator, LatencyIndicator):
            metric = registry.get(indicator.histogram)
            if metric is not None and not isinstance(metric, Histogram):
                violations.append(
                    f"slo {slo.name}: {indicator.histogram} is not a histogram"
                )
            elif metric is not None and indicator.threshold_s not in metric.buckets:
                violations.append(
                    f"slo {slo.name}: threshold {indicator.threshold_s}s is not "
                    f"a bucket boundary of {indicator.histogram} "
                    f"(buckets: {metric.buckets})"
                )
        elif isinstance(indicator, EventRatioIndicator):
            metric = registry.get(indicator.counter)
            if metric is not None and not isinstance(metric, Counter):
                violations.append(
                    f"slo {slo.name}: {indicator.counter} is not a counter"
                )
            elif metric is not None:
                unknown = [
                    label for label, _ in indicator.good_labels
                    if label not in metric.label_names
                ]
                if unknown:
                    violations.append(
                        f"slo {slo.name}: good_labels {unknown} not labels of "
                        f"{indicator.counter} (has {list(metric.label_names)})"
                    )
        elif isinstance(indicator, GaugeIndicator):
            metric = registry.get(indicator.gauge)
            if metric is not None and not isinstance(metric, Gauge):
                violations.append(
                    f"slo {slo.name}: {indicator.gauge} is not a gauge"
                )
        else:
            violations.append(
                f"slo {slo.name}: unknown indicator type "
                f"{type(indicator).__name__}"
            )
    budgets = {slo.name: slo.error_budget for slo in slos}
    for rule in rules:
        if rule.slo not in names:
            violations.append(
                f"alert rule {rule.name}: references undefined SLO {rule.slo!r}"
            )
        for window in (rule.long_window, rule.short_window):
            if window not in WINDOW_SECONDS:
                violations.append(
                    f"alert rule {rule.name}: unknown window {window!r} "
                    f"(known: {sorted(WINDOW_SECONDS)})"
                )
        if rule.burn_threshold <= 0:
            violations.append(
                f"alert rule {rule.name}: burn threshold must be > 0"
            )
        elif rule.slo in budgets:
            # burn = (1 - compliance) / budget is capped at 1/budget: a
            # threshold above the cap can never fire — a silently dead rule
            max_burn = 1.0 / budgets[rule.slo]
            if rule.burn_threshold > max_burn:
                violations.append(
                    f"alert rule {rule.name}: threshold "
                    f"{rule.burn_threshold}x exceeds the maximum possible "
                    f"burn {max_burn:.1f}x for objective "
                    f"{1.0 - budgets[rule.slo]:g} — the rule can never fire"
                )
    return violations


def check_registry(registry) -> List[str]:
    """Runtime lint of a live Registry: naming rules over every registered
    family, plus the exposition-completeness check (every family must appear
    in render() output — a family a scraper cannot see is a dead metric)."""
    from odh_kubeflow_tpu.runtime.metrics import Histogram

    violations: List[str] = []
    for metric in registry._metrics.values():
        violations.extend(
            check_metric(metric.name, metric.type_name, metric.help, metric.label_names)
        )
        if isinstance(metric, Histogram):
            violations.extend(
                check_histogram_buckets(metric.name, metric.buckets)
            )
    text = registry.render()
    families = set()
    for line in text.splitlines():
        if line.startswith("# HELP "):
            families.add(line.split(" ", 3)[2])
    for metric in registry._metrics.values():
        if metric.name not in families:
            violations.append(f"{metric.name}: missing from rendered exposition")
    return violations
