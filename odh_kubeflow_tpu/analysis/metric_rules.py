"""Prometheus naming rules — the ONE source of truth shared by the static
AST checker (checkers/conventions.py) and the runtime registry lint that
`ci/metrics_lint.sh` delegates to.

These started life as an inline grep in metrics_lint.sh; the rules are
byte-for-byte the same here so the lane's contract did not change when the
shell script became a thin wrapper.
"""
from __future__ import annotations

import re
from typing import List, Optional, Sequence

METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def check_metric(
    name: str,
    type_name: str,
    help_text: Optional[str],
    label_names: Sequence[str] = (),
) -> List[str]:
    """Violation strings for one metric family (empty = compliant)."""
    violations: List[str] = []
    if not METRIC_NAME_RE.match(name):
        violations.append(f"{name}: invalid metric name")
    if type_name == "counter" and not name.endswith("_total"):
        violations.append(f"{name}: counter without _total suffix")
    if help_text is not None and not help_text.strip():
        violations.append(f"{name}: empty help string")
    for label in label_names:
        if not LABEL_NAME_RE.match(label) or label == "le":
            violations.append(f"{name}: invalid label name {label!r}")
    return violations


def check_registry(registry) -> List[str]:
    """Runtime lint of a live Registry: naming rules over every registered
    family, plus the exposition-completeness check (every family must appear
    in render() output — a family a scraper cannot see is a dead metric)."""
    violations: List[str] = []
    for metric in registry._metrics.values():
        violations.extend(
            check_metric(metric.name, metric.type_name, metric.help, metric.label_names)
        )
    text = registry.render()
    families = set()
    for line in text.splitlines():
        if line.startswith("# HELP "):
            families.add(line.split(" ", 3)[2])
    for metric in registry._metrics.values():
        if metric.name not in families:
            violations.append(f"{metric.name}: missing from rendered exposition")
    return violations
