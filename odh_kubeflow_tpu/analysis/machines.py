"""The three durable state machines, declared as DATA — the verification
contract (ISSUE 8).

PRs 4 and 7 grew three interacting annotation-durable machines whose
contracts (repair stands down while suspend owns a slice, the culler's stop
stamp rides atomically with `suspend-state=checkpointing`, reclaim never
victimizes the canary) were enforced only by example-based tests. These
specs are the single source of truth three consumers share:

- the `machine-conformance` static checker (checkers/machine_conformance.py)
  AST-extracts every write of the state annotations from `controllers/` and
  flags writes that are not a declared transition,
- the INVCHECK=1 runtime monitor (utils/invcheck.py) validates every
  OBSERVED old->new state change at the store against the same transitions,
- `render_markdown()` renders the canonical contract tables embedded in
  ARCHITECTURE.md (round 9) — docs can no longer drift from the code
  because both are generated from this module.

State names are the literal annotation VALUES; `""` is the cleared/absent
key (each machine's rest state). A transition's `via` is the
`module.py:function` whose AST contains the write — `None` marks an
external actor (the user's unstop is a kubectl patch, not our code).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple


@dataclass(frozen=True)
class State:
    name: str  # annotation value; "" = key absent (rest state)
    title: str
    doc: str = ""
    terminal: bool = False
    # terminal escape hatches (a terminal state with neither is a dead end
    # the conformance checker flags): self_healing = a declared transition
    # leaves it; incident = entering it snapshots a flight-recorder bundle
    self_healing: bool = False
    incident: bool = False


@dataclass(frozen=True)
class Transition:
    src: str  # state name, or "*" (defensive clear from any state)
    dst: str
    # "module.py:function" containing the write; None = external actor
    via: Optional[str]
    trigger: str = ""


@dataclass(frozen=True)
class MachineSpec:
    name: str
    # constant NAME in controllers/constants.py holding the annotation key
    annotation: str
    owner: str  # owning controller module (basename)
    states: Tuple[State, ...]
    transitions: Tuple[Transition, ...]
    doc: str = ""
    # API kind the annotation lives on — the INVCHECK write monitor judges a
    # machine only against writes of its own kind (the inference machine's
    # states on an InferenceEndpoint, never on a Notebook)
    kind: str = "Notebook"
    # annotation VALUE -> state name, for values that are not state names
    # themselves (the webhook's reconciliation-lock sentinel)
    value_states: Dict[str, str] = field(default_factory=dict)
    # the state a non-literal (computed) write maps to, e.g. the culler's
    # `now_rfc3339()` stop timestamp; None = computed writes are findings
    dynamic_state: Optional[str] = None

    def state(self, name: str) -> Optional[State]:
        for s in self.states:
            if s.name == name:
                return s
        return None

    def writer_modules(self) -> Tuple[str, ...]:
        return tuple(sorted({
            t.via.split(":", 1)[0] for t in self.transitions if t.via
        }))

    def classify_value(self, value: Optional[str], dynamic: bool = False
                       ) -> Optional[str]:
        """Map a written annotation value to a state name; None = unmappable
        (an undeclared state — a conformance finding)."""
        if dynamic:
            return self.dynamic_state
        if value is None:
            value = ""
        if value in self.value_states:
            return self.value_states[value]
        if self.state(value) is not None:
            return value
        return None

    def allows(self, src: Optional[str], dst: str) -> bool:
        """Is src->dst a declared transition? src=None means 'unknown source'
        (the static checker cannot see it): any declared inbound edge to dst
        counts. Same-state writes are always legal (level-triggered
        controllers re-assert)."""
        if src is not None and src == dst:
            return True
        for t in self.transitions:
            if t.dst != dst:
                continue
            if src is None or t.src == src or t.src == "*":
                return True
        return False


# ---------------------------------------------------------------------------
# suspend/resume (controllers/suspend.py, PR 7)
# ---------------------------------------------------------------------------

SUSPEND_MACHINE = MachineSpec(
    name="suspend",
    annotation="TPU_SUSPEND_STATE_ANNOTATION",
    owner="suspend.py",
    doc="Checkpointed capacity multiplexing: cull/stop checkpoints kernel "
        "state and releases the slice warm; unstop resumes from the pool.",
    states=(
        State("", "Active", "no suspend episode; slice owned by its pods"),
        State("checkpointing", "Checkpointing",
              "stop stamped; replicas held while every ready host's "
              "/tpu/checkpoint hook is driven inside a bounded window"),
        State("suspended", "Suspended",
              "slice released (warm pool, or general capacity when "
              "reclaim-forced); replicas 0"),
        State("resuming", "Resuming",
              "unstopped; warm claim bound or cold fallback placing"),
        State("resume-failed", "ResumeFailed",
              "attempts exhausted; the reclaimer keeps watching",
              terminal=True, self_healing=True, incident=True),
    ),
    transitions=(
        Transition("", "checkpointing", "culling.py:reconcile",
                   "cull: the checkpointing stamp rides the SAME patch as "
                   "the stop annotation"),
        Transition("", "checkpointing", "suspend.py:reconcile",
                   "user stop without the culler's atomic stamp"),
        Transition("", "checkpointing", "suspend.py:_maybe_reclaim_for",
                   "oversubscription reclaim: victim checkpoint-suspends"),
        Transition("checkpointing", "suspended",
                   "suspend.py:_run_checkpoint_window",
                   "window closed (all ready hosts acked, or deadline)"),
        Transition("checkpointing", "", "suspend.py:_clear_updates",
                   "abort: notebook unstopped during the window"),
        Transition("suspended", "resuming", "suspend.py:_begin_resume",
                   "unstop: warm claim or cold fallback"),
        Transition("resuming", "suspended", "suspend.py:reconcile",
                   "re-stopped mid-resume: park; claims return to warm"),
        Transition("resume-failed", "suspended", "suspend.py:reconcile",
                   "re-stopped after a failed resume"),
        Transition("resuming", "", "suspend.py:_clear_updates",
                   "mesh ready: resume complete; idle clock re-arms"),
        Transition("resuming", "resume-failed", "suspend.py:_fail_resume",
                   "attempts exhausted"),
        Transition("resume-failed", "", "suspend.py:_clear_updates",
                   "self-heal: capacity returned and the mesh formed"),
        Transition("*", "", "suspend.py:reconcile",
                   "defensive clear of an unknown state value"),
    ),
)

# ---------------------------------------------------------------------------
# slice repair (controllers/slice_repair.py, PR 4)
# ---------------------------------------------------------------------------

REPAIR_MACHINE = MachineSpec(
    name="slice-repair",
    annotation="TPU_REPAIR_STATE_ANNOTATION",
    owner="slice_repair.py",
    doc="Survive the accelerator layer: checkpoint-before-evict, whole-gang "
        "re-placement, bounded retry. Stands down whenever the suspend "
        "machine owns the slice (any suspend-state, or the stop annotation).",
    states=(
        State("", "Ready", "no repair episode"),
        State("degraded", "Degraded",
              "fault detected; checkpoint-before-evict window open"),
        State("repairing", "Repairing",
              "gang evicted; waiting for all-or-nothing re-placement"),
        State("failed", "RepairFailed",
              "attempts exhausted; operator attention required",
              terminal=True, self_healing=True, incident=True),
    ),
    transitions=(
        Transition("", "degraded", "slice_repair.py:_enter_degraded",
                   "node taint/NotReady, chip/ICI fault, or unreachable "
                   "hosts past the dwell"),
        Transition("degraded", "repairing",
                   "slice_repair.py:_run_checkpoint_window",
                   "checkpoint window closed; gang evicted"),
        Transition("repairing", "failed", "slice_repair.py:_fail",
                   "attempts exhausted"),
        Transition("repairing", "", "slice_repair.py:_clear_updates",
                   "slice healthy again; MTTR observed"),
        Transition("degraded", "", "slice_repair.py:_clear_updates",
                   "abort: notebook stopped or suspend machine took over"),
        Transition("failed", "", "slice_repair.py:_clear_updates",
                   "self-heal: capacity returned and the slice recovered"),
        Transition("*", "", "slice_repair.py:reconcile",
                   "defensive clear of an unknown state value"),
    ),
)

# ---------------------------------------------------------------------------
# culling / probe-gate stop machine (kubeflow-resource-stopped)
# ---------------------------------------------------------------------------

CULLING_MACHINE = MachineSpec(
    name="culling",
    annotation="STOP_ANNOTATION",
    owner="culling.py",
    doc="The reference's stop/culling contract: the stop annotation scales "
        "the slice away; the webhook's reconciliation lock rides the SAME "
        "key with a sentinel value until the extension controller clears it.",
    states=(
        State("", "Running", "no stop annotation; slice live"),
        State("locked", "ReconciliationLock",
              "webhook handshake: replicas held at 0 until the extension "
              "controller finishes bring-up"),
        State("stopped", "Stopped",
              "culled or user-stopped; replicas scale to 0 (or the suspend "
              "machine checkpoints first)"),
    ),
    transitions=(
        Transition("", "locked", "webhook.py:inject_reconciliation_lock",
                   "CREATE admission stamps the lock sentinel"),
        Transition("locked", "", "extension.py:remove_reconciliation_lock",
                   "extension controller releases the handshake"),
        Transition("", "stopped", "culling.py:reconcile",
                   "idle (Jupyter AND TPU duty-cycle agree): cull"),
        Transition("", "stopped", "suspend.py:_maybe_reclaim_for",
                   "oversubscription reclaim stops the victim"),
        Transition("stopped", "", None,
                   "user unstop (kubectl annotate / UI) — external actor"),
        Transition("locked", "stopped", None,
                   "user stop during bring-up overwrites the lock sentinel "
                   "— external actor"),
    ),
    value_states={"odh-notebook-controller-lock": "locked"},
    dynamic_state="stopped",  # the stop value is the cull/stop timestamp
)

# ---------------------------------------------------------------------------
# inference endpoint promotion/serving (controllers/inference.py, ISSUE 9)
# ---------------------------------------------------------------------------

INFERENCE_MACHINE = MachineSpec(
    name="inference",
    annotation="INFERENCE_STATE_ANNOTATION",
    owner="inference.py",
    kind="InferenceEndpoint",
    doc="Notebook->serving promotion: a Pending endpoint warm-binds its "
        "source notebook's released slice, Loading restores+verifies the "
        "checkpoint, Serving holds the route, and a stop drains bounded "
        "before the slice is released back warm. ISSUE 16 grows the machine "
        "a scale-to-zero edge: an idle fleet parks Suspended with the route "
        "left up, and the first request (or any desired-replicas bump) "
        "cold-wakes it through a fresh Pending episode.",
    states=(
        State("", "Pending",
              "STS/services converging; pods scheduling (warm claim bound "
              "at promotion when the source notebook just suspended)"),
        State("loading", "Loading",
              "all hosts ready; checkpoint restore driven and verified "
              "(checksum parity with the saved state) inside a bounded "
              "window"),
        State("serving", "Serving",
              "restore verified, mesh ready; HTTPRoute live, engine "
              "accepting traffic"),
        State("draining", "Draining",
              "stop requested: route torn down first, in-flight requests "
              "drain inside a bounded window; never a reclaim victim"),
        State("terminated", "Terminated",
              "drained; replicas 0, slice released (warm unless "
              "reclaim-forced)",
              terminal=True, self_healing=True),
        State("load-failed", "LoadFailed",
              "loading window expired or restore checksum mismatched",
              terminal=True, self_healing=True, incident=True),
        State("suspended", "Suspended",
              "scale-to-zero park (ISSUE 16): replicas 0, every slice "
              "released warm, route left UP — the router's cold-wake (first "
              "request) or a desired-replicas bump pops it back to Pending"),
    ),
    transitions=(
        Transition("", "loading", "inference.py:_run_pending",
                   "every host Ready: open the restore/verify window"),
        Transition("serving", "loading", "inference.py:_run_serving",
                   "host readiness lost while Serving (preemption/crash): "
                   "re-form and re-verify — the repair machine never touches "
                   "endpoints, so this edge is the recovery story"),
        Transition("loading", "serving", "inference.py:_complete_loading",
                   "restore verified and the mesh gate green"),
        Transition("loading", "load-failed", "inference.py:_fail_loading",
                   "window expired or checksum mismatch"),
        Transition("load-failed", "", "inference.py:reconcile",
                   "self-heal: pods ready again (or spec changed) — retry "
                   "the load"),
        Transition("", "draining", "inference.py:reconcile",
                   "stopped before serving: drain whatever started"),
        Transition("loading", "draining", "inference.py:reconcile",
                   "stopped mid-load"),
        Transition("serving", "draining", "inference.py:reconcile",
                   "stop/reclaim: route torn down, drain window opens"),
        Transition("load-failed", "draining", "inference.py:reconcile",
                   "stopped while LoadFailed: wind down cleanly"),
        Transition("draining", "terminated", "inference.py:_complete_drain",
                   "drained (or deadline): replicas 0, slice released"),
        Transition("terminated", "", "inference.py:reconcile",
                   "unstop: serve again (a fresh Pending episode)"),
        Transition("serving", "suspended", "inference.py:_park_suspended",
                   "scale-to-zero: desired replicas 0 with "
                   "autoscaling.scaleToZero — drain every replica warm, "
                   "keep the route for the cold-wake"),
        Transition("suspended", "", "inference.py:reconcile",
                   "cold-wake: first request (router) or desired-replicas "
                   "bump clears the park — a fresh Pending episode "
                   "warm-binds from the pool"),
        Transition("suspended", "draining", "inference.py:reconcile",
                   "stopped while parked: wind down for real (route torn "
                   "down, Terminated keeps nothing routable)"),
        Transition("*", "", "inference.py:reconcile",
                   "defensive clear of an unknown state value"),
    ),
)

# ---------------------------------------------------------------------------
# gang-scheduled batch/RL job (controllers/job.py, ISSUE 10)
# ---------------------------------------------------------------------------

JOB_MACHINE = MachineSpec(
    name="job",
    annotation="JOB_STATE_ANNOTATION",
    owner="job.py",
    kind="TPUJob",
    doc="Gang-scheduled batch/RL jobs (Podracer anakin/sebulba layouts): "
        "all-or-nothing gang admission through the scheduler/slicepool "
        "(warm-claim first; sebulba secures BOTH gangs atomically or "
        "neither), checkpoint-before-preempt when the reclaimer or a host "
        "preemption takes the slice, and a Preempted job requeues to resume "
        "from the saved step — it loses only progress since the last "
        "checkpoint.",
    states=(
        State("", "Pending",
              "not admitted; gang capacity being secured (queued-over-"
              "budget jobs wait here with a QueuedOverBudget condition)"),
        State("admitted", "Admitted",
              "gangs secured (warm claims bound or free capacity found) and "
              "the workload created; waiting for every host of every gang "
              "to come ready"),
        State("running", "Running",
              "all gangs ready; steps progressing (the workload reports "
              "progress through checkpoint acks)"),
        State("checkpointing", "Checkpointing",
              "cadence or preempt: the learner gang's /tpu/checkpoint hooks "
              "are driven inside a bounded window and the acked step is "
              "recorded; never a reclaim victim mid-window"),
        State("preempted", "Preempted",
              "gang(s) scaled away, slice released (warm at the JOB's "
              "priority unless reclaim-forced); requeues to Pending to "
              "resume from the saved step"),
        State("succeeded", "Succeeded",
              "acked step reached the budget; replicas 0, slice released",
              terminal=True, self_healing=True),
        State("failed", "Failed",
              "backoffLimit or maxRuntime exhausted",
              terminal=True, self_healing=True, incident=True),
    ),
    transitions=(
        Transition("", "admitted", "job.py:_run_pending",
                   "gang capacity secured: warm claim(s) bound — sebulba "
                   "claims BOTH gangs atomically or neither — or whole free "
                   "slices found for every gang; workload created"),
        Transition("admitted", "running", "job.py:_run_admitted",
                   "every host of every gang ready; queue-wait observed and "
                   "the job.ready root closes"),
        Transition("admitted", "preempted", "job.py:_preempt",
                   "preempt requested (or placement lost) before the run "
                   "started: nothing to checkpoint, park and requeue"),
        Transition("running", "checkpointing", "job.py:_run_running",
                   "checkpoint cadence due, or preempt requested: save "
                   "before anything moves"),
        Transition("checkpointing", "running", "job.py:_complete_checkpoint",
                   "acked (or window expired): cadence checkpoint, keep "
                   "running"),
        Transition("checkpointing", "succeeded",
                   "job.py:_complete_checkpoint",
                   "acked step reached steps x completions: done"),
        Transition("checkpointing", "preempted", "job.py:_preempt",
                   "preempt requested: state saved (_complete_checkpoint "
                   "banked the ack), park and requeue"),
        Transition("running", "preempted", "job.py:_preempt",
                   "host preemption / readiness lost mid-run: park and "
                   "requeue; progress since the last checkpoint is lost"),
        Transition("running", "failed", "job.py:_fail",
                   "backoffLimit exhausted or maxRuntime exceeded"),
        Transition("preempted", "", "job.py:reconcile",
                   "requeue: a fresh Pending episode resumes from the "
                   "saved step"),
        Transition("succeeded", "", "job.py:reconcile",
                   "user rerun (spec bump / annotation clear): a fresh "
                   "episode"),
        Transition("failed", "", "job.py:reconcile",
                   "self-heal: user reset after the failure"),
        Transition("*", "", "job.py:reconcile",
                   "defensive clear of an unknown state value"),
    ),
)

# ---------------------------------------------------------------------------
# warm-pool node machine (cluster/slicepool.py) — NOT statically checked
# (its annotations live on Nodes and their canonical home is slicepool.py);
# declared here so the INVCHECK monitor and the explorer validate observed
# Node pool-state transitions against the same kind of contract
# ---------------------------------------------------------------------------

POOL_MACHINE = MachineSpec(
    name="slice-pool",
    annotation="POOL_STATE_ANNOTATION",
    owner="slicepool.py",
    kind="Node",
    doc="Node-durable warm pool: release holds a suspended slice warm; "
        "claims CAS through the lead node's resourceVersion.",
    states=(
        State("", "GeneralCapacity", "no pool mark; the scheduler owns it"),
        State("warm", "Warm", "held for resume binds; scheduler places "
              "nobody here"),
        State("claimed", "Claimed", "a resuming notebook owns the bind "
              "window; only the claimant's pods may land"),
    ),
    transitions=(
        Transition("", "warm", "slicepool.py:release",
                   "suspend released the slice warm"),
        Transition("warm", "claimed", "slicepool.py:claim",
                   "resume won the lead-node CAS"),
        Transition("", "claimed", "slicepool.py:claim",
                   "follower re-stamp: the lead CAS already serialized the "
                   "claim; a racing sweep may have cleared this follower"),
        Transition("claimed", "warm", "slicepool.py:release",
                   "claim abandoned (poisoned slice / raced reclaim): "
                   "back to warm"),
        Transition("warm", "", "slicepool.py:reclaim_idle",
                   "idle warm slice reclaimed under capacity pressure"),
        Transition("warm", "", "slicepool.py:_clear",
                   "swept (poisoned / half-marked remnant)"),
        Transition("claimed", "", "slicepool.py:_clear",
                   "resume completed (unclaim) or swept"),
    ),
)

# the statically-checked machines (ISSUE 8 contract + ISSUE 9's inference
# machine + ISSUE 10's job machine, covered by the conformance checker and
# explorer from day one) + the runtime-only pool machine
MACHINES: Tuple[MachineSpec, ...] = (
    SUSPEND_MACHINE, REPAIR_MACHINE, CULLING_MACHINE, INFERENCE_MACHINE,
    JOB_MACHINE,
)
ALL_MACHINES: Tuple[MachineSpec, ...] = MACHINES + (POOL_MACHINE,)


def machine_for_annotation(const_name: str) -> Optional[MachineSpec]:
    for spec in MACHINES:
        if spec.annotation == const_name:
            return spec
    return None


def spec_errors(spec: MachineSpec) -> Tuple[str, ...]:
    """Data-level validation: dead/unreachable declared states and terminal
    dead ends, before any code is consulted. Shared by the conformance
    checker's finish() pass and the spec self-tests."""
    errors = []
    names = {s.name for s in spec.states}
    if "" not in names:
        errors.append(f"machine {spec.name!r}: no rest state ('') declared")
    for t in spec.transitions:
        for endpoint in (t.src, t.dst):
            if endpoint != "*" and endpoint not in names:
                errors.append(
                    f"machine {spec.name!r}: transition {t.src or 'rest'!r}"
                    f"->{t.dst or 'rest'!r} references undeclared state"
                )
    inbound = {t.dst for t in spec.transitions}
    outbound = {t.src for t in spec.transitions}
    for s in spec.states:
        if s.name and s.name not in inbound:
            errors.append(
                f"machine {spec.name!r}: state {s.name!r} is unreachable "
                "(no declared transition enters it)"
            )
        if s.terminal:
            if not (s.self_healing or s.incident):
                errors.append(
                    f"machine {spec.name!r}: terminal state {s.name!r} has "
                    "neither a self-heal path nor an incident bundle — a "
                    "silent dead end"
                )
            if s.self_healing and s.name not in outbound:
                errors.append(
                    f"machine {spec.name!r}: state {s.name!r} claims "
                    "self-healing but no declared transition leaves it"
                )
        elif s.name and s.name not in outbound and "*" not in outbound:
            errors.append(
                f"machine {spec.name!r}: non-terminal state {s.name!r} has "
                "no exit transition (would wedge forever)"
            )
    return tuple(errors)


def render_markdown(specs: Tuple[MachineSpec, ...] = ALL_MACHINES) -> str:
    """The canonical contract tables ARCHITECTURE.md round 9 embeds
    (python -m odh_kubeflow_tpu.analysis --machines-doc)."""
    out = []
    for spec in specs:
        out.append(f"#### `{spec.name}` — `{spec.annotation}` "
                   f"(owner: `{spec.owner}`)")
        out.append("")
        out.append(spec.doc)
        out.append("")
        out.append("| state | annotation value | terminal | notes |")
        out.append("|---|---|---|---|")
        for s in spec.states:
            flags = []
            if s.terminal:
                flags.append("terminal")
                if s.self_healing:
                    flags.append("self-healing")
                if s.incident:
                    flags.append("incident bundle")
            out.append(
                f"| {s.title} | `{s.name or '(absent)'}` | "
                f"{', '.join(flags) or '—'} | {s.doc} |"
            )
        out.append("")
        out.append("| from | to | via | trigger |")
        out.append("|---|---|---|---|")
        for t in spec.transitions:
            via = f"`{t.via}`" if t.via else "_external (user)_"
            out.append(
                f"| `{t.src or 'rest'}` | `{t.dst or 'rest'}` | {via} "
                f"| {t.trigger} |"
            )
        out.append("")
    return "\n".join(out)
