"""Hot-region registry: the data-plane regions where a host sync or a
retrace is an SLO bug, not a style nit (ISSUE 12).

One table, two consumers:

- the STATIC half (`checkers/jaxlint.py` host-transfer) treats the listed
  functions as roots and flags any host-transfer surface (`.item()`,
  `jax.device_get`, `np.array`, implicit bool on device values) inside them
  or inside same-module callees they reach;
- the RUNTIME half (`utils/jaxguard.py`) looks the region up by name when a
  `jaxguard.region(...)` context is armed, and enforces the declared
  budgets: `compile_budget` caps traces of guarded jits attributed to the
  region over one region object's lifetime, `transfer_budget` caps
  `jax.device_get` calls PER ENTRY (each `with region:` resets it).

The budgets are the contract ARCHITECTURE.md round 12 records: a region's
budget is the number the bench asserts and the number a ROADMAP-item-3
regression has to argue with. `None` means "unbudgeted by design" (e.g.
prefill compiles once per distinct prompt length — that IS the design; the
guard still counts so the bench can report it).

Declaring a new hot region is two lines here plus the `with` block at the
call site — the registry stays import-light (stdlib only) because the
static checker runs in bare environments without jax.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple


@dataclass(frozen=True)
class HotRegion:
    """One declared hot region.

    `module` is a repo-relative path suffix (matched with endswith so
    fixture tests and installed-package scans both resolve); `functions`
    are the root qualnames (`Class.method` or bare function) the static
    host-transfer checker starts its same-module reachability walk from.
    """

    name: str
    module: str
    functions: Tuple[str, ...]
    # max traces of guarded jits attributed to one region consumer's
    # lifetime; None = unbudgeted (counted, reported, never fatal)
    compile_budget: Optional[int]
    # max jax.device_get calls per region ENTRY; None = unbudgeted
    transfer_budget: Optional[int]
    rationale: str


REGIONS: Tuple[HotRegion, ...] = (
    HotRegion(
        name="serving.decode_burst",
        module="odh_kubeflow_tpu/serving/engine.py",
        functions=("ServingEngine.step",),
        # the burst program itself plus ONE spare trace for a deliberate
        # shape migration (cache growth / burst retune on a live engine);
        # a third trace is a retrace leak and fails the region exit
        compile_budget=2,
        # steady state is ZERO in-region transfers: the one intentional
        # post-burst drain happens AFTER the region closes (one
        # device_get per burst, asserted separately via transfer_count)
        transfer_budget=0,
        rationale="a decode burst is one dispatch; any in-burst host sync "
        "or retrace multiplies per-token latency by the tunnel floor",
    ),
    HotRegion(
        name="serving.prefill",
        module="odh_kubeflow_tpu/serving/engine.py",
        functions=("ServingEngine._admit",),
        # one compiled program per distinct prompt length is the DESIGN
        # (_prefill_jit docstring) — counted for stats, never fatal
        compile_budget=None,
        # exactly one budgeted transfer: the first-token argmax fetch
        # that makes TTFT independent of the decode batch
        transfer_budget=1,
        rationale="admission runs between bursts; a second host sync here "
        "stalls every active slot, not just the admitted request",
    ),
    HotRegion(
        name="models.generate",
        module="odh_kubeflow_tpu/models/decode.py",
        functions=("generate",),
        # compiles once per (prompt shape, max_new, sample) by design —
        # the whole generate call is ONE program; counted for stats
        compile_budget=None,
        transfer_budget=0,
        rationale="generate() is one compiled program per shape; a host "
        "sync inside it would reintroduce the per-token dispatch floor",
    ),
    HotRegion(
        name="bench.train_step",
        module="bench.py",
        functions=(),
        # the train step compiles exactly once; a second trace means the
        # step function closed over something shape-varying
        compile_budget=1,
        transfer_budget=None,
        rationale="bench_train_step's two-length slope assumes one "
        "compiled program; a retrace poisons the timing math",
    ),
)

_BY_NAME: Dict[str, HotRegion] = {r.name: r for r in REGIONS}


def get(name: str) -> HotRegion:
    """Look a region up by name — unknown names raise so a typo'd guard
    cannot silently run unbudgeted."""
    try:
        return _BY_NAME[name]
    except KeyError:
        raise KeyError(
            f"unknown hot region {name!r} — declare it in "
            f"analysis/hotregions.py (known: {sorted(_BY_NAME)})"
        ) from None


def hot_functions_for(path: str) -> Dict[str, HotRegion]:
    """Root qualname -> region for every region whose module matches
    `path` (endswith, so cwd-relative and absolute paths both hit). The
    static host-transfer checker's entry point."""
    out: Dict[str, HotRegion] = {}
    for region in REGIONS:
        if path.endswith(region.module):
            for fn in region.functions:
                out[fn] = region
    return out
