"""API-drift shims for the accelerator stack.

The model/parallel code is written against the current public surface
(`jax.shard_map` with `check_vma=`); older pinned environments (<= 0.4.x)
only ship `jax.experimental.shard_map.shard_map` with the pre-rename
`check_rep=` keyword. One shim here, consulted by every call site, keeps the
code on the modern spelling without a hard floor on the jax pin.
"""
from __future__ import annotations

import jax

if hasattr(jax.lax, "axis_size"):
    axis_size = jax.lax.axis_size
else:
    def axis_size(axis_name):
        # pre-0.5 idiom: psum of the python scalar 1 over a named axis is
        # constant-folded to the (static) axis size
        return jax.lax.psum(1, axis_name)


if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kwargs):
        # pre-0.5 spelling: check_vma was check_rep (same semantics for the
        # False we pass: skip the replication-consistency check)
        return _experimental_shard_map(
            f,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_rep=check_vma,
            **kwargs,
        )
