"""networking.k8s.io/v1 — NetworkPolicy (per-notebook ingress isolation,
reference odh controllers/notebook_network.go:132-211)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..apimachinery import KubeObject, KubeModel, default_scheme
from ..apimachinery.labels import LabelSelector


@dataclass
class NetworkPolicyPort(KubeModel):
    protocol: str = ""
    port: Any = None


@dataclass
class NetworkPolicyPeer(KubeModel):
    pod_selector: Optional[LabelSelector] = None
    namespace_selector: Optional[LabelSelector] = None
    ip_block: Dict[str, Any] = field(default_factory=dict)


@dataclass
class NetworkPolicyIngressRule(KubeModel):
    ports: List[NetworkPolicyPort] = field(default_factory=list)
    from_: List[NetworkPolicyPeer] = field(
        default_factory=list, metadata={"json": "from"}
    )


@dataclass
class NetworkPolicySpec(KubeModel):
    pod_selector: LabelSelector = field(default_factory=LabelSelector)
    ingress: List[NetworkPolicyIngressRule] = field(default_factory=list)
    policy_types: List[str] = field(default_factory=list)


@dataclass
class NetworkPolicy(KubeObject):
    spec: NetworkPolicySpec = field(default_factory=NetworkPolicySpec)


default_scheme.register("networking.k8s.io/v1", "NetworkPolicy", NetworkPolicy)
