from .v1beta1 import (
    API_VERSION,
    GROUP,
    KIND,
    AutoscalingSpec,
    InferenceEndpoint,
    InferenceEndpointSpec,
    InferenceEndpointStatus,
    NotebookRef,
    ServingSpec,
)
