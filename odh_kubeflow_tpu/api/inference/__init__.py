from .v1beta1 import (
    API_VERSION,
    GROUP,
    KIND,
    InferenceEndpoint,
    InferenceEndpointSpec,
    InferenceEndpointStatus,
    NotebookRef,
    ServingSpec,
)
