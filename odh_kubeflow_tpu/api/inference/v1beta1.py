"""InferenceEndpoint CRD, v1beta1 (ISSUE 9).

The second workload class: a long-lived serving deployment promoted from an
interactive notebook (or pointed straight at a checkpoint path). The spec
deliberately mirrors the Notebook CR's shape — the same ``spec.tpu`` block
drives slice planning, the same pod-template escape hatch exists — so the
reconciler reuses the STS/headless-service/HTTPRoute/scheduler/slicepool
machinery rather than growing a parallel stack.

Promotion contract: with ``spec.notebookRef`` set, the endpoint inherits the
source notebook's slice shape (when ``spec.tpu`` is empty) and its saved
checkpoint lineage (step + checksum annotations), and — when the notebook
just suspended — claims its warm slice from the pool, so promotion is a warm
bind, not a cold create.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ...apimachinery import Condition, KubeModel, KubeObject, default_scheme
from ..notebook.v1beta1 import NotebookTemplateSpec, TPUSpec, TPUStatus

GROUP = "kubeflow.org"
API_VERSION = "kubeflow.org/v1beta1"
KIND = "InferenceEndpoint"


@dataclass
class NotebookRef(KubeModel):
    """Source notebook of a promotion; empty = serve straight from
    ``spec.serving.checkpointPath`` with no lineage."""

    name: str = ""
    namespace: str = ""  # "" -> the endpoint's own namespace


@dataclass
class ServingSpec(KubeModel):
    """Continuous-batching engine shape (serving/engine.py): KV-cache slots,
    admission-queue bound, and sequence budget per request."""

    max_batch_slots: int = 8  # concurrent sequences (KV-cache slots)
    max_queue_depth: int = 64  # bounded admission queue; overflow = 429
    max_seq: int = 2048  # per-slot KV-cache extent
    max_new_tokens: int = 256  # per-request generation cap
    # decode steps per dispatch (the prefill/decode scheduling knob):
    # amortizes the per-dispatch latency floor while bounding admission
    # delay at this many decode steps
    decode_burst: int = 8
    checkpoint_path: str = ""  # orbax dir; promotion fills it from the source
    # bounded drain: Draining waits this long for in-flight requests before
    # the gang scales away (0 -> the controller default)
    drain_timeout_s: float = 0.0


@dataclass
class InferenceEndpointSpec(KubeModel):
    notebook_ref: Optional[NotebookRef] = None
    tpu: Optional[TPUSpec] = None  # empty + notebookRef -> inherited
    serving: ServingSpec = field(default_factory=ServingSpec)
    # pod template override (the serving image); defaulted like a notebook's
    template: NotebookTemplateSpec = field(default_factory=NotebookTemplateSpec)


@dataclass
class InferenceEndpointStatus(KubeModel):
    conditions: List[Condition] = field(default_factory=list)
    ready_replicas: int = 0
    # human mirror of the annotation-durable machine (the annotation is the
    # durable truth; this is for kubectl get)
    phase: str = ""
    tpu: Optional[TPUStatus] = None
    url: str = ""  # route path once Serving


@dataclass
class InferenceEndpoint(KubeObject):
    spec: InferenceEndpointSpec = field(default_factory=InferenceEndpointSpec)
    status: InferenceEndpointStatus = field(
        default_factory=InferenceEndpointStatus
    )


default_scheme.register(API_VERSION, KIND, InferenceEndpoint)
