"""InferenceEndpoint CRD, v1beta1 (ISSUE 9).

The second workload class: a long-lived serving deployment promoted from an
interactive notebook (or pointed straight at a checkpoint path). The spec
deliberately mirrors the Notebook CR's shape — the same ``spec.tpu`` block
drives slice planning, the same pod-template escape hatch exists — so the
reconciler reuses the STS/headless-service/HTTPRoute/scheduler/slicepool
machinery rather than growing a parallel stack.

Promotion contract: with ``spec.notebookRef`` set, the endpoint inherits the
source notebook's slice shape (when ``spec.tpu`` is empty) and its saved
checkpoint lineage (step + checksum annotations), and — when the notebook
just suspended — claims its warm slice from the pool, so promotion is a warm
bind, not a cold create.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ...apimachinery import Condition, KubeModel, KubeObject, default_scheme
from ..notebook.v1beta1 import NotebookTemplateSpec, TPUSpec, TPUStatus

GROUP = "kubeflow.org"
API_VERSION = "kubeflow.org/v1beta1"
KIND = "InferenceEndpoint"


@dataclass
class NotebookRef(KubeModel):
    """Source notebook of a promotion; empty = serve straight from
    ``spec.serving.checkpointPath`` with no lineage."""

    name: str = ""
    namespace: str = ""  # "" -> the endpoint's own namespace


@dataclass
class AutoscalingSpec(KubeModel):
    """SLO-burn autoscaling bounds (runtime/autoscaler.py). The signal is
    burn rate / queue pressure from the SLO engine — never CPU. minReplicas
    is a hard floor under sustained burn; maxReplicas caps how much of the
    warm pool one endpoint may bind; scaleToZero allows parking the whole
    fleet Suspended-with-a-route when idle (cold-wake on first request)."""

    min_replicas: int = 1
    max_replicas: int = 4
    # scale up when the serving SLOs' fast-window burn rate crosses this
    # (1.0 = burning exactly the error budget); 0 keeps the default
    target_burn_rate: float = 2.0
    scale_to_zero: bool = False
    # flap damping: a scale-down (or park-to-zero) only fires after the
    # signal has been below target for this long (0 -> controller default)
    scale_down_stabilization_s: float = 0.0
    # idle window before scale-to-zero parks the fleet (0 -> default)
    scale_to_zero_idle_s: float = 0.0


@dataclass
class ServingSpec(KubeModel):
    """Continuous-batching engine shape (serving/engine.py): KV-cache slots,
    admission-queue bound, and sequence budget per request."""

    max_batch_slots: int = 8  # concurrent sequences (KV-cache slots)
    max_queue_depth: int = 64  # bounded admission queue; overflow = 429
    max_seq: int = 2048  # per-slot KV-cache extent
    max_new_tokens: int = 256  # per-request generation cap
    # decode steps per dispatch (the prefill/decode scheduling knob):
    # amortizes the per-dispatch latency floor while bounding admission
    # delay at this many decode steps
    decode_burst: int = 8
    checkpoint_path: str = ""  # orbax dir; promotion fills it from the source
    # bounded drain: Draining waits this long for in-flight requests before
    # the gang scales away (0 -> the controller default)
    drain_timeout_s: float = 0.0
    # serving fleet (ISSUE 16): N independent per-replica gangs, each its own
    # STS + gang-DNS Service + slicepool claim. The endpoint stays Serving
    # while >=1 replica is healthy (DegradedServing condition below full
    # strength). The autoscaler moves the live count within
    # autoscaling.{min,max}; `replicas` is the static default
    replicas: int = 1
    autoscaling: Optional[AutoscalingSpec] = None


@dataclass
class InferenceEndpointSpec(KubeModel):
    notebook_ref: Optional[NotebookRef] = None
    tpu: Optional[TPUSpec] = None  # empty + notebookRef -> inherited
    serving: ServingSpec = field(default_factory=ServingSpec)
    # pod template override (the serving image); defaulted like a notebook's
    template: NotebookTemplateSpec = field(default_factory=NotebookTemplateSpec)


@dataclass
class InferenceEndpointStatus(KubeModel):
    conditions: List[Condition] = field(default_factory=list)
    ready_replicas: int = 0  # ready HOSTS across the whole fleet
    # human mirror of the annotation-durable machine (the annotation is the
    # durable truth; this is for kubectl get)
    phase: str = ""
    tpu: Optional[TPUStatus] = None
    url: str = ""  # route path while Serving (or parked Suspended)
    # fleet view (ISSUE 16) — the router's signal contract: `replicas` is
    # the converged-toward fleet size, `servingReplicas` how many full gangs
    # can take traffic, `drainingReplicas` which gang indexes are in their
    # route-first drain window (the router must stop picking them)
    replicas: int = 0
    serving_replicas: int = 0
    draining_replicas: List[int] = field(default_factory=list)


@dataclass
class InferenceEndpoint(KubeObject):
    spec: InferenceEndpointSpec = field(default_factory=InferenceEndpointSpec)
    status: InferenceEndpointStatus = field(
        default_factory=InferenceEndpointStatus
    )


default_scheme.register(API_VERSION, KIND, InferenceEndpoint)
