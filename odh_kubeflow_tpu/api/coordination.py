"""coordination.k8s.io/v1 — Lease, for manager leader election (the reference
enables it as "kubeflow-notebook-controller" / "odh-notebook-controller" —
notebook-controller/main.go:91-93, odh main.go:133-135)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..apimachinery import KubeObject, KubeModel, default_scheme


@dataclass
class LeaseSpec(KubeModel):
    holder_identity: str = ""
    lease_duration_seconds: Optional[int] = None
    acquire_time: str = ""
    renew_time: str = ""
    lease_transitions: int = 0


@dataclass
class Lease(KubeObject):
    spec: LeaseSpec = field(default_factory=LeaseSpec)


default_scheme.register("coordination.k8s.io/v1", "Lease", Lease)
