"""Spoke versions (v1, v1alpha1) and hub conversion.

The reference serves three structurally-identical versions with v1beta1 as hub
(reference api/v1/notebook_conversion.go:25-69, api/v1alpha1/...); conversion is
a field-wise copy. Here the spokes share the hub's dataclasses, so conversion
is an apiVersion rewrite with a lossless round-trip through the JSON form.
"""
from __future__ import annotations

from ...apimachinery import default_scheme
from ...cluster.store import register_storage_alias
from .v1beta1 import API_VERSION as HUB_API_VERSION
from .v1beta1 import KIND, Notebook

SERVED_VERSIONS = ("kubeflow.org/v1beta1", "kubeflow.org/v1", "kubeflow.org/v1alpha1")

for _v in SERVED_VERSIONS[1:]:
    default_scheme.register(_v, KIND, Notebook)
    # spoke writes land in the hub bucket so hub watches/reads see them
    # (the conversion-webhook analog; reference serves all three versions
    # through one storage version)
    register_storage_alias(_v, KIND, HUB_API_VERSION)


def convert_to_hub(nb: Notebook) -> Notebook:
    if nb.api_version == HUB_API_VERSION:
        return nb
    out = nb.deepcopy()
    out.api_version = HUB_API_VERSION
    return out


def convert_from_hub(nb: Notebook, api_version: str) -> Notebook:
    if api_version not in SERVED_VERSIONS:
        raise ValueError(f"unserved Notebook version {api_version}")
    out = nb.deepcopy()
    out.api_version = api_version
    return out
