from .v1beta1 import (
    API_VERSION,
    GROUP,
    KIND,
    Notebook,
    NotebookSpec,
    NotebookStatus,
    NotebookTemplateSpec,
    TPUSpec,
    TPUStatus,
)
from .conversion import SERVED_VERSIONS, convert_from_hub, convert_to_hub
