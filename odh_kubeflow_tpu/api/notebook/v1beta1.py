"""Notebook CRD, v1beta1 (hub version).

Shape-compatible with the reference CRD (reference components/notebook-controller/
api/v1beta1/notebook_types.go:27-88: Spec.Template.Spec is a raw corev1.PodSpec;
Status mirrors conditions + ReadyReplicas + ContainerState), extended with a
first-class ``spec.tpu`` block and ``status.tpu`` — the TPU-native surface the
north star requires (slice accelerator/topology in, hosts/chips/mesh readiness
out)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ...apimachinery import Condition, KubeObject, KubeModel, default_scheme
from ..core import ContainerState, PodSpec

GROUP = "kubeflow.org"
API_VERSION = "kubeflow.org/v1beta1"
KIND = "Notebook"


@dataclass
class TPUSpec(KubeModel):
    """What slice this notebook binds. Empty accelerator = CPU notebook."""

    accelerator: str = ""  # e.g. "v4" | "v5e" | "v5p" | "v6e"
    topology: str = ""  # e.g. "2x2x1", "2x4", "2x2x4"; "" -> smallest for chips
    chips: int = 0  # alternative to topology: minimum total chip count
    runtime: str = ""  # "jax" (default) | "pytorch-xla"
    reserved: Optional[bool] = None  # reservation-bound node pool
    # oversubscription reclaim ordering (controllers/suspend.py): under
    # capacity pressure the LOWEST-priority suspend-eligible slice is
    # checkpoint-suspended first; higher survives longer
    priority: int = 0


@dataclass
class NotebookTemplateSpec(KubeModel):
    spec: PodSpec = field(default_factory=PodSpec)


@dataclass
class NotebookSpec(KubeModel):
    template: NotebookTemplateSpec = field(default_factory=NotebookTemplateSpec)
    tpu: Optional[TPUSpec] = None


@dataclass
class TPUStatus(KubeModel):
    """Slice bring-up state, aggregated from per-host probe reports."""

    accelerator: str = ""
    topology: str = ""
    hosts: int = 0
    hosts_ready: int = 0
    chips_per_host: int = 0
    chips_expected: int = 0
    chips_visible: int = 0
    mesh_ready: bool = False
    first_ready_time: str = ""  # set once; anchors the CR->ready latency metric


@dataclass
class NotebookStatus(KubeModel):
    conditions: List[Condition] = field(default_factory=list)
    ready_replicas: int = 0
    container_state: Optional[ContainerState] = None
    tpu: Optional[TPUStatus] = None


@dataclass
class Notebook(KubeObject):
    spec: NotebookSpec = field(default_factory=NotebookSpec)
    status: NotebookStatus = field(default_factory=NotebookStatus)

    def primary_container(self) -> Optional["object"]:
        """The container named after the notebook, else the first container
        (the reference indexes by name match — notebook_controller.go:493-521)."""
        podspec = self.spec.template.spec
        for c in podspec.containers:
            if c.name == self.metadata.name:
                return c
        return podspec.containers[0] if podspec.containers else None


default_scheme.register(API_VERSION, KIND, Notebook)
