"""Core (v1) workload types — the subset of corev1 the notebook stack speaks,
as from-scratch dataclasses. Field shapes/JSON keys match Kubernetes so specs
written for the reference (whose NotebookSpec.Template.Spec is a raw
corev1.PodSpec — reference api/v1beta1/notebook_types.go:27-40) parse here
unchanged. Unmodeled fields ride through losslessly via KubeModel._extra."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..apimachinery import (
    Condition,
    KubeObject,
    KubeModel,
    ObjectMeta,
    default_scheme,
    jfield,
)


@dataclass
class EnvVarSource(KubeModel):
    field_ref: Optional[Dict[str, Any]] = None
    config_map_key_ref: Optional[Dict[str, Any]] = None
    secret_key_ref: Optional[Dict[str, Any]] = None


@dataclass
class EnvVar(KubeModel):
    name: str = ""
    value: str = ""
    value_from: Optional[EnvVarSource] = None


@dataclass
class ContainerPort(KubeModel):
    name: str = ""
    container_port: int = 0
    protocol: str = ""


@dataclass
class VolumeMount(KubeModel):
    name: str = ""
    mount_path: str = ""
    sub_path: str = ""
    read_only: Optional[bool] = None


@dataclass
class ResourceRequirements(KubeModel):
    limits: Dict[str, str] = field(default_factory=dict)
    requests: Dict[str, str] = field(default_factory=dict)


@dataclass
class Probe(KubeModel):
    http_get: Optional[Dict[str, Any]] = None
    tcp_socket: Optional[Dict[str, Any]] = None
    exec_: Optional[Dict[str, Any]] = jfield("exec", default=None)
    initial_delay_seconds: int = 0
    period_seconds: int = 0
    timeout_seconds: int = 0
    failure_threshold: int = 0


@dataclass
class Container(KubeModel):
    name: str = ""
    image: str = ""
    command: List[str] = field(default_factory=list)
    args: List[str] = field(default_factory=list)
    working_dir: str = ""
    env: List[EnvVar] = field(default_factory=list)
    ports: List[ContainerPort] = field(default_factory=list)
    resources: Optional[ResourceRequirements] = None
    volume_mounts: List[VolumeMount] = field(default_factory=list)
    image_pull_policy: str = ""
    liveness_probe: Optional[Probe] = None
    readiness_probe: Optional[Probe] = None
    security_context: Optional[Dict[str, Any]] = None

    def env_dict(self) -> Dict[str, str]:
        return {e.name: e.value for e in self.env}

    def set_env(self, name: str, value: str) -> None:
        for e in self.env:
            if e.name == name:
                e.value = value
                return
        self.env.append(EnvVar(name=name, value=value))

    def get_env(self, name: str) -> Optional[EnvVar]:
        for e in self.env:
            if e.name == name:
                return e
        return None


@dataclass
class Volume(KubeModel):
    name: str = ""
    config_map: Optional[Dict[str, Any]] = None
    secret: Optional[Dict[str, Any]] = None
    empty_dir: Optional[Dict[str, Any]] = None
    persistent_volume_claim: Optional[Dict[str, Any]] = None
    projected: Optional[Dict[str, Any]] = None


@dataclass
class PodSecurityContext(KubeModel):
    fs_group: Optional[int] = None
    run_as_user: Optional[int] = None
    run_as_non_root: Optional[bool] = None


@dataclass
class Toleration(KubeModel):
    key: str = ""
    operator: str = ""
    value: str = ""
    effect: str = ""


@dataclass
class PodSpec(KubeModel):
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    volumes: List[Volume] = field(default_factory=list)
    service_account_name: str = ""
    node_selector: Dict[str, str] = field(default_factory=dict)
    tolerations: List[Toleration] = field(default_factory=list)
    security_context: Optional[PodSecurityContext] = None
    affinity: Optional[Dict[str, Any]] = None
    subdomain: str = ""
    hostname: str = ""
    enable_service_links: Optional[bool] = None
    restart_policy: str = ""
    scheduler_name: str = ""
    node_name: str = ""

    def container(self, name: str) -> Optional[Container]:
        for c in self.containers:
            if c.name == name:
                return c
        return None

    def volume(self, name: str) -> Optional[Volume]:
        for v in self.volumes:
            if v.name == name:
                return v
        return None


@dataclass
class PodTemplateSpec(KubeModel):
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)


@dataclass
class ContainerState(KubeModel):
    running: Optional[Dict[str, Any]] = None
    waiting: Optional[Dict[str, Any]] = None
    terminated: Optional[Dict[str, Any]] = None


@dataclass
class ContainerStatus(KubeModel):
    name: str = ""
    ready: bool = False
    restart_count: int = 0
    state: Optional[ContainerState] = None
    image: str = ""


@dataclass
class PodStatus(KubeModel):
    phase: str = ""
    conditions: List[Condition] = field(default_factory=list)
    container_statuses: List[ContainerStatus] = field(default_factory=list)
    pod_ip: str = ""
    host_ip: str = ""
    message: str = ""
    reason: str = ""


@dataclass
class Pod(KubeObject):
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    def is_ready(self) -> bool:
        """Pod Ready condition is True (the single definition every
        controller shares — kubelet sim, STS status, probe gate, culler)."""
        return any(
            c.type == "Ready" and c.status == "True"
            for c in self.status.conditions
        )


@dataclass
class ServicePort(KubeModel):
    name: str = ""
    port: int = 0
    target_port: Any = None
    protocol: str = ""


@dataclass
class ServiceSpec(KubeModel):
    ports: List[ServicePort] = field(default_factory=list)
    selector: Dict[str, str] = field(default_factory=dict)
    cluster_ip: str = ""
    type: str = ""


@dataclass
class Service(KubeObject):
    spec: ServiceSpec = field(default_factory=ServiceSpec)
    status: Dict[str, Any] = field(default_factory=dict)


@dataclass
class ConfigMap(KubeObject):
    data: Dict[str, str] = field(default_factory=dict)
    binary_data: Dict[str, str] = field(default_factory=dict)


@dataclass
class Secret(KubeObject):
    data: Dict[str, str] = field(default_factory=dict)
    string_data: Dict[str, str] = field(default_factory=dict)
    type: str = ""


@dataclass
class LocalObjectReference(KubeModel):
    name: str = ""


@dataclass
class ServiceAccount(KubeObject):
    secrets: List[Dict[str, Any]] = field(default_factory=list)
    image_pull_secrets: List[LocalObjectReference] = field(default_factory=list)


@dataclass
class ObjectReference(KubeModel):
    api_version: str = ""
    kind: str = ""
    name: str = ""
    namespace: str = ""
    uid: str = ""


@dataclass
class Event(KubeObject):
    """Events are re-emitted onto Notebook CRs by the core reconciler
    (reference notebook_controller.go:98-126)."""

    involved_object: ObjectReference = field(default_factory=ObjectReference)
    reason: str = ""
    message: str = ""
    type: str = ""
    count: int = 0
    first_timestamp: str = ""
    last_timestamp: str = ""
    source: Dict[str, Any] = field(default_factory=dict)
    reporting_component: str = ""


def emit_deduped_event(
    client,
    owner: KubeObject,
    name: str,
    reason: str,
    message: str,
    etype: str = "Warning",
    api_version: str = "",
    kind: str = "",
) -> None:
    """Kubernetes-style deduplicated Event on `owner`: a repeat of the same
    event `name` bumps count/lastTimestamp instead of piling up objects; the
    first occurrence is created with an ownerRef so it's GC'd with the
    owner. The ONE emitter behind the scheduler's Unschedulable events, the
    slice-repair episode events, and the alert manager's SLOBurnRate events
    — dedup/race semantics live here exactly once."""
    from ..apimachinery import AlreadyExistsError, NotFoundError, now_rfc3339

    namespace = owner.metadata.namespace
    try:
        existing = client.get(Event, namespace, name)
        client.patch(
            Event,
            namespace,
            name,
            {
                "count": existing.count + 1,
                "lastTimestamp": now_rfc3339(),
                "message": message,
            },
        )
        return
    except NotFoundError:
        pass
    ev = Event()
    ev.metadata.name = name
    ev.metadata.namespace = namespace
    ev.involved_object = ObjectReference(
        api_version=api_version or owner.api_version,
        kind=kind or owner.kind or type(owner).__name__,
        name=owner.metadata.name,
        namespace=namespace,
        uid=owner.metadata.uid,
    )
    ev.set_owner(owner)  # GC'd with the owner
    ev.reason = reason
    ev.type = etype
    ev.message = message
    ev.first_timestamp = now_rfc3339()
    ev.last_timestamp = now_rfc3339()
    ev.count = 1
    try:
        client.create(ev)
    except AlreadyExistsError:
        pass  # racing emitter created it; count bump next time


@dataclass
class Namespace(KubeObject):
    status: Dict[str, Any] = field(default_factory=dict)


@dataclass
class NodeStatus(KubeModel):
    capacity: Dict[str, str] = field(default_factory=dict)
    allocatable: Dict[str, str] = field(default_factory=dict)
    conditions: List[Condition] = field(default_factory=list)
    addresses: List[Dict[str, str]] = field(default_factory=list)


@dataclass
class Node(KubeObject):
    spec: Dict[str, Any] = field(default_factory=dict)
    status: NodeStatus = field(default_factory=NodeStatus)


@dataclass
class PersistentVolumeClaim(KubeObject):
    spec: Dict[str, Any] = field(default_factory=dict)
    status: Dict[str, Any] = field(default_factory=dict)


for _kind, _cls in [
    ("Pod", Pod),
    ("Service", Service),
    ("ConfigMap", ConfigMap),
    ("Secret", Secret),
    ("ServiceAccount", ServiceAccount),
    ("Event", Event),
    ("Namespace", Namespace),
    ("Node", Node),
    ("PersistentVolumeClaim", PersistentVolumeClaim),
]:
    default_scheme.register("v1", _kind, _cls)
