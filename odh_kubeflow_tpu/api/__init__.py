from . import apps, core, gateway, networking, rbac
from . import notebook
