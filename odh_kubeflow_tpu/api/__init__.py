from . import admission, apps, coordination, core, gateway, networking, rbac
from . import notebook
