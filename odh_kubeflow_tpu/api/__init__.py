from . import admission, apps, coordination, core, dspa, gateway, networking, rbac
from . import notebook
