"""rbac.authorization.k8s.io/v1 — the auth-delegation objects the extension
controller manages (reference odh controllers/notebook_kube_rbac_auth.go,
notebook_rbac.go)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from ..apimachinery import KubeObject, KubeModel, default_scheme


@dataclass
class Subject(KubeModel):
    kind: str = ""
    name: str = ""
    namespace: str = ""
    api_group: str = ""


@dataclass
class RoleRef(KubeModel):
    api_group: str = "rbac.authorization.k8s.io"
    kind: str = ""
    name: str = ""


@dataclass
class PolicyRule(KubeModel):
    api_groups: List[str] = field(default_factory=list)
    resources: List[str] = field(default_factory=list)
    resource_names: List[str] = field(default_factory=list)
    verbs: List[str] = field(default_factory=list)


@dataclass
class Role(KubeObject):
    rules: List[PolicyRule] = field(default_factory=list)


@dataclass
class RoleBinding(KubeObject):
    subjects: List[Subject] = field(default_factory=list)
    role_ref: RoleRef = field(default_factory=RoleRef)


@dataclass
class ClusterRoleBinding(KubeObject):
    subjects: List[Subject] = field(default_factory=list)
    role_ref: RoleRef = field(default_factory=RoleRef)


_g = "rbac.authorization.k8s.io/v1"
default_scheme.register(_g, "Role", Role)
default_scheme.register(_g, "RoleBinding", RoleBinding)
default_scheme.register(_g, "ClusterRoleBinding", ClusterRoleBinding)
