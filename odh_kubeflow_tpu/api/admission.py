"""admissionregistration.k8s.io/v1 — MutatingWebhookConfiguration.

The reference registers its webhook endpoint via a kustomize-shipped
MutatingWebhookConfiguration (reference odh-notebook-controller
config/webhook/manifests.yaml; served at main.go:213-227). Here the type is
first-class so the in-tree API server can perform the same callout: on
matching writes it POSTs AdmissionReview v1 to clientConfig.url (verified
against caBundle) and applies the returned JSONPatch.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ..apimachinery import KubeModel, KubeObject, default_scheme


@dataclass
class WebhookServiceReference(KubeModel):
    name: str = ""
    namespace: str = ""
    path: str = ""
    port: int = 443


@dataclass
class WebhookClientConfig(KubeModel):
    url: str = ""
    service: Optional[WebhookServiceReference] = None
    ca_bundle: str = ""  # base64 PEM, as on the wire


@dataclass
class RuleWithOperations(KubeModel):
    operations: List[str] = field(default_factory=list)  # CREATE/UPDATE/*
    api_groups: List[str] = field(default_factory=list)
    api_versions: List[str] = field(default_factory=list)
    resources: List[str] = field(default_factory=list)


@dataclass
class MutatingWebhook(KubeModel):
    name: str = ""
    client_config: WebhookClientConfig = field(default_factory=WebhookClientConfig)
    rules: List[RuleWithOperations] = field(default_factory=list)
    failure_policy: str = "Fail"
    side_effects: str = "None"
    admission_review_versions: List[str] = field(default_factory=lambda: ["v1"])
    timeout_seconds: int = 10


@dataclass
class MutatingWebhookConfiguration(KubeObject):
    webhooks: List[MutatingWebhook] = field(default_factory=list)


default_scheme.register(
    "admissionregistration.k8s.io/v1",
    "MutatingWebhookConfiguration",
    MutatingWebhookConfiguration,
)
