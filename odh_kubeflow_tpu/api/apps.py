"""apps/v1 — StatefulSet (the workload primitive: one Notebook -> one STS whose
replicas = TPU slice host count) and a minimal Deployment (the reference's
reconcilehelper also handles Deployments — common/reconcilehelper/util.go:18-60)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..apimachinery import KubeObject, KubeModel, default_scheme
from ..apimachinery.labels import LabelSelector
from .core import PodTemplateSpec


@dataclass
class StatefulSetSpec(KubeModel):
    replicas: Optional[int] = None
    selector: LabelSelector = field(default_factory=LabelSelector)
    service_name: str = ""
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    pod_management_policy: str = ""
    volume_claim_templates: List[Dict[str, Any]] = field(default_factory=list)


@dataclass
class StatefulSetStatus(KubeModel):
    replicas: int = 0
    ready_replicas: int = 0
    current_replicas: int = 0
    updated_replicas: int = 0
    observed_generation: int = 0


@dataclass
class StatefulSet(KubeObject):
    spec: StatefulSetSpec = field(default_factory=StatefulSetSpec)
    status: StatefulSetStatus = field(default_factory=StatefulSetStatus)


@dataclass
class DeploymentSpec(KubeModel):
    replicas: Optional[int] = None
    selector: LabelSelector = field(default_factory=LabelSelector)
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)


@dataclass
class DeploymentStatus(KubeModel):
    replicas: int = 0
    ready_replicas: int = 0


@dataclass
class Deployment(KubeObject):
    spec: DeploymentSpec = field(default_factory=DeploymentSpec)
    status: DeploymentStatus = field(default_factory=DeploymentStatus)


default_scheme.register("apps/v1", "StatefulSet", StatefulSet)
default_scheme.register("apps/v1", "Deployment", Deployment)
