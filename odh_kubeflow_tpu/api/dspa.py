"""DataSciencePipelinesApplication — the pipeline server CR the Elyra
runtime config is derived from.

Minimal model of the fields the reference consumes
(odh controllers/notebook_dspa_secret.go:189-273: spec.objectStorage.
externalStorage {host, scheme, bucket, s3CredentialsSecret{secretName,
accessKey, secretKey}} plus the CR's existence/name for endpoints and
ownership).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ..apimachinery import KubeModel, KubeObject, default_scheme

DSPA_API_VERSION = "datasciencepipelinesapplications.opendatahub.io/v1"
DSPA_NAME = "dspa"  # the reference hard-codes this instance name


@dataclass
class S3CredentialsSecret(KubeModel):
    secret_name: str = ""
    access_key: str = ""  # key inside the secret holding the access key id
    secret_key: str = ""  # key inside the secret holding the secret key


@dataclass
class ExternalStorage(KubeModel):
    host: str = ""
    scheme: str = "https"
    bucket: str = ""
    region: str = ""
    s3_credentials_secret: Optional[S3CredentialsSecret] = None


@dataclass
class ObjectStorage(KubeModel):
    external_storage: Optional[ExternalStorage] = None


@dataclass
class DSPASpec(KubeModel):
    object_storage: Optional[ObjectStorage] = None
    dsp_version: str = ""


@dataclass
class DataSciencePipelinesApplication(KubeObject):
    spec: DSPASpec = field(default_factory=DSPASpec)
    status: Dict[str, Any] = field(default_factory=dict)


default_scheme.register(
    DSPA_API_VERSION, "DataSciencePipelinesApplication", DataSciencePipelinesApplication
)
