"""TPUJob CRD, v1beta1 (ISSUE 10).

The third workload class: gang-scheduled batch/RL training jobs contending
for the same chips as notebooks and serving endpoints. The spec mirrors the
Notebook CR's shape — the same ``spec.tpu`` block drives slice planning, the
same pod-template escape hatch exists — so the reconciler reuses the
STS/headless-service/scheduler/slicepool machinery rather than growing a
parallel batch stack.

Layouts come straight from the Podracer paper (PAPERS.md):

- ``anakin``: ONE SPMD gang — acting and learning colocated on a single
  slice (``spec.tpu`` is the whole job),
- ``sebulba``: a SPLIT actor-gang + learner-gang — ``spec.tpu`` shapes the
  learner slice, ``spec.actors`` shapes the actor slice, and admission is
  atomic across BOTH gangs (both slices secured, or neither; a half-placed
  sebulba job would deadlock against another half-placed one).

A job is preemptible by design: the oversubscription reclaimer ranks it in
the ONE priority ordering with notebooks and endpoints (batch defaults
BELOW interactive via ``JOB_DEFAULT_PRIORITY``), and a preempted job
checkpoints, parks ``Preempted``, and requeues to resume from the saved
step — it loses only progress since the last checkpoint.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ...apimachinery import Condition, KubeModel, KubeObject, default_scheme
from ..notebook.v1beta1 import NotebookTemplateSpec, TPUSpec, TPUStatus

GROUP = "kubeflow.org"
API_VERSION = "kubeflow.org/v1beta1"
KIND = "TPUJob"

LAYOUT_ANAKIN = "anakin"
LAYOUT_SEBULBA = "sebulba"


@dataclass
class TPUJobSpec(KubeModel):
    # learner/SPMD gang (anakin: the whole job). `priority` rides here and
    # feeds the one reclaim ordering shared with notebooks/endpoints; unset
    # (0) reads as JOB_DEFAULT_PRIORITY — batch below interactive.
    tpu: Optional[TPUSpec] = None
    layout: str = LAYOUT_ANAKIN  # anakin | sebulba
    # sebulba actor gang shape (required for layout=sebulba; per-gang
    # topology — actors typically run a smaller/cheaper slice)
    actors: Optional[TPUSpec] = None
    # step budget per completion; the job Succeeds when the last ACKED
    # checkpoint step reaches steps * completions (the workload reports
    # progress through the /tpu/checkpoint ack's step counter)
    steps: int = 1000
    completions: int = 1
    # checkpoint cadence: while Running, every `checkpointPeriodS` the
    # controller opens a Checkpointing window and drives the learner gang's
    # /tpu/checkpoint hooks — the durable resume point preemption relies on
    checkpoint_period_s: float = 30.0
    # unexplained failures (host loss with no preemption notice) tolerated
    # before Failed; reclaim-driven preemptions never count against this
    backoff_limit: int = 3
    # wallclock cap from the FIRST admission (queue wait before it is free;
    # parked/requeued time after it is not); 0 = off
    max_runtime_s: float = 0.0
    # pod template override (the training image); defaulted like a notebook's
    template: NotebookTemplateSpec = field(default_factory=NotebookTemplateSpec)


@dataclass
class TPUJobStatus(KubeModel):
    conditions: List[Condition] = field(default_factory=list)
    # human mirror of the annotation-durable machine (the annotation is the
    # durable truth; this is for kubectl get)
    phase: str = ""
    ready_replicas: int = 0  # ready hosts across all gangs
    completed_steps: int = 0  # last acked checkpoint step
    preemptions: int = 0  # checkpoint-preempt-requeue round trips survived
    failures: int = 0  # unexplained interruptions charged to backoffLimit
    # spec generation the terminal state judged: a spec bump past it reruns
    observed_generation: int = 0
    tpu: Optional[TPUStatus] = None  # learner gang


@dataclass
class TPUJob(KubeObject):
    spec: TPUJobSpec = field(default_factory=TPUJobSpec)
    status: TPUJobStatus = field(default_factory=TPUJobStatus)


default_scheme.register(API_VERSION, KIND, TPUJob)
