from .v1beta1 import (
    API_VERSION,
    GROUP,
    KIND,
    LAYOUT_ANAKIN,
    LAYOUT_SEBULBA,
    TPUJob,
    TPUJobSpec,
    TPUJobStatus,
)

__all__ = [
    "API_VERSION",
    "GROUP",
    "KIND",
    "LAYOUT_ANAKIN",
    "LAYOUT_SEBULBA",
    "TPUJob",
    "TPUJobSpec",
    "TPUJobStatus",
]
