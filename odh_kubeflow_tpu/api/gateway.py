"""gateway.networking.k8s.io — HTTPRoute / ReferenceGrant / Gateway.

The reference routes every notebook through a central-namespace HTTPRoute with
a cross-namespace backendRef authorized by a per-user-namespace ReferenceGrant
(reference odh controllers/notebook_route.go:50-131,
notebook_referencegrant.go:39-69). Same model here, on GKE Gateway API.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..apimachinery import KubeObject, KubeModel, default_scheme

GATEWAY_V1 = "gateway.networking.k8s.io/v1"
GATEWAY_V1BETA1 = "gateway.networking.k8s.io/v1beta1"


@dataclass
class ParentReference(KubeModel):
    group: str = ""
    kind: str = ""
    namespace: str = ""
    name: str = ""


@dataclass
class HTTPPathMatch(KubeModel):
    type: str = "PathPrefix"
    value: str = "/"


@dataclass
class HTTPRouteMatch(KubeModel):
    path: Optional[HTTPPathMatch] = None


@dataclass
class BackendRef(KubeModel):
    group: str = ""
    kind: str = ""
    name: str = ""
    namespace: str = ""
    port: Optional[int] = None
    weight: Optional[int] = None


@dataclass
class HTTPBackendRef(BackendRef):
    pass


@dataclass
class HTTPRouteRule(KubeModel):
    matches: List[HTTPRouteMatch] = field(default_factory=list)
    backend_refs: List[HTTPBackendRef] = field(default_factory=list)
    filters: List[Dict[str, Any]] = field(default_factory=list)


@dataclass
class HTTPRouteSpec(KubeModel):
    parent_refs: List[ParentReference] = field(default_factory=list)
    hostnames: List[str] = field(default_factory=list)
    rules: List[HTTPRouteRule] = field(default_factory=list)


@dataclass
class HTTPRoute(KubeObject):
    spec: HTTPRouteSpec = field(default_factory=HTTPRouteSpec)
    status: Dict[str, Any] = field(default_factory=dict)


@dataclass
class ReferenceGrantFrom(KubeModel):
    group: str = ""
    kind: str = ""
    namespace: str = ""


@dataclass
class ReferenceGrantTo(KubeModel):
    group: str = ""
    kind: str = ""
    name: str = ""


@dataclass
class ReferenceGrantSpec(KubeModel):
    from_: List[ReferenceGrantFrom] = field(
        default_factory=list, metadata={"json": "from"}
    )
    to: List[ReferenceGrantTo] = field(default_factory=list)


@dataclass
class ReferenceGrant(KubeObject):
    spec: ReferenceGrantSpec = field(default_factory=ReferenceGrantSpec)


@dataclass
class GatewayListener(KubeModel):
    name: str = ""
    hostname: str = ""
    port: int = 0
    protocol: str = ""


@dataclass
class GatewaySpec(KubeModel):
    gateway_class_name: str = ""
    listeners: List[GatewayListener] = field(default_factory=list)


@dataclass
class Gateway(KubeObject):
    spec: GatewaySpec = field(default_factory=GatewaySpec)
    status: Dict[str, Any] = field(default_factory=dict)


default_scheme.register(GATEWAY_V1, "HTTPRoute", HTTPRoute)
default_scheme.register(GATEWAY_V1, "Gateway", Gateway)
default_scheme.register(GATEWAY_V1BETA1, "ReferenceGrant", ReferenceGrant)
