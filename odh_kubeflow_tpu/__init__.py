"""odh_kubeflow_tpu — a TPU-native notebook workbench operator framework.

A from-scratch re-imagining of the ODH Kubeflow notebook-controller stack
(see SURVEY.md / ARCHITECTURE.md): Kubernetes-style API machinery, an
in-process control plane, a controller runtime, the Notebook operator suite
(core reconciler, mutating webhook, culler, TPU extension), and the JAX-side
components (slice planner, in-pod probe, workbench workload library).
"""

__version__ = "0.1.0"
