"""Composition root: wire the full notebook operator onto a store.

Single manager, single binary — SURVEY §7's deliberate simplification of the
reference's two-process split (notebook-controller/main.go:58-148 + odh
main.go:117-245 watch the same CR from two managers; here one manager hosts
all four controllers and the webhook registers into the store's admission
chain)."""
from __future__ import annotations

import logging
from typing import Optional

from .cluster.store import Store
from .controllers import (
    Config,
    CullingReconciler,
    EventMirrorController,
    InferenceEndpointReconciler,
    NotebookReconciler,
    NotebookWebhook,
    ProbeStatusController,
    SliceRepairController,
    SuspendResumeController,
    TPUJobReconciler,
    TPUWorkbenchReconciler,
)
from .controllers.metrics import NotebookMetrics
from .runtime.manager import Manager

log = logging.getLogger(__name__)


def build_manager(
    store: Store,
    config: Optional[Config] = None,
    leader_election: bool = False,
    http_get=None,
    shard=None,
    lease_duration: float = 15.0,
    renew_period: float = 5.0,
    register_webhook: bool = True,
) -> Manager:
    """Everything the two reference managers run, on one Manager.

    `store` is either the in-process Store (sim / single-binary mode: the
    webhook registers straight into its admission chain) or a RemoteStore
    speaking to an API server over the wire — in that mode admission runs
    server-side via MutatingWebhookConfiguration + the HTTPS webhook server
    (runtime/webhook_server.py; see serve_webhook), exactly the reference's
    deployment shape (odh main.go:213-227).

    `shard` (runtime/manager.py ShardSpec) partitions the reconcile keyspace:
    run one build_manager per shard (plus standbys with leader_election=True)
    and each manager reconciles only the objects its shard owns, under its
    own per-shard lease. In that wiring pass `register_webhook=False` for
    every replica but one — the in-process admission chain is store-global,
    and mutation must run once per request, not once per manager."""
    config = config or Config.from_env()
    mgr = Manager(
        store,
        leader_election=leader_election,
        leader_election_id="tpu-notebook-controller",
        shard=shard,
        lease_duration=lease_duration,
        renew_period=renew_period,
    )
    # status-write coalescing (runtime/coalesce.py): the notebook/endpoint/
    # job mirrors route their patch_status through this, batching adjacent
    # patches per object per window; rides the manager lifecycle so stop()
    # flushes whatever is parked
    from .runtime.coalesce import StatusCoalescer

    mgr.status_coalescer = StatusCoalescer(
        mgr.client, window_s=config.status_coalesce_window_s
    )
    mgr.add_service(mgr.status_coalescer)
    metrics = NotebookMetrics(mgr.metrics, mgr.client)

    if register_webhook and hasattr(store, "register_webhook"):
        NotebookWebhook(mgr.client, config).register(store)
    NotebookReconciler(mgr, config, metrics=metrics).setup()
    EventMirrorController(mgr).setup()
    TPUWorkbenchReconciler(mgr, config).setup()
    ProbeStatusController(mgr, config, http_get=http_get, metrics=metrics).setup()
    CullingReconciler(mgr, config, http_get=http_get, metrics=metrics).setup()
    SliceRepairController(mgr, config, http_get=http_get).setup()
    SuspendResumeController(mgr, config, http_get=http_get).setup()
    InferenceEndpointReconciler(mgr, config, http_get=http_get).setup()
    TPUJobReconciler(mgr, config, http_get=http_get).setup()
    if config.pool_prewarm > 0:
        from .cluster.slicepool import PoolPrewarmer
        from .tpu import plan_slice

        shape = plan_slice(
            config.pool_prewarm_accelerator, config.pool_prewarm_topology
        )
        mgr.add_service(PoolPrewarmer(
            mgr.client, shape.gke_accelerator, shape.topology,
            target=config.pool_prewarm,
            period_s=max(0.5, config.readiness_probe_period_s / 2),
        ))
    if config.slo_enabled:
        _wire_observability(mgr, config)
    return mgr


def _wire_observability(mgr: Manager, config: Config) -> None:
    """SLO engine -> alert manager -> flight recorder -> canary prober: the
    judgement layer over the raw telemetry (ISSUE 5). All of it rides the
    manager lifecycle (add_service) and the debug mux (/debug/slo,
    /debug/incidents) finds it through the named manager attributes."""
    from .runtime.alerts import AlertManager, default_rules
    from .runtime.flightrecorder import recorder
    from .runtime.slo import SLOEngine, default_slos
    from .tpu import telemetry

    slos = default_slos()
    engine = SLOEngine(
        registry=mgr.metrics,
        slos=slos,
        window_scale=config.slo_window_scale,
        eval_period_s=config.slo_eval_period_s or None,
    )
    alert_mgr = AlertManager(
        rules=default_rules(slos), manager=mgr, recorder=recorder
    )
    # THE inhibition contract (ARCHITECTURE.md): an active repair episode
    # already explains degraded readiness — suppress the symptom alerts,
    # keep the availability page live
    alert_mgr.register_inhibitor(
        "readiness",
        lambda: telemetry.slice_repairs_in_progress.value() > 0,
        name="slice-repair-in-progress",
    )
    engine.add_listener(alert_mgr.evaluate)
    mgr.slo_engine = engine
    mgr.alert_manager = alert_mgr
    mgr.flight_recorder = recorder
    mgr.add_service(engine)
    if config.canary_period_s > 0:
        from .runtime.prober import CanaryProber

        prober = CanaryProber(
            mgr,
            period_s=config.canary_period_s,
            timeout_s=config.canary_timeout_s,
            namespace=config.canary_namespace,
            accelerator=config.canary_accelerator,
            topology=config.canary_topology,
        )
        mgr.prober = prober
        mgr.add_service(prober)
    if config.autoscale_period_s > 0:
        from .runtime.autoscaler import ReplicaAutoscaler

        autoscaler = ReplicaAutoscaler(
            mgr,
            period_s=config.autoscale_period_s,
            stabilization_s=config.autoscale_stabilization_s,
            idle_s=config.autoscale_idle_s,
        )
        mgr.autoscaler = autoscaler
        mgr.add_service(autoscaler)
    if config.accounting_period_s > 0:
        from .runtime import accounting

        accountant = accounting.ChipAccountant(
            mgr.client,
            period_s=config.accounting_period_s,
            idle_after_s=config.accounting_idle_after_s,
        )
        # module handle: the flight recorder freezes this accountant's
        # snapshot into incident bundles; /debug/accounting reads it via
        # the named manager attribute
        accounting.set_current(accountant)
        mgr.accountant = accountant
        mgr.add_service(accountant)


def serve_webhook(client, config: Config, cert_dir: str, port: int = 8443):
    """Serve the mutating webhook over HTTPS from a cert dir (tls.crt/tls.key,
    the kubernetes.io/tls Secret layout) — the remote-mode admission path."""
    import os

    from .runtime.cached_client import TTLReadClient
    from .runtime.webhook_server import WebhookServer

    server = WebhookServer(
        host="0.0.0.0",
        port=port,
        certfile=os.path.join(cert_dir, "tls.crt"),
        keyfile=os.path.join(cert_dir, "tls.key"),
    )
    # TTL read memo over the webhook's dedicated client: admission reads the
    # same per-ns ConfigMaps every review; see TTLReadClient
    server.register(
        "/mutate-notebook-v1", NotebookWebhook(TTLReadClient(client), config).handle
    )
    return server.start()


def main() -> None:  # pragma: no cover - thin CLI shell
    """Entrypoint, resolved like ctrl.GetConfigOrDie:

    - in a pod (KUBERNETES_SERVICE_HOST set): in-cluster config — SA token +
      CA from the ServiceAccount mount; the deployed shape,
    - KUBECONFIG set: connect via kubeconfig (remote dev shape),
    - otherwise: boot the in-process SimCluster (demo shape).
    In both real modes the mutating webhook serves over HTTPS from
    WEBHOOK_CERT_DIR and all controllers run against the real cluster.
    """
    import os

    # structured JSON logs by default (every record carries trace/span ids +
    # notebook identity via utils/logging.py); LOG_FORMAT=text opts out
    if os.environ.get("LOG_FORMAT", "json") == "json":
        from .utils.logging import setup_json_logging

        setup_json_logging(level=logging.INFO)
    else:
        logging.basicConfig(level=logging.INFO)
    # warnings+ also land in the flight-recorder ring, so incident bundles
    # carry the log lines around the failure
    from .runtime.flightrecorder import recorder as _recorder

    logging.getLogger().addHandler(_recorder.log_handler(level=logging.WARNING))
    config = Config.from_env()
    cluster = None
    webhook_server = None
    # explicit signals only: a merely-existing ~/.kube/config must never flip
    # a demo run into mutating whatever cluster current-context points at
    if os.environ.get("KUBERNETES_SERVICE_HOST") or os.environ.get("KUBECONFIG"):
        from .cluster.remote import RemoteStore

        # --qps/--burst analog (reference notebook-controller/main.go:65-85
        # overrides the rest config the same way): 0/unset keeps the client
        # defaults (20/30), negative means unlimited (rest.Config's -1
        # convention), junk falls back to the default rather than crashing
        # the manager at boot
        def _env_num(name, default, cast):
            try:
                val = cast(os.environ.get(name, "") or default)
            except ValueError:
                logging.getLogger(__name__).warning(
                    "ignoring non-numeric %s=%r", name, os.environ.get(name)
                )
                return default
            return val if val else default

        qps = _env_num("KUBE_API_QPS", 20.0, float)
        burst = _env_num("KUBE_API_BURST", 30, int)
        if qps < 0:
            qps = 0.0  # RemoteStore treats qps<=0 as unthrottled

        # KUBECONFIG first (GetConfig precedence): an explicit override must
        # win over the auto-injected pod env, or a manager run inside ANY pod
        # would silently target the host cluster
        if os.environ.get("KUBECONFIG"):
            store = RemoteStore.from_kubeconfig(qps=qps, burst=burst)
        else:
            store = RemoteStore.in_cluster(qps=qps, burst=burst)
        cert_dir = os.environ.get("WEBHOOK_CERT_DIR", "/tmp/k8s-webhook-server/serving-certs")
        if os.path.exists(os.path.join(cert_dir, "tls.crt")):
            from .cluster.client import Client

            webhook_server = serve_webhook(
                Client(store),
                config,
                cert_dir,
                # deploy webhook Service targets 9443 (controller-runtime's
                # default serving port; see deploy/manifests.py webhook_service)
                port=int(os.environ.get("WEBHOOK_PORT", "9443")),
            )
            log.info("mutating webhook serving on :%s", webhook_server.httpd.server_address[1])
        elif os.environ.get("KUBERNETES_SERVICE_HOST") and not os.environ.get(
            "KUBECONFIG"
        ):
            # (an explicit KUBECONFIG override may legitimately run in a pod
            # without webhook certs — only the DEPLOYED shape must fail hard)
            # deployed shape: a MutatingWebhookConfiguration points at this
            # pod — starting without the webhook would silently bypass
            # admission (Ignore) or hard-fail every Notebook write (Fail)
            raise RuntimeError(
                f"webhook serving certs missing at {cert_dir} "
                "(is the webhook-server-cert secret mounted?)"
            )
        else:
            log.warning(
                "WEBHOOK_CERT_DIR %s has no tls.crt: mutating webhook NOT "
                "served (admission runs only if the cluster calls it)",
                cert_dir,
            )
        mgr = build_manager(store, config, leader_election=True)
        log.info("tpu-notebook-controller running (kubeconfig: %s)", store.base_url)
    else:
        from .cluster.sim import SimCluster

        cluster = SimCluster().start()
        # somewhere for the CPU canary (and demo notebooks) to land
        cluster.add_cpu_pool("default", nodes=2)
        if config.canary_period_s <= 0 and "CANARY_PERIOD_S" not in os.environ:
            # demo shape: the black-box canary is on by default against the
            # sim — but an EXPLICIT CANARY_PERIOD_S=0 stays off (the env knob
            # documents 0 as disabled; only the unset default is upgraded)
            config.canary_period_s = 60.0
        mgr = build_manager(cluster.store, config, http_get=cluster.http_get)
        log.info("tpu-notebook-controller running (in-process cluster)")
    # /metrics on :8080, /healthz + /readyz on :8081 (reference
    # notebook-controller/main.go:125-133; deploy probes point here).
    # MUST bind before start(): with leader election, start() blocks waiting
    # out the old lease, and a standby that doesn't answer its liveness
    # probe would be killed into CrashLoopBackOff
    endpoints = mgr.serve_endpoints(
        metrics_port=int(os.environ.get("METRICS_PORT", "8080")),
        health_port=int(os.environ.get("HEALTH_PORT", "8081")),
    )
    mgr.start()
    try:
        import signal
        import threading

        stop = threading.Event()
        signal.signal(signal.SIGINT, lambda *a: stop.set())
        signal.signal(signal.SIGTERM, lambda *a: stop.set())
        stop.wait()
    finally:
        mgr.stop()
        endpoints.stop()
        if webhook_server is not None:
            webhook_server.stop()
        if cluster is not None:
            cluster.stop()


if __name__ == "__main__":
    main()
