"""Composition root: wire the full notebook operator onto a store.

Single manager, single binary — SURVEY §7's deliberate simplification of the
reference's two-process split (notebook-controller/main.go:58-148 + odh
main.go:117-245 watch the same CR from two managers; here one manager hosts
all four controllers and the webhook registers into the store's admission
chain)."""
from __future__ import annotations

import logging
from typing import Optional

from .cluster.store import Store
from .controllers import (
    Config,
    CullingReconciler,
    EventMirrorController,
    NotebookReconciler,
    NotebookWebhook,
    TPUWorkbenchReconciler,
)
from .controllers.metrics import NotebookMetrics
from .runtime.manager import Manager

log = logging.getLogger(__name__)


def build_manager(
    store: Store,
    config: Optional[Config] = None,
    leader_election: bool = False,
    http_get=None,
) -> Manager:
    """Everything the two reference managers run, on one Manager."""
    config = config or Config.from_env()
    mgr = Manager(
        store,
        leader_election=leader_election,
        leader_election_id="tpu-notebook-controller",
    )
    metrics = NotebookMetrics(mgr.metrics, mgr.client)

    NotebookWebhook(mgr.client, config).register(store)
    NotebookReconciler(mgr, config, metrics=metrics).setup()
    EventMirrorController(mgr).setup()
    TPUWorkbenchReconciler(mgr, config).setup()
    CullingReconciler(mgr, config, http_get=http_get, metrics=metrics).setup()
    return mgr


def main() -> None:  # pragma: no cover - thin CLI shell
    logging.basicConfig(level=logging.INFO)
    from .cluster.sim import SimCluster

    config = Config.from_env()
    cluster = SimCluster().start()
    mgr = build_manager(cluster.store, config, http_get=cluster.http_get)
    mgr.start()
    log.info("tpu-notebook-controller running (in-process cluster)")
    try:
        import signal
        import threading

        stop = threading.Event()
        signal.signal(signal.SIGINT, lambda *a: stop.set())
        signal.signal(signal.SIGTERM, lambda *a: stop.set())
        stop.wait()
    finally:
        mgr.stop()
        cluster.stop()


if __name__ == "__main__":
    main()
