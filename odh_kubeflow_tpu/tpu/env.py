"""TPU runtime environment injection.

The reference webhook injects CUDA-toolkit/GPU env; here the webhook injects
the JAX/PJRT/libtpu contract instead (BASELINE.json north star): platform
selection, per-ordinal worker identity, the slice's host roster, and the
`jax.distributed` coordinator derived from the headless Service's stable DNS
(host 0). For multi-host slices these env vars are exactly what
`jax.distributed.initialize()` and libtpu need to wire the ICI mesh.
"""
from __future__ import annotations

from typing import Dict, List

from .topology import SliceShape, chips_per_host_bounds, host_bounds

COORDINATOR_PORT = 8476  # jax.distributed default coordinator port


def pod_dns(name: str, ordinal: int, service: str, namespace: str, domain: str) -> str:
    return f"{name}-{ordinal}.{service}.{namespace}.svc.{domain}"


def tpu_env(
    shape: SliceShape,
    notebook_name: str,
    service_name: str,
    namespace: str,
    cluster_domain: str = "cluster.local",
    runtime: str = "jax",
) -> List[Dict[str, str]]:
    """Env var list (name/value dicts, ordinal templated) for the primary
    container. TPU_WORKER_ID derives from the pod ordinal via the downward
    API (statefulset pod-index label) — see webhook injection."""
    hostnames = ",".join(
        pod_dns(notebook_name, i, service_name, namespace, cluster_domain)
        for i in range(shape.hosts)
    )
    coordinator = (
        pod_dns(notebook_name, 0, service_name, namespace, cluster_domain)
        + f":{COORDINATOR_PORT}"
    )
    env = [
        {"name": "TPU_ACCELERATOR_TYPE", "value": shape.accelerator_type},
        {"name": "TPU_TOPOLOGY", "value": shape.topology},
        {"name": "TPU_WORKER_HOSTNAMES", "value": hostnames},
        {"name": "TPU_CHIPS_PER_HOST_BOUNDS", "value": chips_per_host_bounds(shape)},
        {"name": "TPU_HOST_BOUNDS", "value": host_bounds(shape)},
        {"name": "TPU_RUNTIME_METRICS_PORTS", "value": "8431"},
        {"name": "NB_TPU_HOSTS", "value": str(shape.hosts)},
        {"name": "NB_TPU_CHIPS_EXPECTED", "value": str(shape.chips)},
    ]
    if runtime == "pytorch-xla":
        env += [
            {"name": "PJRT_DEVICE", "value": "TPU"},
            {"name": "XLA_USE_SPMD", "value": "1"},
        ]
    else:
        env += [{"name": "JAX_PLATFORMS", "value": "tpu"}]
    if shape.multi_host:
        env += [
            {"name": "JAX_COORDINATOR_ADDRESS", "value": coordinator},
            {"name": "JAX_NUM_PROCESSES", "value": str(shape.hosts)},
            # TPU_WORKER_ID / JAX_PROCESS_ID come from the pod ordinal,
            # injected per-pod via the downward-API (pod-index label)
        ]
    return env


def ordinal_env() -> List[Dict[str, object]]:
    """Downward-API env: the StatefulSet pod index becomes the TPU worker id
    (the per-ordinal piece the reference's single-pod design never needed —
    SURVEY §5 long-context analog: every {name}-0 site generalized)."""
    field_ref = {
        "fieldRef": {"fieldPath": "metadata.labels['apps.kubernetes.io/pod-index']"}
    }
    return [
        {"name": "TPU_WORKER_ID", "valueFrom": field_ref},
        {"name": "JAX_PROCESS_ID", "valueFrom": field_ref},
    ]
