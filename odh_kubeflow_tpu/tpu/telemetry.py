"""TPU-side telemetry: workload signals in the same registry the manager
scrapes (ISSUE 2 tentpole).

The control plane can say how fast a slice came up; these series say what the
slice is DOING once up — train/decode step-time histograms, throughput and
MFU gauges, and per-device memory. Sources:

- explicit observations from the workload host loop (`observe_train_step` /
  `observe_decode_step`: bench.py and any training driver call these at the
  same place they already compute tokens/s),
- the in-pod probe agent's runtime-state sampler (probe/agent.py), which
  feeds `record_device_memory` from the per-device memory_stats it already
  collects for activity detection — no extra device round-trips.

Everything registers idempotently on the global registry, so the manager's
`/metrics`, the probe agent's process, and a notebook kernel all share one
series set when co-located (the sim), and partition naturally when not.
"""
from __future__ import annotations

from typing import Iterable, Optional, Sequence, Tuple

from ..runtime.metrics import global_registry
from ..utils import profiler

_STEP_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30)
# decode needs sub-ms resolution the train buckets don't: a v5e decode step
# lands around 0.5-1ms/token (BENCH_r05: 10k tok/s single-slot), so the
# shared seconds-leaning buckets collapsed the entire observed range into
# the first bucket (metrics_lint bucket-coverage rule, ISSUE 15)
_DECODE_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                   0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30)

train_step_seconds = global_registry.histogram(
    "tpu_train_step_duration_seconds",
    "Per-step wall-clock of the training loop (host-observed, jit dispatch "
    "amortized by the caller's timing method)",
    buckets=_STEP_BUCKETS,
)
decode_step_seconds = global_registry.histogram(
    "tpu_decode_step_duration_seconds",
    "Per-token wall-clock of autoregressive decode",
    buckets=_DECODE_BUCKETS,
)
tokens_per_second = global_registry.gauge(
    "tpu_tokens_per_second",
    "Most recent throughput, by phase (train | decode)",
    labels=("phase",),
)
mfu = global_registry.gauge(
    "tpu_mfu",
    "Most recent model-FLOPs utilization (0-1), by phase (train | decode)",
    labels=("phase",),
)
device_memory_bytes = global_registry.gauge(
    "tpu_device_memory_bytes",
    "Bytes in use per local device (from the runtime's memory_stats)",
    labels=("device",),
)

# ---- slice interruption / repair telemetry (ISSUE 4): what the accelerator
# layer does TO the fleet, and how fast the repair loop heals it. Sources:
# controllers/slice_repair.py observes these at detection / completion. ----

slice_interruptions_total = global_registry.counter(
    "tpu_slice_interruptions_total",
    "Slice-level interruptions detected (a Ready slice going Degraded), "
    "by cause (HostPreempted | ChipFailure | ICIDegraded | HostUnreachable)",
    labels=("cause",),
)
slice_repair_duration_seconds = global_registry.histogram(
    "tpu_slice_repair_duration_seconds",
    "Degraded -> Ready-again wall-clock per repaired slice (MTTR)",
    buckets=(0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60, 120, 300, 600),
)
slice_repairs_total = global_registry.counter(
    "tpu_slice_repairs_total",
    "Completed repair episodes, by result (repaired | failed)",
    labels=("result",),
)
slice_checkpoint_saves_total = global_registry.counter(
    "tpu_slice_checkpoint_saves_total",
    "Hosts that acked a checkpoint save inside a checkpoint-before-evict "
    "window",
)
slice_goodput_ratio = global_registry.gauge(
    "tpu_slice_goodput_ratio",
    "Cumulative fraction of tracked slice-lifetime spent Ready rather than "
    "Degraded/Repairing (1.0 = no interruption downtime observed)",
)
slice_repairs_in_progress = global_registry.gauge(
    "tpu_slice_repairs_in_progress",
    "Notebooks currently inside a repair episode (any repair state). The "
    "alert manager's slice-repair inhibitor keys off this: readiness-"
    "category burn alerts are suppressed while > 0 (ARCHITECTURE.md)",
)


class GoodputAccounting:
    """Cumulative goodput bookkeeping behind `tpu_slice_goodput_ratio`.

    The slice-repair controller calls `observe(lifetime_s, downtime_s)` on
    every reconcile: the delta since the notebook was last seen extends
    tracked lifetime, and counts as downtime when the notebook was in any
    repair state for that interval. One process-wide instance — goodput is
    a fleet number.

    Since ISSUE 17 the accumulators live in the fleet accounting ledger
    (runtime/accounting.py `slice_goodput`) — this class keeps the public
    observe() surface as a VIEW, and gains the ledger's reset_for_test():
    lifetime-downtime is the "good" numerator, lifetime the total."""

    def __init__(self) -> None:
        from ..runtime.accounting import slice_goodput

        self._ledger = slice_goodput
        self._ledger.bind_gauge(slice_goodput_ratio)

    def observe(self, lifetime_s: float, downtime_s: float = 0.0) -> None:
        lifetime_s = max(0.0, lifetime_s)
        downtime_s = min(max(0.0, downtime_s), lifetime_s)
        self._ledger.record(lifetime_s - downtime_s, lifetime_s)

    def reset_for_test(self) -> None:
        self._ledger.reset_for_test()


goodput = GoodputAccounting()


def observe_train_step(
    step_s: float,
    tokens: Optional[float] = None,
    mfu_est: Optional[float] = None,
) -> None:
    """One training step: step wall-clock, plus derived throughput/MFU when
    the caller knows them (bench.py passes its slope-measured values)."""
    train_step_seconds.observe(step_s)
    if tokens is not None and step_s > 0:
        tokens_per_second.set(tokens / step_s, phase="train")
    if mfu_est is not None:
        mfu.set(mfu_est, phase="train")


def observe_decode_step(
    step_s: float,
    tokens: Optional[float] = None,
    mfu_est: Optional[float] = None,
) -> None:
    decode_step_seconds.observe(step_s)
    if tokens is not None and step_s > 0:
        tokens_per_second.set(tokens / step_s, phase="decode")
    if mfu_est is not None:
        mfu.set(mfu_est, phase="decode")


def record_device_memory(
    mems: Iterable[Tuple[Optional[float], Optional[float]]]
) -> None:
    """Publish per-device bytes-in-use from (bytes_in_use, num_allocs) pairs
    (the probe agent's sampler shape); devices are labeled by local index.
    Under PROFILE=1 the max across devices also feeds the profiler's
    per-region HBM watermarks — the sampler the agent already runs doubles
    as the profiler's memory probe, zero extra device round-trips."""
    peak: Optional[float] = None
    for i, (bytes_in_use, _allocs) in enumerate(mems):
        if bytes_in_use is not None:
            device_memory_bytes.set(float(bytes_in_use), device=str(i))
            if peak is None or float(bytes_in_use) > peak:
                peak = float(bytes_in_use)
    if peak is not None:
        profiler.on_device_memory(peak)


def update_device_memory() -> int:
    """Scrape jax.local_devices() memory_stats directly (for hosts that run
    no probe agent); returns devices published. Never raises — a CPU-only or
    jax-less process simply publishes nothing."""
    try:
        import jax

        devices: Sequence = jax.local_devices()
    except Exception:
        return 0
    published = 0
    peak: Optional[float] = None
    limit: Optional[float] = None
    for i, d in enumerate(devices):
        try:
            stats = getattr(d, "memory_stats", lambda: None)()
        except Exception:
            stats = None
        if stats and stats.get("bytes_in_use") is not None:
            device_memory_bytes.set(float(stats["bytes_in_use"]), device=str(i))
            published += 1
            if peak is None or float(stats["bytes_in_use"]) > peak:
                peak = float(stats["bytes_in_use"])
            if stats.get("bytes_limit") is not None:
                limit = float(stats["bytes_limit"])
    if peak is not None:
        profiler.on_device_memory(peak, limit_bytes=limit)
    return published
