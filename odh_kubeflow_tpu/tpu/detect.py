"""Positive-evidence TPU/accelerator detection.

Round 3's driver-captured benchmark silently skipped every TPU section
because detection was `jax.default_backend() == "tpu"` — and the bench
host's JAX initialized the experimental `axon` dispatch platform, whose
backend string is "axon" even though the device behind it is a real TPU
chip. A *renamed* platform must not read as *no accelerator*.

Detection here is positive-evidence based instead:

- `accelerator_present()` reports True iff `jax.devices()` contains any
  non-CPU device (the axon tunnel, a real local TPU, a future plugin —
  anything that isn't the host platform). It never raises; failures carry
  an explicit reason so callers can record WHY a hardware section was
  skipped rather than emitting a silently valid-looking artifact.
- `tpu_like()` additionally checks the device self-describes as a TPU
  (platform or device_kind mentions "tpu") OR is a non-CPU platform whose
  kind is unknown — the pallas TPU kernels key off this. A CPU-only
  process (tests force JAX_PLATFORMS=cpu) stays False either way.

Reference parity note: the reference has no hardware detection (it is a
Go control plane); this exists because the north star's benchmarks are
self-measured (SURVEY §6) and the measurement pipeline must fail loudly,
not silently (VERDICT r3 weak #1).
"""
from __future__ import annotations

from typing import Optional, Tuple

_CPU_PLATFORMS = frozenset({"cpu", "interpreter"})


def probe_devices() -> Tuple[list, Optional[str]]:
    """(devices, error_reason). Never raises; empty list + reason on failure."""
    try:
        import jax

        return list(jax.devices()), None
    except Exception as e:  # backend init failed / no jax
        return [], f"jax.devices() failed: {e!r}"


def accelerator_present() -> Tuple[bool, Optional[str]]:
    """(present, skip_reason). present=True iff any non-CPU device exists.

    skip_reason is a human-readable explanation when present is False —
    callers MUST record it in their artifacts (bench.py)."""
    devices, err = probe_devices()
    if err is not None:
        return False, err
    plats = sorted({d.platform for d in devices})
    if all(p in _CPU_PLATFORMS for p in plats):
        return False, f"only CPU devices present (platforms={plats})"
    return True, None


def tpu_like(devices=None) -> bool:
    """True iff the default devices look like TPU hardware — by self-
    description when available, by being the only non-CPU accelerator
    otherwise (the axon tunnel's platform string is not "tpu" but the chip
    behind it is). Used to enable the pallas TPU kernel path."""
    if devices is None:
        devices, err = probe_devices()
        if err is not None:
            return False
    for d in devices:
        plat = (d.platform or "").lower()
        if plat in _CPU_PLATFORMS:
            continue
        kind = str(getattr(d, "device_kind", "") or "").lower()
        if "tpu" in plat or "tpu" in kind:
            return True
        if (
            plat in ("gpu", "cuda", "rocm", "metal", "vulkan", "oneapi")
            or "gpu" in kind
            or "nvidia" in kind
            or "amd" in kind
        ):
            continue  # a GPU is non-CPU but NOT pallas-TPU-lowerable
        # Unknown non-CPU platform (axon and successors): treat as TPU.
        # This deliberately FAILS OPEN — in this deployment the only
        # accelerator access path is a (renamed) TPU dispatch platform, and
        # the two failure modes are asymmetric: guessing TPU on a future
        # non-TPU plugin breaks loudly at pallas lowering, while guessing
        # non-TPU on a renamed TPU platform silently forfeits every kernel
        # (exactly how round 3 lost its benchmark evidence).
        return True
    return False
