from .env import COORDINATOR_PORT, ordinal_env, pod_dns, tpu_env
from .topology import (
    GENERATIONS,
    GKE_NODEPOOL_LABEL,
    GKE_TPU_ACCELERATOR_LABEL,
    GKE_TPU_TOPOLOGY_LABEL,
    TPU_RESOURCE,
    SliceShape,
    TPUGeneration,
    chips_per_host_bounds,
    host_bounds,
    parse_topology,
    plan_slice,
)
