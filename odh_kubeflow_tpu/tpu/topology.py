"""TPU generations, topologies, and the slice planner.

This is the TPU-native replacement for the reference's GPU path: where the
reference schedules a notebook onto "a node with nvidia.com/gpu", this module
turns ``Notebook.spec.tpu`` (accelerator + topology or chip count) into the
concrete slice shape — host count, chips per host, GKE node selectors
(`cloud.google.com/gke-tpu-accelerator`, `cloud.google.com/gke-tpu-topology`)
and the `google.com/tpu` resource request — per the BASELINE.json north star.

Topology model (public TPU system architecture):
- a *slice* is a set of hosts wired by ICI; each host carries a fixed number
  of chips (4 for v4/v5p boards; v5e/v6e also offer 1- and 8-chip single-host
  machine shapes),
- v4/v5p topologies are 3D meshes "XxYxZ" of chips; v5e/v6e are 2D "XxY",
- workloads occupy whole hosts: `google.com/tpu` is requested per pod at
  chips-per-host granularity, one pod per host, `replicas = hosts`.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..apimachinery import InvalidError

GKE_TPU_ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"
GKE_TPU_TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"
GKE_NODEPOOL_LABEL = "cloud.google.com/gke-nodepool"
TPU_RESOURCE = "google.com/tpu"


@dataclass(frozen=True)
class TPUGeneration:
    name: str  # "v4" | "v5e" | "v5p" | "v6e"
    gke_accelerator: str  # value of the gke-tpu-accelerator node label
    dims: int  # topology rank: 3 for v4/v5p, 2 for v5e/v6e
    chips_per_host: int  # chips on one multi-host board
    max_single_host_chips: int  # largest single-host machine shape
    cores_per_chip: int  # for the "v5p-32"-style core-count alias
    max_chips: int  # largest supported slice


GENERATIONS: Dict[str, TPUGeneration] = {
    "v4": TPUGeneration("v4", "tpu-v4-podslice", 3, 4, 4, 2, 4096),
    "v5e": TPUGeneration("v5e", "tpu-v5-lite-podslice", 2, 4, 8, 1, 256),
    "v5p": TPUGeneration("v5p", "tpu-v5p-slice", 3, 4, 4, 2, 8960),
    "v6e": TPUGeneration("v6e", "tpu-v6e-slice", 2, 4, 8, 1, 256),
}


def parse_topology(topology: str, dims: int) -> Tuple[int, ...]:
    try:
        parts = tuple(int(p) for p in topology.lower().split("x"))
    except ValueError:
        raise InvalidError(f"malformed TPU topology {topology!r}")
    if len(parts) != dims or any(p < 1 for p in parts):
        raise InvalidError(
            f"TPU topology {topology!r} must be {dims} positive dims (e.g. "
            + ("'2x2x2'" if dims == 3 else "'2x4'")
        )
    return parts


@dataclass(frozen=True)
class SliceShape:
    """Fully-resolved slice placement plan."""

    accelerator: str  # generation name, e.g. "v5p"
    topology: str  # canonical "XxY[xZ]"
    chips: int  # total chips in the slice
    hosts: int  # pod/host count (StatefulSet replicas)
    chips_per_host: int  # google.com/tpu request per pod
    gke_accelerator: str  # node label value
    multi_host: bool = False

    @property
    def accelerator_type(self) -> str:
        """Core-count alias, e.g. v5p 2x2x4 -> 'v5p-32' (16 chips x 2 cores)."""
        gen = GENERATIONS[self.accelerator]
        return f"{self.accelerator}-{self.chips * gen.cores_per_chip}"

    def node_selector(self) -> Dict[str, str]:
        return {
            GKE_TPU_ACCELERATOR_LABEL: self.gke_accelerator,
            GKE_TPU_TOPOLOGY_LABEL: self.topology,
        }


def _standard_topologies(gen: TPUGeneration) -> List[Tuple[int, ...]]:
    """Enumerate doubling topologies (1x1[x1] ... up to max_chips), the shapes
    GKE node pools actually come in."""
    shapes: List[Tuple[int, ...]] = []
    dims = [1] * gen.dims
    shapes.append(tuple(dims))
    while math.prod(dims) * 2 <= gen.max_chips:
        # double the smallest dimension (keeps shapes near-cubic/square)
        j = min(range(gen.dims), key=lambda k: dims[k])
        dims[j] *= 2
        shapes.append(tuple(sorted(dims)))
    return shapes


def plan_slice(
    accelerator: str, topology: str = "", chips: int = 0
) -> SliceShape:
    """Resolve a ``spec.tpu`` block into a SliceShape.

    Exactly one of topology/chips may drive sizing; with neither, the minimum
    slice (one host, all its chips) is planned.
    """
    gen = GENERATIONS.get(accelerator)
    if gen is None:
        raise InvalidError(
            f"unknown TPU accelerator {accelerator!r}; valid: {sorted(GENERATIONS)}"
        )
    if topology and chips:
        raise InvalidError("spec.tpu: set topology or chips, not both")

    if topology:
        shape = parse_topology(topology, gen.dims)
        total = math.prod(shape)
    elif chips:
        for cand in _standard_topologies(gen):
            if math.prod(cand) >= chips:
                shape, total = cand, math.prod(cand)
                break
        else:
            raise InvalidError(
                f"no {gen.name} topology with >= {chips} chips (max {gen.max_chips})"
            )
    else:
        total = gen.chips_per_host
        shape = parse_topology(
            {2: f"2x2", 3: f"2x2x1"}[gen.dims], gen.dims
        )

    if total > gen.max_chips:
        raise InvalidError(f"{gen.name} slice of {total} chips exceeds max {gen.max_chips}")

    if total <= gen.max_single_host_chips:
        hosts, per_host = 1, total
    else:
        if total % gen.chips_per_host != 0:
            raise InvalidError(
                f"{gen.name} multi-host slice must be a multiple of "
                f"{gen.chips_per_host} chips, got {total}"
            )
        hosts, per_host = total // gen.chips_per_host, gen.chips_per_host

    return SliceShape(
        accelerator=gen.name,
        topology="x".join(str(d) for d in shape),
        chips=total,
        hosts=hosts,
        chips_per_host=per_host,
        gke_accelerator=gen.gke_accelerator,
        multi_host=hosts > 1,
    )


def chips_per_host_bounds(shape: SliceShape) -> str:
    """TPU_CHIPS_PER_HOST_BOUNDS-style chip layout on one host ("2,2,1")."""
    gen = GENERATIONS[shape.accelerator]
    if gen.dims == 3:
        return {4: "2,2,1", 1: "1,1,1"}.get(shape.chips_per_host, "2,2,1")
    return {8: "2,4", 4: "2,2", 1: "1,1"}.get(shape.chips_per_host, "2,2")


def host_bounds(shape: SliceShape) -> str:
    """TPU_HOST_BOUNDS-style host grid within the slice."""
    dims = parse_topology(shape.topology, GENERATIONS[shape.accelerator].dims)
    per_host = [int(p) for p in chips_per_host_bounds(shape).split(",")]
    return ",".join(str(max(1, d // p)) for d, p in zip(dims, per_host))
