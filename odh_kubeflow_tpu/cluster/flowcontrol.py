"""API Priority & Fairness analog for the apiserver sim (ISSUE 13).

Real kube-apiserver puts every request through APF: a FlowSchema matches the
request (by user/verb/resource) onto a PriorityLevelConfiguration, which owns
a bounded number of concurrency "seats" and per-flow FIFO queues; exceeding
the queue bound sheds with 429 + Retry-After, and an *exempt* level keeps the
system-critical traffic (leader-election leases here) out of the contention
entirely so an admission storm can never starve failover.

This module is that shape over the repo's request paths. Identity travels as
a `flow` string: in-process callers carry it in a thread-local set by the
controller worker loop (`flow_context`), and the wire client stamps it as an
`X-Flow-Schema` header that `ApiServer` reads back. Both enforcement points
funnel into one `FlowController.admit()`:

- `Client._call` consults `store.flowcontrol` (sim mode: every typed client
  shares the Store, so the controller is effectively "in front of" the
  apiserver the same way the wire path is),
- `ApiServer._dispatch_traced` admits around verb dispatch (wire mode).

Shed uses the existing idiom — `TooManyRequestsError(retry_after=...)` →
Status.details.retryAfterSeconds + Retry-After header — which every client
in the repo already retries with bounded jittered backoff.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, Iterator, List, Optional, Tuple

from ..apimachinery import TooManyRequestsError
from ..utils import racecheck

# thread-local flow identity: the controller worker loop (runtime/controller)
# enters flow_context(controller_name); everything the reconciler does below
# that frame — including RemoteStore requests — inherits it.
_flow_local = threading.local()

# the flow name leader-election clients declare; always routed to the exempt
# level regardless of schema configuration (failover must never queue)
LEADER_ELECTION_FLOW = "leader-election"


def current_flow() -> str:
    return getattr(_flow_local, "flow", "") or ""


@contextmanager
def flow_context(flow: str) -> Iterator[None]:
    prev = getattr(_flow_local, "flow", "")
    _flow_local.flow = flow
    try:
        yield
    finally:
        _flow_local.flow = prev


@dataclass
class PriorityLevel:
    """A concurrency budget: `seats` simultaneous requests, and per-flow FIFO
    queues holding at most `queue_length` waiters each. exempt levels bypass
    seats entirely (counted, never queued, never shed)."""

    name: str
    seats: int = 4
    queue_length: int = 16
    queue_timeout_s: float = 5.0
    exempt: bool = False


@dataclass
class FlowSchema:
    """Match a request onto a priority level. First match wins in list order
    (precedence = position, like APF's matchingPrecedence). Empty criteria
    match everything — put the catch-all last."""

    name: str
    level: str
    flows: Tuple[str, ...] = ()
    kinds: Tuple[str, ...] = ()
    verbs: Tuple[str, ...] = ()

    def matches(self, flow: str, verb: str, kind: str) -> bool:
        if self.flows and flow not in self.flows:
            return False
        if self.kinds and kind not in self.kinds:
            return False
        if self.verbs and verb not in self.verbs:
            return False
        return True


def default_levels() -> List[PriorityLevel]:
    return [
        # failover traffic: never queued, never shed
        PriorityLevel("exempt", exempt=True),
        # node-level machinery (kubelet/scheduler/statefulset): wide budget
        PriorityLevel("system", seats=16, queue_length=64, queue_timeout_s=10.0),
        # interactive + serving reconcilers: the protected class
        PriorityLevel("workload-high", seats=12, queue_length=64, queue_timeout_s=10.0),
        # data-plane inference requests (serving/router.py holds a seat per
        # routed generation): a hot endpoint contends HERE — its shed is a
        # wire 429 from the router — and can never starve the API levels
        PriorityLevel("serving", seats=8, queue_length=32, queue_timeout_s=5.0),
        # batch admission (TPUJob storms land here): narrow seats, short
        # queue — overload sheds HERE instead of starving the levels above
        PriorityLevel("batch", seats=4, queue_length=8, queue_timeout_s=2.0),
        PriorityLevel("default", seats=8, queue_length=32, queue_timeout_s=5.0),
    ]


def default_flow_schemas() -> List[FlowSchema]:
    return [
        FlowSchema(
            "exempt-leases",
            "exempt",
            flows=(LEADER_ELECTION_FLOW,),
        ),
        FlowSchema("exempt-lease-kind", "exempt", kinds=("Lease",)),
        FlowSchema(
            "system-nodes",
            "system",
            flows=("kubelet", "scheduler", "statefulset", "node-lifecycle"),
        ),
        FlowSchema(
            "workload-controllers",
            "workload-high",
            flows=(
                "notebook",
                "probe-status",
                "culling",
                "suspend-resume",
                "tpu-workbench",
                "event-mirror",
                "slice-repair",
                "inference-endpoint",
                "canary",
                # ISSUE 16 control plane: the autoscaler's list/patch sweep
                # and the router's cold-wake patch ride the protected class
                # — a parked endpoint must wake even under admission storms
                "endpoint-autoscaler",
                "token-router",
            ),
        ),
        # ISSUE 16 data plane: routed generations (whatever their dynamic
        # per-endpoint flow name) land in the serving budget by KIND
        FlowSchema(
            "serving-requests", "serving", kinds=("InferenceRequest",)
        ),
        FlowSchema("batch-controllers", "batch", flows=("tpu-job",)),
        # unclassified callers creating/deleting TPUJobs (the loadtest driver,
        # an admission storm) contend in the batch budget, not the default one
        FlowSchema("batch-kind", "batch", kinds=("TPUJob",)),
        FlowSchema("catch-all", "default"),
    ]


class _Ticket:
    """Context manager releasing a seat on exit."""

    __slots__ = ("_ctrl", "_level")

    def __init__(self, ctrl: "FlowController", level: PriorityLevel):
        self._ctrl = ctrl
        self._level = level

    def __enter__(self) -> "_Ticket":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def release(self) -> None:
        ctrl, self._ctrl = self._ctrl, None
        if ctrl is not None:
            ctrl._release(self._level)


@dataclass
class _LevelState:
    level: PriorityLevel
    inflight: int = 0
    # flow name -> FIFO of waiter events; round-robin order across flows
    queues: Dict[str, Deque[threading.Event]] = field(default_factory=dict)
    rr: Deque[str] = field(default_factory=deque)
    dispatched: int = 0
    rejected: int = 0
    timed_out: int = 0
    queued_total: int = 0
    waits: List[float] = field(default_factory=list)


class FlowController:
    """Classify + admit requests. Thread-safe; one instance per apiserver."""

    def __init__(
        self,
        schemas: Optional[List[FlowSchema]] = None,
        levels: Optional[List[PriorityLevel]] = None,
    ):
        self.schemas = list(schemas) if schemas is not None else default_flow_schemas()
        lvls = list(levels) if levels is not None else default_levels()
        if not any(lv.exempt for lv in lvls):
            # the exempt level is an INVARIANT, not a configuration: whatever
            # levels a caller scripts, leader-election/Lease traffic must
            # always have somewhere shed-proof to land (classify() routes it
            # here first), or an admission storm could starve failover
            lvls.append(PriorityLevel("exempt", exempt=True))
        self._levels: Dict[str, _LevelState] = {
            lv.name: _LevelState(level=lv) for lv in lvls
        }
        for s in self.schemas:
            if s.level not in self._levels:
                raise ValueError(f"flow schema {s.name!r} names unknown level {s.level!r}")
        self._lock = racecheck.make_lock("FlowController._lock")

    # -- classification --

    def classify(self, flow: str, verb: str = "", kind: str = "") -> PriorityLevel:
        if flow == LEADER_ELECTION_FLOW or kind == "Lease":
            for st in self._levels.values():
                if st.level.exempt:
                    return st.level
        for s in self.schemas:
            if s.matches(flow, verb, kind):
                return self._levels[s.level].level
        return self._levels["default"].level

    # -- admission --

    def admit(self, flow: str, verb: str = "", kind: str = "") -> _Ticket:
        """Take a seat at the matched priority level, queueing FIFO-per-flow
        behind a full level. Raises TooManyRequestsError on queue-full or
        queue-timeout (the shed path)."""
        from ..runtime.metrics import (
            flowcontrol_inflight,
            flowcontrol_queue_depth,
            flowcontrol_requests_total,
            flowcontrol_wait_seconds,
        )

        level = self.classify(flow, verb, kind)
        st = self._levels[level.name]
        flow = flow or "anonymous"
        t0 = time.monotonic()
        with self._lock:
            if level.exempt or st.inflight < level.seats and not st.rr:
                st.inflight += 1
                st.dispatched += 1
                flowcontrol_inflight.set(st.inflight, level=level.name)
                flowcontrol_requests_total.inc(level=level.name, outcome="dispatched")
                flowcontrol_wait_seconds.observe(0.0, level=level.name)
                return _Ticket(self, level)
            q = st.queues.get(flow)
            if q is None:
                q = st.queues[flow] = deque()
            if len(q) >= level.queue_length:
                st.rejected += 1
                flowcontrol_requests_total.inc(level=level.name, outcome="rejected")
                raise TooManyRequestsError(
                    f"flow {flow!r} queue full at priority level {level.name!r}",
                    retry_after=min(level.queue_timeout_s, 1.0),
                )
            ev = threading.Event()
            q.append(ev)
            if flow not in st.rr:
                st.rr.append(flow)
            st.queued_total += 1
            flowcontrol_queue_depth.set(self._depth_locked(st), level=level.name)
        if not ev.wait(level.queue_timeout_s):
            with self._lock:
                # either we timed out, or the dispatcher set the event in the
                # race window — the set() path already granted us the seat
                if not ev.is_set():
                    try:
                        st.queues[flow].remove(ev)
                    except (KeyError, ValueError):
                        pass
                    st.timed_out += 1
                    flowcontrol_queue_depth.set(self._depth_locked(st), level=level.name)
                    flowcontrol_requests_total.inc(level=level.name, outcome="timeout")
                    raise TooManyRequestsError(
                        f"flow {flow!r} timed out queued at level {level.name!r}",
                        retry_after=min(level.queue_timeout_s, 1.0),
                    )
        wait = time.monotonic() - t0
        with self._lock:
            st.dispatched += 1
            st.waits.append(wait)
            if len(st.waits) > 4096:
                del st.waits[:2048]
        flowcontrol_requests_total.inc(level=level.name, outcome="dispatched")
        flowcontrol_wait_seconds.observe(wait, level=level.name)
        return _Ticket(self, level)

    def _depth_locked(self, st: _LevelState) -> int:
        return sum(len(q) for q in st.queues.values())

    def _release(self, level: PriorityLevel) -> None:
        from ..runtime.metrics import flowcontrol_inflight, flowcontrol_queue_depth

        st = self._levels[level.name]
        with self._lock:
            st.inflight -= 1
            if not level.exempt:
                # hand the freed seat to the next waiter, round-robin across
                # flows so one hot flow can't monopolize the level
                while st.rr:
                    f = st.rr[0]
                    q = st.queues.get(f)
                    if not q:
                        st.rr.popleft()
                        st.queues.pop(f, None)
                        continue
                    ev = q.popleft()
                    st.rr.rotate(-1)
                    if not q:
                        try:
                            st.rr.remove(f)
                        except ValueError:
                            pass
                        st.queues.pop(f, None)
                    st.inflight += 1
                    ev.set()
                    break
            flowcontrol_inflight.set(st.inflight, level=level.name)
            flowcontrol_queue_depth.set(self._depth_locked(st), level=level.name)

    # -- observability --

    def summary(self) -> Dict[str, Dict[str, Any]]:
        """Per-level dispatch/shed/wait stats for bench + /debug."""
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            for name, st in self._levels.items():
                waits = sorted(st.waits)
                p99 = waits[min(len(waits) - 1, int(len(waits) * 0.99))] if waits else 0.0
                out[name] = {
                    "exempt": st.level.exempt,
                    "seats": st.level.seats,
                    "inflight": st.inflight,
                    "queue_depth": self._depth_locked(st),
                    "dispatched": st.dispatched,
                    "rejected": st.rejected,
                    "timed_out": st.timed_out,
                    "queued": st.queued_total,
                    "p99_wait_s": round(p99, 6),
                }
        return out
