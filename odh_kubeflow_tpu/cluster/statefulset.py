"""StatefulSet controller for the in-process cluster.

Gives our control plane the STS semantics the reference's notebooks depend on
(reference relies on real Kubernetes for this; envtest can't run it at all —
suite comment at notebook_controller_bdd_test.go:73-77 — so this build's test
cluster is strictly more capable):

- ordinal pod identity {name}-{i} with stable hostname/subdomain,
- `apps.kubernetes.io/pod-index` + `statefulset.kubernetes.io/pod-name` labels
  (the pod-index label feeds TPU_WORKER_ID via the downward API),
- scale up/down to spec.replicas (stop-annotation culling scales to 0),
- template-hash-based recreate on template change,
- status.replicas / readyReplicas aggregation.
"""
from __future__ import annotations

import hashlib
import json
from typing import Optional

from ..api.apps import StatefulSet
from ..api.core import Pod
from ..apimachinery import AlreadyExistsError, NotFoundError, ignore_not_found
from .client import retry_on_conflict
from ..runtime.controller import Request, Result
from ..runtime.manager import Manager

POD_INDEX_LABEL = "apps.kubernetes.io/pod-index"
POD_NAME_LABEL = "statefulset.kubernetes.io/pod-name"
REVISION_LABEL = "controller-revision-hash"


def template_hash(sts: StatefulSet) -> str:
    blob = json.dumps(sts.spec.template.to_dict(), sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:10]


class StatefulSetController:
    def __init__(self, manager: Manager):
        self.manager = manager
        self.client = manager.client
        self.api_reader = manager.api_reader

    def setup(self) -> None:
        (
            self.manager.builder("statefulset")
            .for_(StatefulSet)
            .owns(Pod)
            .complete(self.reconcile)
        )

    def reconcile(self, req: Request) -> Optional[Result]:
        try:
            sts = self.client.get(StatefulSet, req.namespace, req.name)
        except NotFoundError:
            return None
        if sts.metadata.deletion_timestamp:
            return None
        desired = sts.spec.replicas if sts.spec.replicas is not None else 1
        rev = template_hash(sts)

        pods = [
            p
            for p in self.client.list(Pod, namespace=req.namespace)
            if p.owned_by(sts)
        ]
        by_name = {p.metadata.name: p for p in pods}

        ready = 0
        running = 0
        for i in range(desired):
            pod_name = f"{sts.metadata.name}-{i}"
            pod = by_name.pop(pod_name, None)
            if pod is None:
                self._create_pod(sts, i, rev)
                continue
            if pod.metadata.deletion_timestamp:
                continue
            if pod.metadata.labels.get(REVISION_LABEL) != rev:
                # template changed: recreate (rolling, highest ordinal first is
                # not modeled; recreate-on-sight is sufficient for notebooks)
                ignore_not_found(
                    self._try(lambda: self.client.delete(Pod, req.namespace, pod_name))
                )
                continue
            running += 1
            if pod.is_ready():
                ready += 1

        # scale down: delete pods with ordinal >= desired (and strays)
        for pod in by_name.values():
            ignore_not_found(
                self._try(lambda name=pod.metadata.name: self.client.delete(Pod, req.namespace, name))
            )

        def write_status():
            # re-GET inside the retry: concurrent reconcilers racing the
            # notebook controller's status mirror made a blind
            # read-modify-write conflict-crash here (retry.RetryOnConflict
            # at every multi-writer site — SURVEY §5)
            try:
                cur = self.api_reader.get(StatefulSet, req.namespace, req.name)
            except NotFoundError:
                return
            if (
                cur.status.replicas != running
                or cur.status.ready_replicas != ready
                or cur.status.observed_generation != cur.metadata.generation
            ):
                cur.status.replicas = running
                cur.status.ready_replicas = ready
                cur.status.current_replicas = running
                cur.status.updated_replicas = running
                cur.status.observed_generation = cur.metadata.generation
                self.client.update_status(cur)

        retry_on_conflict(write_status)
        return None

    def _try(self, fn):
        try:
            fn()
            return None
        except Exception as e:  # noqa: BLE001 - converted to return-value
            return e

    def _create_pod(self, sts: StatefulSet, ordinal: int, rev: str) -> None:
        pod = Pod()
        pod.metadata.name = f"{sts.metadata.name}-{ordinal}"
        pod.metadata.namespace = sts.metadata.namespace
        pod.metadata.labels = dict(sts.spec.template.metadata.labels)
        pod.metadata.labels[POD_INDEX_LABEL] = str(ordinal)
        pod.metadata.labels[POD_NAME_LABEL] = pod.metadata.name
        pod.metadata.labels[REVISION_LABEL] = rev
        pod.metadata.annotations = dict(sts.spec.template.metadata.annotations)
        pod.spec = sts.spec.template.spec.deepcopy()
        pod.spec.hostname = pod.metadata.name
        if sts.spec.service_name:
            pod.spec.subdomain = sts.spec.service_name
        pod.set_owner(sts)
        try:
            self.client.create(pod)
        except AlreadyExistsError:
            pass  # race with a concurrent reconcile; next pass adopts it
