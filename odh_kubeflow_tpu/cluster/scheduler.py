"""TPU-aware scheduler for the in-process cluster.

Models the piece of GKE the north star depends on: TPU slice node pools where
every multi-host pool IS one ICI slice. Placement rules:

- nodeSelector labels must match the node,
- `google.com/tpu` requests bind whole hosts (one TPU pod per node),
- **gang placement**: all pods of a multi-host StatefulSet must land in the
  SAME node pool (= same ICI slice), all-or-nothing — if the pool can't hold
  every replica, nothing schedules and an Unschedulable event is emitted
  (SURVEY §7 hard part (d): scheduling atomicity for multi-host slices),
- CPU/memory capacity accounting for non-TPU pods,
- NotReady nodes (drained/preempted hosts) take no new pods,
- unschedulable pods requeue with exponential backoff AND are re-attempted
  the moment capacity frees (node added/restored, a scheduled pod deleted) —
  a waiting gang must not sit out a full backoff window after the slice it
  needs opens up,
- **warm slice pools** (cluster/slicepool.py): a pool whose nodes carry
  `pool-state=warm` is held for resume binds — no pods land there until the
  suspend controller claims it or the reclaimer returns it to general
  capacity; `pool-state=claimed` pools accept ONLY the claiming notebook's
  pods (the resume fast path).
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..api.apps import StatefulSet
from ..api.core import Node, Pod, emit_deduped_event
from ..apimachinery import NotFoundError, controller_owner
from ..runtime.controller import Request, Result
from ..runtime.manager import Manager
from ..tpu import GKE_NODEPOOL_LABEL, TPU_RESOURCE
from ..utils import parse_quantity
from .store import DELETED

def claim_owner_labels() -> tuple:
    """The claim-owner table: pod labels that name the workload owning a
    claimed slice pool, in precedence order. Three workload classes share
    the pool's claim namespace (ns/name keys), so a FOURTH class joins by
    adding its label here — not by growing another special case inline in
    the scheduler (ISSUE 10 satellite: `_pod_owner` used to if/else
    notebook and inference-endpoint owners by hand)."""
    from ..controllers.constants import (
        INFERENCE_NAME_LABEL,
        JOB_NAME_LABEL,
        NOTEBOOK_NAME_LABEL,
    )

    return (NOTEBOOK_NAME_LABEL, INFERENCE_NAME_LABEL, JOB_NAME_LABEL)


def pod_claim_owner(pod: Pod) -> str:
    """ns/name of the workload that owns this pod — what a claimed pool's
    `pool-claimed-by` must equal for the bind to be allowed; "" for an
    owner-less pod (which must never slip through the warm sentinel)."""
    for label in claim_owner_labels():
        owner = pod.metadata.labels.get(label, "")
        if owner:
            return f"{pod.metadata.namespace}/{owner}"
    return ""


def pod_tpu_request(pod: Pod) -> int:
    total = 0
    for c in pod.spec.containers:
        if c.resources and c.resources.requests.get(TPU_RESOURCE):
            total += int(parse_quantity(c.resources.requests[TPU_RESOURCE]))
        elif c.resources and c.resources.limits.get(TPU_RESOURCE):
            total += int(parse_quantity(c.resources.limits[TPU_RESOURCE]))
    return total


def pod_resource_request(pod: Pod, resource: str) -> float:
    total = 0.0
    for c in pod.spec.containers:
        if c.resources and c.resources.requests.get(resource):
            total += parse_quantity(c.resources.requests[resource])
    return total


class Scheduler:
    # unschedulable requeue: exponential from base to cap. The cap stays
    # coarse because the capacity-freed watches below are the fast path —
    # backoff is only the safety net for capacity changes with no event.
    backoff_base_s = 0.25
    backoff_max_s = 5.0

    def __init__(self, manager: Manager):
        self.manager = manager
        self.client = manager.client
        # pod key -> consecutive unschedulable attempts (single scheduler
        # worker: no lock needed; pruned on schedule/delete)
        self._unsched_attempts: Dict[str, int] = {}

    def setup(self) -> None:
        def pending_pods(_obj: dict) -> List[tuple]:
            """Capacity-freed mapper: re-enqueue every unscheduled pod."""
            return [
                (p.metadata.namespace, p.metadata.name)
                for p in self.client.list(Pod)
                if not p.spec.node_name and not p.metadata.deletion_timestamp
            ]

        def frees_capacity(ev: str, obj: dict, _old: Optional[dict]) -> bool:
            # a scheduled pod leaving the cluster returns its node's capacity
            return ev == DELETED and bool(obj.get("spec", {}).get("nodeName"))

        (
            self.manager.builder("scheduler")
            .for_(Pod, predicate=lambda ev, obj, old: not obj.get("spec", {}).get("nodeName"))
            # nodes appearing/changing (new pool, maintenance ending) and
            # scheduled pods departing both free capacity: re-attempt every
            # pending pod immediately instead of waiting out its backoff
            .watches(Node, pending_pods)
            .watches(Pod, pending_pods, predicate=frees_capacity)
            .complete(self.reconcile)
        )

    # -- capacity --
    def _assignment_map(self) -> Dict[str, List[Pod]]:
        """node name -> assigned pods, built once per scheduling pass."""
        out: Dict[str, List[Pod]] = {}
        for p in self.client.list(Pod):
            if p.spec.node_name and not p.metadata.deletion_timestamp:
                out.setdefault(p.spec.node_name, []).append(p)
        return out

    def _node_free(
        self, node: Node, pod: Pod, tpu_chips: int, assignment: Dict[str, List[Pod]]
    ) -> bool:
        assigned = assignment.get(node.metadata.name, [])
        if tpu_chips > 0:
            cap = int(parse_quantity(node.status.allocatable.get(TPU_RESOURCE, "0")))
            if cap < tpu_chips:
                return False
            # TPU hosts are exclusively bound: one TPU workload pod per node
            if any(pod_tpu_request(p) > 0 for p in assigned):
                return False
            return True
        for resource in ("cpu", "memory"):
            want = pod_resource_request(pod, resource)
            if want == 0:
                continue
            cap = parse_quantity(node.status.allocatable.get(resource, "0"))
            used = sum(pod_resource_request(p, resource) for p in assigned)
            if used + want > cap:
                return False
        return True

    def _node_healthy(self, node: Node) -> bool:
        """Ready=False nodes (drained/preempted hosts) take no new pods; a
        node with no Ready condition at all is healthy (sim default)."""
        return not any(
            c.type == "Ready" and c.status == "False"
            for c in node.status.conditions
        )

    def _selector_matches(self, pod: Pod, node: Node) -> bool:
        if not self._node_healthy(node):
            return False
        for k, v in pod.spec.node_selector.items():
            if node.metadata.labels.get(k) != v:
                return False
        return self._tolerates(pod, node)

    def _tolerates(self, pod: Pod, node: Node) -> bool:
        """NoSchedule taint semantics (GKE TPU pools carry a google.com/tpu
        taint so non-TPU pods never land on TPU hosts)."""
        for taint in node.spec.get("taints", []):
            if taint.get("effect") not in ("NoSchedule", "NoExecute"):
                continue
            key = taint.get("key", "")
            if key == TPU_RESOURCE and pod_tpu_request(pod) > 0:
                continue  # device-plugin auto-toleration
            if not any(
                t.key == key or (not t.key and t.operator == "Exists")
                for t in pod.spec.tolerations
            ):
                return False
        return True

    def _gang_size(self, pod: Pod) -> int:
        """Replicas of the owning StatefulSet (1 for standalone pods)."""
        ref = controller_owner(pod)
        if ref is None or ref.kind != "StatefulSet":
            return 1
        try:
            sts = self.client.get(StatefulSet, pod.metadata.namespace, ref.name)
        except NotFoundError:
            return 1
        return sts.spec.replicas if sts.spec.replicas is not None else 1

    def reconcile(self, req: Request) -> Optional[Result]:
        try:
            pod = self.client.get(Pod, req.namespace, req.name)
        except NotFoundError:
            self._unsched_attempts.pop(req.key, None)
            return None
        if pod.spec.node_name or pod.metadata.deletion_timestamp:
            self._unsched_attempts.pop(req.key, None)
            return None

        nodes = self.client.list(Node)
        candidates = [n for n in nodes if self._selector_matches(pod, n)]
        tpu_chips = pod_tpu_request(pod)
        assignment = self._assignment_map()
        chosen: Optional[Node] = None

        if tpu_chips > 0:
            # group candidate nodes by pool; a pool == one ICI slice
            pools: Dict[str, List[Node]] = {}
            for n in candidates:
                pools.setdefault(
                    n.metadata.labels.get(GKE_NODEPOOL_LABEL, n.metadata.name), []
                ).append(n)
            gang = self._gang_size(pod)
            sibling_pool = self._sibling_pool(pod)
            for pool_name in sorted(pools):
                # siblings already placed in a pool pin the gang there
                if sibling_pool is not None and sibling_pool != pool_name:
                    continue
                pool_nodes = pools[pool_name]
                # warm-pool reservation: warm slices take nobody; claimed
                # slices take only the claiming notebook's pods. An owner-less
                # pod (no notebook-name label) must never slip through the
                # warm sentinel ("" == "") onto a reserved slice.
                reservation = self._pool_reservation(pool_nodes)
                if reservation is not None:
                    owner = self._pod_owner(pod)
                    if not owner or reservation != owner:
                        continue
                free = [
                    n for n in pool_nodes if self._node_free(n, pod, tpu_chips, assignment)
                ]
                if sibling_pool is None and gang > 1 and len(free) < gang:
                    continue  # all-or-nothing: a fresh gang needs the whole slice
                if free:
                    ordinal = pod.metadata.labels.get("apps.kubernetes.io/pod-index")
                    free.sort(key=lambda n: n.metadata.name)
                    idx = int(ordinal) % len(free) if ordinal is not None else 0
                    chosen = free[min(idx, len(free) - 1)]
                    break
        else:
            free = [n for n in candidates if self._node_free(n, pod, 0, assignment)]
            chosen = min(
                free,
                key=lambda n: len(assignment.get(n.metadata.name, [])),
                default=None,
            )

        if chosen is None:
            self._emit_unschedulable(pod, tpu_chips)
            # exponential backoff; the capacity-freed watches (setup) are the
            # fast path back in, so the poll only backstops eventless changes
            attempts = self._unsched_attempts.get(req.key, 0)
            self._unsched_attempts[req.key] = attempts + 1
            return Result(
                requeue_after=min(
                    self.backoff_max_s, self.backoff_base_s * (2 ** attempts)
                )
            )

        self._unsched_attempts.pop(req.key, None)
        pod.spec.node_name = chosen.metadata.name
        self.client.update(pod)
        return None

    @staticmethod
    def _pod_owner(pod: Pod) -> str:
        """Delegates to the shared claim-owner table (pod_claim_owner):
        notebooks, InferenceEndpoints, and TPUJobs share the claim
        namespace — a promoted endpoint claims its source notebook's
        released slice under its OWN key (ISSUE 9), a batch job warm-claims
        a suspended notebook's slice the same way (ISSUE 10) — and only the
        claimant's pods may land there."""
        return pod_claim_owner(pod)

    @staticmethod
    def _pool_reservation(pool_nodes: List[Node]) -> Optional[str]:
        """None = unreserved; "" = warm (held for resume binds, takes
        nobody); "ns/name" = claimed by that notebook. Judged off the lead
        node — the claim CAS serializes on it (cluster/slicepool.py)."""
        from .slicepool import (
            POOL_CLAIMED_BY_ANNOTATION,
            POOL_STATE_ANNOTATION,
            POOL_STATE_CLAIMED,
            POOL_STATE_WARM,
        )

        lead = min(pool_nodes, key=lambda n: n.metadata.name)
        state = lead.metadata.annotations.get(POOL_STATE_ANNOTATION, "")
        if state == POOL_STATE_WARM:
            return ""
        if state == POOL_STATE_CLAIMED:
            return lead.metadata.annotations.get(POOL_CLAIMED_BY_ANNOTATION, "")
        return None

    def _sibling_pool(self, pod: Pod) -> Optional[str]:
        ref = controller_owner(pod)
        if ref is None or ref.kind != "StatefulSet":
            return None
        for p in self.client.list(Pod, namespace=pod.metadata.namespace):
            if p.metadata.name == pod.metadata.name or not p.spec.node_name:
                continue
            pref = controller_owner(p)
            if pref and pref.uid == ref.uid:
                try:
                    node = self.client.get(Node, "", p.spec.node_name)
                except NotFoundError:
                    continue
                return node.metadata.labels.get(GKE_NODEPOOL_LABEL)
        return None

    def _emit_unschedulable(self, pod: Pod, tpu_chips: int) -> None:
        """One Event per pod+reason, deduplicated Kubernetes-style via the
        shared emitter (api/core.py emit_deduped_event): repeats bump
        count/lastTimestamp instead of growing the store."""
        message = (
            f"0/{len(self.client.list(Node))} nodes available for "
            f"{tpu_chips} {TPU_RESOURCE} chips (gang all-or-nothing)"
            if tpu_chips
            else "no node with sufficient cpu/memory"
        )
        emit_deduped_event(
            self.client, pod, f"{pod.metadata.name}.unschedulable",
            reason="FailedScheduling", message=message, etype="Warning",
            api_version="v1", kind="Pod",
        )
