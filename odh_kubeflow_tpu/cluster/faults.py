"""Fault injection for the sim control plane.

The reference inherits its resilience from client-go/controller-runtime and
never has to prove it; this build's runtime (runtime/informer.py,
runtime/cached_client.py, cluster/remote.py) is reimplemented from scratch,
so its recovery behavior is exercised explicitly: a `FaultInjector` that the
Store, the sim ApiServer, the kubelet, the webhook dispatcher, and the sim's
cluster DNS all consult at named fault sites. Tests (tests/test_faults.py)
script rules against those sites and assert the cluster still converges.

Design constraints:
- **Deterministic.** Rules fire on call counts ("the next N updates of
  Notebook conflict"), never on wall-clock timers or unseeded randomness.
  The seeded "bad day" schedule derives every count from random.Random(seed).
- **Zero-cost when idle.** Every hook site is `if faults is not None` on a
  plain attribute; a store without an injector pays one identity check.
- **Layered like production faults.** Injection happens at the boundary the
  real failure would occur at: watch severing at the store's subscriber
  queues (a dropped TCP stream), 410 at watch-resume (trimmed watch cache),
  429 at request admission (API priority & fairness), webhook faults at the
  dispatcher's callout, crashes at the kubelet, partitions at cluster DNS.

Fault sites (the `site` strings components consult):
- ``store.read``          GET/LIST against the store (ctx: kind)
- ``store.write``         create/update/patch/delete (ctx: kind, obj)
- ``store.watch_resume``  a watch resuming from a resourceVersion (ctx: kind)
- ``apiserver.request``   every HTTP request before dispatch (ctx: method, path)
- ``webhook.call``        the dispatcher's AdmissionReview POST (ctx: name, url)
- ``kubelet.pod``         each kubelet reconcile (ctx: namespace, name, obj) —
  action rules here ("crash") are *decided*, not raised
- ``probe.http``          the sim cluster-DNS HTTP transport (ctx: host, url)

Slice-level faults (the accelerator layer, ISSUE 4): host preemption is an
*active operation* like drop_watches — `preempt_host` taints the node with a
cluster-autoscaler-style deletion-candidate taint plus a maintenance-window
notice, and the sim's node lifecycle (cluster/kubelet.py NodeLifecycle)
drains it when the grace window lapses. Chip loss / ICI degradation are
scripted at the in-pod probe agent (its monitor REPORTS the fault; the probe
controller aggregates it into the `TPUHealthy` condition). The combined
seeded schedule is `seeded_slice_bad_day`.
"""
from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..apimachinery import ConflictError, GoneError, TooManyRequestsError
from ..utils import racecheck

# Host preemption surfaces exactly the way GKE announces it: a soft
# cluster-autoscaler-style taint plus a maintenance-window notice annotation
# carrying the drain deadline. These are CLUSTER-side contracts (node keys),
# not operator annotation keys — their home is the fault substrate.
PREEMPTION_TAINT_KEY = "DeletionCandidateOfClusterAutoscaler"
MAINTENANCE_WINDOW_ANNOTATION = "cloud.google.com/active-node-maintenance"


@dataclass
class FaultRule:
    """One scripted fault: fires at a site while its budget lasts.

    `times=None` keeps firing until the rule is removed; an exhausted rule
    stays registered (fired == times) so tests can assert how often it hit.
    """

    site: str
    error: Optional[Callable[[], Exception]] = None  # raise-on-match
    action: str = ""  # non-raising verdict ("crash", "partition", "delay")
    kind: Optional[str] = None  # match ctx["kind"]
    name: Optional[str] = None  # substring match on ctx name/host/url
    times: Optional[int] = None  # budget; None = unlimited
    match: Optional[Callable[[Dict[str, Any]], bool]] = None  # extra predicate
    param: float = 0.0  # action parameter (e.g. "delay" sleep seconds)
    fired: int = 0

    def _matches(self, site: str, ctx: Dict[str, Any]) -> bool:
        if site != self.site:
            return False
        if self.times is not None and self.fired >= self.times:
            return False
        if self.kind is not None and ctx.get("kind") != self.kind:
            return False
        if self.name is not None:
            hay = str(
                ctx.get("name") or ctx.get("host") or ctx.get("url") or ""
            )
            if self.name not in hay:
                return False
        if self.match is not None and not self.match(ctx):
            return False
        return True

    @property
    def exhausted(self) -> bool:
        return self.times is not None and self.fired >= self.times


class FaultInjector:
    """Registry of FaultRules plus active operations (watch severing).

    Components hold a reference and call `check(site, **ctx)` (raises the
    first matching rule's error) or `decide(site, **ctx)` (returns the
    matching action rule, for sites where the component — not an exception —
    implements the fault, e.g. the kubelet's crash-restart).
    """

    def __init__(self, seed: Optional[int] = None):
        self._lock = racecheck.make_lock("FaultInjector._lock")
        self._rules: List[FaultRule] = []
        self.rng = random.Random(seed)
        self._stores: List[Any] = []  # bound Stores, for sever_watches
        self._cluster: Any = None  # bound SimCluster, for preempt_host

    # -- rule management --

    def add(self, rule: FaultRule) -> FaultRule:
        with self._lock:
            self._rules.append(rule)
        return rule

    def remove(self, rule: FaultRule) -> None:
        with self._lock:
            try:
                self._rules.remove(rule)
            except ValueError:
                pass

    def clear(self) -> None:
        with self._lock:
            self._rules.clear()

    def rules(self) -> List[FaultRule]:
        with self._lock:
            return list(self._rules)

    # -- consult sites --

    def check(self, site: str, **ctx: Any) -> None:
        """Raise the first matching error rule (consuming one firing)."""
        err: Optional[Exception] = None
        with self._lock:
            for rule in self._rules:
                if rule.error is not None and rule._matches(site, ctx):
                    rule.fired += 1
                    err = rule.error()
                    break
        if err is not None:
            raise err

    def decide(self, site: str, **ctx: Any) -> Optional[FaultRule]:
        """Return the first matching action rule (consuming one firing)."""
        with self._lock:
            for rule in self._rules:
                if rule.action and rule._matches(site, ctx):
                    rule.fired += 1
                    return rule
        return None

    # -- active operations --

    def bind_store(self, store: Any) -> None:
        """Register a Store so drop_watches can sever its streams."""
        with self._lock:
            if store not in self._stores:
                self._stores.append(store)

    def drop_watches(self, api_version: Optional[str] = None,
                     kind: Optional[str] = None) -> int:
        """Sever matching live watch streams on every bound store — the
        network-level analog of an apiserver closing watch connections.
        Returns the number of subscriber queues severed."""
        with self._lock:
            stores = list(self._stores)
        severed = 0
        for store in stores:
            severed += store.sever_watches(api_version=api_version, kind=kind)
        return severed

    def bind_cluster(self, cluster: Any) -> None:
        """Register the SimCluster so host-level faults can be enacted
        through one injector handle (SimCluster binds itself at __init__)."""
        with self._lock:
            self._cluster = cluster

    def preempt_host(self, node_name: str, grace_s: float = 0.5) -> None:
        """Preempt a TPU host: the node gets the deletion-candidate taint +
        a maintenance-window notice whose deadline is now+grace_s; the node
        lifecycle drains it when the window lapses. The grace window is the
        slice-repair controller's checkpoint-before-evict opportunity."""
        with self._lock:
            cluster = self._cluster
        if cluster is None:
            raise RuntimeError("no SimCluster bound (FaultInjector.bind_cluster)")
        cluster.preempt_node(node_name, grace_s=grace_s)

    def restore_host(self, node_name: str) -> None:
        """End a host's maintenance: taint + notice removed, capacity returns
        (the scheduler's capacity-freed watch re-attempts pending gangs)."""
        with self._lock:
            cluster = self._cluster
        if cluster is None:
            raise RuntimeError("no SimCluster bound (FaultInjector.bind_cluster)")
        cluster.restore_node(node_name)

    def poison_host(self, node_name: str) -> None:
        """Silently fail a host (Ready=False, no taint, no notice) — the
        pool-poisoning op: a warm slice whose host dies unannounced sits in
        the pool as a trap until the suspend controller's sweep or a
        claim-time health check evicts it. Heal with restore_host."""
        with self._lock:
            cluster = self._cluster
        if cluster is None:
            raise RuntimeError("no SimCluster bound (FaultInjector.bind_cluster)")
        cluster.fail_node(node_name)

    # -- scripted fault constructors --

    def conflict_storm(self, kind: str, times: int = 3) -> FaultRule:
        """The next `times` UPDATEs of `kind` fail with 409 Conflict
        (optimistic-concurrency conflicts only exist on updates — a create
        can 409 AlreadyExists, never Conflict)."""
        return self.add(FaultRule(
            site="store.write", kind=kind, times=times,
            match=lambda ctx: ctx.get("verb") == "update",
            error=lambda: ConflictError(
                f"injected conflict storm on {kind}"),
        ))

    def throttle(self, times: int = 5, retry_after: float = 0.05,
                 kind: Optional[str] = None, writes_only: bool = False,
                 match: Optional[Callable[[Dict[str, Any]], bool]] = None,
                 ) -> List[FaultRule]:
        """429 + Retry-After on the next `times` store operations."""
        def err() -> Exception:
            return TooManyRequestsError(
                "injected throttle", retry_after=retry_after)

        sites = ["store.write"] if writes_only else ["store.write", "store.read"]
        return [
            self.add(FaultRule(site=s, kind=kind, times=times, error=err,
                               match=match))
            for s in sites
        ]

    def expire_watch(self, kind: Optional[str] = None,
                     times: int = 1) -> FaultRule:
        """The next `times` watch resumes answer 410 Expired — forces the
        informer/reflector relist path regardless of history depth."""
        return self.add(FaultRule(
            site="store.watch_resume", kind=kind, times=times,
            error=lambda: GoneError("injected: too old resource version"),
        ))

    def webhook_outage(self, name: Optional[str] = None,
                       times: int = 3, mode: str = "timeout") -> FaultRule:
        """The dispatcher's next `times` webhook callouts fail before the
        POST — `timeout` (socket timeout) or `error` (connection refused)."""
        import socket

        def err() -> Exception:
            if mode == "timeout":
                return socket.timeout("injected webhook timeout")
            return ConnectionError("injected webhook connection failure")

        return self.add(FaultRule(
            site="webhook.call", name=name, times=times, error=err))

    def crash_pod(self, name: str, restarts: int = 1) -> FaultRule:
        """The kubelet crash-restarts matching pods: container goes
        not-ready (CrashLoopBackOff, restartCount++), its server dies, and
        after `restarts` firings the pod comes back up."""
        return self.add(FaultRule(
            site="kubelet.pod", name=name, times=restarts, action="crash"))

    def reclaim_race(self, times: int = 3) -> FaultRule:
        """The next `times` Node updates 409 — exactly the write the warm-
        pool claim CAS rides (cluster/slicepool.py _stamp). Two resumes
        racing for the last warm slice plus this storm exercise the
        lose-and-move-on path: the loser must fall to the next pool or a
        cold miss, never double-claim or wedge."""
        return self.add(FaultRule(
            site="store.write", kind="Node", times=times,
            match=lambda ctx: ctx.get("verb") == "update",
            error=lambda: ConflictError("injected reclaim race on Node"),
        ))

    def partition_probe(self, host: Optional[str] = None,
                        times: Optional[int] = None) -> FaultRule:
        """Cluster-DNS HTTP requests to matching hosts fail — the probe
        agent's network partition. times=None holds the partition until the
        rule is removed (heal by `injector.remove(rule)`)."""
        return self.add(FaultRule(
            site="probe.http", name=host, times=times,
            error=lambda: ConnectionError("injected network partition"),
        ))


def apiserver_overload(injector: FaultInjector, seed: int,
                       scale: float = 1.0) -> List[FaultRule]:
    """A deterministic apiserver-overload schedule (ISSUE 13): the symptoms
    an admission storm produces at the API boundary — bursts of 429 on create
    traffic plus request-latency injection — with every budget drawn from
    random.Random(seed). Pair it with a driver-side TPUJob create storm (the
    overload lane in tests/test_overload.py, loadtest/tiers.py) so recovery
    has real work: clients must retry through the bursts, nothing may wedge,
    and exempt-level (lease) traffic must never be starved.

    `scale` multiplies the drawn budgets so soak lanes can lengthen the bad
    day without changing its shape."""
    rng = random.Random(seed)

    def n(lo: int, hi: int) -> int:
        return max(1, int(rng.randint(lo, hi) * scale))

    rules = [
        # 429 bursts on create traffic at the HTTP boundary (wire mode)
        injector.add(FaultRule(
            site="apiserver.request", times=n(5, 15),
            match=lambda ctx: ctx.get("method") == "POST",
            error=lambda: TooManyRequestsError(
                "injected apiserver overload", retry_after=0.05),
        )),
        # request-latency injection: every verb slows down under load
        injector.add(FaultRule(
            site="apiserver.request", action="delay",
            param=0.005 * rng.randint(1, 6), times=n(10, 30),
        )),
        # the same 429 bursts at the store boundary (sim mode, where typed
        # clients skip the HTTP layer); creates excluded per seeded_bad_day's
        # rationale — the driver's storm itself must enter the system
        *injector.throttle(times=n(4, 10), retry_after=0.02 * rng.randint(1, 3),
                           match=lambda ctx: ctx.get("verb") != "create"),
    ]
    return rules


def seeded_bad_day(injector: FaultInjector, seed: int,
                   kind: str = "Notebook") -> List[FaultRule]:
    """A deterministic combined fault schedule: every budget is drawn from
    random.Random(seed), so two runs with the same seed inject the identical
    fault set. Watch drops are count-scheduled by the caller (the test loop
    calls injector.drop_watches between convergence waits) — nothing here
    fires on wall-clock time."""
    rng = random.Random(seed)
    rules = [
        injector.conflict_storm(kind, times=rng.randint(2, 6)),
        # throttle everything except creates: the scenario driver's own
        # object creation must enter the system so recovery has work to do
        *injector.throttle(times=rng.randint(3, 8),
                           retry_after=0.02 * rng.randint(1, 3),
                           match=lambda ctx: ctx.get("verb") != "create"),
        injector.expire_watch(times=rng.randint(1, 3)),
        injector.webhook_outage(times=rng.randint(1, 4), mode="timeout"),
        injector.partition_probe(times=rng.randint(2, 5)),
    ]
    return rules


def seeded_slice_bad_day(
    cluster: Any,
    seed: int,
    pod_nodes: Dict[str, str],
    agents: Optional[Dict[str, Any]] = None,
    grace_s: float = 0.4,
    control_plane: bool = True,
) -> Dict[str, List[str]]:
    """One deterministic accelerator-layer bad day on top of the control-plane
    schedule: every victim choice is drawn from random.Random(seed).

    `pod_nodes` maps pod name -> node name for the candidate victims (the
    caller reads placements after bring-up). Enacts, per seeded draw:
    - host preemption (taint + maintenance notice; the node lifecycle drains
      after `grace_s`) on 1..len/2 distinct hosts,
    - chip loss (agent's monitor drops half its visible chips) or ICI
      degradation on 0..2 of the remaining pods, when `agents` is given.

    Returns the enacted plan {"preempted": [nodes], "chip_loss": [pods],
    "ici": [pods]} so the soak can heal preemptions and assert outcomes."""
    rng = random.Random(seed)
    # draw the control-plane seed FIRST so the fault set is a pure function
    # of `seed`, but install those rules LAST: the slice-fault enactment
    # below goes through the same store, and a 429 rule swallowing the
    # scenario driver's own taint write would silently shrink the bad day
    cp_seed = rng.randrange(2**31) if control_plane else None
    plan: Dict[str, List[str]] = {"preempted": [], "chip_loss": [], "ici": []}
    pods = sorted(pod_nodes)
    if pods:
        n_preempt = rng.randint(1, max(1, len(pods) // 2))
        victims = rng.sample(pods, n_preempt)
        for pod in victims:
            cluster.preempt_node(pod_nodes[pod], grace_s=grace_s)
            plan["preempted"].append(pod_nodes[pod])
        if agents is not None:
            survivors = [p for p in pods if p not in victims and p in agents]
            for pod in rng.sample(survivors, min(len(survivors), rng.randint(0, 2))):
                monitor = agents[pod].monitor
                if rng.random() < 0.5 and getattr(monitor, "chips", 0) > 1:
                    monitor.chips = monitor.chips // 2
                    plan["chip_loss"].append(pod)
                else:
                    monitor.ici_fault = True
                    plan["ici"].append(pod)
    if cp_seed is not None:
        seeded_bad_day(cluster.faults, seed=cp_seed)
    return plan


def seeded_pool_bad_day(
    cluster: Any,
    seed: int,
    warm_nodes: List[str],
    control_plane: bool = True,
) -> Dict[str, List[str]]:
    """One deterministic warm-pool bad day (ISSUE 7): every choice drawn from
    random.Random(seed).

    - **pool poisoning**: a seeded subset of the given WARM hosts fails
      silently (Ready=False, nothing announced) — resumes must route around
      the trap via the pool sweep / claim-time health check, never wedge on
      a dead warm slice,
    - **reclaim race**: a Node-update conflict storm lands exactly on the
      claim CAS writes, so racing claimants exercise the lose-and-move-on
      path,
    - plus the usual control-plane schedule (seeded_bad_day).

    Returns {"poisoned": [nodes]} so the soak can heal and assert outcomes.
    """
    rng = random.Random(seed)
    cp_seed = rng.randrange(2**31) if control_plane else None
    plan: Dict[str, List[str]] = {"poisoned": []}
    candidates = sorted(warm_nodes)
    if candidates:
        n = rng.randint(1, max(1, len(candidates) // 2))
        for node in rng.sample(candidates, min(n, len(candidates))):
            cluster.fail_node(node)
            plan["poisoned"].append(node)
    cluster.faults.reclaim_race(times=rng.randint(2, 6))
    if cp_seed is not None:
        seeded_bad_day(cluster.faults, seed=cp_seed)
    return plan


def seeded_router_bad_day(
    cluster: Any,
    seed: int,
    replica_nodes: Dict[int, List[str]],
    grace_s: float = 0.4,
    control_plane: bool = True,
    slow_factor_range: Tuple[float, float] = (2.0, 6.0),
) -> Dict[str, Any]:
    """One deterministic serving-fleet bad day (ISSUE 16): every victim
    choice is drawn from random.Random(seed).

    `replica_nodes` maps replica index -> the node names hosting that gang
    (the caller reads placements after fleet bring-up). Enacts, per draw:

    - **replica loss mid-stream**: EVERY host of one seeded victim replica
      is preempted (taint + maintenance notice; NodeLifecycle drains after
      `grace_s`) — the fleet's unit of failure is a whole gang, and the
      router must eject it while the controller re-places through the
      repair/warm-pool paths,
    - **slow replica**: one surviving replica is named in the plan with a
      seeded latency factor. The engines live OUTSIDE the cluster sim, so
      the caller applies the slowdown at its engine boundary (the loadtest
      wraps submit with the factor) — the router's TTFT-tail scoring and
      hedging must route around it,
    - **probe flaps**: a count-bounded cluster-DNS partition on half of one
      surviving replica's hosts — transient probe failures that must feed
      the router's breaker WITHOUT permanently ejecting a healthy replica
      (bounded re-admission earns it back),
    - plus the usual control-plane schedule (seeded_bad_day).

    Returns the enacted plan {"killed_replica", "preempted": [nodes],
    "slow_replica", "slow_factor", "probe_flap_hosts": [nodes]} so the soak
    can heal and assert outcomes."""
    rng = random.Random(seed)
    # draw the control-plane seed FIRST, install its rules LAST (the
    # preemption writes below must not be swallowed by a 429 rule) — the
    # seeded_slice_bad_day idiom
    cp_seed = rng.randrange(2**31) if control_plane else None
    plan: Dict[str, Any] = {
        "killed_replica": None,
        "preempted": [],
        "slow_replica": None,
        "slow_factor": 1.0,
        "probe_flap_hosts": [],
    }
    indexes = sorted(replica_nodes)
    if indexes:
        victim = rng.choice(indexes)
        plan["killed_replica"] = victim
        for node in sorted(replica_nodes[victim]):
            cluster.preempt_node(node, grace_s=grace_s)
            plan["preempted"].append(node)
        survivors = [i for i in indexes if i != victim]
        if survivors:
            plan["slow_replica"] = rng.choice(survivors)
            plan["slow_factor"] = round(rng.uniform(*slow_factor_range), 2)
            flap_hosts = sorted(replica_nodes[rng.choice(survivors)])
            for node in flap_hosts[: max(1, len(flap_hosts) // 2)]:
                cluster.faults.partition_probe(
                    host=node, times=rng.randint(1, 3)
                )
                plan["probe_flap_hosts"].append(node)
    if cp_seed is not None:
        seeded_bad_day(cluster.faults, seed=cp_seed)
    return plan
